"""Headline benchmark: MoEvA2 on LCLD at the north-star budget.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} where
the extra keys record BOTH timings — ``steady_s`` (minimum of two compiled
re-runs; ``steady_estimator: "min2"`` in the record — the min is the
standard estimator of intrinsic cost under the tunnelled device's ~±10%
run-to-run jitter) and ``cold_s`` (first call, including jit compile
or persistent-cache load) — plus ``speedup_cold`` and a ``real_botnet``
sub-record measured on the reference's committed 387×756 candidate set and
Keras model (no synthetic data). The headline ``value`` is judged on the
STEADY number: the north star targets the recurring per-experiment cost of
the rq1 grid (many runs of one compiled program), and the one-time compile
is amortised by the persistent cache across bench invocations; ``cold_s``
is reported alongside so the amortisation is visible, not hidden.

The reference publishes no absolute numbers (BASELINE.md) and cannot run in
this image (pymoo/autograd absent), so the CPU denominator is *measured
operationally* on this host as a conservative floor of the reference's
per-generation cost: the reference's own Keras SavedModel forward (TF, CPU)
plus a numpy twin of the 10 LCLD constraint formulas, times the north-star
budget (n_states x n_gen), divided by the host's core count (assuming the
reference's joblib fan-out scales perfectly — it does not). Excludes all
pymoo/keras.predict per-call overheads, so the reported speedup is an
UNDERESTIMATE of the true advantage.

North star (BASELINE.json): LCLD rq1, n_init=1000, pop=100, n_gen=1000,
L2, success-rate parity. Env knobs: BENCH_STATES / BENCH_GENS / BENCH_POP
shrink the run for smoke-testing.
"""

import json
import os
import sys
import time

import numpy as np

N_STATES = int(os.environ.get("BENCH_STATES", 1000))
N_GEN = int(os.environ.get("BENCH_GENS", 1000))
N_POP = int(os.environ.get("BENCH_POP", 100))
N_OFF = int(os.environ.get("BENCH_OFF", 100))

LCLD_DIR = "/root/reference/data/lcld"
MODEL = "/root/reference/models/lcld/nn.model"
SCALER = "/root/reference/models/lcld/scaler.joblib"

# Per-(generation x state) reference CPU cost [s] calibrated on an idle dev
# host (TF SavedModel forward on (100, 47): 0.69 ms + numpy constraints
# 0.06 ms). Used as the fallback when TF cannot run, and as a CAP on the
# live measurement: a busy bench host inflates the TF timing (observed up to
# 8x under concurrent load), which would inflate the reported speedup — the
# denominator is clamped to the calibrated idle number so the headline can
# only be under-, never over-stated by host noise.
FALLBACK_REF_PERGEN_S = 7.5e-4


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bound_record(rec: dict) -> dict:
    """Bound every list-valued telemetry field of the printed record.

    The committed ``BENCH_r*.json`` driver wrapper stores bench stdout's
    one JSON line; an unbounded line — hundreds of ledger entries, every
    cold-classified executable, a full-budget quality curve — risks
    wrapper-side truncation, which parses as an EMPTY ``parsed`` payload
    and silently drops the very telemetry the ``bench_diff --overlap``
    / ``--cold`` gates need (r05's ``parsed.telemetry`` came back empty
    exactly this way). Every gated scalar (overlap_ratio,
    cold_steady_ratio, flops_total, interior rates, knee, by_outcome
    counts) is kept exact; only the long per-item lists are capped, each
    with an ``<key>_omitted`` count — bounded, never silently truncated.
    Mutates and returns ``rec`` (called right before the final print)."""

    def cap(d, key, n, sort_key=None):
        lst = d.get(key)
        if isinstance(lst, list) and len(lst) > n:
            if sort_key is not None:
                lst = sorted(lst, key=sort_key, reverse=True)
            d[key + "_omitted"] = len(lst) - n
            d[key] = lst[:n]

    subs = [rec] + [
        rec.get(k) for k in ("real_botnet", "early_exit", "serving")
        if isinstance(rec.get(k), dict)
    ]
    for sub in subs:
        tele = sub.get("telemetry") or {}
        cap(
            tele.get("cost") or {}, "entries", 12,
            sort_key=lambda e: e.get("dispatches") or 0,
        )
        cap(tele.get("quality") or {}, "curve", 24)
    cap(((rec.get("real_botnet") or {}).get("quality")) or {}, "curve", 24)
    cap(
        ((rec.get("cold") or {}).get("persistent_cache")) or {},
        "by_executable", 24,
    )
    return rec


def np_lcld_constraints(x):
    """Numpy twin of the 10 LCLD formulas (for CPU cost measurement only)."""
    def months(f):
        return np.floor(f / 100) * 12 + f % 100

    r = x[:, 2] / 1200.0
    g = (1 + r) ** x[:, 1]
    with np.errstate(divide="ignore", invalid="ignore"):
        g1 = np.abs(x[:, 3] - x[:, 0] * r * g / (g - 1)) - 0.099999
        g5 = np.abs(x[:, 20] - x[:, 0] / x[:, 6])
        g6 = np.abs(x[:, 21] - x[:, 10] / x[:, 14])
        g8 = np.abs(x[:, 23] - x[:, 11] / x[:, 22])
        g9 = np.abs(x[:, 24] - x[:, 16] / x[:, 22])
        ratio = np.where(x[:, 11] == 0, -1, x[:, 16] / np.where(x[:, 11] == 0, 1, x[:, 11]))
    g2 = x[:, 10] - x[:, 14]
    g3 = x[:, 16] - x[:, 11]
    g4 = np.abs((36 - x[:, 1]) * (60 - x[:, 1]))
    g7 = np.abs(x[:, 22] - (months(x[:, 7]) - months(x[:, 9])))
    g10 = np.abs(x[:, 25] - ratio)
    return np.stack([g1, g2, g3, g4, g5, g6, g7, g8, g9, g10], 1)


def measure_ref_pergen() -> float:
    """Per-(generation x state) cost of the reference hot loop on this CPU."""
    try:
        os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
        import tensorflow as tf

        m = tf.saved_model.load(MODEL)
        f = m.signatures["serving_default"]
        xb = tf.constant(np.random.rand(N_OFF, 47).astype(np.float32))
        for _ in range(3):
            f(xb)
        t0 = time.perf_counter()
        reps = 30
        for _ in range(reps):
            f(xb)
        t_fwd = (time.perf_counter() - t0) / reps
    except Exception as e:  # TF unavailable on bench host
        log(f"[bench] TF baseline measurement failed ({e}); using fallback")
        return FALLBACK_REF_PERGEN_S

    xc = np.random.rand(N_OFF, 47) * 100 + 1
    np_lcld_constraints(xc)
    t0 = time.perf_counter()
    reps = 100
    for _ in range(reps):
        np_lcld_constraints(xc)
    t_cons = (time.perf_counter() - t0) / reps
    log(f"[bench] ref CPU per-gen/state: fwd {t_fwd*1e3:.3f} ms + cons {t_cons*1e3:.3f} ms")
    return t_fwd + t_cons


def measure_grid_wallclock() -> dict | None:
    """VERDICT r3/r5 item: the ≥50× claim must survive a WHOLE-GRID
    measurement including compile amortisation. Times the full LCLD rq1 grid
    (MoEvA + 5 PGD loss variants × budgets {100, 1000}) end-to-end through
    the real rq runner, twice back-to-back in fresh working directories:
    ``cold`` = first pass (compiles come from the persistent .jax_cache when
    it is populated — that IS the amortisation story across bench/grid
    invocations), ``warm`` = second pass (cache guaranteed hot). Runs BEFORE
    the parent process initialises the TPU backend (the chip is exclusive).
    ``BENCH_SKIP_GRID=1`` skips."""
    if os.environ.get("BENCH_SKIP_GRID"):
        return None
    import shutil
    import subprocess
    import tempfile

    repo = os.path.dirname(os.path.abspath(__file__))
    if not os.path.isdir(os.path.join(repo, "models", "lcld")):
        log("[bench] grid wallclock skipped: ./models/lcld not bootstrapped")
        return None
    os.makedirs(os.path.join(repo, ".jax_cache"), exist_ok=True)
    out = {"grid": "rq1.lcld (moeva + 5 pgd losses, budgets 100/1000)"}
    for label in ("cold", "warm"):
        td = tempfile.mkdtemp(prefix=f"bench_grid_{label}_")
        try:
            for name in ("config", "models", "data", ".jax_cache"):
                os.symlink(os.path.join(repo, name), os.path.join(td, name))
            t0 = time.perf_counter()
            try:
                r = subprocess.run(
                    [
                        sys.executable, "-m",
                        "moeva2_ijcai22_replication_tpu.experiments.rq",
                        "-c", "config/rq1.lcld.yaml",
                    ],
                    cwd=td, capture_output=True, text=True,
                    # a hung tunnel in the grid must not take the whole
                    # bench record down with it
                    timeout=int(os.environ.get("BENCH_GRID_TIMEOUT", 1200)),
                    env=dict(
                        os.environ,
                        PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
                    ),
                )
            except subprocess.TimeoutExpired:
                log(f"[bench] grid {label}: timed out; skipping grid metric")
                out[label + "_rc"] = "timeout"
                continue
            dt = time.perf_counter() - t0
            n_metrics = 0
            report = None
            for root, _, fs in os.walk(os.path.join(td, "out")):
                for f in fs:
                    n_metrics += f.startswith("metrics_")
                    if f.startswith("grid_report_"):
                        try:
                            with open(os.path.join(root, f)) as fh:
                                report = json.load(fh)
                        except Exception:
                            pass
            out[label + "_s"] = round(dt, 1)
            out[label + "_runs"] = n_metrics
            if report:
                # pipeline observability: attribute grid-wallclock movement
                # to executable/artifact reuse vs raw compute across rounds
                out[label + "_pipeline"] = {
                    k: report.get(k)
                    for k in (
                        "distinct_compiled_programs",
                        "attack_compile_s",
                        "attack_run_s",
                        "evaluate_s",
                        "write_s",
                        "artifact_cache",
                        "engine_cache",
                    )
                }
            log(
                f"[bench] grid {label}: {dt:.1f}s, {n_metrics} metrics files, "
                f"rc={r.returncode}"
            )
            if r.returncode != 0:
                out[label + "_rc"] = r.returncode
                log("[bench] grid stderr tail: " + r.stderr.strip()[-300:])
        finally:
            shutil.rmtree(td, ignore_errors=True)
    out["jax_cache_entries"] = len(os.listdir(os.path.join(repo, ".jax_cache")))
    return out


def run_real_botnet() -> dict | None:
    """Second metric on REAL reference inputs (no synthetic data): MoEvA on
    the committed 387×756 botnet candidate set against the committed Keras
    model, o-rates at the rq2 ε=4 setting. Budget via BENCH_BOTNET_GENS —
    default 1000, the reference's own rq1 budget: the corrected
    (pymoo-oracle-validated) survival semantics are budget-sensitive below
    ~300 generations (o2@100 ≈ 0.2 on a trajectory that saturates to 1.0 by
    1000 — see docs/DESIGN.md §9), so the honest parity point is the
    reference's budget, not a truncated one."""
    if os.environ.get("BENCH_SKIP_BOTNET"):
        return None
    n_gen = int(os.environ.get("BENCH_BOTNET_GENS", 1000))
    try:
        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
        from moeva2_ijcai22_replication_tpu.attacks.objective import (
            ObjectiveCalculator,
        )
        from moeva2_ijcai22_replication_tpu.domains.botnet import BotnetConstraints
        from moeva2_ijcai22_replication_tpu.models.io import load_classifier
        from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler

        base = "/root/reference"
        cons = BotnetConstraints(
            f"{base}/data/botnet/features.csv", f"{base}/data/botnet/constraints.csv"
        )
        x = np.load(f"{base}/data/botnet/x_candidates_common.npy")
        sur = load_classifier(f"{base}/models/botnet/nn.model")
        scaler = load_joblib_scaler(f"{base}/models/botnet/scaler.joblib")
        from moeva2_ijcai22_replication_tpu.observability import quality_block

        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler,
            norm=2, n_gen=n_gen, n_pop=200, n_offsprings=100, seed=42,
            archive_size=24,  # the production default (config/moeva.yaml)
            # convergence telemetry: quality samples every 100 generations
            # — the interior points ({100, 300}) are exactly where the
            # adjudicated trajectory is budget-sensitive (0.199/0.080 @100)
            # and where tools/bench_diff.py pins drift; sampling splits the
            # scan at semantics-free boundaries, bit-identical results
            record_quality=True,
            quality_every=int(os.environ.get("BENCH_QUALITY_EVERY", 100)),
        )
        t0 = time.perf_counter()
        res = moeva.generate(x, minimize_class=1)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        res = moeva.generate(x, minimize_class=1)
        steady = time.perf_counter() - t0
        calc = ObjectiveCalculator(
            classifier=sur, constraints=cons,
            thresholds={"f1": 0.5, "f2": 4.0},
            min_max_scaler=scaler, ml_scaler=scaler,
            minimize_class=1, norm=2,
        )
        rates = [round(float(r), 4) for r in calc.success_rate_3d(x, res.x_ml)]
        log(
            f"[bench] real botnet ({x.shape[0]} states x {n_gen} gens): "
            f"{steady:.1f}s steady / {cold:.1f}s cold; o1..o7 @eps=4: "
            + " ".join(f"{r:.3f}" for r in rates)
        )
        return {
            "n_states": int(x.shape[0]),
            "n_gen": n_gen,
            "steady_s": round(steady, 2),
            "cold_s": round(cold, 2),
            "cold_steady_ratio": round(cold / steady, 3) if steady else None,
            "o_rates_eps4": rates,
            # engine-judged convergence curve + interior-point summary —
            # the saturation-proof record: a survival-semantics regression
            # moves the @100/@300 rates even when the full-budget o-rates
            # stay all-ones (bench_diff gates on these)
            "quality": quality_block(
                res.quality,
                final={"judged": "post_hoc_f64", "eps": 4.0, "o_rates": rates},
            ),
        }
    except Exception as e:
        log(f"[bench] real-botnet metric skipped: {e}")
        return None


def run_early_exit_bench() -> dict | None:
    """Success-gated early exit A/B (the ``early_exit`` record): one engine,
    one seed, one candidate set — a fixed-budget strict run vs an early-exit
    run (``early_stop_check_every``) on the code-derived synthetic LCLD
    schema, so the record reproduces in any CI container with no reference
    tree. The scenario is the serving layer's "easy rows" case: candidates
    are picked near the decision boundary so most states hold a constrained
    adversarial well before half the budget — exactly the population the
    round-5 adjudication measured (success 0.959 by gen 300 of 1000). The
    record carries wall-clock for both modes (min-of-2 steady), generations
    executed vs budget, the compaction trace, the distinct compiled segment
    programs of the shrinking run (bounded by the bucket-menu length), and
    the criterion success rates of both runs (archive on, so early exit
    cannot lose successes). ``BENCH_SKIP_EARLY_EXIT=1`` skips;
    BENCH_EE_STATES / _GENS / _CHECK / _POP / _OFF reshape the run."""
    if os.environ.get("BENCH_SKIP_EARLY_EXIT"):
        return None
    try:
        import tempfile

        import jax.numpy as jnp

        from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import (
            synth_lcld,
            synth_lcld_schema,
        )
        from moeva2_ijcai22_replication_tpu.models.io import Surrogate
        from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
        from moeva2_ijcai22_replication_tpu.models.scalers import fit_minmax

        s = int(os.environ.get("BENCH_EE_STATES", 64))
        n_gen = int(os.environ.get("BENCH_EE_GENS", 201))  # 200 scan steps
        check = int(os.environ.get("BENCH_EE_CHECK", 10))
        n_pop = int(os.environ.get("BENCH_EE_POP", 40))
        n_off = int(os.environ.get("BENCH_EE_OFF", 20))
        threshold = 0.5

        tmp = tempfile.mkdtemp(prefix="bench_early_exit_")
        paths = synth_lcld_schema(tmp)
        cons = LcldConstraints(paths["features"], paths["constraints"])
        model = lcld_mlp()
        sur = Surrogate(model, init_params(model, cons.schema.n_features, seed=1))

        # easy-rows candidate selection: states already near (or past) the
        # boundary converge early — the workload the gate exists for
        pool = synth_lcld(8 * s, cons.schema, seed=7)
        scaler = fit_minmax(pool.min(0), pool.max(0))
        p1 = np.asarray(sur.predict_proba(scaler.transform(pool)))[:, 1]
        x = pool[np.argsort(np.abs(p1 - threshold))[:s]]

        from moeva2_ijcai22_replication_tpu.observability import (
            Trace, TraceRecorder, get_gap_tracker, get_ledger, quality_block,
            telemetry_block, validate_record,
        )

        moeva = Moeva2(
            classifier=sur, constraints=cons, ml_scaler=scaler, norm=2,
            n_gen=n_gen, n_pop=n_pop, n_offsprings=n_off, seed=42,
            archive_size=8, early_stop_threshold=threshold,
            # quality samples ride the early-exit gates for free (the gate
            # program computes them either way)
            record_quality=True,
        )
        # gate progress events (gen index, success fraction, active set,
        # HBM) land in the record's telemetry block
        recorder = TraceRecorder(spans_enabled=True)
        moeva.trace = Trace(recorder, trace_id="bench-early-exit")
        # cost window: this record reports the A/B's own executables, not
        # whatever the rest of the bench invocation compiled
        ledger_mark = get_ledger().mark()
        gaps_mark = get_gap_tracker().mark()

        def timed(check_every):
            moeva.early_stop_check_every = check_every
            best, res = None, None
            for _ in range(2):  # min-of-2: first call may include compiles
                t0 = time.perf_counter()
                res = moeva.generate(x, minimize_class=1)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best, res

        def success(res):
            f = res.f
            return float(
                ((f[..., 0] < threshold) & (f[..., 2] <= 0)).any(axis=1).mean()
            )

        fixed_s, fixed = timed(0)
        traces0 = moeva.trace_count
        early_s, early = timed(check)
        seg_programs = moeva.trace_count - traces0
        trace = early.early_stop["compaction"]
        # states already solved by the last gate at or before half budget
        converged_half = 0
        for t in trace:
            if t["gen"] <= (n_gen - 1) // 2:
                converged_half = s - t["active"]
        menu_len = len(moeva._compaction_menu().sizes)
        record = {
            "n_states": s,
            "budget_gens": n_gen - 1,
            "check_every": check,
            "steady_estimator": "min2",
            "fixed_s": round(fixed_s, 3),
            "early_s": round(early_s, 3),
            "speedup": round(fixed_s / early_s, 2),
            "gens_executed": int(early.gens_executed),
            "converged_by_half_budget": round(converged_half / s, 3),
            "compaction": trace,
            "distinct_segment_programs": int(seg_programs),
            "bucket_menu_len": menu_len,
            "success_fixed": round(success(fixed), 4),
            "success_early": round(success(early), 4),
            # shared record schema (observability.records): execution mode
            # + telemetry travel with every committed number
            "execution": {
                "max_states_per_call": moeva.effective_states_chunk(),
                "mesh": None,
                "early_stop_check_every": check,
                "gens_executed": int(early.gens_executed),
            },
            "telemetry": telemetry_block(
                recorder=recorder,
                trace=moeva.trace,
                ledger_since=ledger_mark,
                gaps_since=gaps_mark,
                # the early-exit run's quality curve (gate-cadence samples)
                quality=quality_block(early.quality),
            ),
        }
        validate_record(record, "early_exit")
        log(
            f"[bench] early_exit: fixed {fixed_s:.2f}s vs early {early_s:.2f}s "
            f"({record['speedup']}x), gens {early.gens_executed}/{n_gen - 1}, "
            f"{seg_programs} segment programs (menu {menu_len}), success "
            f"{record['success_fixed']} -> {record['success_early']}, "
            f"{record['converged_by_half_budget']:.0%} converged by half budget"
        )
        return record
    except Exception as e:
        log(f"[bench] early-exit metric skipped: {e}")
        return None


def lcld_serving_artifacts() -> dict:
    """LCLD artifact paths for the serving/fleet benches: the reference
    tree when present, else the code-derived synthetic schema + a random
    surrogate written to a temp dir (latency/occupancy/routing are
    engine-shape properties, not weight properties — the CI fallback
    serves the same compiled shapes). Returns ``{features, constraints,
    model, ml_scaler, kind}``."""
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints

    features = os.path.join(LCLD_DIR, "features.csv")
    constraints_csv = os.path.join(LCLD_DIR, "constraints.csv")
    model, scaler_path = MODEL, SCALER
    kind = "reference"
    if not os.path.exists(features):
        import tempfile

        import joblib
        from sklearn.preprocessing import MinMaxScaler as SkMinMax

        from moeva2_ijcai22_replication_tpu.domains.synth import (
            synth_lcld,
            synth_lcld_schema,
        )
        from moeva2_ijcai22_replication_tpu.models.io import (
            Surrogate, save_params,
        )
        from moeva2_ijcai22_replication_tpu.models.mlp import (
            init_params, lcld_mlp,
        )

        kind = "synthetic"
        tmp = tempfile.mkdtemp(prefix="bench_serving_")
        paths = synth_lcld_schema(tmp)
        features, constraints_csv = paths["features"], paths["constraints"]
        cons0 = LcldConstraints(features, constraints_csv)
        mlp = lcld_mlp()
        sur = Surrogate(mlp, init_params(mlp, cons0.schema.n_features, seed=1))
        model = os.path.join(tmp, "nn.msgpack")
        save_params(sur, model)
        x0 = synth_lcld(512, cons0.schema, seed=7)
        xl, xu = cons0.get_feature_min_max(dynamic_input=x0)
        xl = np.broadcast_to(np.asarray(xl, float), x0.shape)
        xu = np.broadcast_to(np.asarray(xu, float), x0.shape)
        scaler_path = os.path.join(tmp, "scaler.joblib")
        joblib.dump(SkMinMax().fit(np.vstack([x0, xl, xu])), scaler_path)
    return {
        "features": features,
        "constraints": constraints_csv,
        "model": model,
        "ml_scaler": scaler_path,
        "kind": kind,
    }


def run_serving_bench() -> dict | None:
    """Request-path metric (no network, single process, CPU-able — the CI
    mode behind ``bench.py --serving``): an offered-load sweep of mixed-size
    PGD requests through the in-process AttackService/microbatcher, on the
    same reference LCLD artifacts as the headline metric. Reports per-level
    throughput, client latency quantiles, and mean batch occupancy — the
    trajectory record for the request path, next to the batch path's.
    ``BENCH_SKIP_SERVING=1`` skips; BENCH_SERVING_LOADS / _REQUESTS /
    _BUDGET / _DELAY shrink or reshape the sweep."""
    if os.environ.get("BENCH_SKIP_SERVING"):
        return None
    try:
        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
        from moeva2_ijcai22_replication_tpu.serving import AttackRequest, AttackService
        from moeva2_ijcai22_replication_tpu.serving.sweep import offered_load_sweep

        art = lcld_serving_artifacts()
        features, constraints_csv = art["features"], art["constraints"]
        model, scaler_path = art["model"], art["ml_scaler"]
        artifacts_kind = art["kind"]

        domain = {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": model,
                "features": features,
                "constraints": constraints_csv,
                "ml_scaler": scaler_path,
            },
            "system": {"mesh_devices": 0},
        }
        loads = [
            float(v)
            for v in os.environ.get("BENCH_SERVING_LOADS", "16,64,256").split(",")
        ]
        n_requests = int(os.environ.get("BENCH_SERVING_REQUESTS", 96))
        budget = int(os.environ.get("BENCH_SERVING_BUDGET", 10))
        max_delay_s = float(os.environ.get("BENCH_SERVING_DELAY", 0.01))
        buckets = (8, 16, 32, 64)

        cons = LcldConstraints(features, constraints_csv)
        pool = synth_lcld(512, cons.schema, seed=7)
        sizes = [1 + i % 13 for i in range(max(n_requests, 64))]

        service = AttackService(
            {"lcld": domain},
            bucket_sizes=buckets,
            max_delay_s=max_delay_s,
            max_queue_rows=4096,
        )

        def make_request(i: int) -> AttackRequest:
            n = sizes[i % len(sizes)]
            start = (i * 17) % (pool.shape[0] - n)
            return AttackRequest(
                domain="lcld",
                x=pool[start : start + n],
                eps=0.2,
                budget=budget,
                loss_evaluation="flip",
            )

        # pay the per-bucket-size compiles outside the measured levels: one
        # warmup request per menu size (serving steady state is the metric;
        # the compile count still lands in the record's counters)
        from moeva2_ijcai22_replication_tpu.observability import get_coldstart

        cs = get_coldstart()
        compile0 = cs.compile_phase_seconds()
        t0 = time.perf_counter()
        for b in service.menu.sizes:
            service.attack(
                AttackRequest(
                    domain="lcld", x=pool[:b], eps=0.2, budget=budget
                ),
                timeout=300.0,
            )
        warmup_s = time.perf_counter() - t0
        # the explicit warmup loop IS the device_warmup phase of this
        # process's cold path — minus the compile seconds it contained,
        # which note_compile already booked under trace_lower/xla_compile
        # (the phases must decompose the cold wall, not double-count it)
        get_coldstart().record_phase(
            "device_warmup",
            max(warmup_s - (cs.compile_phase_seconds() - compile0), 0.0),
        )

        record = offered_load_sweep(service, make_request, loads, n_requests)
        record["warmup_s"] = round(warmup_s, 2)
        record["budget"] = budget
        record["artifacts"] = artifacts_kind
        service.close()
        for lv in record["levels"]:
            log(
                f"[bench] serving @{lv['offered_rps']:g} rps: "
                f"{lv['throughput_rps']} rps, p50 {lv['p50_ms']} ms, "
                f"p99 {lv['p99_ms']} ms, occupancy {lv['mean_batch_occupancy']}, "
                f"rejected {lv['rejected']}"
            )
        knee = record["telemetry"]["slo"]["knee"]
        shed = record["telemetry"]["slo"]["shed"]
        log(
            f"[bench] serving knee: {knee['knee_rps']} rps "
            f"(first saturated {knee['first_saturated_rps']}), "
            f"shed {shed['total']}"
        )
        return record
    except Exception as e:
        log(f"[bench] serving metric skipped: {e}")
        return None


def run_fleet_bench() -> dict | None:
    """Fleet metric (``bench.py --fleet``): the multi-replica proof — N
    real ``tools/serve.py`` subprocesses over one shared AOT cache behind
    the capacity router, measured at 1/2/4 replicas with a kill-a-replica
    chaos segment (``serving.fleet.sweep.fleet_sweep``).

    Single-host honesty: the replicas are configured *admission-limited*
    — ``max_queue_rows`` below the largest bucket disables the
    capacity-flush path, so each replica admits at most Q rows per
    ``max_delay_s`` window at a few percent CPU. The per-replica knee is
    then a queueing property, not a device property, and N replicas on
    one small host genuinely multiply aggregate admission capacity —
    which is precisely the fleet property under test (routing, failover,
    shed accounting), not a claim about N× device FLOPs.

    Env knobs: BENCH_FLEET_COUNTS / _RATES (per-replica rps ladder) /
    _REQUESTS (per replica per level) / _DELAY / _QUEUE_ROWS / _BUDGET /
    _SKIP_CHAOS / _PLATFORM (replica JAX_PLATFORMS, default cpu)."""
    if os.environ.get("BENCH_SKIP_FLEET"):
        return None
    try:
        import tempfile

        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
        from moeva2_ijcai22_replication_tpu.serving.fleet.sweep import fleet_sweep

        art = lcld_serving_artifacts()
        counts = [
            int(v)
            for v in os.environ.get("BENCH_FLEET_COUNTS", "1,2,4").split(",")
        ]
        rates = [
            float(v)
            for v in os.environ.get("BENCH_FLEET_RATES", "8,13,18,25").split(",")
        ]
        n_requests = int(os.environ.get("BENCH_FLEET_REQUESTS", 80))
        max_delay_s = float(os.environ.get("BENCH_FLEET_DELAY", 0.35))
        queue_rows = int(os.environ.get("BENCH_FLEET_QUEUE_ROWS", 6))
        budget = int(os.environ.get("BENCH_FLEET_BUDGET", 5))

        # one shared cache tree per sweep: the warm-seed replica pays the
        # compiles into it, every measured replica AOT-loads from it —
        # the record's warm fractions prove exactly this directory's worth
        run_dir = tempfile.mkdtemp(prefix="bench_fleet_")
        trace_dir = os.path.join(run_dir, "traces")
        os.makedirs(trace_dir, exist_ok=True)
        cfg = {
            "domains": {
                "lcld": {
                    "project_name": "lcld",
                    "norm": 2,
                    "paths": {
                        "model": art["model"],
                        "features": art["features"],
                        "constraints": art["constraints"],
                        "ml_scaler": art["ml_scaler"],
                    },
                    "system": {"mesh_devices": 0},
                }
            },
            "serving": {
                # admission-limited shape: queue bound (6) < largest
                # bucket (8) => only the deadline flush drains the queue;
                # per-replica admission knee ~ queue_rows / max_delay_s
                "bucket_sizes": [4, 8],
                "max_delay_s": max_delay_s,
                "max_queue_rows": queue_rows,
                "request_timeout_s": 30.0,
                "capacity_window": 256,
                "prewarm": True,
                # fleet tracing on: per-replica JSONL sinks (templated
                # trace_r01.jsonl, ...) merged after the sweep into the
                # committed cross-replica Perfetto doc; the flight ring
                # makes chaos losses attributable from the harvested dump
                "trace_log": os.path.join(trace_dir, "trace.jsonl"),
                "flight_dir": os.path.join(run_dir, "flight"),
            },
            "system": {"jax_cache_dir": os.path.join(run_dir, "jax_cache")},
        }
        config_path = os.path.join(run_dir, "fleet_config.json")
        with open(config_path, "w") as f:
            json.dump(cfg, f, indent=2)

        cons = LcldConstraints(art["features"], art["constraints"])
        pool = synth_lcld(256, cons.schema, seed=7)
        rows = [list(map(float, pool[i % pool.shape[0]])) for i in range(256)]

        def make_body(i: int) -> bytes:
            # 1-row requests: the admission-limited design counts rows ==
            # requests, so offered rps compares directly to queue_rows/delay
            return json.dumps(
                {
                    "domain": "lcld",
                    "rows": [rows[i % len(rows)]],
                    "attack": "pgd",
                    "loss_evaluation": "flip",
                    "eps": 0.2,
                    "budget": budget,
                }
            ).encode()

        # replica env: force a CPU backend by default (N replicas cannot
        # share an exclusive TPU on one host) and make sure the AOT cache
        # is LIVE in the children even when the parent runs with it off
        # (the test conftest exports MOEVA2_AOT_CACHE_DISABLE=1)
        env = dict(os.environ)
        env.pop("MOEVA2_AOT_CACHE_DISABLE", None)
        env["JAX_PLATFORMS"] = os.environ.get("BENCH_FLEET_PLATFORM", "cpu")

        # the router's own spans ride a sink too, so the merged doc shows
        # route -> attempt spans ABOVE the replicas' request trees (one
        # trace id across processes: replicas adopt X-Moeva2-Trace)
        from moeva2_ijcai22_replication_tpu.observability import TraceRecorder
        from moeva2_ijcai22_replication_tpu.observability.fleetrace import (
            merge_fleet_traces,
            replica_sink_path,
        )

        router_sink = os.path.join(trace_dir, "trace_router.jsonl")
        router_rec = TraceRecorder(sink_path=router_sink)
        try:
            record = fleet_sweep(
                config_path,
                make_body,
                counts=counts,
                per_replica_rates=rates,
                n_requests=n_requests,
                chaos=not os.environ.get("BENCH_FLEET_SKIP_CHAOS"),
                manager_kw={
                    "env": env,
                    "log_dir": os.path.join(run_dir, "logs"),
                },
                router_kw={"recorder": router_rec},
            )
        finally:
            router_rec.close()
        record["artifacts"] = art["kind"]
        record["serving_config"] = cfg["serving"]

        # merge the per-process sinks onto the router's wall clock (each
        # replica's offset was measured at its last /healthz poll). The
        # FULL doc stays in the run dir (MBs — every request of the
        # sweep); the committed doc is pruned to the cross-process traces
        # (one id spanning router + replica sinks), which is the proof
        merge_out = os.environ.get(
            "BENCH_FLEET_TRACE_OUT", os.path.join("out", "fleet_trace.json")
        )
        full_out = os.path.join(trace_dir, "fleet_trace_full.json")
        sinks = {"router": router_sink}
        offsets: dict[str, float] = {}
        for r in record["fleet_final"]["replicas"]:
            rid = r["replica_id"]
            sinks[rid] = replica_sink_path(
                cfg["serving"]["trace_log"], rid
            )
            offsets[rid] = r.get("clock_offset_s") or 0.0
        doc = merge_fleet_traces(sinks, offsets, out_path=full_out)
        merge_report = doc["otherData"]["fleet_merge"]
        # cross-process trace ids: events in MORE than one source sink
        # (the router's attempt span + the replica tree that adopted its
        # X-Moeva2-Trace id — the end-to-end journey the merge exists for)
        from moeva2_ijcai22_replication_tpu.observability.export import (
            read_jsonl,
        )

        trace_sources: dict[str, set] = {}
        for label, path in sinks.items():
            if not os.path.exists(path):
                continue
            for ev in read_jsonl(path):
                tid = ev.get("trace")
                if tid:
                    trace_sources.setdefault(tid, set()).add(label)
        cross = sorted(
            t for t, srcs in trace_sources.items() if len(srcs) > 1
        )
        # EVERY routed request is cross-process (the replica adopts the
        # router's id), so the committed subset is the failover chains —
        # connection-cause first (the requests that crossed the chaos
        # kill), capped; dropped counts stay on the record (no silent cap)
        failover_causes: dict[str, set] = {}
        for ev in read_jsonl(router_sink):
            if ev.get("kind") == "event" and ev.get("name") == "failover":
                failover_causes.setdefault(ev.get("trace"), set()).add(
                    (ev.get("attrs") or {}).get("cause")
                )
        conn = sorted(
            t for t, c in failover_causes.items() if "connection" in c
        )
        other = sorted(set(failover_causes) - set(conn))
        keep = (conn + other)[:40] or cross[:8]
        keep_pids = {
            ev["pid"]
            for ev in doc.get("traceEvents", [])
            if ev.get("ph") == "M"
            and ev.get("name") == "process_name"
            and (ev.get("args") or {}).get("name") in set(keep)
        }
        pruned = dict(
            doc,
            traceEvents=[
                ev
                for ev in doc.get("traceEvents", [])
                if ev.get("pid") in keep_pids
            ],
        )
        pruned["otherData"] = dict(
            doc.get("otherData") or {},
            pruned_to="failover_traces",
            kept_traces=len(keep),
            cross_process_total=len(cross),
            full_doc=full_out,
        )
        with open(merge_out, "w") as f:
            json.dump(pruned, f)
        record["trace_merge"] = {
            "out_path": merge_out,
            "full_doc": full_out,
            "events": sum(
                v["events"] for v in merge_report["replicas"].values()
            ),
            "replicas": merge_report["replicas"],
            "skipped": merge_report["skipped"],
            "cross_process_traces": len(cross),
            "failover_traces": {
                "connection": len(conn),
                "other": len(other),
                "committed": len(keep),
            },
            "committed_events": len(pruned["traceEvents"]),
        }
        for stage in record["stages"]:
            knee = stage["knee"]["knee_rps"]
            log(
                f"[bench] fleet x{stage['replicas']}: knee {knee} rps "
                + ", ".join(
                    f"@{lv['offered_rps']:g}->{lv['throughput_rps']}rps"
                    f"(cr {lv['completion_ratio']})"
                    for lv in stage["levels"]
                )
            )
        log(
            f"[bench] fleet scaling {record['scaling']['linear_ratio']} "
            f"(knees {record['scaling']['knee_by_replicas']}), min warm "
            f"{record['warm']['min_warm_fraction']}"
        )
        if record.get("chaos"):
            acct = record["chaos"]["shed_accounting"]
            flight = acct.get("flight") or {}
            attrib = flight.get("attribution") or {}
            log(
                f"[bench] fleet chaos: killed "
                f"{record['chaos']['kill'].get('replica_id')} with "
                f"{acct['in_flight_at_kill']} in flight; lost "
                f"{acct['lost_dead_replica']} (unaccounted "
                f"{acct['lost_unaccounted']}), retried {acct['retried']}, "
                f"recovery {record['chaos']['recovery']['recovery_ratio']}; "
                f"flight dump: {flight.get('harvested')} "
                f"(attributed {attrib.get('attributed')}, untracked "
                f"{len(attrib.get('untracked') or [])})"
            )
        tm = record["trace_merge"]
        log(
            f"[bench] fleet trace merge: {tm['events']} events from "
            f"{len(tm['replicas'])} sinks, {tm['cross_process_traces']} "
            f"cross-process traces; committed {tm['committed_events']} "
            f"events ({tm['failover_traces']}) -> {tm['out_path']}"
        )
        incs = record["telemetry"]["incidents"]
        log(
            f"[bench] fleet incidents: total {incs['total']} "
            f"by_kind {incs['by_kind']} (open {incs['open']})"
        )
        return record
    except Exception as e:
        log(f"[bench] fleet metric skipped: {e}")
        return None


def run_qos_bench() -> dict | None:
    """QoS metric (``bench.py --qos``): the three-part proof behind the
    committed ``QOS_r*.json`` series.

    **saturation** — one in-process service with the QoS policy on
    (admission priced from its own capacity model), driven past its
    measured ``max_sustainable_qps`` with a mixed-class offered load
    that deliberately over-offers scavenger traffic. The record carries
    the per-class level view (``by_class``), the shed matrix
    (``shed_by_class`` + admission denials), interactive's p99 against
    the SLO target derived from the light-load baseline, and the share
    of the total shed the scavenger class absorbed — the
    low-priority-absorbs-overload invariant bench_diff --qos gates.

    **streaming** — a MoEvA early-exit request (easy rows near the
    surrogate's boundary plus a hard tail, the early_exit bench's
    workload) through ``submit_stream``: solved rows surface as the
    gate parks them, and the final meta's ``time_to_first_solved_s``
    vs ``time_to_complete_s`` is the streaming headline ratio.

    **identity** — the overhead contract: the same PGD requests through
    a QoS-off service and a QoS-on service (admission armed but its
    capacity window unprimed) must be BIT-identical per row, with zero
    extra compiles and the same dispatch count in the ledger window —
    QoS off the request path is pure host-side bookkeeping.

    ``BENCH_SKIP_QOS=1`` skips; BENCH_QOS_REQUESTS / _SAT_MULT /
    _BURST_S / _SLO_FACTOR / _SLO_FLOOR_MS / _EE_GENS / _EE_CHECK
    reshape the run."""
    if os.environ.get("BENCH_SKIP_QOS"):
        return None
    try:
        import random
        import tempfile

        import joblib
        from sklearn.preprocessing import MinMaxScaler as SkMinMax

        from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
        from moeva2_ijcai22_replication_tpu.domains.synth import (
            synth_lcld,
            synth_lcld_schema,
        )
        from moeva2_ijcai22_replication_tpu.models.io import Surrogate, save_params
        from moeva2_ijcai22_replication_tpu.models.mlp import init_params, lcld_mlp
        from moeva2_ijcai22_replication_tpu.observability import (
            get_gap_tracker, get_ledger, quality_block, telemetry_block,
            validate_record,
        )
        from moeva2_ijcai22_replication_tpu.serving import (
            AttackRequest, AttackService, QosClass, QosPolicy,
        )
        from moeva2_ijcai22_replication_tpu.serving.sweep import run_level

        art = lcld_serving_artifacts()
        domain = {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": art["model"],
                "features": art["features"],
                "constraints": art["constraints"],
                "ml_scaler": art["ml_scaler"],
            },
            "system": {"mesh_devices": 0},
        }
        n_requests = int(os.environ.get("BENCH_QOS_REQUESTS", 240))
        sat_mult = float(os.environ.get("BENCH_QOS_SAT_MULT", 3.0))
        burst_s = float(os.environ.get("BENCH_QOS_BURST_S", 1.0))
        slo_factor = float(os.environ.get("BENCH_QOS_SLO_FACTOR", 4.0))
        slo_floor_ms = float(os.environ.get("BENCH_QOS_SLO_FLOOR_MS", 750.0))
        budget = int(os.environ.get("BENCH_QOS_BUDGET", 10))

        ledger = get_ledger()
        ledger_mark = ledger.mark()
        gaps_mark = get_gap_tracker().mark()

        # -- part A: saturation with a mixed-class offered load ----------
        # rate shares are deliberately NOT the config defaults: scavenger
        # gets 5% of sustainable QPS while the offered mix over-offers it
        # (70% of requests), so admission — not queue depth — is the
        # binding shedder and the scavenger bucket drains first by
        # construction. max_queue_rows stays high for the same reason.
        policy = QosPolicy(
            classes={
                "interactive": QosClass(
                    "interactive", priority=0, weight=4.0, rate_share=0.60
                ),
                "batch": QosClass(
                    "batch", priority=1, weight=2.0, rate_share=0.35
                ),
                "scavenger": QosClass(
                    "scavenger", priority=2, weight=1.0, rate_share=0.05
                ),
            },
            default_class="batch",
            admission=True,
            admission_burst_s=burst_s,
        )
        mix = {"interactive": 0.15, "batch": 0.15, "scavenger": 0.70}
        service = AttackService(
            {"lcld": domain},
            bucket_sizes=(8, 16, 32, 64),
            max_delay_s=0.01,
            max_queue_rows=4096,
            qos=policy,
        )
        cons = LcldConstraints(art["features"], art["constraints"])
        pool = synth_lcld(512, cons.schema, seed=7)
        sizes = [1 + i % 13 for i in range(64)]
        names = sorted(mix)
        rng = random.Random(2027)
        classes = rng.choices(
            names, weights=[mix[n] for n in names], k=4 * n_requests
        )

        def make_request(i: int) -> AttackRequest:
            n = sizes[i % len(sizes)]
            start = (i * 17) % (pool.shape[0] - n)
            return AttackRequest(
                domain="lcld",
                x=pool[start : start + n],
                eps=0.2,
                budget=budget,
                loss_evaluation="flip",
                priority=classes[i % len(classes)],
            )

        # pay the per-bucket compiles outside the measured levels, and
        # prime the capacity model the admission buckets price from
        for b in service.menu.sizes:
            service.attack(
                AttackRequest(domain="lcld", x=pool[:b], eps=0.2, budget=budget),
                timeout=300.0,
            )
        cap = service.capacity.domain_block("lcld") or {}
        qps = float(cap.get("max_sustainable_qps") or 0.0)

        # light-load baseline: calibrates interactive's SLO target (the
        # record carries both, so the gate is self-describing)
        base_rps = min(max(0.3 * qps, 8.0), 48.0)
        baseline = run_level(service, make_request, base_rps, n_requests // 3)
        base_p99 = (baseline.get("by_class", {}).get("interactive") or {}).get(
            "p99_ms"
        ) or baseline["p99_ms"]
        slo_target_ms = max(slo_factor * float(base_p99), slo_floor_ms)

        # re-read capacity (the baseline refreshed the window), then
        # saturate: offered load past the knee with the scavenger-heavy mix
        cap = service.capacity.domain_block("lcld") or cap
        qps = float(cap.get("max_sustainable_qps") or qps or base_rps)
        sat_rps = max(sat_mult * qps, 2.0 * base_rps)
        slo_mark = service.slo.mark()
        adm = service.admission
        adm_admitted0 = adm.admitted if adm else 0
        adm_denied0 = dict(adm.denied_by_class) if adm else {}
        level = run_level(service, make_request, sat_rps, n_requests)
        shed = service.slo.shed_block(since=slo_mark)
        by_class_shed = shed.get("by_class", {})
        shed_totals = {
            k: sum(sum(stages.values()) for stages in causes.values())
            for k, causes in by_class_shed.items()
        }
        total_shed = sum(shed_totals.values())
        scavenger_share = (
            round(shed_totals.get("scavenger", 0) / total_shed, 4)
            if total_shed
            else None
        )
        interactive_p99 = (
            level.get("by_class", {}).get("interactive") or {}
        ).get("p99_ms")
        admission_block = {
            "admitted": (adm.admitted - adm_admitted0) if adm else None,
            "denied_by_class": {
                k: n - adm_denied0.get(k, 0)
                for k, n in (adm.denied_by_class if adm else {}).items()
                if n - adm_denied0.get(k, 0) > 0
            },
        }
        quality_snap = service.quality_snapshot()
        service.close()
        saturation = {
            "mix": mix,
            "rate_shares": {
                k: c.rate_share for k, c in sorted(policy.classes.items())
            },
            "burst_s": burst_s,
            "max_sustainable_qps": qps,
            "baseline_rps": round(base_rps, 2),
            "baseline_interactive_p99_ms": base_p99,
            "slo_target_ms": round(slo_target_ms, 2),
            "offered_rps": round(sat_rps, 2),
            "level": level,
            "interactive_p99_ms": interactive_p99,
            "interactive_slo_held": (
                interactive_p99 is not None
                and interactive_p99 <= slo_target_ms
            ),
            "shed_by_class": by_class_shed,
            "shed_totals": shed_totals,
            "scavenger_shed_share": scavenger_share,
            "admission": admission_block,
        }

        # -- part B: streaming partial results over MoEvA early exit -----
        # own synthetic surrogate domain (the early_exit bench's recipe):
        # candidate ranking needs the model in hand, and the boundary-easy
        # + hard-tail split is what makes first-solved land generations
        # before completion
        ee_gens = int(os.environ.get("BENCH_QOS_EE_GENS", 301))
        ee_check = int(os.environ.get("BENCH_QOS_EE_CHECK", 5))
        tmp = tempfile.mkdtemp(prefix="bench_qos_stream_")
        spaths = synth_lcld_schema(tmp)
        scons = LcldConstraints(spaths["features"], spaths["constraints"])
        mlp = lcld_mlp()
        sur = Surrogate(mlp, init_params(mlp, scons.schema.n_features, seed=1))
        smodel = os.path.join(tmp, "nn.msgpack")
        save_params(sur, smodel)
        spool = synth_lcld(256, scons.schema, seed=7)
        sk = SkMinMax().fit(spool)
        sscaler = os.path.join(tmp, "scaler.joblib")
        joblib.dump(sk, sscaler)
        p1 = np.asarray(sur.predict_proba(sk.transform(spool)))[:, 1]
        order = np.argsort(np.abs(p1 - 0.5))
        # 12 boundary-easy rows (park at the first gates) + the 4 rows the
        # surrogate is most confident about (keep the scan running past
        # the first gate, so completion genuinely trails first-solved)
        x_stream = np.concatenate(
            [spool[order[:12]], spool[np.argsort(p1)[-4:]]], axis=0
        )
        sdomain = {
            "project_name": "lcld",
            "norm": 2,
            "paths": {
                "model": smodel,
                "features": spaths["features"],
                "constraints": spaths["constraints"],
                "ml_scaler": sscaler,
            },
            "system": {"mesh_devices": 0},
        }
        sservice = AttackService(
            {"lcld": sdomain},
            bucket_sizes=(16,),
            max_delay_s=0.005,
            qos=QosPolicy(admission=False),
        )
        ee_params = {
            "n_pop": 40,
            "n_offsprings": 20,
            "archive_size": 8,
            "early_stop_check_every": ee_check,
            "early_stop_threshold": 0.5,
        }

        def stream_request() -> AttackRequest:
            return AttackRequest(
                domain="lcld",
                x=x_stream,
                attack="moeva",
                budget=ee_gens,
                params=dict(ee_params),
                priority="interactive",
            )

        # warmup: pay the segment-program compiles outside the measurement
        sservice.attack(stream_request(), timeout=600.0)
        stream, fut = sservice.submit_stream(stream_request())
        chunks = []
        try:
            for chunk in stream.chunks(timeout=600.0):
                chunks.append(
                    {"rows": len(chunk["rows"]), "gen": chunk["gen"]}
                )
        except TimeoutError:
            pass
        _, meta = fut.result(timeout=600.0)
        ttfs = meta.get("time_to_first_solved_s")
        ttc = meta.get("time_to_complete_s")
        squality = sservice.quality_snapshot()
        sservice.close()
        streaming = {
            "n_rows": int(x_stream.shape[0]),
            "easy_rows": 12,
            "budget_gens": ee_gens - 1,
            "check_every": ee_check,
            "rows_streamed": meta.get("rows_streamed"),
            "chunks": chunks,
            "time_to_first_solved_s": ttfs,
            "time_to_complete_s": ttc,
            "ttfs_ratio": (
                round(ttc / ttfs, 2) if ttfs and ttc else None
            ),
        }

        # -- part C: QoS-off identity (the overhead contract) ------------
        x_id = pool[:8]
        id_reqs = 3

        def run_plain(svc) -> tuple[list, dict]:
            mark = ledger.mark()
            outs = []
            for i in range(id_reqs):
                resp = svc.attack(
                    AttackRequest(
                        domain="lcld", x=x_id, eps=0.2, budget=budget
                    ),
                    timeout=300.0,
                )
                outs.append(np.asarray(resp.x_adv))
            return outs, ledger.cost_block(since=mark)

        svc_off = AttackService(
            {"lcld": domain}, bucket_sizes=(8,), max_delay_s=0.005, qos=None
        )
        off_outs, off_cost = run_plain(svc_off)
        svc_off.close()
        # QoS on, admission armed — but ITS capacity window is unprimed,
        # so every request is admitted and the only difference from the
        # off path is host-side bookkeeping. Same engine cache, so any
        # extra compile or dispatch in this window is a QoS leak.
        svc_on = AttackService(
            {"lcld": domain},
            bucket_sizes=(8,),
            max_delay_s=0.005,
            qos=QosPolicy(admission_burst_s=burst_s),
        )
        on_outs, on_cost = run_plain(svc_on)
        svc_on.close()
        bit_identical = all(
            np.array_equal(a, b) for a, b in zip(off_outs, on_outs)
        )
        extra_compiles = sum(
            1 for e in on_cost["entries"] if e.get("compile_s", 0) > 0
        )
        identity = {
            "n_requests": id_reqs,
            "bit_identical": bool(bit_identical),
            "extra_compiles": int(extra_compiles),
            "dispatches_off": int(off_cost["dispatches"]),
            "dispatches_on": int(on_cost["dispatches"]),
            "dispatches_equal": off_cost["dispatches"] == on_cost["dispatches"],
        }

        record = {
            "saturation": saturation,
            "streaming": streaming,
            "identity": identity,
            "artifacts": art["kind"],
            "execution": {
                "bucket_menu": [8, 16, 32, 64],
                "max_delay_s": 0.01,
                "mesh": None,
                "early_stop_check_every": ee_check,
            },
            "telemetry": telemetry_block(
                ledger_since=ledger_mark,
                gaps_since=gaps_mark,
                quality=dict(
                    quality_block(judged="engine"),
                    **{**quality_snap, **squality},
                ),
            ),
        }
        validate_record(record, "qos")
        log(
            f"[bench] qos saturation @{sat_rps:.0f} rps (cap {qps:.0f}): "
            f"interactive p99 {interactive_p99} ms vs SLO "
            f"{slo_target_ms:.0f} ms, shed {total_shed} "
            f"(scavenger share {scavenger_share}), admission denied "
            f"{admission_block['denied_by_class']}"
        )
        log(
            f"[bench] qos streaming: first solved {ttfs}s vs complete "
            f"{ttc}s (ratio {streaming['ttfs_ratio']}), "
            f"{meta.get('rows_streamed')}/{x_stream.shape[0]} rows over "
            f"{len(chunks)} chunks"
        )
        log(
            f"[bench] qos identity: bit_identical={bit_identical}, "
            f"extra_compiles={extra_compiles}, dispatches "
            f"{off_cost['dispatches']}=={on_cost['dispatches']}"
        )
        return record
    except Exception as e:
        log(f"[bench] qos metric skipped: {e}")
        return None


def main():
    def _wrap(metric: str, key: str, rec: dict | None) -> dict:
        # the printed record mirrors the sub-record's shared schema keys
        # (execution + telemetry) so every bench JSON line carries them
        out = {"metric": metric, key: rec}
        if rec:
            out["execution"] = rec.get("execution")
            out["telemetry"] = rec.get("telemetry")
        return bound_record(out)

    # --serving: ONLY the request-path sweep — no grid subprocesses, no
    # network, one process; the CI-reproducible serving record.
    if "--serving" in sys.argv:
        rec = run_serving_bench()
        print(json.dumps(_wrap("serving_offered_load_sweep", "serving", rec)))
        return

    # --fleet: ONLY the multi-replica fleet sweep — real serve.py
    # subprocesses over one shared AOT cache behind the capacity router,
    # with the kill-a-replica chaos segment; the committed FLEET record.
    if "--fleet" in sys.argv:
        rec = run_fleet_bench()
        print(json.dumps(_wrap("fleet_knee_scaling", "fleet", rec)))
        return

    # --qos: ONLY the QoS three-part proof — mixed-class saturation with
    # cost-predictive admission, streaming partial results over MoEvA
    # early exit, and the QoS-off identity contract; the committed QOS
    # record (tools/bench_diff.py --qos gates its series).
    if "--qos" in sys.argv:
        rec = run_qos_bench()
        print(
            json.dumps(_wrap("qos_saturation_streaming_identity", "qos", rec))
        )
        return

    # --early-exit: ONLY the success-gated early-exit A/B — synthetic
    # schema, one process, CPU-able; the CI-reproducible early_exit record.
    if "--early-exit" in sys.argv:
        rec = run_early_exit_bench()
        print(json.dumps(_wrap("moeva_early_exit_ab", "early_exit", rec)))
        return

    # Whole-grid wallclock FIRST: its subprocesses need the (exclusive) TPU,
    # so it must run before this process initialises the backend below.
    grid = measure_grid_wallclock()

    import jax

    # Persistent XLA compilation cache: the jitted attack program is identical
    # across bench invocations, so after the first run on a given backend the
    # compile cost (~tens of seconds) is a disk load. Same helper as the
    # experiment runners (one cache layout for bench + grids).
    from moeva2_ijcai22_replication_tpu.experiments.common import setup_jax_cache

    cache_dir = os.environ.get("BENCH_JAX_CACHE", "./.jax_cache")
    setup_jax_cache({"system": {"jax_cache_dir": cache_dir}})

    log(f"[bench] devices: {jax.devices()}")

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler, fit_minmax

    cons = LcldConstraints(
        os.path.join(LCLD_DIR, "features.csv"),
        os.path.join(LCLD_DIR, "constraints.csv"),
    )
    x = synth_lcld(N_STATES, cons.schema, seed=42)
    cons.check_constraints_error(x)

    sur = load_classifier(MODEL)
    try:
        scaler = load_joblib_scaler(SCALER)
    except Exception:
        scaler = fit_minmax(x.min(0), x.max(0))

    moeva = Moeva2(
        classifier=sur, constraints=cons, ml_scaler=scaler,
        norm=2, n_gen=N_GEN, n_pop=N_POP, n_offsprings=N_OFF, seed=42,
        # convergence telemetry for the headline record: interior samples
        # every BENCH_QUALITY_EVERY generations (default 100 — the budgets
        # bench_diff pins). Sampling splits the scan at semantics-free
        # boundaries: results stay bit-identical, steady cost is a handful
        # of tiny gate dispatches
        record_quality=True,
        quality_every=int(os.environ.get("BENCH_QUALITY_EVERY", 100)),
    )
    # unified tracing: engine progress events + HBM watermarks for the
    # record's telemetry block (host-side emission only — the measured
    # device programs are identical with or without it)
    from moeva2_ijcai22_replication_tpu.attacks.sharding import describe_mesh
    from moeva2_ijcai22_replication_tpu.observability import (
        Trace, TraceRecorder, get_ledger, quality_block, telemetry_block,
        validate_record,
    )

    bench_recorder = TraceRecorder(spans_enabled=True)
    moeva.trace = Trace(bench_recorder, trace_id="bench-headline")
    # cost window for the headline record: opened here, closed right after
    # the steady runs — the later sub-benchmarks (botnet/serving/early-exit)
    # must not leak their executables into the headline's flops_total,
    # which bench_diff uses as the steady_s work normalizer
    headline_mark = get_ledger().mark()
    from moeva2_ijcai22_replication_tpu.observability import (
        get_coldstart, get_gap_tracker, get_mesh_capture,
    )

    mesh_mark = get_mesh_capture().mark()
    gaps_mark = get_gap_tracker().mark()

    # cold/steady on time.perf_counter (monotonic): an NTP step during the
    # minutes-long cold run must not corrupt the cold decomposition the
    # watchdog gates on (same fix PR 4 applied to PhaseTimer)
    t0 = time.perf_counter()
    res = moeva.generate(x, minimize_class=1)
    cold_s = time.perf_counter() - t0  # includes jit compile / cache load
    # steady state: best of two compiled runs — the tunnelled device shows
    # ~±10% run-to-run jitter, and the minimum is the standard estimator of
    # a program's intrinsic cost under external interference
    steady_runs = []
    for _ in range(2):
        t0 = time.perf_counter()
        res = moeva.generate(x, minimize_class=1)
        steady_runs.append(time.perf_counter() - t0)
    ours_s = min(steady_runs)
    headline_telemetry = telemetry_block(
        recorder=bench_recorder,
        trace=moeva.trace,
        ledger_since=headline_mark,
        # dispatch-gap window: the headline's overlap ratio (device-busy /
        # compile-free wall) + its top attributed gap stages — the number
        # that says which host stage to double-buffer next
        gaps_since=gaps_mark,
        # the headline run's engine-judged convergence curve + interior
        # summary — what bench_diff diffs across the committed series
        quality=quality_block(res.quality),
        # a mesh-backed bench run carries telemetry.mesh (per-device
        # roofline + balance ratio — the block bench_diff --mesh gates)
        mesh=describe_mesh(moeva.mesh),
        mesh_since=mesh_mark,
    )
    log(f"[bench] ours: {ours_s:.1f}s steady / {cold_s:.1f}s cold "
        f"(compile-or-cache-load {cold_s - ours_s:.1f}s) for "
        f"{N_STATES} states x {N_GEN} gens (pop {moeva.pop_size})")
    evals = N_STATES * (moeva.pop_size + (N_GEN - 1) * N_OFF)
    log(f"[bench] {evals / ours_s / 1e6:.1f}M candidate evals/s "
        "(per-stage breakdown: tools/profile_moeva.py)")

    # success metrics for the record (north star: parity at o-columns).
    # Scaler envelope = feature bounds ∪ data (01_train_robust.py:50-66) so
    # attacked candidates at their per-state bound extremes stay in [0, 1].
    try:
        xl_d, xu_d = cons.get_feature_min_max(dynamic_input=x)
        xl_d = np.broadcast_to(np.asarray(xl_d, float), x.shape)
        xu_d = np.broadcast_to(np.asarray(xu_d, float), x.shape)
        lo = np.minimum(x.min(0), xl_d.min(0))
        hi = np.maximum(x.max(0), xu_d.max(0))
        calc = ObjectiveCalculator(
            classifier=sur, constraints=cons,
            thresholds={"f1": 0.25, "f2": 0.2},
            min_max_scaler=fit_minmax(lo, hi),
            minimize_class=1, norm=2, ml_scaler=scaler,
        )
        rates = calc.success_rate_3d(x, res.x_ml)
        log("[bench] success rates o1..o7 (all states): "
            + " ".join(f"{r:.3f}" for r in rates))
    except Exception as e:
        log(f"[bench] success-rate eval skipped: {e}")

    # stage split (objective kernel / +operators / full step) for the log
    if not os.environ.get("BENCH_SKIP_PROFILE"):
        import subprocess

        # run on CPU: the parent process holds the (single) TPU chip, and the
        # split's purpose is relative stage cost, not absolute time
        repo_root = os.path.dirname(os.path.abspath(__file__))
        env = dict(
            os.environ,
            P_STATES=str(min(N_STATES, 64)),
            P_GENS="10",
            JAX_PLATFORMS="cpu",
            PYTHONPATH=repo_root
            + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
        )
        prof = subprocess.run(
            [sys.executable, os.path.join(repo_root, "tools", "profile_moeva.py")],
            capture_output=True, text=True, env=env,
        )
        split = [l for l in prof.stdout.splitlines() if "ms/gen" in l]
        for line in split:
            log(f"[bench] stage(cpu) {line.strip()}")
        if not split:
            tail = prof.stderr.strip().splitlines()[-1][:200] if prof.stderr.strip() else ""
            log(f"[bench] stage split unavailable (rc={prof.returncode}): {tail}")

    real_botnet = run_real_botnet()
    serving = run_serving_bench()
    early_exit = run_early_exit_bench()

    t_measured = measure_ref_pergen()
    t_pergen = min(t_measured, FALLBACK_REF_PERGEN_S)
    if t_pergen < t_measured:
        log(
            f"[bench] measured ref per-gen {t_measured*1e3:.2f} ms clamped to "
            f"the calibrated idle {FALLBACK_REF_PERGEN_S*1e3:.2f} ms "
            "(busy host would inflate the speedup)"
        )
    cores = os.cpu_count() or 1
    ref_s = t_pergen * N_STATES * N_GEN / cores
    log(f"[bench] ref CPU estimate: {ref_s:.1f}s (perfect {cores}-core scaling assumed)")

    speedup = ref_s / ours_s
    record = {
        "metric": "lcld_rq1_moeva_wallclock_speedup_vs_cpu_ref_estimate",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
        "basis": "steady",
        "steady_estimator": "min2",
        "steady_s": round(ours_s, 2),
        "cold_s": round(cold_s, 2),
        "speedup_cold": round(ref_s / cold_s, 2),
        # the ratio the --overlap watchdog gates (ROADMAP item 2 exit
        # criterion: cold <= 1.2x steady) next to its decomposition
        "cold_steady_ratio": round(cold_s / ours_s, 3),
        # structured cold breakdown (observability.coldstart): import /
        # artifact-build / trace-lower / XLA-compile phase seconds,
        # per-executable persistent-cache hit/miss classification against
        # the .jax_cache dir (the "N entries rebuilt per process" number),
        # and time-to-first-dispatch — where the cold seconds GO
        "cold": get_coldstart().cold_block(),
        # shared record schema (observability.records)
        "execution": {
            "max_states_per_call": moeva.effective_states_chunk(),
            "mesh": describe_mesh(moeva.mesh),
            "n_states": N_STATES,
            "n_gen": N_GEN,
        },
        # assembled right after the steady runs (see headline_mark): covers
        # the headline executables only
        "telemetry": headline_telemetry,
    }
    validate_record(record, "bench")
    # the executable cost footprint of everything this bench dispatched —
    # the series bench_diff normalizes against (tools/bench_diff.py)
    ls = get_ledger().summary()
    log(
        f"[bench] cost ledger: {ls['executables']} executables, "
        f"{ls['compile_s_total']}s total compile, cache hit ratio "
        f"{ls['cache_hit_ratio']}"
    )
    if real_botnet:
        record["real_botnet"] = real_botnet
    if serving:
        record["serving"] = serving
    if early_exit:
        record["early_exit"] = early_exit
    if grid:
        record["grid_wallclock"] = grid
        # headline key only from a CLEAN warm pass (rc 0, metrics produced) —
        # a crashed grid must not satisfy the whole-grid-evidence item
        if "warm_s" in grid and "warm_rc" not in grid and grid.get("warm_runs"):
            record["grid_wallclock_s"] = grid["warm_s"]
    # bounded print: the driver wrapper must never truncate the line the
    # watchdog gates parse (the satellite — see bound_record)
    print(json.dumps(bound_record(record)))


if __name__ == "__main__":
    main()
