"""Headline benchmark: MoEvA2 on LCLD at the north-star budget.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no absolute numbers (BASELINE.md) and cannot run in
this image (pymoo/autograd absent), so the CPU denominator is *measured
operationally* on this host as a conservative floor of the reference's
per-generation cost: the reference's own Keras SavedModel forward (TF, CPU)
plus a numpy twin of the 10 LCLD constraint formulas, times the north-star
budget (n_states x n_gen), divided by the host's core count (assuming the
reference's joblib fan-out scales perfectly — it does not). Excludes all
pymoo/keras.predict per-call overheads, so the reported speedup is an
UNDERESTIMATE of the true advantage.

North star (BASELINE.json): LCLD rq1, n_init=1000, pop=100, n_gen=1000,
L2, success-rate parity. Env knobs: BENCH_STATES / BENCH_GENS / BENCH_POP
shrink the run for smoke-testing.
"""

import json
import os
import sys
import time

import numpy as np

N_STATES = int(os.environ.get("BENCH_STATES", 1000))
N_GEN = int(os.environ.get("BENCH_GENS", 1000))
N_POP = int(os.environ.get("BENCH_POP", 100))
N_OFF = int(os.environ.get("BENCH_OFF", 100))

LCLD_DIR = "/root/reference/data/lcld"
MODEL = "/root/reference/models/lcld/nn.model"
SCALER = "/root/reference/models/lcld/scaler.joblib"

# Fallback per-(generation x state) reference CPU cost [s], measured on the
# dev host (TF SavedModel forward on (100, 47): 0.69 ms + numpy constraints
# 0.06 ms) — used only if TF cannot run on the bench host.
FALLBACK_REF_PERGEN_S = 7.5e-4


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def np_lcld_constraints(x):
    """Numpy twin of the 10 LCLD formulas (for CPU cost measurement only)."""
    def months(f):
        return np.floor(f / 100) * 12 + f % 100

    r = x[:, 2] / 1200.0
    g = (1 + r) ** x[:, 1]
    with np.errstate(divide="ignore", invalid="ignore"):
        g1 = np.abs(x[:, 3] - x[:, 0] * r * g / (g - 1)) - 0.099999
        g5 = np.abs(x[:, 20] - x[:, 0] / x[:, 6])
        g6 = np.abs(x[:, 21] - x[:, 10] / x[:, 14])
        g8 = np.abs(x[:, 23] - x[:, 11] / x[:, 22])
        g9 = np.abs(x[:, 24] - x[:, 16] / x[:, 22])
        ratio = np.where(x[:, 11] == 0, -1, x[:, 16] / np.where(x[:, 11] == 0, 1, x[:, 11]))
    g2 = x[:, 10] - x[:, 14]
    g3 = x[:, 16] - x[:, 11]
    g4 = np.abs((36 - x[:, 1]) * (60 - x[:, 1]))
    g7 = np.abs(x[:, 22] - (months(x[:, 7]) - months(x[:, 9])))
    g10 = np.abs(x[:, 25] - ratio)
    return np.stack([g1, g2, g3, g4, g5, g6, g7, g8, g9, g10], 1)


def measure_ref_pergen() -> float:
    """Per-(generation x state) cost of the reference hot loop on this CPU."""
    try:
        os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
        import tensorflow as tf

        m = tf.saved_model.load(MODEL)
        f = m.signatures["serving_default"]
        xb = tf.constant(np.random.rand(N_OFF, 47).astype(np.float32))
        for _ in range(3):
            f(xb)
        t0 = time.time()
        reps = 30
        for _ in range(reps):
            f(xb)
        t_fwd = (time.time() - t0) / reps
    except Exception as e:  # TF unavailable on bench host
        log(f"[bench] TF baseline measurement failed ({e}); using fallback")
        return FALLBACK_REF_PERGEN_S

    xc = np.random.rand(N_OFF, 47) * 100 + 1
    np_lcld_constraints(xc)
    t0 = time.time()
    reps = 100
    for _ in range(reps):
        np_lcld_constraints(xc)
    t_cons = (time.time() - t0) / reps
    log(f"[bench] ref CPU per-gen/state: fwd {t_fwd*1e3:.3f} ms + cons {t_cons*1e3:.3f} ms")
    return t_fwd + t_cons


def main():
    import jax

    # Persistent XLA compilation cache: the jitted attack program is identical
    # across bench invocations, so after the first run on a given backend the
    # compile cost (~tens of seconds) is a disk load.
    cache_dir = os.environ.get("BENCH_JAX_CACHE", "./.jax_cache")
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        log(f"[bench] compilation cache unavailable: {e}")

    log(f"[bench] devices: {jax.devices()}")

    from moeva2_ijcai22_replication_tpu.attacks.moeva import Moeva2
    from moeva2_ijcai22_replication_tpu.attacks.objective import ObjectiveCalculator
    from moeva2_ijcai22_replication_tpu.domains.lcld import LcldConstraints
    from moeva2_ijcai22_replication_tpu.domains.synth import synth_lcld
    from moeva2_ijcai22_replication_tpu.models.io import load_classifier
    from moeva2_ijcai22_replication_tpu.models.scalers import load_joblib_scaler, fit_minmax

    cons = LcldConstraints(
        os.path.join(LCLD_DIR, "features.csv"),
        os.path.join(LCLD_DIR, "constraints.csv"),
    )
    x = synth_lcld(N_STATES, cons.schema, seed=42)
    cons.check_constraints_error(x)

    sur = load_classifier(MODEL)
    try:
        scaler = load_joblib_scaler(SCALER)
    except Exception:
        scaler = fit_minmax(x.min(0), x.max(0))

    moeva = Moeva2(
        classifier=sur, constraints=cons, ml_scaler=scaler,
        norm=2, n_gen=N_GEN, n_pop=N_POP, n_offsprings=N_OFF, seed=42,
    )

    t0 = time.time()
    res = moeva.generate(x, minimize_class=1)
    cold_s = time.time() - t0  # includes jit compile / cache load
    t0 = time.time()
    res = moeva.generate(x, minimize_class=1)
    ours_s = time.time() - t0  # steady state: the production-relevant cost
    log(f"[bench] ours: {ours_s:.1f}s steady / {cold_s:.1f}s cold "
        f"(compile-or-cache-load {cold_s - ours_s:.1f}s) for "
        f"{N_STATES} states x {N_GEN} gens (pop {moeva.pop_size})")
    evals = N_STATES * (moeva.pop_size + (N_GEN - 1) * N_OFF)
    log(f"[bench] {evals / ours_s / 1e6:.1f}M candidate evals/s "
        "(per-stage breakdown: tools/profile_moeva.py)")

    # success metrics for the record (north star: parity at o-columns).
    # Scaler envelope = feature bounds ∪ data (01_train_robust.py:50-66) so
    # attacked candidates at their per-state bound extremes stay in [0, 1].
    try:
        xl_d, xu_d = cons.get_feature_min_max(dynamic_input=x)
        xl_d = np.broadcast_to(np.asarray(xl_d, float), x.shape)
        xu_d = np.broadcast_to(np.asarray(xu_d, float), x.shape)
        lo = np.minimum(x.min(0), xl_d.min(0))
        hi = np.maximum(x.max(0), xu_d.max(0))
        calc = ObjectiveCalculator(
            classifier=sur, constraints=cons,
            thresholds={"f1": 0.25, "f2": 0.2},
            min_max_scaler=fit_minmax(lo, hi),
            minimize_class=1, norm=2, ml_scaler=scaler,
        )
        rates = calc.success_rate_3d(x, res.x_ml)
        log("[bench] success rates o1..o7 (all states): "
            + " ".join(f"{r:.3f}" for r in rates))
    except Exception as e:
        log(f"[bench] success-rate eval skipped: {e}")

    t_pergen = measure_ref_pergen()
    cores = os.cpu_count() or 1
    ref_s = t_pergen * N_STATES * N_GEN / cores
    log(f"[bench] ref CPU estimate: {ref_s:.1f}s (perfect {cores}-core scaling assumed)")

    speedup = ref_s / ours_s
    print(json.dumps({
        "metric": "lcld_rq1_moeva_wallclock_speedup_vs_cpu_ref_estimate",
        "value": round(speedup, 2),
        "unit": "x",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
