"""TPU-native framework for adversarial attack and defense in constrained feature space.

A ground-up JAX/XLA re-design of the capabilities of the IJCAI'22 MoEvA2
replication package (`serval-uni-lu/moeva2-ijcai22-replication`): multi-objective
evolutionary attacks, constrained gradient attacks (PGD/AutoPGD), MIP-based
constraint-satisfying attacks, success-rate evaluation, and the matching defense
pipelines — with the hot per-candidate evaluation loop (surrogate forward pass,
constraint kernels, genetic operators, survival) batched on device as
``(n_states, n_pop, n_genes)`` tensors inside a single jit, sharded over a
``jax.sharding.Mesh``.

Subpackages
-----------
- ``core``      feature schema, jittable genetic<->ML codec, constraint engine API
- ``domains``   use-case plugins (LCLD credit scoring, CTU-13 botnet) + registry
- ``models``    Flax surrogate classifiers, Keras/sklearn artifact importers, training
- ``attacks``   MoEvA2 (evolutionary), PGD/AutoPGD (gradient), MIP (exact), objectives
  (device kernels — non-dominated sort, niching, GA operators, ref dirs — live
  under ``attacks/moeva``; mesh sharding is built into the engines)
- ``experiments`` L4/L5 runners: MoEvA/PGD/SAT entry points, RQ1-RQ4/SM1 grids,
  defense pipelines (augmentation + adversarial retraining), run_all
- ``utils``     layered config + md5 experiment identity, file IO, metrics-record
  streaming, phase timers / profiling
"""

__version__ = "0.1.0"
