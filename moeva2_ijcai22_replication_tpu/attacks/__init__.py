"""Attack engines: evolutionary (MoEvA2), gradient (PGD/AutoPGD), MIP (SAT)."""
