from .engine import Moeva2, MoevaResult

__all__ = ["Moeva2", "MoevaResult"]
