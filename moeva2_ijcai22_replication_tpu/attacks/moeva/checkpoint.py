"""Mid-attack checkpointing: crash-recovery for long MoEvA runs.

The reference recovers failed experiments only at whole-run granularity
(config-hash skip, ``/root/reference/src/experiments/united/04_moeva.py:31-36``)
— a crash 900 generations into an rq1 attack restarts from generation 0.
SURVEY.md §5 calls out per-N-generation population checkpointing as the
missing piece; this module adds it around the engine's segmented scan.

Design: the evolution carry (populations, objectives, elite archive,
normalisation memory, PRNG key) is a pytree of device arrays that fully
determines the remaining computation — the PRNG key continues the exact
random stream, so a resumed attack is bit-identical to an uninterrupted one.
Early-exit runs add host state the carry alone cannot express — the
active-set mapping (which original row each compacted carry row tracks) and
the parked results of already solved states — saved as an ``extra`` sidecar
inside the same ``.npz``.
At each ``checkpoint_every``-generation boundary the carry is fetched and
written atomically (tmp + rename) to one ``.npz``; per-segment history
records stream to sidecar files as they are offloaded, so resume also
restores ``save_history`` runs without ever re-buffering old generations.

A fingerprint of the attack identity (inputs + every semantics-affecting
knob) is stored in the checkpoint; a stale file from a different run is
ignored, never resumed into. Successful completion removes the checkpoint.
"""

from __future__ import annotations

import json
import os
import shutil

import jax
import numpy as np

_META = "__meta__"


class AttackCheckpointer:
    """Save/restore the engine's scan carry keyed by an attack fingerprint."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        self.hist_dir = path + ".hist"
        #: host-state sidecar of the last successful :meth:`load` — e.g. the
        #: early-exit active-set mapping + parked results (None when the
        #: snapshot carried none).
        self.extra: dict | None = None

    # -- carry snapshots ----------------------------------------------------
    def save(self, carry, done: int, n_hist: int, extra: dict | None = None) -> None:
        """Atomically persist the carry after ``done`` generation steps.

        ``extra`` is an optional dict of host-side numpy arrays saved (and
        restored) alongside the carry — the engine uses it for the
        early-exit active-set mapping, without which a compacted carry
        could not be resumed (its states axis no longer matches the
        attack's inputs row-for-row).
        """
        leaves, _ = jax.tree_util.tree_flatten(carry)
        leaves = jax.device_get(leaves)
        meta = json.dumps(
            {
                "fingerprint": self.fingerprint,
                "done": int(done),
                "n_leaves": len(leaves),
                "n_hist": int(n_hist),
                "extra_keys": sorted(extra) if extra else [],
            }
        )
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(
                fh,
                **{f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)},
                **{f"extra_{k}": np.asarray(v) for k, v in (extra or {}).items()},
                **{_META: np.asarray(meta)},
            )
        os.replace(tmp, self.path)

    def load(self, carry_template):
        """Restore ``(carry, done, hist_chunks)`` or None.

        ``carry_template`` (a freshly initialised carry) supplies the pytree
        structure and the per-leaf device/sharding placement, so a resumed
        mesh-sharded attack lands its shards back where the segment program
        expects them.
        """
        if not os.path.exists(self.path):
            return None
        try:
            with np.load(self.path, allow_pickle=False) as z:
                meta = json.loads(str(z[_META]))
                if meta.get("fingerprint") != self.fingerprint:
                    return None
                leaves = [z[f"leaf_{i}"] for i in range(meta["n_leaves"])]
                extra_keys = meta.get("extra_keys") or []
                self.extra = (
                    {k: z[f"extra_{k}"] for k in extra_keys}
                    if extra_keys
                    else None
                )
        except Exception:
            return None  # truncated/corrupt file: start fresh
        tmpl_leaves, treedef = jax.tree_util.tree_flatten(carry_template)
        if len(tmpl_leaves) != len(leaves):
            return None
        restored = [
            jax.device_put(np.asarray(leaf), tmpl.sharding)
            for leaf, tmpl in zip(leaves, tmpl_leaves)
        ]
        hist = []
        for i in range(meta["n_hist"]):
            try:
                hist.append(np.load(self._hist_file(i)))
            except Exception:
                return None  # sidecar missing/truncated: start fresh
        return treedef.unflatten(restored), meta["done"], hist

    # -- history sidecars ---------------------------------------------------
    def _hist_file(self, idx: int) -> str:
        return os.path.join(self.hist_dir, f"chunk_{idx:05d}.npy")

    def add_hist_chunk(self, idx: int, arr: np.ndarray) -> None:
        os.makedirs(self.hist_dir, exist_ok=True)
        tmp = os.path.join(self.hist_dir, ".tmp.npy")
        np.save(tmp, arr)
        os.replace(tmp, self._hist_file(idx))

    # -- lifecycle ----------------------------------------------------------
    def clear(self) -> None:
        """Completed run: the recovery artifacts have served their purpose."""
        for p in (self.path, self.path + ".tmp"):
            if os.path.exists(p):
                os.remove(p)
        if os.path.isdir(self.hist_dir):
            shutil.rmtree(self.hist_dir)
