"""MoEvA2 — the multi-objective evolutionary attack, batched on device.

Capability parity with the reference driver
(``/root/reference/src/attacks/moeva2/moeva2.py``): R-NSGA-III with energy
aspiration points (seed-pinned), mixed two-point crossover + polynomial
mutation, initial-state tiling, objectives (misclassification probability,
scaled Lp distance, summed constraint violations), ``n_gen`` termination.

Architecture (TPU-first, NOT the reference's): where the reference forks one
OS process per initial state and crawls pymoo's object graph per generation
(``moeva2.py:194-205``), here the *entire attack over all initial states* is
one jitted program: a ``lax.scan`` over generations whose body evaluates
``(n_states, n_pop + n_off)`` candidates as a single MXU batch and runs the
survival/operators vmapped over the states axis. States are embarrassingly
parallel, so the states axis shards over a ``jax.sharding.Mesh`` with zero
inter-device collectives in the hot loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ...core import codec as codec_lib
from ...core.codec import Codec, make_codec
from ...core.constraints import ConstraintSet
from ...core.norms import is_l2, lp_distance, validate_norm
from ...models.io import Surrogate
from ...models.scalers import MinMaxParams
from ...observability import all_device_memory_stats, device_memory_stats, maybe_span
from ...observability.gaps import emit_window_trace, get_gap_tracker
from ...observability.ledger import LedgeredJit, get_ledger
from ...observability.mesh import get_mesh_capture
from ...observability.quality import merge_chunk_quality, sample_from_per_state
from ..objective import engine_quality_stats
from .initialisation import lp_ratio_init, tile_init
from .operators import OperatorTables, make_operator_tables, make_offspring
from .refdirs import energy_ref_dirs, rnsga3_geometry
from .survival import NormState, survive_batch


@dataclass
class MoevaResult:
    """Final populations for every initial state (EfficientResult parity:
    ``moeva2/result_process.py:3-16`` keeps pop X/F + the initial state)."""

    #: P below = pop_size + archive_size: with an elite archive the returned
    #: "population" is final pop columns first, then the archive columns.
    x_gen: np.ndarray  # (S, P, L) genetic populations
    f: np.ndarray  # (S, P, 3) objectives
    x_ml: np.ndarray  # (S, P, D) decoded ML-space populations
    x_initial: np.ndarray  # (S, D)
    n_gen: int
    time: float
    #: per-evaluation records (parity: ``default_problem.py:137-140``):
    #: entry 0 = initial population (S, P, C), then one (S, n_off, C) per
    #: generation; C = 3 for "reduced", 3 + n_constraints for "full".
    history: list | None = None
    #: generation steps actually executed on device — ``n_gen - 1`` per state
    #: chunk (summed across chunks) unless the success gate exited early.
    gens_executed: int = 0
    #: early-exit observability (None in strict mode): ``{"check_every",
    #: "gens_executed", "budget_gens", "compaction": [{"gen", "active",
    #: "bucket"(, "chunk")}, ...]}`` — the compaction trace records every
    #: gate at which states parked (``bucket`` = the post-gate dispatch
    #: shape, so a repack shows as a shrink) and the full early exit as
    #: ``active: 0``.
    early_stop: dict | None = None
    #: convergence-quality history (None unless ``record_quality``):
    #: ``{"gate_every", "threshold", "eps", "archive_size", "judged",
    #: "samples": [...]}`` where each sample carries the per-gate
    #: engine-space o1–o7 rates, best/mean constraint violation, best
    #: distance (full precision — export rounding happens in
    #: ``observability.quality``) plus the raw (S, 9) ``per_state`` stats
    #: used for chunk merging. The last sample has ``final: True`` and is
    #: computed host-side from the returned populations (pop ∪ archive).
    quality: dict | None = None


@dataclass
class _InFlightRun:
    """A fully dispatched attack whose results have not been fetched.

    ``_launch_one`` enqueues every segment (syncing only on the tiny
    early-exit masks) and returns this; ``_finalize_one`` performs the
    device→host fetch, the parked/active merge, and the ML decode. The
    split lets ``_generate_chunked`` fetch chunk *i*'s results while chunk
    *i+1*'s dispatch is already executing — the same one-dispatch-late
    pattern the history ``pending`` buffer uses.
    """

    x: np.ndarray
    t0: float
    carry: tuple
    #: original row index each current carry row tracks (pads duplicate a
    #: live row's index) and whether the row's final result is wanted.
    row_src: np.ndarray
    row_live: np.ndarray
    #: host-frozen final populations of solved states: {"mask", "x", "f"}.
    parked: dict | None
    check: int
    n_steps: int
    gens_executed: int
    trace: list
    init_hist: Any
    hist_chunks: list
    pending: Any
    cp: Any
    #: quality capture state (``record_quality``): the gate cadence that
    #: actually ran, the last-known (S, 9) per-state stats at original row
    #: indices (parked rows frozen at park time), and the recorded samples.
    gate_every: int = 0
    qual_latest: Any = None
    qual_samples: list = None
    #: deferred host tail of the last gate (double-buffering): finalize
    #: runs it before reading the parked/quality state — under chunking
    #: that happens one dispatch late, so the flush overlaps the NEXT
    #: chunk's device work too.
    flush_gate: Any = None


@dataclass
class Moeva2:
    """TPU-native MoEvA2.

    Parameters mirror the reference's knobs (``moeva2.py:36-55``); defaults
    follow the experiment configs (n_gen=100, n_pop=200, n_offsprings=100 —
    ``config/moeva.yaml``) rather than the driver's unused 625/640/320.
    """

    classifier: Surrogate
    constraints: ConstraintSet
    ml_scaler: MinMaxParams | None = None
    norm: Any = 2
    n_gen: int = 100
    n_pop: int = 200
    n_offsprings: int = 100
    crossover_prob: float = 0.9
    eta_mutation: float = 20.0
    seed: int = 0
    #: initial-population strategy: "tile" (InitialStateSampling parity) or
    #: "lp_ratio" (MixedSamplingLp parity — perturb ``init_ratio`` of the
    #: population inside an ``init_eps`` Lp ball in normalised genetic space).
    init: str = "tile"
    init_eps: float = 0.1
    init_ratio: float = 0.5
    #: per-state elite archive: keep the ``archive_size`` best candidates
    #: seen across ALL generations, ranked feasible-first (Σ violations = 0)
    #: then by misclassification probability then distance, and append them
    #: to the returned populations. 0 (default) = reference semantics (final
    #: population only — the reference's own pareto archive is dead code,
    #: ``pareto_operation.py``). With an archive, success rates are monotone
    #: in the generation budget: converged late populations can no longer
    #: lose the constrained adversarials found mid-run.
    archive_size: int = 0
    #: niche-association formulation: None = one-shot einsum; an int = the
    #: blocked scan with that direction-block size (peak memory
    #: (S, M, block) instead of the (S, M, R) distance tensor) —
    #: bit-identical results either way. A hand-written Pallas kernel for
    #: this stage was removed as a recorded negative result (it could crash
    #: the TPU worker process at specific state counts; docs/DESIGN.md §3).
    assoc_block: int | None = None
    save_history: str | None = None
    #: generations per jitted scan segment when history is recorded; each
    #: segment's records are offloaded to host so "full" history at rq1 scale
    #: (1000 gens) never accumulates on device.
    history_chunk: int = 50
    #: crash recovery (SURVEY §5's missing per-N-generation checkpointing —
    #: the reference restarts a crashed attack from generation 0): every
    #: ``checkpoint_every`` generations the evolution carry is written
    #: atomically to ``checkpoint_path``; a rerun of the identical attack
    #: resumes the random stream mid-run, bit-identical to an uninterrupted
    #: one. 0 / None = off. Completed runs remove the checkpoint.
    checkpoint_every: int = 0
    checkpoint_path: str | None = None
    #: process the states axis in sequential chunks of at most this many
    #: states through ONE compiled program (the tail chunk is padded with
    #: copies of the last state and trimmed afterwards). States are
    #: embarrassingly parallel — a chunked run is a concatenation of
    #: independent attacks with per-chunk folded keys — so this changes
    #: random draws but not semantics (the reference runs every state as its
    #: own process). Bounds device memory at large state counts and
    #: sidesteps the worker-fault program-size band documented in
    #: docs/DESIGN.md §3. None = one batch.
    max_states_per_call: int | None = None
    #: success-gated early exit (0 = strict mode, the default: bit-identical
    #: to a run without the knob). Every ``early_stop_check_every``
    #: generations the scan pauses at a segment boundary and fetches a tiny
    #: on-device (S,) success mask — the ObjectiveCalculator criterion
    #: (misclassified ∧ Σ violations = 0 ∧ within ``early_stop_eps``)
    #: evaluated over the population ∪ archive objectives. Solved states are
    #: parked (their populations frozen on host) and the surviving active
    #: set is repacked down the shared power-of-two bucket menu
    #: (``experiments.common.DEFAULT_BUCKET_SIZES``), so a shrinking run
    #: dispatches at most one extra executable per menu size; when every
    #: state is solved the remaining budget is skipped entirely. RNG caveat:
    #: compaction changes the states-batch shape mid-run and therefore the
    #: per-generation random draws, exactly like ``max_states_per_call``
    #: chunking — strict mode stays available for parity runs. With
    #: ``archive_size > 0`` the criterion is monotone (a success, once in
    #: the archive, cannot be lost), so early-stopped success rates are >=
    #: the fixed-budget run's; parking preserves the observed success even
    #: without an archive. Incompatible with ``save_history`` (history
    #: records are not reassembled across repacks). Prefer a value dividing
    #: ``n_gen - 1`` so all segments share one compiled length.
    early_stop_check_every: int = 0
    #: misclassification-probability threshold of the success criterion
    #: (the runner plumbs ``misclassification_threshold`` here).
    early_stop_threshold: float = 0.5
    #: distance bound of the success criterion, in the engine's min-max
    #: normalised feature space (before the L2 sqrt(D) objective scaling).
    #: inf (default) judges misclassified ∧ feasible only — the engine's
    #: per-state normalisation differs from the global scaler the post-hoc
    #: ObjectiveCalculator uses, so a finite ε here is a gate on the
    #: engine's own objective, not the exact o7 judgement.
    early_stop_eps: float = float("inf")
    #: compaction bucket sizes; None = the shared serving/batcher menu
    #: (``experiments.common.DEFAULT_BUCKET_SIZES``). Sizes not divisible by
    #: the mesh size are skipped (states-axis sharding contract).
    compaction_buckets: tuple | None = None
    #: generation double-buffering (default on): each gate's host-side
    #: tail — the packed-stats scatter into the quality buffer, the
    #: parked-population fetch + merge, and the gate progress events —
    #: is deferred until the NEXT segment is already enqueued, so it runs
    #: while the device executes that segment instead of idling it (the
    #: PR-3 launch/finalize split extended from chunks to gate segments).
    #: The only remaining inter-segment sync is the tiny packed (S, 9)
    #: stats fetch the park/compaction DECISION needs. Pure host-side
    #: scheduling: device programs, dispatch order, and RNG streams are
    #: untouched, so ``False`` (serial flush, the pre-double-buffer
    #: schedule) is bit-identical with zero extra compiles — pinned by
    #: tier-1 ``tests/test_double_buffer.py``.
    double_buffer: bool = True
    #: record the convergence-quality history (``MoevaResult.quality``):
    #: per-gate engine-space o1–o7 rates, best/mean constraint violation,
    #: best distance, judged by the same criterion the early-exit gate uses
    #: (``early_stop_threshold`` / ``early_stop_eps``). The gate program
    #: computes the per-state stats unconditionally (a ~9-float reduction
    #: riding the success-mask dispatch), so toggling this knob changes
    #: which host-side fetches are *kept*, never the compiled programs, the
    #: dispatch schedule, or the results — quality capture on/off is
    #: bit-identical with zero extra compiles/dispatches (pinned by the
    #: tier-1 smoke in ``tests/test_quality.py``). With no gates at all
    #: (strict mode, ``quality_every`` 0) the history is the single final
    #: sample, computed in numpy from the already-fetched populations.
    record_quality: bool = False
    #: gate cadence for quality sampling when early exit is OFF: split the
    #: generation scan at every ``quality_every`` steps and sample quality
    #: at each boundary. Segment chaining is bit-identical to one scan
    #: (same RNG stream — keys split per generation inside the body), so
    #: this changes results never, only the dispatch schedule (one extra
    #: compiled segment length unless it divides ``n_gen - 1``, plus one
    #: tiny gate dispatch per sample). Ignored when
    #: ``early_stop_check_every`` is set — quality samples then ride the
    #: early-exit gates. Prefer a value dividing the interior budgets the
    #: watchdog pins ({100, 300}: 100, 50, 25 …).
    quality_every: int = 0
    #: observability handle (``observability.Trace`` or None): a host-side
    #: dispatch knob like ``seed`` — NOT engine-cache key material, reset
    #: per grid point / serving batch by the callers. When set (and its
    #: recorder has spans enabled) the engine emits per-gate progress
    #: events — generation index, success fraction, active-set size, bucket
    #: transitions — and per-phase device-memory watermarks into the
    #: unified event stream. Pure host-side emission between dispatches:
    #: device programs and RNG streams are untouched.
    trace: Any = None
    #: streaming partial-result sink (``serving`` wires the batcher's
    #: partial router here): a host-side dispatch knob like ``trace`` —
    #: NOT engine-cache key material, reset per serving batch by the
    #: callers. When set, each early-exit gate that parks solved rows
    #: also decodes JUST those rows' populations (host CPU backend, the
    #: finalize decode idiom) and calls ``partial_sink(rows, x_ml, gen)``
    #: with the ORIGINAL row indices — solved rows surface to callers
    #: before the scan ends. Pure host-side emission at the deferred
    #: gate flush: device programs, dispatch order, and RNG streams are
    #: untouched, and ``None`` (the default) does zero extra work.
    partial_sink: Any = None
    dtype: Any = jnp.float32
    mesh: jax.sharding.Mesh | None = None
    states_axis: str = "states"

    def __post_init__(self):
        self.codec: Codec = make_codec(self.constraints.schema)
        self.tables: OperatorTables = make_operator_tables(self.codec)
        # Survival consumes the raw aspiration (energy) points and rebuilds
        # normalised directions per generation; only the population size comes
        # from the full RNSGA3 geometry (n_asp * pop_per_ref_point + n_obj).
        _, self.pop_size = rnsga3_geometry(3, self.n_pop, seed=1)
        self.asp_points = jnp.asarray(
            energy_ref_dirs(3, self.n_pop, seed=1), dtype=self.dtype
        )
        # Parity: default_problem.py:87 raises for norms other than 2/inf;
        # f2 scaling by sqrt(D) for L2 per get_scaler_from_norm.
        validate_norm(self.norm)
        self._f2_scale = (
            float(np.sqrt(self.codec.n_features)) if is_l2(self.norm) else 1.0
        )
        if self.save_history not in (None, False, "reduced", "full"):
            raise ValueError(
                f"save_history must be None, 'reduced' or 'full', got {self.save_history!r}"
            )
        if self.init not in ("tile", "lp_ratio"):
            raise ValueError(f"init must be 'tile' or 'lp_ratio', got {self.init!r}")
        if not 0 <= self.archive_size <= self.pop_size:
            raise ValueError(
                f"archive_size={self.archive_size} must be in [0, pop_size="
                f"{self.pop_size}] (the archive seeds from the initial population)"
            )
        self._jit_init = None
        self._jit_segment = None
        self._jit_success = None
        self._jit_take = None
        #: gate host-work flushes that actually overlapped the next
        #: segment's device execution in the most recent ``generate`` —
        #: the double-buffer's structural witness (0 in serial mode).
        self.last_deferred_gate_flushes = 0
        #: success-gate scalar args (threshold, ε) placed once per engine:
        #: on a mesh they must be explicitly replicated — a device-0 scalar
        #: would be implicitly respread across the mesh at every gate
        #: dispatch (tools/shard_lint.py's transfer-guard rule trips on it).
        self._gate_scalars = None
        #: number of program (re)traces across init + segment — one per
        #: distinct executable (grid observability reads the delta per point).
        self.trace_count = 0
        #: (entry, compile_s) per ledger dispatch of the current ``generate``
        #: — drained by :meth:`_attribute_run` into roofline run seconds.
        self._dispatch_log: list = []
        #: (per-device live-row counts, generation steps) per segment
        #: dispatch of the current ``generate`` — drained by
        #: :meth:`_attribute_run` into the mesh balance capture (per-device
        #: run-second skew at the existing sync points, never a new one).
        self._balance_log: list = []
        #: ledger keys (and per-key dispatch counts) the most recent
        #: ``generate`` dispatched — serving joins them with its
        #: device_run span for per-span roofline attribution.
        self.last_run_executables: list[str] = []
        self.last_run_dispatch_counts: dict[str, int] = {}

    def _ledger_identity(self) -> dict:
        """Compile-time identity of this engine's executables for the cost
        ledger (mirrors the engine-cache key, human-readable)."""
        from ..sharding import describe_mesh

        return {
            "engine": "moeva2",
            "cache_key": getattr(self, "cache_key", None),
            # stable domain identity: the constraint set is CODE traced
            # into the compiled program (unlike the model weights, which
            # are runtime arguments), so the persistent AOT cache needs a
            # process-independent field discriminating domains of equal
            # shape — the engine-cache slot id above hashes object id()s
            # and cannot serve across processes; spec-compiled domains
            # discriminate by spec hash (ledger_tag), hand-written ones by
            # class name exactly as before
            "constraints": self.constraints.ledger_tag,
            "n_features": self.codec.n_features,
            "n_constraints": self.constraints.get_nb_constraints(),
            "norm": str(self.norm),
            "n_pop": self.n_pop,
            "pop_size": self.pop_size,
            "n_offsprings": self.n_offsprings,
            "archive_size": self.archive_size,
            "save_history": self.save_history,
            "mesh": describe_mesh(self.mesh),
        }

    def _on_ledger_dispatch(self, entry, compile_s: float) -> None:
        # the enqueue instant rides along so the dispatch-gap tracker can
        # place this dispatch on the process device timeline — a clock
        # read the dispatch path makes anyway, never a device sync
        self._dispatch_log.append((entry, compile_s, time.perf_counter()))

    def _attribute_run(self, t0: float, elapsed: float) -> None:
        """Split one ``generate``'s measured wall-clock (compile excluded)
        across the executables it dispatched, weighted by the cost model
        (per-dispatch FLOPs; uniform when no backend cost model) — the
        engine's dispatches are chained asynchronously, so per-executable
        timing exists only at this aggregate level (documented as
        approximate in DESIGN § cost ledger)."""
        log, self._dispatch_log = self._dispatch_log, []
        balance_log, self._balance_log = self._balance_log, []
        entries = [e for e, _, _ in log if e is not None]
        self.last_run_executables = list(
            dict.fromkeys(e.key for e in entries)
        )
        counts: dict[str, int] = {}
        for e in entries:
            counts[e.key] = counts.get(e.key, 0) + 1
        self.last_run_dispatch_counts = counts
        run_total = max(elapsed - sum(c for _, c, _ in log), 0.0)
        # per-device balance: split the run seconds across the logged
        # segment windows by generation count, attributing each window's
        # seconds to devices in proportion to their live-row share — pads
        # and parked rows are wall-clock without useful work, which is
        # exactly the skew the telemetry.mesh balance ratio surfaces.
        # Before the ledger early-out: balance needs only the wall-clock
        # and the window log, so a cost_ledger-off run keeps its mesh
        # telemetry (the two knobs are independent)
        total_gens = sum(g for _, g in balance_log)
        if total_gens > 0 and run_total > 0:
            capture = get_mesh_capture()
            for rows, gens in balance_log:
                capture.record_balance(
                    rows, run_total * gens / total_gens
                )
        # dispatch-gap ledger: place this run's dispatches on the process
        # device timeline (recorded at this same sync point, zero new
        # syncs). Independent of the cost-ledger knob — with the ledger
        # off entries are None and the run splits uniformly.
        if log:
            weights_all = [
                (e.flops if e is not None and e.flops else None)
                for e, _, _ in log
            ]
            if any(w is None for w in weights_all):
                weights_all = [1.0] * len(log)
            wsum = sum(weights_all) or 1.0
            window = get_gap_tracker().record_window(
                producer="moeva",
                engine=getattr(self, "cache_key", None),
                start=t0,
                end=t0 + elapsed,
                dispatches=[
                    (
                        ts,
                        run_total * w / wsum,
                        c,
                        e.key if e is not None else None,
                    )
                    for (e, c, ts), w in zip(log, weights_all)
                ],
            )
            # Perfetto: device-busy counter sample + named gap slices at
            # their true timeline instants (no-op when the trace is off)
            emit_window_trace(self.trace, window)
        if not entries:
            return
        weights = [e.flops for e in entries]
        if not all(weights):
            weights = [1.0] * len(entries)
        total_w = sum(weights)
        ledger = get_ledger()
        for e, w in zip(entries, weights):
            ledger.add_run_seconds(e.key, run_total * w / total_w)

    # -- objective kernel ---------------------------------------------------
    def _evaluate(self, params, x_gen, x_init_ml, x_init_mm, xl_ml, xu_ml, minimize_class):
        """(S, N, L) genetic candidates -> (S, N, 3) objectives.

        The hot kernel (reference: ``default_problem.py:99-140``): decode,
        normalise, classifier forward, Lp distance, constraint violations —
        one fused XLA program over the full (states x candidates) batch.
        """
        x_f = codec_lib.genetic_to_ml(self.codec, x_gen, x_init_ml[:, None, :])
        x_mm = codec_lib.minmax_normalize(
            x_f, xl_ml[:, None, :], xu_ml[:, None, :]
        )
        x_in = self.ml_scaler.transform(x_f) if self.ml_scaler is not None else x_f
        probs = Surrogate(self.classifier.model, params).predict_proba(x_in)
        f1 = jnp.take_along_axis(
            probs, minimize_class[:, None, None], axis=-1
        )[..., 0]
        diff = x_mm - x_init_mm[:, None, :]
        f2 = lp_distance(diff, self.norm) / self._f2_scale
        g_all = self.constraints.evaluate(x_f)
        return jnp.stack([f1, f2, g_all.sum(-1)], axis=-1), g_all

    def _evaluate_hist(self, params, x_gen, x_init_ml, x_init_mm, xl_ml, xu_ml, minimize_class):
        """Evaluate + the per-evaluation history record.

        History parity (``default_problem.py:137-140``): "reduced" records F
        per evaluation, "full" appends per-constraint G.
        """
        f, g_all = self._evaluate(
            params, x_gen, x_init_ml, x_init_mm, xl_ml, xu_ml, minimize_class
        )
        if self.save_history == "full":
            return f, jnp.concatenate([f, g_all], axis=-1)
        return f, f

    # -- attack programs ----------------------------------------------------
    # The attack is two jitted programs: ``init`` (initial population +
    # normalisation warm-up) and ``segment`` (a lax.scan over a static number
    # of generations). ``generate`` chains segments, offloading each
    # segment's history records to host between dispatches so "full" history
    # at rq1 scale never accumulates in HBM; without history there is exactly
    # one segment, i.e. the round-2 single-scan program.

    def _build_init(self):
        codec = self.codec
        pop_size = self.pop_size
        asp = self.asp_points

        def init(params, x_init_ml, minimize_class, xl_ml, xu_ml, key):
            eng = self  # close over static config
            eng.trace_count += 1  # body runs once per (re)trace
            s = x_init_ml.shape[0]
            xl_gen, xu_gen = codec_lib.genetic_bounds(codec, xl_ml, xu_ml)
            x_init_mm = codec_lib.minmax_normalize(x_init_ml, xl_ml, xu_ml)

            key, k_init, k0 = jax.random.split(key, 3)
            if eng.init == "lp_ratio":
                pop_x = lp_ratio_init(
                    k_init,
                    codec,
                    x_init_ml,
                    pop_size,
                    xl_gen,
                    xu_gen,
                    eps=eng.init_eps,
                    ratio=eng.init_ratio,
                    norm=eng.norm,
                ).astype(eng.dtype)
            else:
                pop_x = tile_init(codec, x_init_ml, pop_size).astype(eng.dtype)
            pop_f, init_hist = eng._evaluate_hist(
                params, pop_x, x_init_ml, x_init_mm, xl_ml, xu_ml, minimize_class
            )

            # Initialisation survival: everyone survives, normalisation state
            # (ideal/worst/extreme) warms up — pymoo GeneticAlgorithm._initialize.
            norm0 = jax.vmap(lambda _: NormState.init(3, eng.dtype))(jnp.arange(s))
            _, norm_state, _ = survive_batch(
                k0, pop_f, asp, norm0, pop_size,
                assoc_block=eng.assoc_block,
            )

            # archive seeded with the elite of the FULL initial population
            # (lp_ratio init can already contain feasible adversarials at any
            # row index; survival may drop them in generation 1)
            arch_x, arch_f = eng._archive_select(pop_x, pop_f)

            if not eng.save_history:
                init_hist = jnp.zeros((), eng.dtype)
            return (pop_x, pop_f, arch_x, arch_f, norm_state, key), init_hist

        return init

    @staticmethod
    def _archive_score(f):
        """Feasible-first elite ranking. Feasible candidates (Σ violations
        = 0) score in [0, ~1] by misclassification prob + distance tiebreak;
        infeasible ones score in (2, 3) by squashed violation mass — every
        term stays O(1) so the ordering survives float32 (a 1e9-offset
        construction would absorb all other terms at f32 precision)."""
        g = f[..., 2]
        feasible_score = f[..., 0] + 1e-3 * f[..., 1]
        return jnp.where(g > 0, 2.0 + g / (1.0 + g), feasible_score)

    def _archive_select(self, cand_x, cand_f):
        """Top-``archive_size`` candidates by feasible-first score — the one
        elite-selection rule, shared by the seeding and per-generation update."""
        elite = jnp.argsort(self._archive_score(cand_f), axis=1)[
            :, : self.archive_size
        ]
        return (
            jnp.take_along_axis(cand_x, elite[..., None], axis=1),
            jnp.take_along_axis(cand_f, elite[..., None], axis=1),
        )

    def _build_segment(self):
        codec = self.codec
        tables = self.tables
        pop_size = self.pop_size
        n_off = self.n_offsprings
        asp = self.asp_points

        def segment(params, x_init_ml, minimize_class, xl_ml, xu_ml, carry, length):
            eng = self
            eng.trace_count += 1  # one per (re)trace: distinct length retraces
            s = x_init_ml.shape[0]
            xl_gen, xu_gen = codec_lib.genetic_bounds(codec, xl_ml, xu_ml)
            x_init_mm = codec_lib.minmax_normalize(x_init_ml, xl_ml, xu_ml)

            def gen_step(carry, _):
                pop_x, pop_f, arch_x, arch_f, norm_state, key = carry
                key, k_mate, k_surv = jax.random.split(key, 3)

                off = jax.vmap(
                    lambda k, x, xl, xu: make_offspring(
                        k,
                        tables,
                        x,
                        xl,
                        xu,
                        n_off,
                        crossover_prob=eng.crossover_prob,
                        eta_mutation=eng.eta_mutation,
                    )
                )(jax.random.split(k_mate, s), pop_x, xl_gen, xu_gen)
                off_f, off_hist = eng._evaluate_hist(
                    params, off, x_init_ml, x_init_mm, xl_ml, xu_ml, minimize_class
                )

                merged_x = jnp.concatenate([pop_x, off], axis=1)
                merged_f = jnp.concatenate([pop_f, off_f], axis=1)

                mask, norm_state, _ = survive_batch(
                    k_surv, merged_f, asp, norm_state,
                    pop_size, assoc_block=eng.assoc_block,
                )

                # Dense survivor extraction, stable order survivors-first:
                # the permutation comes from two cumsums + a scatter (a
                # stable bool argsort costs a full sort per state per
                # generation on TPU; this is linear).
                m_tot = mask.shape[1]
                n_true = mask.sum(1, keepdims=True)
                dest = jnp.where(
                    mask,
                    jnp.cumsum(mask, axis=1) - 1,
                    n_true + jnp.cumsum(~mask, axis=1) - 1,
                )
                order = (
                    jnp.zeros_like(dest)
                    .at[jnp.arange(dest.shape[0])[:, None], dest]
                    .set(jnp.broadcast_to(jnp.arange(m_tot), dest.shape))
                )[:, :pop_size]
                pop_x = jnp.take_along_axis(merged_x, order[..., None], axis=1)
                pop_f = jnp.take_along_axis(merged_f, order[..., None], axis=1)

                if eng.archive_size:
                    # elite archive update: top-A by feasible-first score over
                    # archive ∪ offspring (monotone across generations)
                    arch_x, arch_f = eng._archive_select(
                        jnp.concatenate([arch_x, off], axis=1),
                        jnp.concatenate([arch_f, off_f], axis=1),
                    )

                hist = off_hist if eng.save_history else jnp.zeros((), eng.dtype)
                return (pop_x, pop_f, arch_x, arch_f, norm_state, key), hist

            # scan unroll=2 measured noise-neutral on the tunnelled v5e
            # (round-5 A/B) — keep the default single-step body.
            return jax.lax.scan(gen_step, carry, None, length=length)

        return segment

    # -- public API ---------------------------------------------------------
    def effective_states_chunk(self) -> int | None:
        """The states-chunk size :meth:`generate` actually dispatches with:
        ``max_states_per_call`` rounded DOWN to a mesh-size multiple (never
        up — the configured chunk is a device-memory / program-size ceiling),
        e.g. a configured 500 on an 8-device mesh runs as 496 (the shipped
        ``config/moeva.yaml`` default of 256 is already aligned). Chunking folds
        per-chunk RNG keys, so runners record this value in the metrics to
        keep every committed number's execution mode traceable."""
        chunk = self.max_states_per_call
        if chunk and self.mesh is not None and chunk % self.mesh.size:
            chunk = max(chunk - chunk % self.mesh.size, self.mesh.size)
        return chunk

    def generate(self, x: np.ndarray, minimize_class=1) -> MoevaResult:
        """Attack every row of ``x`` (parity: ``Moeva2.generate``,
        ``moeva2.py:174-207`` — but batched on device instead of forked)."""
        x = np.asarray(x)
        if x.ndim != 2:
            raise ValueError(f"x must be 2-D (n_states, n_features), got {x.shape}")
        if x.shape[1] != self.codec.n_features:
            raise ValueError(
                f"x has {x.shape[1]} features, schema expects {self.codec.n_features}"
            )
        s = x.shape[0]
        if isinstance(minimize_class, (int, np.integer)):
            minimize_class = np.full((s,), int(minimize_class))
        minimize_class = np.asarray(minimize_class)
        if minimize_class.shape[0] != s:
            raise ValueError("minimize_class must be scalar or length n_states")

        chunk = self.effective_states_chunk()
        self._dispatch_log = []
        self._balance_log = []
        self.last_deferred_gate_flushes = 0
        t0 = time.perf_counter()
        try:
            if chunk and s > chunk:
                return self._generate_chunked(x, minimize_class, chunk)
            return self._generate_one(
                x, minimize_class,
                jax.random.PRNGKey(self.seed), self.checkpoint_path,
            )
        finally:
            # roofline attribution at the one point where every dispatched
            # segment has been fetched (the result decode above synced)
            self._attribute_run(t0, time.perf_counter() - t0)

    def _generate_chunked(self, x, minimize_class, chunk) -> MoevaResult:
        """Sequential chunks of one compiled program; the tail chunk is
        padded (states are independent, the pad rows are trimmed) so every
        dispatch reuses the same executable. Chunk keys are folds of the
        seed key, so chunks draw independent random streams.

        Host/device overlap: chunk *i*'s results are fetched one dispatch
        late — after chunk *i+1*'s segments are enqueued — so the fetch,
        the parked/active merge, and the host-side ML decode run while the
        device executes the next chunk (the history ``pending`` pattern
        applied to the final populations)."""
        t0 = time.time()
        s = x.shape[0]
        base_key = jax.random.PRNGKey(self.seed)
        parts: list[MoevaResult] = []
        prev: tuple[_InFlightRun, int] | None = None

        def finalize(run: _InFlightRun, n_real: int) -> MoevaResult:
            res = self._finalize_one(run)
            return MoevaResult(
                x_gen=res.x_gen[:n_real],
                f=res.f[:n_real],
                x_ml=res.x_ml[:n_real],
                x_initial=res.x_initial[:n_real],
                n_gen=res.n_gen,
                time=res.time,
                history=None
                if res.history is None
                else [h[:n_real] for h in res.history],
                gens_executed=res.gens_executed,
                early_stop=res.early_stop,
                # per-chunk quality keeps its padded per_state rows; the
                # merge below trims them by each chunk's real row count
                quality=res.quality,
            )

        for i, start in enumerate(range(0, s, chunk)):
            xc = x[start : start + chunk]
            mc = minimize_class[start : start + chunk]
            n_real = xc.shape[0]
            if n_real < chunk:  # pad the tail with the last state
                pad = chunk - n_real
                xc = np.concatenate([xc, np.repeat(xc[-1:], pad, axis=0)])
                mc = np.concatenate([mc, np.repeat(mc[-1:], pad, axis=0)])
            cp_path = (
                f"{self.checkpoint_path}.chunk{i}" if self.checkpoint_path else None
            )
            run = self._launch_one(
                xc, mc, jax.random.fold_in(base_key, i), cp_path
            )
            if prev is not None:
                parts.append(finalize(*prev))
            prev = (run, n_real)
        parts.append(finalize(*prev))
        history = None
        if parts[0].history is not None:
            history = [
                np.concatenate(hs, axis=0) for hs in zip(*(p.history for p in parts))
            ]
        gens_executed = sum(p.gens_executed for p in parts)
        early_stop = None
        if parts[0].early_stop is not None:
            early_stop = {
                "check_every": parts[0].early_stop["check_every"],
                "gens_executed": gens_executed,
                "budget_gens": (self.n_gen - 1) * len(parts),
                "compaction": [
                    dict(t, chunk=i)
                    for i, p in enumerate(parts)
                    for t in p.early_stop["compaction"]
                ],
            }
        return MoevaResult(
            x_gen=np.concatenate([p.x_gen for p in parts], axis=0),
            f=np.concatenate([p.f for p in parts], axis=0),
            x_ml=np.concatenate([p.x_ml for p in parts], axis=0),
            x_initial=x,
            n_gen=self.n_gen,
            time=time.time() - t0,
            history=history,
            gens_executed=gens_executed,
            early_stop=early_stop,
            # chunks share budget + gate cadence, so their per-gate samples
            # concatenate along the states axis (aggregates recomputed)
            quality=merge_chunk_quality(
                [p.quality for p in parts],
                [p.x_gen.shape[0] for p in parts],
            ),
        )

    def _generate_one(
        self,
        x: np.ndarray,
        minimize_class: np.ndarray,
        key: jax.Array,
        checkpoint_path: str | None,
    ) -> MoevaResult:
        return self._finalize_one(
            self._launch_one(x, minimize_class, key, checkpoint_path)
        )

    def _trace_event(self, name: str, **attrs) -> None:
        """Emit a progress event (+ HBM watermark) into the attached trace;
        no-op without one — the overhead contract of the tracing layer."""
        tr = self.trace
        if tr is None or not getattr(tr, "enabled", False):
            return
        if self.mesh is not None and self.mesh.size > 1:
            # all mesh devices, not device 0: the max is the watermark a
            # capacity planner sizes for, the per-device list is where an
            # imbalance (one shard's archive blowing up) shows first
            stats = all_device_memory_stats(list(self.mesh.devices.flat))
            tr.event(
                name,
                hbm=(stats or {}).get("max"),
                hbm_devices=(stats or {}).get("per_device"),
                **attrs,
            )
            return
        dev = self.mesh.devices.flat[0] if self.mesh is not None else None
        tr.event(name, hbm=device_memory_stats(dev), **attrs)

    # -- early-exit machinery ------------------------------------------------
    def _compaction_menu(self):
        """The shared fixed-shape dispatch menu, filtered to mesh-aligned
        sizes — ONE source of truth with the serving microbatcher."""
        from ...experiments.common import DEFAULT_BUCKET_SIZES, BucketMenu

        sizes = tuple(self.compaction_buckets or DEFAULT_BUCKET_SIZES)
        if self.mesh is not None:
            sizes = tuple(b for b in sizes if b % self.mesh.size == 0)
        return BucketMenu(sizes) if sizes else None

    def _success_mask(self, carry):
        """The packed (S, 9) on-device gate output from the carried
        objectives: the per-state quality stats
        (``attacks.objective.QUALITY_STAT_COLUMNS``) judged over
        population ∪ archive by the ObjectiveCalculator criterion
        (misclassified ∧ Σ violations = 0 ∧ within ε). The (S,) success
        mask IS the o7 column (``stats[..., 6] > 0``), derived host-side
        after the fetch — so each gate costs exactly ONE packed
        device→host transfer (the roofline satellite: the former
        bool-mask fetch and stats fetch were two round trips over a
        tunnelled device). One tiny program computes the stats
        unconditionally, so quality capture on/off shares the same
        executable and dispatch schedule."""

        if self._jit_success is None:

            def gate_stats(pop_f, arch_f, thr, eps):
                f = (
                    jnp.concatenate([pop_f, arch_f], axis=1)
                    if arch_f.shape[1]
                    else pop_f
                )
                return engine_quality_stats(f, thr, eps, xp=jnp)

            self._jit_success = LedgeredJit(
                jax.jit(gate_stats),
                producer="moeva_success",
                identity=self._ledger_identity,
                describe_args=lambda pop_f, *rest: {
                    "rows": int(pop_f.shape[0])
                },
                on_dispatch=self._on_ledger_dispatch,
            )
        # early_stop_eps is a distance in normalised feature space; the
        # carried f2 objective divides L2 distances by sqrt(D)
        if self._gate_scalars is None:
            eps = float(self.early_stop_eps) / self._f2_scale
            scalars = (
                jnp.asarray(self.early_stop_threshold, self.dtype),
                jnp.asarray(eps, self.dtype),
            )
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                repl = NamedSharding(self.mesh, P())
                scalars = tuple(jax.device_put(a, repl) for a in scalars)
            self._gate_scalars = scalars
        return self._jit_success(carry[1], carry[3], *self._gate_scalars)

    def _take_carry(self, carry, sel: np.ndarray):
        """Repack the carry's states axis to ``sel`` through ONE jitted
        fused gather (device-side — the populations never round-trip
        through host memory). The op-by-op eager gather this replaces
        enqueued ~10 separate device ops per compaction (one per carry
        leaf plus the mesh re-placements); the fused executable is one
        dispatch — one round trip over a tunnelled device. One tiny
        executable per (source shape, bucket) pair, bounded by the menu
        length like the segment programs, and dispatched through
        :class:`LedgeredJit` like every other engine program so its
        compiles land in the cost/cold ledgers and the persistent AOT
        cache (an uninstrumented compile would re-pay trace+compile per
        process and leak its first-call wall into run attribution).
        Donating the source carry was tried and recorded as a negative
        result: XLA input-output aliasing requires equal shapes, and a
        shrinking gather can never alias, so ``donate_argnums`` only
        produced unusable-donation warnings (docs/DESIGN.md § spending
        the ledger, donation inventory) — the source buffers free via
        the normal refcount when the caller rebinds the carry."""
        if self._jit_take is None:
            mesh, axis = self.mesh, self.states_axis

            def take(carry, sel):
                pop_x, pop_f, arch_x, arch_f, norm_state, key = carry
                gather = lambda a: jnp.take(a, sel, axis=0)  # noqa: E731
                out = (
                    gather(pop_x),
                    gather(pop_f),
                    gather(arch_x),
                    gather(arch_f),
                    jax.tree.map(gather, norm_state),
                    key,
                )
                if mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec

                    sh = NamedSharding(mesh, PartitionSpec(axis))
                    cons = lambda a: jax.lax.with_sharding_constraint(  # noqa: E731
                        a, sh
                    )
                    out = (
                        *(cons(a) for a in out[:4]),
                        jax.tree.map(cons, out[4]),
                        key,
                    )
                return out

            self._jit_take = LedgeredJit(
                jax.jit(take),
                producer="moeva_compact",
                identity=self._ledger_identity,
                describe_args=lambda carry, sel: {
                    "rows": int(carry[0].shape[0]),
                    "bucket": int(sel.shape[0]),
                },
                on_dispatch=self._on_ledger_dispatch,
            )
        return self._jit_take(carry, jnp.asarray(sel))

    def _final_columns(self, carry, idx: np.ndarray):
        """Rows ``idx``'s returned-population columns (pop + archive)."""
        pop_x, pop_f, arch_x, arch_f = carry[0], carry[1], carry[2], carry[3]
        sel = jnp.asarray(idx)
        px = jnp.take(pop_x, sel, axis=0)
        pf = jnp.take(pop_f, sel, axis=0)
        if self.archive_size:
            px = jnp.concatenate([px, jnp.take(arch_x, sel, axis=0)], axis=1)
            pf = jnp.concatenate([pf, jnp.take(arch_f, sel, axis=0)], axis=1)
        return px, pf

    def _place_rows(self, x, minimize_class, xl_ml, xu_ml, rows: np.ndarray):
        """Device placement of the per-state attack inputs for the current
        active set (``rows`` = original row indices, pads duplicated)."""
        arrs = (
            jnp.asarray(x[rows], self.dtype),
            jnp.asarray(minimize_class[rows], jnp.int32),
            jnp.asarray(xl_ml[rows], self.dtype),
            jnp.asarray(xu_ml[rows], self.dtype),
        )
        if self.mesh is not None:
            from ..sharding import shard_states_args

            _, arrs = shard_states_args(
                self.mesh, self.states_axis, (), arrs
            )
        return arrs

    @staticmethod
    def _early_stop_extra(s, row_src, row_live, parked, gens_executed, trace):
        """Checkpoint payload of the early-exit host state: the active-set
        mapping plus the parked final populations — a resumed compacted run
        must rebuild its (shrunken) dispatch arguments and keep the already
        solved states' results."""
        import json

        if parked is None:
            parked_mask = np.zeros(s, dtype=bool)
            parked_x = np.zeros((s, 0, 0))
            parked_f = np.zeros((s, 0, 0))
        else:
            parked_mask, parked_x, parked_f = (
                parked["mask"], parked["x"], parked["f"]
            )
        return {
            "row_src": np.asarray(row_src),
            "row_live": np.asarray(row_live),
            "parked_mask": parked_mask,
            "parked_x": parked_x,
            "parked_f": parked_f,
            "state_json": np.asarray(
                json.dumps(
                    {
                        "gens_executed": int(gens_executed),
                        "trace": trace,
                        "parked": parked is not None,
                    }
                )
            ),
        }

    # -- dispatch ------------------------------------------------------------
    def _launch_one(
        self,
        x: np.ndarray,
        minimize_class: np.ndarray,
        key: jax.Array,
        checkpoint_path: str | None,
    ) -> _InFlightRun:
        s = x.shape[0]
        check = int(self.early_stop_check_every or 0)
        qual_on = bool(self.record_quality)
        # quality samples ride the early-exit gates when they exist;
        # otherwise ``quality_every`` introduces its own (semantics-free)
        # gate cadence. gate_every = 0 means no mid-run sync points.
        gate_every = check or (int(self.quality_every or 0) if qual_on else 0)
        if check and self.save_history:
            raise ValueError(
                "early_stop_check_every is incompatible with save_history: "
                "active-set compaction changes the states axis mid-run and "
                "per-generation history records are not reassembled across "
                "repacks (run strict mode for history)"
            )
        xl_ml, xu_ml = self.constraints.get_feature_min_max(dynamic_input=x)
        xl_ml = np.broadcast_to(np.asarray(xl_ml, dtype=np.float64), x.shape)
        xu_ml = np.broadcast_to(np.asarray(xu_ml, dtype=np.float64), x.shape)

        if self._jit_init is None:
            # LedgeredJit = AOT compile + dispatch of the exact executable
            # the jit cache would build, with the cost ledger observing
            # every compile (identity, cost/memory analysis, wall-clock)
            self._jit_init = LedgeredJit(
                jax.jit(self._build_init()),
                producer="moeva_init",
                identity=self._ledger_identity,
                describe_args=lambda params, x_init_ml, *rest: {
                    "rows": int(x_init_ml.shape[0])
                },
                on_dispatch=self._on_ledger_dispatch,
            )
            # Donate the evolution carry: without donation every chained
            # segment holds TWO full population copies in HBM (the consumed
            # input and the produced output); with it XLA reuses the buffers
            # in place. Host code never touches a carry after re-dispatching
            # it (checkpoint saves and mask fetches read the *output* carry
            # before the next dispatch consumes it).
            self._jit_segment = LedgeredJit(
                jax.jit(
                    self._build_segment(),
                    static_argnames="length",
                    donate_argnums=(5,),
                ),
                producer="moeva_segment",
                identity=self._ledger_identity,
                describe_args=lambda params, x_init_ml, *rest, **kw: {
                    "rows": int(x_init_ml.shape[0]),
                    "length": int(kw.get("length", 0)),
                },
                static_argnames=("length",),
                on_dispatch=self._on_ledger_dispatch,
            )

        args = (
            self.classifier.params,
            jnp.asarray(x, self.dtype),
            jnp.asarray(minimize_class, jnp.int32),
            jnp.asarray(xl_ml, self.dtype),
            jnp.asarray(xu_ml, self.dtype),
            key,
        )
        if self.mesh is not None:
            args = self._shard_args(args)
        params, x_dev, mc_dev, xl_dev, xu_dev, key = args

        cp = None
        if self.checkpoint_every and checkpoint_path:
            from .checkpoint import AttackCheckpointer

            cp = AttackCheckpointer(
                checkpoint_path,
                self._fingerprint(x, minimize_class, xl_ml, xu_ml),
            )

        t0 = time.time()
        carry, init_hist = self._jit_init(*args)
        self._trace_event("moeva.init", states=int(s), n_gen=int(self.n_gen))
        n_steps = self.n_gen - 1
        # Without history or early exit a single segment reproduces the
        # one-scan program; with history, fixed-size segments bound HBM
        # usage and each chunk's records move to host while the next segment
        # runs; with early exit, segments end on ``check`` boundaries so the
        # success mask can gate the next dispatch. Checkpoint boundaries cap
        # segment length so saves land exactly on ``checkpoint_every``
        # multiples.
        chunk = n_steps if not self.save_history else max(1, self.history_chunk)
        if gate_every:
            chunk = max(1, min(chunk, gate_every))
        hist_chunks = []
        pending = None  # previous chunk's device buffer, fetched one dispatch late
        done = 0
        # early-exit host state: which original row each carry row tracks,
        # whether its final result is still wanted, and the frozen results
        # of already solved rows
        row_src = np.arange(s)
        row_live = np.ones(s, dtype=bool)
        parked: dict | None = None
        trace: list = []
        gens_executed = 0
        # quality capture state: last-known per-state stats at ORIGINAL row
        # indices (the scatter below freezes parked rows at park time).
        # NaN rows only exist before the first gate; the final sample in
        # ``_finalize_one`` always covers every row from the returned
        # populations. The history is observability, not semantics, so it
        # is deliberately not checkpointed — a resumed run's curve starts
        # at the resume point.
        qual_samples: list = []
        qual_latest = np.full((s, 9), np.nan) if qual_on else None
        if cp is not None:
            resumed = cp.load(carry)
            if resumed is not None:
                carry, done, hist_chunks = resumed
                gens_executed = done
                extra = cp.extra
                if extra is not None:
                    import json

                    row_src = np.asarray(extra["row_src"])
                    row_live = np.asarray(extra["row_live"]).astype(bool)
                    state = json.loads(str(extra["state_json"]))
                    gens_executed = int(state["gens_executed"])
                    trace = list(state["trace"])
                    if state["parked"]:
                        parked = {
                            "mask": np.asarray(extra["parked_mask"]).astype(bool),
                            "x": np.asarray(extra["parked_x"]),
                            "f": np.asarray(extra["parked_f"]),
                        }
                    if len(row_src) != s:
                        # the snapshot was compacted: rebuild the dispatch
                        # arguments for the restored active set
                        x_dev, mc_dev, xl_dev, xu_dev = self._place_rows(
                            x, minimize_class, xl_ml, xu_ml, row_src
                        )
        menu = self._compaction_menu() if check else None
        #: at most one gate's deferred host work (list so the flush
        #: closure can pop without nonlocal rebinding)
        pending_gate: list = []

        def flush_gate():
            """Run the last gate's host-side tail: the quality-stats
            scatter, the parked-population fetch + merge, and the gate
            progress events. Under double-buffering this executes AFTER
            the next segment was enqueued — the fetched ``px``/``pf``
            gathers were dispatched before that segment on the serial
            device queue, so the device_get here waits only for the tiny
            gathers while the segment's generations overlap the host
            work. All decisions (park set, compaction) were already made
            at gate time; this is bookkeeping, so serial mode
            (``double_buffer=False``) is bit-identical."""
            if not pending_gate:
                return
            g = pending_gate.pop()
            if g["dispatches_at"] < len(self._dispatch_log):
                # at least one dispatch was enqueued since the gate:
                # this flush genuinely overlapped device work (the
                # double-buffer's structural witness, pinned by tests)
                self.last_deferred_gate_flushes += 1
            if qual_on:
                # scatter the packed stats home: pads (row_live False)
                # never overwrite a real row, parked rows keep the stats
                # frozen at park time (row_src/row_live are the gate-time
                # copies — compaction may have remapped them since)
                live = g["row_live"]
                qual_latest[g["row_src"][live]] = g["stats"][live]
                qual_samples.append(
                    sample_from_per_state(g["gen"], qual_latest)
                )
                if not check:
                    # quality-only gate (strict semantics, no early
                    # exit): rounded progress event, full precision in
                    # the history
                    sf = qual_samples[-1]["success_frac"]
                    self._trace_event(
                        "moeva.quality",
                        gen=int(g["gen"]),
                        success_frac=None if sf is None else round(sf, 4),
                    )
            if g.get("px") is not None:
                # park merge: freeze the solved rows' populations on host
                with maybe_span(
                    self.trace, "parked_merge", rows=int(len(g["park_rows"]))
                ):
                    px, pf = jax.device_get((g["px"], g["pf"]))
                    parked["x"][g["park_rows"]] = px
                    parked["f"][g["park_rows"]] = pf
                if self.partial_sink is not None:
                    # streaming: decode JUST the newly parked rows on the
                    # host CPU backend (the finalize decode idiom —
                    # genetic_to_ml is eager, so no tracked executables)
                    # and surface them under their ORIGINAL row indices.
                    # The sink is a consumer boundary: its failures must
                    # never poison the batch or perturb the scan.
                    try:
                        rows = g["park_rows"]
                        try:
                            decode_dev = jax.devices("cpu")[0]
                        except RuntimeError:
                            decode_dev = None
                        with maybe_span(
                            self.trace, "partial_decode", rows=int(len(rows))
                        ), jax.default_device(decode_dev):
                            x_ml_rows = np.asarray(
                                codec_lib.genetic_to_ml(
                                    self.codec,
                                    jnp.asarray(px),
                                    jnp.asarray(x[rows], self.dtype)[:, None, :],
                                )
                            )
                        self.partial_sink(
                            [int(r) for r in rows], x_ml_rows, int(g["gen"])
                        )
                    except Exception:
                        pass
            if g.get("event") is not None:
                self._trace_event("moeva.gate", **g["event"])

        while done < n_steps:
            length = min(chunk, n_steps - done)
            if gate_every:
                # re-align on gate boundaries: a checkpoint cap below can
                # shift ``done`` off the gate multiples, and the gate must
                # keep firing every ``gate_every`` generations regardless
                length = min(length, gate_every - done % gate_every)
            if cp is not None:
                length = min(
                    length, self.checkpoint_every - done % self.checkpoint_every
                )
            carry, gen_hist = self._jit_segment(
                params, x_dev, mc_dev, xl_dev, xu_dev, carry, length=length
            )
            done += length
            gens_executed += length
            if (
                self.mesh is not None
                and self.mesh.size > 1
                and len(row_live) % self.mesh.size == 0
            ):
                # per-device live rows of this segment window (the states
                # axis shards contiguously over the 1-D mesh, so ordinal d
                # owns rows [d*k, (d+1)*k)) — host-side bookkeeping on a
                # mask already in hand, drained by _attribute_run
                live = (
                    row_live.reshape(self.mesh.size, -1)
                    .sum(axis=1)
                    .tolist()
                )
                self._balance_log.append((live, length))

            def flush_pending():
                # fetch the in-flight chunk; with checkpointing it also
                # lands on disk so a later carry snapshot can claim it
                nonlocal pending
                if pending is None:
                    return
                with maybe_span(self.trace, "fetch", what="history"):
                    arr = np.asarray(jax.device_get(pending))
                if cp is not None:
                    cp.add_hist_chunk(len(hist_chunks), arr)
                hist_chunks.append(arr)
                pending = None

            # generation double-buffering: the PREVIOUS gate's host-side
            # tail runs here, while the segment just enqueued executes on
            # device — the top_gap_stages the PR-9 tracker attributed
            # (gate quality fetch, parked_merge) move off the device's
            # critical path. No-op in serial mode (already flushed).
            flush_gate()
            if self.save_history:
                # the next segment is already enqueued (async dispatch), so
                # fetching the *previous* chunk overlaps with its compute
                flush_pending()
                pending = gen_hist
            if gate_every and done % gate_every == 0 and done < n_steps:
                # the ONE remaining inter-segment sync: a single packed
                # (S, 9) stats fetch — the park/compaction decision needs
                # it (the success mask is its o7 column)
                with maybe_span(self.trace, "gate_fetch", what="stats"):
                    stats = np.asarray(
                        jax.device_get(self._success_mask(carry))
                    )
                succ = stats[..., 6] > 0
                gate = {
                    "gen": done,
                    "stats": stats,
                    # gate-time copies: compaction below remaps the live
                    # arrays before the deferred flush reads them
                    "row_src": row_src.copy(),
                    "row_live": row_live.copy(),
                }
                if check:
                    solved = row_live & succ
                    n_parked = int(solved.sum())
                    if n_parked:
                        # park decision NOW (host bools — checkpoint and
                        # finalize need the mask); the population fetch +
                        # merge is the flush's business
                        idx = np.where(solved)[0]
                        if parked is None:
                            cols = self.pop_size + self.archive_size
                            parked = {
                                "mask": np.zeros(s, dtype=bool),
                                "x": np.zeros(
                                    (s, cols, self.codec.gen_length),
                                    dtype=np.dtype(self.dtype),
                                ),
                                "f": np.zeros(
                                    (s, cols, 3), dtype=np.dtype(self.dtype)
                                ),
                            }
                        rows = row_src[idx]
                        parked["mask"][rows] = True
                        gate["park_rows"] = rows
                        # device-side gather of the solved rows' returned
                        # populations — enqueued BEFORE the compaction
                        # gather donates this carry and before the next
                        # segment dispatch, fetched at the next flush
                        gate["px"], gate["pf"] = self._final_columns(
                            carry, idx
                        )
                        row_live = row_live & ~succ
                    n_active = int(row_live.sum())
                    if n_active == 0:
                        # every state holds a success: skip the rest of the
                        # budget (the flush runs at finalize)
                        trace.append(
                            {"gen": done, "active": 0, "bucket": len(row_src)}
                        )
                        gate["event"] = dict(
                            gen=int(done),
                            active=0,
                            parked=int(n_parked),
                            success_frac=1.0,
                            bucket=int(len(row_src)),
                            early_exit=True,
                        )
                        gate["dispatches_at"] = len(self._dispatch_log)
                        pending_gate.append(gate)
                        break
                    bucket = (
                        menu.shrink_bucket(n_active, len(row_src))
                        if menu
                        else None
                    )
                    if bucket is not None:
                        # compact: repack the unsolved active set down the
                        # shared bucket menu (pads duplicate the last live
                        # row; their results are never read back)
                        keep = np.where(row_live)[0]
                        sel = np.concatenate(
                            [
                                keep,
                                np.full(bucket - n_active, keep[-1], keep.dtype),
                            ]
                        )
                        carry = self._take_carry(carry, sel)
                        row_src = row_src[sel]
                        row_live = np.concatenate(
                            [
                                np.ones(n_active, dtype=bool),
                                np.zeros(bucket - n_active, dtype=bool),
                            ]
                        )
                        x_dev, mc_dev, xl_dev, xu_dev = self._place_rows(
                            x, minimize_class, xl_ml, xu_ml, row_src
                        )
                        trace.append(
                            {"gen": done, "active": n_active, "bucket": bucket}
                        )
                    elif n_parked:
                        # states parked without a repack (no smaller menu
                        # size): record the gate anyway — the trace must
                        # account for every convergence, not only bucket
                        # transitions
                        trace.append(
                            {
                                "gen": done,
                                "active": n_active,
                                "bucket": len(row_src),
                            }
                        )
                    # per-gate progress event: generation index, cumulative
                    # success fraction, active set, and the (possibly just
                    # shrunk) dispatch bucket. The payload rounds for
                    # display; the recorded quality history keeps the full-
                    # precision numbers. Emitted at the deferred flush.
                    gate["event"] = dict(
                        gen=int(done),
                        active=n_active,
                        parked=int(n_parked),
                        success_frac=round(1.0 - n_active / s, 4),
                        bucket=int(len(row_src)),
                    )
                # dispatch watermark taken AFTER the decision block: the
                # (ledgered) compaction gather is part of THIS gate's own
                # work, not the next segment — only dispatches enqueued
                # after the gate parks count as overlap for the witness
                gate["dispatches_at"] = len(self._dispatch_log)
                pending_gate.append(gate)
                if not self.double_buffer:
                    flush_gate()
            if (
                cp is not None
                and done < n_steps
                and done % self.checkpoint_every == 0
            ):
                # a snapshot only counts history already durable on disk —
                # and the parked populations must be merged before the
                # early-stop extra freezes them into the snapshot
                flush_pending()
                flush_gate()
                cp.save(
                    carry,
                    done,
                    n_hist=len(hist_chunks),
                    extra=self._early_stop_extra(
                        s, row_src, row_live, parked, gens_executed, trace
                    )
                    if check
                    else None,
                )
        return _InFlightRun(
            x=x,
            t0=t0,
            carry=carry,
            row_src=row_src,
            row_live=row_live,
            parked=parked,
            check=check,
            n_steps=n_steps,
            gens_executed=gens_executed,
            trace=trace,
            init_hist=init_hist,
            hist_chunks=hist_chunks,
            pending=pending,
            cp=cp,
            gate_every=gate_every,
            qual_latest=qual_latest,
            qual_samples=qual_samples,
            flush_gate=flush_gate,
        )

    def _finalize_one(self, run: _InFlightRun) -> MoevaResult:
        if run.flush_gate is not None:
            # drain the last gate's deferred host tail (parked merge,
            # quality scatter, events) before reading that state below
            run.flush_gate()
        if run.pending is not None:
            with maybe_span(self.trace, "fetch", what="history"):
                run.hist_chunks.append(np.asarray(jax.device_get(run.pending)))
            run.pending = None
        pop_x, pop_f, arch_x, arch_f, _, _ = run.carry
        with maybe_span(self.trace, "fetch", what="populations"):
            if self.archive_size:
                # archive members join the returned populations (extra
                # columns) — concatenated on HOST from one coalesced
                # fetch: the former device-side concat allocated a
                # transient (S, P+A, L) copy in HBM and cost two extra
                # dispatches plus a second fetch, for arrays that were
                # about to cross to host anyway
                pop_x, pop_f, arch_x, arch_f = jax.device_get(
                    (pop_x, pop_f, arch_x, arch_f)
                )
                pop_x = np.concatenate([pop_x, arch_x], axis=1)
                pop_f = np.concatenate([pop_f, arch_f], axis=1)
            else:
                pop_x, pop_f = jax.device_get((pop_x, pop_f))
        s = run.x.shape[0]
        if run.parked is not None or len(run.row_src) != s:
            # merge: parked rows keep their frozen populations; surviving
            # rows land back at their original indices; pad rows drop
            with maybe_span(self.trace, "parked_merge", rows=int(s)):
                out_x = np.zeros((s,) + pop_x.shape[1:], pop_x.dtype)
                out_f = np.zeros((s,) + pop_f.shape[1:], pop_f.dtype)
                if run.parked is not None:
                    m = run.parked["mask"]
                    out_x[m] = run.parked["x"][m]
                    out_f[m] = run.parked["f"][m]
                out_x[run.row_src[run.row_live]] = pop_x[run.row_live]
                out_f[run.row_src[run.row_live]] = pop_f[run.row_live]
                pop_x, pop_f = out_x, out_f
        elapsed = time.time() - run.t0
        if run.cp is not None:
            run.cp.clear()  # run finished: recovery artifacts no longer needed

        history = None
        if self.save_history:
            init_hist = np.asarray(jax.device_get(run.init_hist))
            # (n_gen-1, S, O, C) across chunks
            gen_hist = (
                np.concatenate(run.hist_chunks, axis=0)
                if run.hist_chunks
                else np.zeros((0, *init_hist.shape))
            )
            history = [init_hist] + [gen_hist[i] for i in range(gen_hist.shape[0])]

        # Decode the final populations on the host CPU backend: the genetic
        # tensor already crossed host↔device once, and decoding there avoids
        # a second full-population transfer (measurable when the accelerator
        # sits behind a network tunnel).
        try:
            decode_dev = jax.devices("cpu")[0]
        except RuntimeError:
            decode_dev = None
        with maybe_span(self.trace, "decode"), jax.default_device(decode_dev):
            x_ml = np.asarray(
                codec_lib.genetic_to_ml(
                    self.codec,
                    jnp.asarray(pop_x),
                    jnp.asarray(run.x, self.dtype)[:, None, :],
                )
            )
        early_stop = None
        if run.check:
            early_stop = {
                "check_every": run.check,
                "gens_executed": run.gens_executed,
                "budget_gens": run.n_steps,
                "compaction": run.trace,
            }
        quality = None
        if self.record_quality:
            # final sample from the returned populations (pop ∪ archive,
            # parked rows restored) — pure numpy on arrays already fetched
            # above, so strict-mode quality costs zero device work
            eps = float(self.early_stop_eps) / self._f2_scale
            final_ps = engine_quality_stats(
                np.asarray(pop_f, np.float64),
                float(self.early_stop_threshold),
                eps,
                xp=np,
            )
            quality = {
                "gate_every": run.gate_every,
                "threshold": float(self.early_stop_threshold),
                "eps": float(self.early_stop_eps),
                "archive_size": int(self.archive_size),
                "judged": "engine",
                "samples": list(run.qual_samples or [])
                + [
                    sample_from_per_state(
                        run.gens_executed, final_ps, final=True
                    )
                ],
            }
        self._trace_event(
            "moeva.done",
            states=int(s),
            gens_executed=int(run.gens_executed),
            budget_gens=int(run.n_steps),
            time_s=round(elapsed, 4),
        )
        return MoevaResult(
            x_gen=np.asarray(pop_x),
            f=np.asarray(pop_f),
            x_ml=x_ml,
            x_initial=run.x,
            n_gen=self.n_gen,
            time=elapsed,
            history=history,
            gens_executed=run.gens_executed,
            early_stop=early_stop,
            quality=quality,
        )

    def _fingerprint(
        self,
        x: np.ndarray,
        minimize_class: np.ndarray,
        xl_ml: np.ndarray,
        xu_ml: np.ndarray,
    ) -> str:
        """Attack identity for checkpoint validity: the inputs plus every
        *data* ingredient that changes the computation — engine knobs,
        classifier weights, scaler, feature bounds, and the constraint set's
        schema identity (a model retrained to the same path, or a features
        CSV edited, between crash and rerun must invalidate the checkpoint).
        Constraint *formulas* are code, not data: changing them means
        changing this package, which ships with its own tests. A checkpoint
        whose fingerprint differs is ignored (fresh start), never resumed
        into."""
        import hashlib

        h = hashlib.md5()
        h.update(np.ascontiguousarray(x).tobytes())
        h.update(np.ascontiguousarray(minimize_class).tobytes())
        h.update(np.ascontiguousarray(xl_ml).tobytes())
        h.update(np.ascontiguousarray(xu_ml).tobytes())
        knobs = [
            self.n_gen, self.pop_size, self.n_offsprings, self.seed,
            self.init, self.init_eps, self.init_ratio, self.archive_size,
            # early-exit knobs change the dispatch schedule and (via
            # compaction) the RNG stream, so they are attack identity
            self.early_stop_check_every, self.early_stop_threshold,
            self.early_stop_eps, tuple(self.compaction_buckets or ()),
            str(self.save_history), str(self.norm), self.crossover_prob,
            self.eta_mutation, str(np.dtype(self.dtype)),
            type(self.constraints).__name__,
            self.constraints.get_nb_constraints(),
        ]
        h.update(repr(knobs).encode())
        for leaf in jax.tree_util.tree_leaves(self.classifier.params):
            h.update(np.ascontiguousarray(jax.device_get(leaf)).tobytes())
        if self.ml_scaler is not None:
            h.update(np.ascontiguousarray(self.ml_scaler.scale).tobytes())
            h.update(np.ascontiguousarray(self.ml_scaler.min_).tobytes())
        schema = self.constraints.schema
        h.update(repr([list(map(str, schema.types)), schema.mutable.tolist()]).encode())
        return h.hexdigest()

    def _shard_args(self, args):
        """Shard the states axis over the mesh; replicate params/key."""
        from ..sharding import shard_states_args

        params, x, mc, xl, xu, key = args
        (params, key), (x, mc, xl, xu) = shard_states_args(
            self.mesh, self.states_axis, (params, key), (x, mc, xl, xu)
        )
        return (params, x, mc, xl, xu, key)
