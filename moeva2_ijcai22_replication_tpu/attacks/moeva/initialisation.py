"""Initial-population strategies for the MoEvA2 engine, on device.

Capability parity with the reference's two samplers
(``/root/reference/src/attacks/moeva2/sampling.py``):

* ``tile`` — every individual starts at the encoded initial state, integer
  genes rounded (``InitialStateSampling``, ``sampling.py:55-78``).
* ``lp_ratio`` — a fixed fraction of the population is perturbed inside an
  Lp ε-ball in normalised genetic space, clipped to bounds, denormalised,
  integer genes rounded; the rest stays at the initial state
  (``MixedSamplingLp``, ``sampling.py:8-52`` with the hyperball/Linf
  samplers of ``src/utils/__init__.py:22-41``).

TPU-first formulation: both strategies are pure jittable functions over the
whole ``(n_states, n_pop, L)`` batch at once (the reference samples one
state per joblib worker with numpy's global RNG); the ball sampler uses the
Gaussian-direction trick as a single batched normal draw.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core import codec as codec_lib
from ...core.codec import Codec
from ...core.norms import is_inf, is_l2


def ball_sample(key: jax.Array, shape: tuple, eps: float, norm) -> jnp.ndarray:
    """Uniform perturbations inside the Lp ε-ball, shape ``(..., d)``.

    L2 uses the (d+2)-dimensional Gaussian projection trick (marginals of a
    uniform ball point); L∞ is a plain uniform cube.
    """
    d = shape[-1]
    if is_l2(norm):
        u = jax.random.normal(key, (*shape[:-1], d + 2))
        u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
        return u[..., :d] * eps
    if is_inf(norm):
        return jax.random.uniform(key, shape, minval=-1.0, maxval=1.0) * eps
    raise NotImplementedError(f"no ball sampler for norm {norm!r}")


def tile_init(codec: Codec, x_init_ml: jnp.ndarray, n_pop: int) -> jnp.ndarray:
    """(S, D) initial states -> (S, n_pop, L) genetic population, all rows at
    the (int-rounded) encoded initial state."""
    x0 = codec_lib.round_int_genes(codec, codec_lib.ml_to_genetic(codec, x_init_ml))
    s = x_init_ml.shape[0]
    return jnp.broadcast_to(x0[:, None, :], (s, n_pop, codec.gen_length))


def lp_ratio_init(
    key: jax.Array,
    codec: Codec,
    x_init_ml: jnp.ndarray,
    n_pop: int,
    xl_gen: jnp.ndarray,
    xu_gen: jnp.ndarray,
    eps: float = 0.1,
    ratio: float = 0.5,
    norm=2,
) -> jnp.ndarray:
    """Tile + perturb the last ``round(ratio * n_pop)`` individuals in the
    normalised genetic box (clip to [0,1], denormalise, round int genes).

    The perturbed rows sit *last*, matching the reference's concatenation
    order (``sampling.py:48-50``).
    """
    pop = tile_init(codec, x_init_ml, n_pop)
    n_pert = int(round(ratio * n_pop))
    if n_pert == 0:
        return pop
    s = x_init_ml.shape[0]
    rng = (xu_gen - xl_gen)[:, None, :]
    # zero-range genes: divide by the guard but denormalise by the true
    # (zero) range, so they stay pinned at their single feasible value
    safe = jnp.where(rng > 0, rng, 1.0)
    base = (pop[:, -n_pert:, :] - xl_gen[:, None, :]) / safe
    delta = ball_sample(key, (s, n_pert, codec.gen_length), eps, norm)
    pert = jnp.clip(base + delta, 0.0, 1.0) * rng + xl_gen[:, None, :]
    pert = codec_lib.round_int_genes(codec, pert)
    return pop.at[:, -n_pert:, :].set(pert)
