"""Batched non-dominated sorting as pure jnp — the MXU-friendly formulation.

The reference relies on pymoo's (optionally Cython) sequential fast
non-dominated sort (``pymoo.util.nds``, used from
``/root/reference/src/attacks/moeva2/default_problem.py:3,52`` and inside the
R-NSGA-III survival). For populations of a few hundred, the O(n²) domination
matrix is tiny and a *batched* matrix formulation vastly outperforms pointer
chasing on TPU: one ``(..., n, n)`` comparison + iterative front peeling,
vmapped over thousands of independent initial states.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

UNRANKED = jnp.iinfo(jnp.int32).max

#: Count-reduction formulation for the M² comparison reductions here and in
#: survival (one switch, imported there): matmul counts on the MXU by
#: default, VPU masked sums via MOEVA_MXU_COUNTS=0 for re-measurement
#: (round-5 A/B: within noise one-shot — docs/DESIGN.md budget table).
_MXU_COUNTS = os.environ.get("MOEVA_MXU_COUNTS", "1") != "0"


def domination_matrix(f: jnp.ndarray) -> jnp.ndarray:
    """``D[..., i, j] = True`` iff candidate i dominates candidate j.

    Minimisation semantics: i is no worse everywhere and strictly better
    somewhere (pymoo's default Dominator semantics with no constraints).
    """
    le = (f[..., :, None, :] <= f[..., None, :, :]).all(-1)
    lt = (f[..., :, None, :] < f[..., None, :, :]).any(-1)
    return le & lt


def nd_ranks(f: jnp.ndarray, n_stop: int | None = None) -> jnp.ndarray:
    """Front index (0 = non-dominated) per candidate, shape ``f.shape[:-1]``.

    Iterative peeling: front r = candidates with no remaining dominator.
    The while_loop runs ``max_front_count`` times — typically ≪ n — and is
    vmap-safe (masked lockstep execution across the batch).

    ``n_stop``: stop peeling once that many candidates are ranked — survival
    only needs fronts up to the splitting front (pymoo's
    ``fast_non_dominated_sort`` stops the same way), so ranking the dominated
    tail is wasted sequential depth. Unpeeled candidates keep the UNRANKED
    sentinel (they share one "worse than everything ranked" bucket, which is
    exactly how the survival consumes them).
    """
    n = f.shape[-2]
    if n_stop is None:
        n_stop = n
    dom = domination_matrix(f)
    # bf16 operands with f32 accumulation keep the per-column dominator
    # counts exact (0/1 inputs, counts < 2^24) while the contraction runs on
    # the MXU instead of a VPU masked reduction
    dom_bf = dom.astype(jnp.bfloat16)

    ranks0 = jnp.full(f.shape[:-1], UNRANKED, dtype=jnp.int32)

    def cond(carry):
        ranks, _ = carry
        return ((ranks != UNRANKED).sum(-1) < n_stop).any() & (
            ranks == UNRANKED
        ).any()

    def peel(ranks, r):
        """Assign rank ``r`` to the current front; returns updated ranks."""
        remaining = ranks == UNRANKED
        done = (~remaining).sum(-1, keepdims=True) >= n_stop
        # dominators still unranked, per candidate j
        if _MXU_COUNTS:
            n_dom = jnp.einsum(
                "...i,...ij->...j",
                remaining.astype(jnp.bfloat16),
                dom_bf,
                preferred_element_type=jnp.float32,
            )
            front = remaining & (n_dom == 0)
        else:
            front = remaining & ~(remaining[..., :, None] & dom).any(-2)
        # Safety: if nothing peels (cannot happen for finite f), mark all to
        # terminate rather than loop forever.
        front = jnp.where(front.any(-1, keepdims=True), front, remaining)
        front = front & ~done  # batch rows past their quota stop updating
        return jnp.where(front, r, ranks)

    def body(carry):
        # Two fronts per trip: the loop cost is dominated by sequential
        # launch latency of ~n_fronts tiny kernels (per-trip FLOPs are
        # negligible), so halving the trip count for one extra count-einsum
        # bounds the worst case. Measured neutral at bench-shape profile
        # distributions (few fronts there); kept for the many-front tail.
        ranks, r = carry
        ranks = peel(ranks, r)
        ranks = peel(ranks, r + 1)
        return ranks, r + 2

    ranks, _ = jax.lax.while_loop(cond, body, (ranks0, jnp.int32(0)))
    return ranks
