"""Mixed-variable genetic operators as batched, jittable kernels.

Semantics follow the reference's operator stack
(``/root/reference/src/attacks/moeva2/moeva2.py:90-126``): mixed-variable
two-point crossover (prob 0.9 per mating, independent cut points per type
sub-vector) + polynomial mutation (eta=20, per-gene prob 1/n_type) with
integer genes running on ±0.5-extended bounds then rounded (pymoo's
``IntegerFromFloatMutation`` contract), and initial sampling that tiles the
encoded initial state with integer genes rounded
(``sampling.py:55-78``).

TPU-first formulation: gene→type assignment is compiled into *static* rank
tables (position of each gene within its type sub-vector), so a per-type
two-point crossover is one comparison against two sampled cut points —
no ragged sub-vectors, no gathers. Everything broadcasts over leading batch
axes ``(n_states, n_matings, ...)`` and is vmap/shard_map-safe.

The "softmax" gene type (dormant in the reference — registered operators
``softmax_crossover.py:9-42`` / ``softmax_mutation.py:8-71`` behind a
commented-out type mask, ``moeva2.py:89``; no shipped dataset uses it) is
supported as a third type family: all softmax genes form one sub-vector that
gets its own two-point crossover and polynomial mutation, and is renormalised
with a softmax afterwards — after crossover only for matings whose crossover
coin fired (pymoo copies un-crossed parents verbatim past ``_do``), after
mutation for every offspring row (the reference applies ``softmax(Y)``
unconditionally).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ...core.codec import Codec


class OperatorTables(NamedTuple):
    """Static per-gene tables for mixed-variable operators.

    ``type_id``: 0 = real, 1 = int (categorical genes count as int, matching
    the reference's type mask where OHE groups become single int genes),
    2 = softmax (one probability-simplex sub-vector, renormalised after the
    operators). Tables are closed over by the jitted programs, so
    ``has_softmax`` stays a static Python bool.
    """

    type_id: jnp.ndarray  # (L,) int32
    rank_in_type: jnp.ndarray  # (L,) int32 — position within own type
    type_sizes: jnp.ndarray  # (3,) int32 — [n_real, n_int, n_softmax]
    mut_prob: jnp.ndarray  # (L,) float — 1 / n_type (pymoo sub-problem prob)
    int_mask: jnp.ndarray  # (L,) bool
    softmax_mask: jnp.ndarray  # (L,) bool
    has_softmax: bool


def make_operator_tables(codec: Codec) -> OperatorTables:
    int_mask = np.asarray(codec.int_mask_gen)
    length = len(int_mask)
    softmax_mask = (
        np.zeros(length, dtype=bool)
        if codec.softmax_mask_gen is None
        else np.asarray(codec.softmax_mask_gen)
    )
    type_id = np.where(softmax_mask, 2, int_mask.astype(np.int32)).astype(np.int32)
    rank = np.zeros(length, dtype=np.int32)
    counters = [0, 0, 0]
    for i, t in enumerate(type_id):
        rank[i] = counters[t]
        counters[t] += 1
    sizes = np.array(counters, dtype=np.int32)
    mut_prob = 1.0 / np.maximum(sizes[type_id], 1)
    return OperatorTables(
        type_id=jnp.asarray(type_id),
        rank_in_type=jnp.asarray(rank),
        type_sizes=jnp.asarray(sizes),
        mut_prob=jnp.asarray(mut_prob),
        int_mask=jnp.asarray(int_mask),
        softmax_mask=jnp.asarray(softmax_mask),
        has_softmax=bool(softmax_mask.any()),
    )


def softmax_renorm(
    mask: jnp.ndarray, x: jnp.ndarray, rows: jnp.ndarray | None = None
) -> jnp.ndarray:
    """Softmax over the masked sub-vector of each row; other genes untouched.

    Reference semantics (``softmax_crossover.py:40``, ``softmax_mutation.py:69``):
    the gene *values* are treated as logits. ``rows`` (broadcastable bool)
    restricts which rows are renormalised.
    """
    logits = jnp.where(mask, x, -jnp.inf)
    m = jnp.max(logits, axis=-1, keepdims=True)
    e = jnp.exp(logits - m)  # -inf pads -> exactly 0
    s = e / jnp.sum(e, axis=-1, keepdims=True)
    out = jnp.where(mask, s, x)
    if rows is not None:
        out = jnp.where(rows, out, x)
    return out


def select_parent_pairs(key: jax.Array, n_matings: int, pop_size: int) -> jnp.ndarray:
    """(n_matings, 2) parent indices.

    The reference's NSGA-III tournament compares constraint violation then
    falls back to random (``comp_by_cv_then_random``); with n_constr=0 every
    comparison is the random branch, so selection is uniform over the
    population — implemented directly as uniform draws.
    """
    return jax.random.randint(key, (n_matings, 2), 0, pop_size)


def _two_cuts(key: jax.Array, n: jnp.ndarray, shape) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sorted swap-segment [lo, hi) from up to two cut points in [1, n).

    Uniform over unordered distinct pairs (pymoo draws a permutation and takes
    the first two). pymoo pads missing cuts with ``n_var``: a 2-gene
    sub-vector (one interior cut) always swaps its second gene; a 1-gene
    sub-vector has no interior cut and never swaps.
    """
    k1, k2 = jax.random.split(key)
    m = jnp.maximum(n - 1, 1)  # interior cut positions 1..n-1
    a = jax.random.randint(k1, shape, 0, 1 << 30) % m
    b = jax.random.randint(k2, shape, 0, 1 << 30) % jnp.maximum(m - 1, 1)
    b = jnp.where(b >= a, b + 1, b)  # distinct
    lo = jnp.minimum(a, b) + 1
    hi = jnp.maximum(a, b) + 1
    # one interior cut: segment [1, n) (pymoo's n_var padding)
    lo = jnp.where(m == 1, jnp.where(n == 2, 1, 0), lo)
    hi = jnp.where(m == 1, jnp.where(n == 2, n, 0), hi)
    return lo, hi


def two_point_crossover(
    key: jax.Array,
    tables: OperatorTables,
    p1: jnp.ndarray,
    p2: jnp.ndarray,
    prob: float = 0.9,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Mixed-variable two-point crossover.

    ``p1``/``p2``: (..., n_matings, L). Cut points AND the ``prob`` coin are
    drawn independently per type sub-vector (pymoo MixedVariableCrossover
    runs each sub-crossover's own ``do`` with its own prob gate).
    """
    batch = p1.shape[:-1]
    k_coin_r, k_coin_i, k_coin_s, k_real, k_int, k_sm = jax.random.split(key, 6)

    lo_r, hi_r = _two_cuts(k_real, tables.type_sizes[0], batch)
    lo_i, hi_i = _two_cuts(k_int, tables.type_sizes[1], batch)
    lo_s, hi_s = _two_cuts(k_sm, tables.type_sizes[2], batch)
    do_r = jax.random.uniform(k_coin_r, batch) < prob
    do_i = jax.random.uniform(k_coin_i, batch) < prob
    do_s = jax.random.uniform(k_coin_s, batch) < prob

    is_real = tables.type_id == 0
    is_int = tables.type_id == 1
    pick = lambda r, i, s: jnp.where(
        is_real, r[..., None], jnp.where(is_int, i[..., None], s[..., None])
    )
    lo = pick(lo_r, lo_i, lo_s)
    hi = pick(hi_r, hi_i, hi_s)
    do = pick(do_r, do_i, do_s)
    swap = (tables.rank_in_type >= lo) & (tables.rank_in_type < hi) & do
    c1 = jnp.where(swap, p2, p1)
    c2 = jnp.where(swap, p1, p2)
    if tables.has_softmax:
        # crossed matings re-project onto the simplex (softmax_crossover.py:40);
        # un-crossed matings are verbatim parent copies in pymoo and skip it
        rows = do_s[..., None]
        c1 = softmax_renorm(tables.softmax_mask, c1, rows)
        c2 = softmax_renorm(tables.softmax_mask, c2, rows)
    return c1, c2


def polynomial_mutation(
    key: jax.Array,
    tables: OperatorTables,
    x: jnp.ndarray,
    xl: jnp.ndarray,
    xu: jnp.ndarray,
    eta: float = 20.0,
) -> jnp.ndarray:
    """Polynomial mutation (Deb & Goyal), vectorised over all leading axes.

    Matches pymoo's ``PolynomialMutation`` update rule; integer genes run on
    ±0.5-extended bounds and are rounded afterwards. Genes mutate with the
    per-type probability in ``tables.mut_prob``; zero-range genes are left
    untouched. Results are clipped to the true bounds.
    """
    k_sel, k_u = jax.random.split(key)
    ext = jnp.where(tables.int_mask, 0.5 - 1e-16, 0.0)
    exl = xl - ext
    exu = xu + ext
    rng = exu - exl
    ok = rng > 0
    safe_rng = jnp.where(ok, rng, 1.0)

    u = jax.random.uniform(k_u, x.shape, dtype=x.dtype)
    d1 = (x - exl) / safe_rng
    d2 = (exu - x) / safe_rng
    mut_pow = 1.0 / (eta + 1.0)

    lower = u <= 0.5
    xy = jnp.where(lower, 1.0 - d1, 1.0 - d2)
    xy = jnp.clip(xy, 0.0, 1.0)
    val = jnp.where(
        lower,
        2.0 * u + (1.0 - 2.0 * u) * xy ** (eta + 1.0),
        2.0 * (1.0 - u) + 2.0 * (u - 0.5) * xy ** (eta + 1.0),
    )
    deltaq = jnp.where(
        lower,
        jnp.clip(val, 0.0, None) ** mut_pow - 1.0,
        1.0 - jnp.clip(val, 0.0, None) ** mut_pow,
    )

    do = (jax.random.uniform(k_sel, x.shape, dtype=x.dtype) < tables.mut_prob) & ok
    y = jnp.where(do, x + deltaq * safe_rng, x)
    y = jnp.where(tables.int_mask, jnp.round(y), y)
    y = jnp.clip(y, xl, xu)
    if tables.has_softmax:
        # every row re-projects onto the simplex (softmax_mutation.py:69
        # applies softmax(Y) unconditionally after the bounds repair)
        y = softmax_renorm(tables.softmax_mask, y)
    return y


def make_offspring(
    key: jax.Array,
    tables: OperatorTables,
    pop_x: jnp.ndarray,  # (P, L)
    xl: jnp.ndarray,
    xu: jnp.ndarray,
    n_offsprings: int,
    crossover_prob: float = 0.9,
    eta_mutation: float = 20.0,
) -> jnp.ndarray:
    """One mating round for a single state: selection → crossover → mutation.

    Returns (n_offsprings, L). vmap over the states axis for the batched
    engine.
    """
    n_matings = (n_offsprings + 1) // 2
    k_sel, k_cx, k_mut = jax.random.split(key, 3)
    pairs = select_parent_pairs(k_sel, n_matings, pop_x.shape[0])
    p1 = pop_x[pairs[:, 0]]
    p2 = pop_x[pairs[:, 1]]
    c1, c2 = two_point_crossover(k_cx, tables, p1, p2, prob=crossover_prob)
    children = jnp.concatenate([c1, c2], axis=0)[:n_offsprings]
    return polynomial_mutation(k_mut, tables, children, xl, xu, eta=eta_mutation)
