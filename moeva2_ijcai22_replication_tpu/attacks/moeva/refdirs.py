"""Reference-direction generation for the many-objective survival.

The reference builds its survival geometry from ``get_reference_directions(
"energy", n_obj, n_pop, seed=1)`` — Riesz s-energy-minimising points on the
unit simplex (Blank & Deb 2019) — passed as the *aspiration points* of
R-NSGA-III (``/root/reference/src/attacks/moeva2/moeva2.py:113-124``).

TPU-first design: the s-energy layout is itself a differentiable optimisation,
so we run it as a jitted optax Adam loop over softmax-parameterised simplex
points instead of porting a CPU solver. Exact point-level parity with pymoo is
neither possible (different RNG) nor needed — what survival consumes is a
well-spaced simplex covering, and parity is defined statistically (SURVEY §7).
"""

from __future__ import annotations

from functools import lru_cache
from itertools import combinations_with_replacement

import jax
import jax.numpy as jnp
import numpy as np
import optax


def das_dennis(n_dim: int, n_points: int) -> np.ndarray:
    """Das-Dennis simplex lattice with the largest partition count whose size
    does not exceed ``n_points`` (pymoo's UniformReferenceDirectionFactory
    contract for small ``n_points``; n_points=1 -> the centroid)."""
    if n_points <= 1:
        return np.full((1, n_dim), 1.0 / n_dim)
    n_part = 1
    while _dd_size(n_dim, n_part + 1) <= n_points:
        n_part += 1
    pts = [
        np.array(c, dtype=float)
        for c in _dd_compositions(n_dim, n_part)
    ]
    return np.array(pts) / n_part


def _dd_size(n_dim: int, n_part: int) -> int:
    from math import comb

    return comb(n_dim + n_part - 1, n_part)


def _dd_compositions(n_dim: int, n_part: int):
    for bars in combinations_with_replacement(range(n_part + 1), n_dim - 1):
        prev = 0
        comp = []
        for b in bars:
            comp.append(b - prev)
            prev = b
        comp.append(n_part - prev)
        yield comp


def _riesz_energy(z: jnp.ndarray, s: float) -> jnp.ndarray:
    diff = z[:, None, :] - z[None, :, :]
    d2 = (diff * diff).sum(-1)
    n = z.shape[0]
    off = ~jnp.eye(n, dtype=bool)
    d2 = jnp.where(off, d2, 1.0)
    return jnp.where(off, d2 ** (-s / 2.0), 0.0).sum()


@lru_cache(maxsize=32)
def energy_ref_dirs(
    n_dim: int, n_points: int, seed: int = 1, n_iter: int = 3000
) -> np.ndarray:
    """Riesz s-energy reference directions on the unit simplex.

    Points are softmax-parameterised so simplex membership holds by
    construction and the whole loop jit-compiles. s = n_dim + 1 with a
    cosine-decayed Adam gives nearest-neighbour distance ratios of 0.6-0.9
    for the population sizes the configs use (10-640) — a well-spaced
    covering, which is all survival consumes.
    """
    if n_points == 1:
        return np.full((1, n_dim), 1.0 / n_dim)
    s = float(n_dim + 1)
    key = jax.random.PRNGKey(seed)
    # Dirichlet-ish init: log of uniform simplex samples.
    init = jax.random.dirichlet(key, jnp.ones((n_dim,)), (n_points,))
    theta0 = jnp.log(jnp.clip(init, 1e-6, 1.0))

    opt = optax.adam(optax.cosine_decay_schedule(5e-2, n_iter))

    def loss(theta):
        return _riesz_energy(jax.nn.softmax(theta, axis=-1), s)

    @jax.jit
    def run(theta):
        state = opt.init(theta)

        def body(carry, _):
            theta, state = carry
            g = jax.grad(loss)(theta)
            updates, state = opt.update(g, state)
            return (optax.apply_updates(theta, updates), state), None

        (theta, _), _ = jax.lax.scan(body, (theta, state), None, length=n_iter)
        return jax.nn.softmax(theta, axis=-1)

    return np.asarray(jax.device_get(run(theta0)), dtype=np.float64)


def aspiration_ref_dirs(
    ref_points: np.ndarray, pop_per_ref_point: int = 1, mu: float = 0.1
) -> np.ndarray:
    """R-NSGA-III survival reference directions from aspiration points.

    Semantics of pymoo 0.4.2.2 ``get_ref_dirs_from_points``
    (`rnsga3.py`, via ``moeva2.py:118-124``): per aspiration point, a
    mu-shrunk Das-Dennis cluster re-centred on the central projection of the
    point onto the unit-simplex hyperplane (clipped to the first octant and
    re-normalised if it leaves it), plus the n_obj extreme axes. With
    ``pop_per_ref_point=1`` each cluster degenerates to the projection itself.
    """
    n_obj = ref_points.shape[1]
    base = das_dennis(n_obj, pop_per_ref_point)  # (K, n_obj)
    shrunk = mu * base
    cent = shrunk.mean(axis=0)

    out = []
    for p in ref_points:
        # Central projection of p onto the plane sum(z) = 1 through the origin.
        denom = p.sum()
        intercept = p / np.where(denom == 0, 1.0, denom)
        cluster = shrunk + (intercept - cent)
        if (cluster <= 0).any():
            cluster = np.clip(cluster, 0.0, None)
            cluster = cluster / cluster.sum(axis=1, keepdims=True)
        out.append(cluster)
    out.append(np.eye(n_obj))
    return np.concatenate(out, axis=0)


def rnsga3_geometry(n_obj: int, n_pop: int, pop_per_ref_point: int = 1, mu: float = 0.1, seed: int = 1):
    """(ref_dirs, pop_size) exactly as the reference's RNSGA3 construction:
    pop_size = n_ref_points * pop_per_ref_point + n_obj."""
    ref_points = energy_ref_dirs(n_obj, n_pop, seed=seed)
    dirs = aspiration_ref_dirs(ref_points, pop_per_ref_point, mu)
    k = das_dennis(n_obj, pop_per_ref_point).shape[0]
    pop_size = ref_points.shape[0] * k + n_obj
    return dirs, pop_size
