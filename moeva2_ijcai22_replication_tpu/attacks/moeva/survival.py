"""Aspiration-point (R-NSGA-III) survival as a fully on-device kernel.

Semantics follow pymoo 0.4.2.2's ``AspirationPointSurvival`` (the algorithm
the reference instantiates at ``/root/reference/src/attacks/moeva2/moeva2.py:
113-124``): persistent ideal/worst points, ASF extreme points, hyperplane
nadir with fallbacks, per-generation re-normalised aspiration reference
directions (+ the n_obj extreme axes), perpendicular-distance niche
association, and min-niche-count filling of the splitting front.

TPU-first formulation: the whole survival — non-dominated peeling,
normalisation state, association, and the niching fill — is static-shaped
jnp with boolean masks, one state per batch row, so it vmaps over thousands
of independent initial states and lives inside the jitted generation scan.
The selection loop runs ``n_survive`` masked iterations of pure argmin/where
updates (the only inherently sequential part of the algorithm).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .nds import _MXU_COUNTS, nd_ranks

_BIG = 1e16


def _rowsum(mask: jnp.ndarray) -> jnp.ndarray:
    """Exact int32 row sums of a boolean matrix. MXU mode: bf16 0/1 operands
    with f32 accumulation are exact for counts < 2^24, and the (M, M)·(M,)
    contraction rides the systolic array; VPU mode: a plain masked sum."""
    if not _MXU_COUNTS:
        return mask.sum(-1).astype(jnp.int32)
    one = jnp.ones((mask.shape[-1],), jnp.bfloat16)
    return jnp.matmul(
        mask.astype(jnp.bfloat16), one, preferred_element_type=jnp.float32
    ).astype(jnp.int32)


class NormState(NamedTuple):
    """Per-state normalisation memory carried across generations."""

    ideal: jnp.ndarray  # (n_obj,)
    worst: jnp.ndarray  # (n_obj,)
    extreme: jnp.ndarray  # (n_obj, n_obj) — ASF extreme points

    @classmethod
    def init(cls, n_obj: int, dtype=jnp.float32) -> "NormState":
        return cls(
            ideal=jnp.full((n_obj,), jnp.inf, dtype),
            worst=jnp.full((n_obj,), -jnp.inf, dtype),
            # Sentinel rows with huge ASF: never win the argmin on first use.
            extreme=jnp.full((n_obj, n_obj), _BIG, dtype),
        )


def _update_extreme_points(f, nd_mask, ideal, extreme, asp_points):
    """ASF-minimising extreme points, previous extremes kept as candidates.

    pymoo ``get_extreme_points_c`` as called by ``AspirationPointSurvival``:
    candidates are [previous extremes, non-dominated front, aspiration
    points] in that order (ties resolve to the earlier row, argmin
    semantics); weights are eye with 1e6 off-axis; values below 1e-3 above
    the ideal point are snapped to 0.
    """
    n_obj = f.shape[-1]
    w = jnp.where(jnp.eye(n_obj, dtype=bool), 1.0, 1e6)
    cand = jnp.concatenate(
        [extreme, jnp.where(nd_mask[:, None], f, _BIG), asp_points], axis=0
    )  # (n_obj + M + A, n_obj)
    shifted = cand - ideal
    shifted = jnp.where(shifted < 1e-3, 0.0, shifted)
    asf = (shifted[None, :, :] * w[:, None, :]).max(-1)  # (n_obj, n_obj+M+A)
    idx = jnp.argmin(asf, axis=1)
    return cand[idx]


def _solve3(m, b):
    """3×3 solve by Cramer's rule (adjugate/determinant): one fused batch of
    multiplies instead of vmapped pivoted LU — the latter dominates survival
    wall-clock on TPU for thousands of tiny systems. det=0 yields inf/nan,
    which the caller's fallback chain already handles."""
    det = (
        m[0, 0] * (m[1, 1] * m[2, 2] - m[1, 2] * m[2, 1])
        - m[0, 1] * (m[1, 0] * m[2, 2] - m[1, 2] * m[2, 0])
        + m[0, 2] * (m[1, 0] * m[2, 1] - m[1, 1] * m[2, 0])
    )
    adj = jnp.array(
        [
            [
                m[1, 1] * m[2, 2] - m[1, 2] * m[2, 1],
                m[0, 2] * m[2, 1] - m[0, 1] * m[2, 2],
                m[0, 1] * m[1, 2] - m[0, 2] * m[1, 1],
            ],
            [
                m[1, 2] * m[2, 0] - m[1, 0] * m[2, 2],
                m[0, 0] * m[2, 2] - m[0, 2] * m[2, 0],
                m[0, 2] * m[1, 0] - m[0, 0] * m[1, 2],
            ],
            [
                m[1, 0] * m[2, 1] - m[1, 1] * m[2, 0],
                m[0, 1] * m[2, 0] - m[0, 0] * m[2, 1],
                m[0, 0] * m[1, 1] - m[0, 1] * m[1, 0],
            ],
        ]
    )
    return (adj @ b) / det


def _nadir_point(extreme, ideal, worst, worst_of_front, worst_of_pop):
    """Hyperplane intercepts with pymoo's fallback chain.

    On a successful solve the nadir is *clamped elementwise* to the running
    worst point (pymoo's "NOTE: different to the proposed version in the
    paper" branch); only a failed solve (singular / inconsistent / tiny
    intercepts) falls back to worst-of-front, and a degenerate range falls
    back per-axis to worst-of-population.
    """
    n_obj = extreme.shape[0]
    m = extreme - ideal
    b = jnp.ones((n_obj,), m.dtype)
    plane = _solve3(m, b) if n_obj == 3 else jnp.linalg.solve(m, b)
    intercepts = 1.0 / plane
    nadir = jnp.minimum(ideal + intercepts, worst)
    ok = (
        jnp.all(jnp.isfinite(plane))
        & jnp.allclose(m @ plane, b, rtol=1e-5, atol=1e-8)
        & jnp.all(intercepts > 1e-6)
        & jnp.all(jnp.isfinite(nadir))
    )
    nadir = jnp.where(ok, nadir, worst_of_front)
    degenerate = (nadir - ideal) <= 1e-6
    return jnp.where(degenerate, worst_of_pop, nadir)


def _unit_ref_dirs(asp_points, ideal, nadir):
    """Per-generation survival directions in normalised objective space:
    central projections of the unit-scaled aspiration points onto the simplex
    plane (octant-clipped), plus the extreme axes."""
    n_obj = asp_points.shape[-1]
    denom = nadir - ideal
    denom = jnp.where(denom == 0, 1e-12, denom)
    unit = (asp_points - ideal) / denom
    s = unit.sum(-1, keepdims=True)
    proj = unit / jnp.where(s == 0, 1.0, s)
    needs_clip = (proj <= 0).any(-1, keepdims=True)
    clipped = jnp.clip(proj, 0.0, None)
    csum = clipped.sum(-1, keepdims=True)
    clipped = clipped / jnp.where(csum == 0, 1.0, csum)
    proj = jnp.where(needs_clip, clipped, proj)
    return jnp.concatenate([proj, jnp.eye(n_obj, dtype=proj.dtype)], axis=0)


def _associate(f, dirs, ideal, nadir):
    """Niche index + perpendicular distance in normalised space (argmax
    proj² — same formulation and tie semantics as :func:`associate_batch`)."""
    denom = nadir - ideal
    denom = jnp.where(denom == 0, 1e-12, denom)
    n = (f - ideal) / denom  # (M, n_obj)
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)  # (R, n_obj)
    proj = n @ d.T  # (M, R)
    p2 = proj * proj
    niche = jnp.argmax(p2, axis=1)
    dist2 = (n * n).sum(-1) - p2[jnp.arange(f.shape[0]), niche]
    return niche, jnp.sqrt(jnp.clip(dist2, 0.0, None))


# -- batched association (the survival hot spot) ----------------------------
# Association materialises (S, M, R) distance tensors; XLA's lowering keeps
# several such temporaries in HBM. The blocked-scan formulation below keeps
# the working set at (S, M, block). A hand-written Pallas kernel for this
# stage was REMOVED as a recorded negative result: it intermittently crashed
# the TPU *worker process* at specific state counts (round-4 bisection:
# 537/538/540/544 states fault repeatably at every n_gen probed, 64/387→392/
# 512/520/1000 run clean — no mod-8, VMEM, scan-length, or invocation-count
# predicate survived probing, and the round-3 "validated shapes" were shown
# to be luck). A ~15% end-to-end win is not worth an unpredictable fault
# that kills the whole experiment and backend; see docs/DESIGN.md §3.

def _associate_blocked(n, d, block=64):
    """Association without the (S, M, R) HBM temporary: scan over direction
    blocks keeping only the running (best proj², argmax) per candidate.

    ``argmin_r dist²`` equals ``argmax_r proj²`` (dist² = |n|² − proj², |n|²
    constant in r), so the scan tracks the maximal squared projection; the
    update keeps the earlier index on exact ties, preserving ``jnp.argmin``'s
    first-index semantics bit for bit. dist at the winner is reconstructed
    as sqrt(|n|² − best proj²) — the same subtraction of the same floats the
    one-shot formulation performs."""
    s, m, k = n.shape
    r = d.shape[1]
    nb = -(-r // block)
    pad = nb * block - r
    d_pad = jnp.pad(d, ((0, 0), (0, pad), (0, 0)))
    d_blocks = d_pad.reshape(s, nb, block, k).transpose(1, 0, 2, 3)
    valid = (jnp.arange(nb * block) < r).reshape(nb, block)

    def body(carry, blk):
        best_p2, best_i = carry
        d_blk, valid_blk, base = blk
        proj = jnp.einsum("smk,sbk->smb", n, d_blk)
        p2 = jnp.where(valid_blk[None, None, :], proj * proj, -jnp.inf)
        i_blk = jnp.argmax(p2, axis=2).astype(jnp.int32)  # first max in block
        p2_blk = jnp.take_along_axis(p2, i_blk[..., None], 2)[..., 0]
        take = p2_blk > best_p2  # strict: earlier blocks win ties
        return (
            jnp.where(take, p2_blk, best_p2),
            jnp.where(take, base + i_blk, best_i),
        ), None

    init = (
        jnp.full((s, m), -jnp.inf, n.dtype),
        jnp.zeros((s, m), jnp.int32),
    )
    bases = jnp.arange(nb, dtype=jnp.int32) * block
    (best_p2, niche), _ = jax.lax.scan(
        body, init, (d_blocks, valid, bases)
    )
    dist2 = (n * n).sum(-1) - best_p2
    return niche, jnp.sqrt(jnp.clip(dist2, 0.0, None))


def associate_batch(f, dirs, ideal, nadir, block=None):
    """Batched niche association over the states axis: every input carries a
    leading (S,) dim. Returns ``(niche (S, M), dist (S, M))``.

    ``block``: use the blocked-scan formulation (peak memory (S, M, block)
    instead of the (S, M, R) projection tensor) — bit-identical to the
    one-shot einsum path: both argmax proj² (dist² = |n|² − proj² with |n|²
    constant in r, so the argmin over dist² IS the argmax over proj², and
    ranking proj² directly also removes the one float-rounding hazard a
    per-direction dist² subtraction would add to tie resolution) and both
    keep the first index on exact proj² ties. Both paths are plain jnp, so
    they partition over a states mesh automatically under pjit (states are
    independent; no collectives)."""
    denom = nadir - ideal
    denom = jnp.where(denom == 0, 1e-12, denom)
    n = (f - ideal[:, None, :]) / denom[:, None, :]
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)
    if block:
        return _associate_blocked(n, d, block=block)
    # Lane-pad the directions axis to the TPU vector lane width: R is
    # arbitrary (n_asp + n_obj), and an unpadded trailing dim forces masked
    # partial-lane reductions (measured ~5% of the whole generation at bench
    # shape). Padded directions are all-zero → proj² = 0, and argmax's
    # first-index tie rule can never pick a pad over a real direction.
    r = d.shape[1]
    pad = -r % 128
    if pad:
        d = jnp.pad(d, ((0, 0), (0, pad), (0, 0)))
    proj = jnp.einsum("smk,srk->smr", n, d)
    p2 = proj * proj
    niche = jnp.argmax(p2, axis=2)
    best = jnp.take_along_axis(p2, niche[..., None], 2)[..., 0]
    dist2 = (n * n).sum(-1) - best
    return niche, jnp.sqrt(jnp.clip(dist2, 0.0, None))


def _niching_fill(gum_cut, gum_mem, ranks, split_rank, niche, dist, niche_count, n_remaining, n_survive):
    """Closed-form niching fill — water-filling instead of a pick loop.

    pymoo's ``niching`` repeatedly gives one slot to every niche at the
    current minimum count (random subset at the final cutoff), taking the
    closest member for an empty niche and a uniformly random member
    otherwise. Incrementing min-count niches level by level is exactly
    *water-filling* of ``n_remaining`` units over niches with initial counts
    ``niche_count`` and capacities = available members, so the per-niche
    quota has a closed form: a fixed 18-step scalar bisection finds the
    integer water level, the cutoff level's partial cohort is a random
    subset, and member selection is a vectorised within-niche ranking
    (closest first for empty niches, Gumbel-random for the rest). Zero
    data-dependent sequential steps — the survival's former ~n_survive
    dependent kernel launches per generation collapse into a handful of
    (M, R)/(M, M) masked matrix ops.
    """
    m = ranks.shape[0]
    r = niche_count.shape[0]
    member = niche[:, None] == jnp.arange(r)[None, :]  # (M, R)
    avail = ranks == split_rank  # (M,)
    member_avail = member & avail[:, None]  # (M, R)
    cap = _rowsum(member_avail.T)  # (R,) members available per niche
    c0 = niche_count

    def filled(level):
        return jnp.clip(level - c0, 0, cap).sum()

    # Largest integer level whose cumulative fill fits the quota.
    def bisect(_, lohi):
        lo, hi = lohi
        mid = (lo + hi + 1) // 2
        ok = filled(mid) <= n_remaining
        return jnp.where(ok, mid, lo), jnp.where(ok, hi, mid - 1)

    level, _ = jax.lax.fori_loop(
        0, 18, bisect, (jnp.int32(0), jnp.int32(m + n_survive + 1))
    )
    quota = jnp.clip(level - c0, 0, cap)  # (R,)

    # Cutoff: the next unit would go to niches sitting exactly at the water
    # level with spare members; pymoo permutes those and keeps the remainder.
    rem = n_remaining - quota.sum()
    elig = (quota < cap) & ((c0 + quota) == level)
    pri = jnp.where(elig, gum_cut, -jnp.inf)
    cut_rank = (pri[None, :] > pri[:, None]).sum(-1)
    quota = quota + (elig & (cut_rank < rem))

    # Within-niche pick order: closest member first when the niche starts
    # empty, then uniformly random members.
    closest = jnp.argmin(
        jnp.where(member_avail, dist[:, None], jnp.inf), axis=0
    )  # (R,)
    is_closest = (
        jnp.zeros((m,), bool).at[closest].max((c0 == 0) & (cap > 0))
    )
    pick_key = jnp.where(is_closest & avail, -jnp.inf, gum_mem)
    same_niche = niche[:, None] == niche[None, :]  # (M, M)
    rank_in_niche = _rowsum(
        same_niche & avail[None, :] & (pick_key[None, :] < pick_key[:, None])
    )
    return avail & (rank_in_niche < quota[niche])


def _survive_pre(f, asp_points, state, n_survive):
    """Per-state phase 1: ranks, normalisation update, survival directions.

    pymoo's ``AspirationPointSurvival`` folds the aspiration points into the
    running ideal/worst updates and the extreme-point candidates (unlike
    plain NSGA-III survival) — diffed against the vendored oracle in
    ``tests/test_survival_pymoo_diff.py``.
    """
    ideal = jnp.minimum(state.ideal, jnp.minimum(f.min(0), asp_points.min(0)))
    worst = jnp.maximum(state.worst, jnp.maximum(f.max(0), asp_points.max(0)))

    # Peel only until n_survive candidates are ranked: fronts beyond the
    # splitting front never survive, and the UNRANKED sentinel on the tail is
    # already "worse than any ranked front" for the cumulative counts below.
    ranks = nd_ranks(f, n_stop=n_survive)
    nd_mask = ranks == 0

    extreme = _update_extreme_points(f, nd_mask, ideal, state.extreme, asp_points)
    worst_of_pop = f.max(0)
    worst_of_front = jnp.where(nd_mask[:, None], f, -jnp.inf).max(0)
    nadir = _nadir_point(extreme, ideal, worst, worst_of_front, worst_of_pop)

    dirs = _unit_ref_dirs(asp_points, ideal, nadir)
    return ranks, dirs, nadir, NormState(ideal=ideal, worst=worst, extreme=extreme)


def _survive_post(gum_cut, gum_mem, f, ranks, niche, dist, n_dirs, n_survive):
    """Per-state phase 2: front filling + niching fill -> survivor mask.

    Front filling: fronts whose cumulative count fits within n_survive
    survive whole; the first front that overflows (if any) is niched.
    Cumulative front sizes as (M, M) comparison matmuls: scatter-add
    histograms are the asymptotically cheaper formulation but lose badly
    to the MXU on TPU at these shapes (measured 2x slower end-to-end).
    A sort-based O(M log M) formulation (sorted ranks + searchsorted for the
    cumulative counts, double stable argsort for the within-niche ranking)
    was also measured bit-identical but ~12x slower at bench shapes — TPU
    sorts are bitonic multi-pass kernels, while the M² comparisons fuse into
    single MXU-friendly reductions. Keep the matmuls.
    """
    m = f.shape[0]
    cum_le = _rowsum(ranks[None, :] <= ranks[:, None])  # per i: #{j: rank_j <= rank_i}
    full_survivor = cum_le <= n_survive  # candidate's whole front fits
    # The splitting front is simply the best-ranked front that did NOT fit
    # whole — min rank over non-survivors (one (M, M) count matmul total; a
    # second cum_lt matmul to flag it is redundant). With an exact
    # front-boundary fit the niching fill is inactive anyway
    # (n_remaining = 0), so the non-survivor min rank is as good as the
    # INT_MAX sentinel; all-survive (init) still yields INT_MAX.
    split_rank = jnp.where(
        full_survivor, jnp.iinfo(jnp.int32).max, ranks
    ).min()

    n_until = full_survivor.sum()
    n_remaining = jnp.maximum(n_survive - n_until, 0)

    member = niche[:, None] == jnp.arange(n_dirs)[None, :]
    niche_count = _rowsum((member & full_survivor[:, None]).T)

    taken = _niching_fill(
        gum_cut, gum_mem, ranks, split_rank, niche, dist, niche_count,
        n_remaining, n_survive,
    )
    return full_survivor | taken


def _niche_gumbels(key: jax.Array, shape_prefix: tuple, n_dirs: int, m: int):
    """The niching fill's two random fields, drawn in two bulk calls: the
    cutoff-cohort priorities (..., R) and the within-niche member priorities
    (..., M). Threefry is a pure function of (key, position), so a global
    draw is identical under any states-mesh partitioning — per-state keys
    would buy nothing but per-state kernel launches."""
    k_cut, k_mem = jax.random.split(key)
    return (
        jax.random.gumbel(k_cut, (*shape_prefix, n_dirs)),
        jax.random.gumbel(k_mem, (*shape_prefix, m)),
    )


def survive(
    key: jax.Array,
    f: jnp.ndarray,  # (M, n_obj) merged objectives
    asp_points: jnp.ndarray,  # (A, n_obj) aspiration (energy) points
    state: NormState,
    n_survive: int,
):
    """One survival round for a single state.

    Returns ``(survive_mask (M,) bool — exactly n_survive True, new_state,
    ranks)``. vmap over the states axis, or use :func:`survive_batch` for the
    engine's batched path (same semantics, selectable association blocking).
    """
    ranks, dirs, nadir, new_state = _survive_pre(f, asp_points, state, n_survive)
    niche, dist = _associate(f, dirs, new_state.ideal, nadir)
    gum_cut, gum_mem = _niche_gumbels(key, (), dirs.shape[0], f.shape[0])
    mask = _survive_post(
        gum_cut, gum_mem, f, ranks, niche, dist, dirs.shape[0], n_survive
    )
    return mask, new_state, ranks


def survive_batch(
    key: jax.Array,  # ONE key for the whole batch (bulk global draws)
    f: jnp.ndarray,  # (S, M, n_obj)
    asp_points: jnp.ndarray,  # (A, n_obj)
    state: NormState,  # batched (S, ...) leaves
    n_survive: int,
    assoc_block: int | None = None,
):
    """Batched survival over the states axis — the same algorithm as
    ``vmap(survive)`` with the batch-level formulation choices lifted out of
    the vmap: association runs as one batched contraction (one-shot einsum or
    blocked scan, ``assoc_block``) and the niching fill's random fields are
    two bulk gumbel draws instead of per-state key chains (measured: the
    per-state threefry chains cost ~1.5 ms/gen at bench shape inside the
    production scan). Everything is plain jnp, so a states-sharded mesh
    partitions it without collectives."""
    ranks, dirs, nadir, new_state = jax.vmap(
        lambda f1, st: _survive_pre(f1, asp_points, st, n_survive)
    )(f, state)
    niche, dist = associate_batch(
        f, dirs, new_state.ideal, nadir, block=assoc_block
    )
    gum_cut, gum_mem = _niche_gumbels(
        key, (f.shape[0],), dirs.shape[1], f.shape[1]
    )
    mask = jax.vmap(
        lambda gc, gm, f1, r1, ni, di: _survive_post(
            gc, gm, f1, r1, ni, di, dirs.shape[1], n_survive
        )
    )(gum_cut, gum_mem, f, ranks, niche, dist)
    return mask, new_state, ranks
