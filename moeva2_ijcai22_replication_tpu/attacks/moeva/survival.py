"""Aspiration-point (R-NSGA-III) survival as a fully on-device kernel.

Semantics follow pymoo 0.4.2.2's ``AspirationPointSurvival`` (the algorithm
the reference instantiates at ``/root/reference/src/attacks/moeva2/moeva2.py:
113-124``): persistent ideal/worst points, ASF extreme points, hyperplane
nadir with fallbacks, per-generation re-normalised aspiration reference
directions (+ the n_obj extreme axes), perpendicular-distance niche
association, and min-niche-count filling of the splitting front.

TPU-first formulation: the whole survival — non-dominated peeling,
normalisation state, association, and the niching fill — is static-shaped
jnp with boolean masks, one state per batch row, so it vmaps over thousands
of independent initial states and lives inside the jitted generation scan.
The selection loop runs ``n_survive`` masked iterations of pure argmin/where
updates (the only inherently sequential part of the algorithm).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .nds import nd_ranks

_BIG = 1e16


class NormState(NamedTuple):
    """Per-state normalisation memory carried across generations."""

    ideal: jnp.ndarray  # (n_obj,)
    worst: jnp.ndarray  # (n_obj,)
    extreme: jnp.ndarray  # (n_obj, n_obj) — ASF extreme points

    @classmethod
    def init(cls, n_obj: int, dtype=jnp.float32) -> "NormState":
        return cls(
            ideal=jnp.full((n_obj,), jnp.inf, dtype),
            worst=jnp.full((n_obj,), -jnp.inf, dtype),
            # Sentinel rows with huge ASF: never win the argmin on first use.
            extreme=jnp.full((n_obj, n_obj), _BIG, dtype),
        )


def _update_extreme_points(f, nd_mask, ideal, extreme):
    """ASF-minimising extreme points, previous extremes kept as candidates.

    pymoo ``get_extreme_points_c``: weights are eye with 1e6 off-axis; values
    below 1e-3 above the ideal point are snapped to 0.
    """
    n_obj = f.shape[-1]
    w = jnp.where(jnp.eye(n_obj, dtype=bool), 1.0, 1e6)
    cand = jnp.concatenate(
        [extreme, jnp.where(nd_mask[:, None], f, _BIG)], axis=0
    )  # (n_obj + M, n_obj)
    shifted = cand - ideal
    shifted = jnp.where(shifted < 1e-3, 0.0, shifted)
    asf = (shifted[None, :, :] * w[:, None, :]).max(-1)  # (n_obj, n_obj+M)
    idx = jnp.argmin(asf, axis=1)
    return cand[idx]


def _nadir_point(extreme, ideal, worst, worst_of_front, worst_of_pop):
    """Hyperplane intercepts with pymoo's fallback chain."""
    n_obj = extreme.shape[0]
    m = extreme - ideal
    b = jnp.ones((n_obj,), m.dtype)
    plane = jnp.linalg.solve(m, b)
    intercepts = 1.0 / plane
    nadir = ideal + intercepts
    ok = (
        jnp.all(jnp.isfinite(plane))
        & jnp.allclose(m @ plane, b, atol=1e-6)
        & jnp.all(intercepts > 1e-6)
        & jnp.all(nadir <= worst + 1e-12)
    )
    nadir = jnp.where(ok, nadir, worst_of_front)
    degenerate = (nadir - ideal) <= 1e-6
    return jnp.where(degenerate, worst_of_pop, nadir)


def _unit_ref_dirs(asp_points, ideal, nadir):
    """Per-generation survival directions in normalised objective space:
    central projections of the unit-scaled aspiration points onto the simplex
    plane (octant-clipped), plus the extreme axes."""
    n_obj = asp_points.shape[-1]
    denom = nadir - ideal
    denom = jnp.where(denom == 0, 1e-12, denom)
    unit = (asp_points - ideal) / denom
    s = unit.sum(-1, keepdims=True)
    proj = unit / jnp.where(s == 0, 1.0, s)
    needs_clip = (proj <= 0).any(-1, keepdims=True)
    clipped = jnp.clip(proj, 0.0, None)
    csum = clipped.sum(-1, keepdims=True)
    clipped = clipped / jnp.where(csum == 0, 1.0, csum)
    proj = jnp.where(needs_clip, clipped, proj)
    return jnp.concatenate([proj, jnp.eye(n_obj, dtype=proj.dtype)], axis=0)


def _associate(f, dirs, ideal, nadir):
    """Niche index + perpendicular distance in normalised space."""
    denom = nadir - ideal
    denom = jnp.where(denom == 0, 1e-12, denom)
    n = (f - ideal) / denom  # (M, n_obj)
    d = dirs / jnp.linalg.norm(dirs, axis=-1, keepdims=True)  # (R, n_obj)
    proj = n @ d.T  # (M, R)
    dist2 = (n * n).sum(-1)[:, None] - proj * proj
    dist = jnp.sqrt(jnp.clip(dist2, 0.0, None))
    niche = jnp.argmin(dist, axis=1)
    return niche, dist[jnp.arange(f.shape[0]), niche]


def _gumbel_argmax(key, logmask):
    return jnp.argmax(logmask + jax.random.gumbel(key, logmask.shape))


def _niching_fill(key, ranks, split_rank, niche, dist, niche_count, n_remaining, n_survive):
    """Fill the splitting front one pick per iteration.

    pymoo's ``niching`` selects whole min-count cohorts per round; picking one
    individual at a time with fresh min-count argmins is the same policy at
    finer granularity (ties broken uniformly via Gumbel noise).
    """
    m = ranks.shape[0]
    r = niche_count.shape[0]
    member = niche[:, None] == jnp.arange(r)[None, :]  # (M, R)

    def body(i, carry):
        taken, niche_count, key = carry
        key, k_niche, k_member = jax.random.split(key, 3)
        active = i < n_remaining

        avail = (ranks == split_rank) & ~taken  # (M,)
        niche_avail = (member & avail[:, None]).any(0)  # (R,)
        counts = jnp.where(niche_avail, niche_count, jnp.inf)
        min_count = counts.min()
        niche_logmask = jnp.where(
            niche_avail & (niche_count == min_count), 0.0, -jnp.inf
        )
        sel_niche = _gumbel_argmax(k_niche, niche_logmask)

        members = avail & (niche == sel_niche)
        empty_niche = niche_count[sel_niche] == 0
        by_dist = jnp.where(members, dist, jnp.inf)
        closest = jnp.argmin(by_dist)
        random_pick = _gumbel_argmax(
            k_member, jnp.where(members, 0.0, -jnp.inf)
        )
        pick = jnp.where(empty_niche, closest, random_pick)

        taken = taken.at[pick].set(taken[pick] | active)
        niche_count = niche_count.at[sel_niche].add(
            jnp.where(active, 1, 0)
        )
        return taken, niche_count, key

    taken0 = jnp.zeros((m,), bool)
    taken, _, _ = jax.lax.fori_loop(0, n_survive, body, (taken0, niche_count, key))
    return taken


def survive(
    key: jax.Array,
    f: jnp.ndarray,  # (M, n_obj) merged objectives
    asp_points: jnp.ndarray,  # (A, n_obj) aspiration (energy) points
    state: NormState,
    n_survive: int,
):
    """One survival round for a single state.

    Returns ``(survive_mask (M,) bool — exactly n_survive True, new_state,
    ranks)``. vmap over the states axis.
    """
    ideal = jnp.minimum(state.ideal, f.min(0))
    worst = jnp.maximum(state.worst, f.max(0))

    ranks = nd_ranks(f)
    nd_mask = ranks == 0

    extreme = _update_extreme_points(f, nd_mask, ideal, state.extreme)
    worst_of_pop = f.max(0)
    worst_of_front = jnp.where(nd_mask[:, None], f, -jnp.inf).max(0)
    nadir = _nadir_point(extreme, ideal, worst, worst_of_front, worst_of_pop)

    dirs = _unit_ref_dirs(asp_points, ideal, nadir)
    niche, dist = _associate(f, dirs, ideal, nadir)

    #

    # Front filling: fronts whose cumulative count fits within n_survive
    # survive whole; the first front that overflows (if any) is niched.
    m = f.shape[0]
    one = jnp.ones((m,), jnp.int32)
    cum_le = (ranks[None, :] <= ranks[:, None]).astype(jnp.int32) @ one  # per i: #{j: rank_j <= rank_i}
    cum_lt = (ranks[None, :] < ranks[:, None]).astype(jnp.int32) @ one
    full_survivor = cum_le <= n_survive  # candidate's whole front fits
    is_split = (cum_lt < n_survive) & ~full_survivor  # candidate's front splits
    # With an exact front-boundary fit there is no splitting front:
    # split_rank = INT_MAX keeps the niching fill inactive (n_remaining = 0).
    split_rank = jnp.where(
        is_split.any(), ranks[jnp.argmax(is_split)], jnp.iinfo(jnp.int32).max
    )

    n_until = full_survivor.sum()
    n_remaining = jnp.maximum(n_survive - n_until, 0)

    r = dirs.shape[0]
    member = niche[:, None] == jnp.arange(r)[None, :]
    niche_count = (member & full_survivor[:, None]).sum(0)

    taken = _niching_fill(
        key, ranks, split_rank, niche, dist, niche_count, n_remaining, n_survive
    )
    mask = full_survivor | taken
    return mask, NormState(ideal=ideal, worst=worst, extreme=extreme), ranks
