"""Post-hoc attack evaluation: the paper's o1..o7 success metrics.

Reference parity (``/root/reference/src/attacks/moeva2/objective_calculator.py``):

- per candidate: ``[constraint_violation, f1, f2]`` where constraint_violation
  sums the domain violations plus the one-hot distance over ALL OHE groups
  (``:44-57``; ``moeva2/utils.py:43-54``), f1 = P(minimize_class), f2 = the
  *unscaled* Lp distance in min-max-scaled feature space (``:59-82``);
- o1..o7 = C, M, D, C∧M, C∧D, M∧D, C∧M∧D against thresholds
  {f1: misclassification, f2: ε} (``:86-100``);
- ``success_rate_3d``: fraction of initial states with ≥1 qualifying candidate
  in their population, per column (``:106-119``);
- ``get_successful_attacks``: best successful candidate(s) per state sorted by
  misclassification or distance (``:150-223``) — feeds adversarial retraining.

TPU-first: the whole (states x population) tensor is evaluated as one jitted
program with a single device→host reduction, instead of the reference's
per-state Python loop over joblib threads.

Precision: success judgement runs in float64 on the host CPU backend
(``precise=True``, the default). The reference evaluates with numpy float64;
at botnet scale the global sum-equality constraints add ~90 features of
magnitude up to ~6e9, where one float32 ulp is 512 — an accelerator f32
evaluation flags exact (f64-verified) MILP repairs as violating by exactly
that ulp. The attack hot loops stay f32 on device; only this post-hoc metric
needs oracle precision.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..core.codec import all_ohe_groups_distance, full_ohe_tables
from ..core.constraints import ConstraintSet
from ..core.norms import lp_distance, validate_norm
from ..models.io import Surrogate
from ..models.scalers import MinMaxParams

O_COLUMNS = ("o1", "o2", "o3", "o4", "o5", "o6", "o7")

#: column layout of :func:`engine_quality_stats` — the single source of
#: truth for every consumer (engine gate program, host aggregation,
#: serving gauges): the seven "state holds ≥1 qualifying candidate"
#: booleans, then the state's best (minimum) summed constraint violation,
#: then the best engine-objective distance among misclassified ∧ feasible
#: candidates (+inf when the state has none yet).
QUALITY_STAT_COLUMNS = O_COLUMNS + ("best_cv", "best_dist")


def engine_quality_stats(f, threshold, eps, xp=jnp):
    """Per-state convergence-quality statistics from *engine-space*
    objective columns ``f`` (..., P, 3) = ``[f1, f2, g]`` (misclassification
    probability, scaled Lp distance, summed violations — the MoEvA carry
    layout). Returns (..., 9) per :data:`QUALITY_STAT_COLUMNS`.

    The C/M/D semantics mirror :meth:`ObjectiveCalculator.respected`
    (C = Σ violations ≤ 0, M = f1 < threshold, D = f2 ≤ eps) but are judged
    on the engine's own objectives — per-state normalisation, engine dtype
    — not the post-hoc f64 oracle judgement; consumers label the numbers
    ``judged: "engine"`` accordingly. ``xp`` selects the backend: ``jnp``
    inside the jitted gate program, ``np`` for host-side samples computed
    from already-fetched arrays (zero extra device work) — one formula,
    both sides, so curves and final samples can never drift apart.
    """
    c = f[..., 2] <= 0.0
    m = f[..., 0] < threshold
    d = f[..., 1] <= eps
    cols = (c, m, d, c & m, c & d, m & d, c & m & d)
    o = [col.any(axis=-1).astype(f.dtype) for col in cols]
    best_cv = f[..., 2].min(axis=-1)
    best_dist = xp.where(c & m, f[..., 1], xp.inf).min(axis=-1)
    return xp.stack([*o, best_cv, best_dist], axis=-1)


@dataclass
class ObjectiveCalculator:
    classifier: Surrogate
    constraints: ConstraintSet
    thresholds: dict  # {"f1": misclassification threshold, "f2": eps}
    min_max_scaler: MinMaxParams
    minimize_class: int = 1
    norm: Any = np.inf
    ml_scaler: MinMaxParams | None = None
    #: evaluate in float64 on the host CPU backend (reference = numpy f64);
    #: False keeps the session's default device/precision.
    precise: bool = True

    def __post_init__(self):
        validate_norm(self.norm)
        self._ohe_idx, self._ohe_mask = full_ohe_tables(self.constraints.schema)
        self._jit_objectives = jax.jit(self._objectives)
        self._params_f64 = None  # lazy f64 host copy of the classifier params

    # -- kernels ------------------------------------------------------------
    def _objectives(self, params, x_initial, x_f):
        """``x_initial`` (..., D), ``x_f`` (..., P, D) -> (..., P, 3)
        columns [constraint_violation, f1, f2]."""
        g = self.constraints.evaluate(x_f)  # already clipped at 0
        ohe = all_ohe_groups_distance(self._ohe_idx, self._ohe_mask, x_f)
        cv = g.sum(-1) + ohe

        x_ml = self.ml_scaler.transform(x_f) if self.ml_scaler is not None else x_f
        probs = Surrogate(self.classifier.model, params).predict_proba(x_ml)
        f1 = probs[..., self.minimize_class]

        xi = self.min_max_scaler.transform(x_initial)[..., None, :]
        xs = self.min_max_scaler.transform(x_f)
        f2 = lp_distance(xi - xs, self.norm)
        # scalar range stats only — the host assert must not pull the full
        # scaled tensors off device
        range_lo = jnp.minimum(xi.min(), xs.min())
        range_hi = jnp.maximum(xi.max(), xs.max())
        return jnp.stack([cv, f1, f2], axis=-1), (range_lo, range_hi)

    def objectives(self, x_initial: np.ndarray, x_f: np.ndarray) -> np.ndarray:
        """[cv, f1, f2] per candidate; scaling-range asserts mirror
        ``objective_calculator.py:72-76``."""
        if self.precise:
            import contextlib
            import warnings

            if self._params_f64 is None:
                self._params_f64 = jax.tree.map(
                    lambda a: np.asarray(a, np.float64), self.classifier.params
                )
            from jax.experimental import enable_x64

            with contextlib.ExitStack() as stack:
                # jax.experimental is the stable home of the context manager
                # across the jax versions this repo runs on (0.4.x has no
                # top-level jax.enable_x64)
                stack.enter_context(enable_x64(True))
                try:
                    stack.enter_context(jax.default_device(jax.devices("cpu")[0]))
                except RuntimeError:
                    warnings.warn(
                        "precise=True but no CPU backend is registered: the "
                        "f64 judgement runs on the default accelerator, which "
                        "may not support native float64"
                    )
                vals, (lo, hi) = self._jit_objectives(
                    self._params_f64,
                    np.asarray(x_initial, np.float64),
                    np.asarray(x_f, np.float64),
                )
        else:
            vals, (lo, hi) = self._jit_objectives(
                self.classifier.params, jnp.asarray(x_initial), jnp.asarray(x_f)
            )
        tol = 1e-4
        if not (float(lo) >= -tol and float(hi) <= 1 + tol):
            raise AssertionError(
                "min-max scaled values outside [0,1]: wrong scaler for this data?"
            )
        return np.asarray(vals)

    def respected(self, objective_values: np.ndarray) -> np.ndarray:
        """o1..o7 booleans from [cv, f1, f2] (parity ``:86-100``)."""
        c = objective_values[..., 0] <= 0
        m = objective_values[..., 1] < self.thresholds["f1"]
        d = objective_values[..., 2] <= self.thresholds["f2"]
        return np.stack([c, m, d, c & m, c & d, m & d, c & m & d], axis=-1)

    # -- success rates ------------------------------------------------------
    def success_rate(self, x_initial: np.ndarray, x_f: np.ndarray) -> np.ndarray:
        """Mean of each o-column over one state's population (``:102-104``)."""
        return self.respected(self.objectives(x_initial, x_f)).mean(axis=-2)

    def at_least_one(self, x_initial, x_f) -> np.ndarray:
        return self.success_rate(x_initial, x_f) > 0

    def success_rate_3d(
        self, x_initial: np.ndarray, x: np.ndarray, objective_values=None
    ) -> np.ndarray:
        """(7,) fraction of states with ≥1 qualifying candidate (``:106-119``).

        ``objective_values`` reuses a prior :meth:`objectives` result —
        thresholds only enter :meth:`respected`, so ε sweeps over the same
        candidates need the expensive evaluation once.
        """
        if objective_values is None:
            objective_values = self.objectives(np.asarray(x_initial), np.asarray(x))
        o = self.respected(objective_values)
        return o.any(axis=1).mean(axis=0)

    def success_rate_3d_df(self, x_initial, x, objective_values=None):
        import pandas as pd

        rates = self.success_rate_3d(x_initial, x, objective_values)
        return pd.DataFrame(rates.reshape(1, -1), columns=list(O_COLUMNS))

    # -- successful-attack extraction ---------------------------------------
    def get_successful_attacks(
        self,
        x_initials: np.ndarray,  # (S, D)
        x_generated: np.ndarray,  # (S, P, D)
        preferred_metrics: str = "misclassification",
        order: str = "asc",
        max_inputs: int = -1,
        return_index_success: bool = False,
    ):
        """Best o7-successful candidates per state, sorted by the preferred
        metric (parity ``:150-223``; the reference caps to 1 whenever
        max_inputs > -1 — here max_inputs is honoured as a true cap).
        """
        metric_col = {"misclassification": 1, "distance": 2}[preferred_metrics]
        vals = self.objectives(np.asarray(x_initials), np.asarray(x_generated))
        ok = self.respected(vals)[..., -1]  # (S, P) o7

        out, index_success = [], []
        for i in range(vals.shape[0]):
            idx = np.argsort(vals[i, :, metric_col], kind="stable")
            if order == "desc":
                idx = idx[::-1]
            idx = idx[ok[i, idx]]
            if max_inputs > -1:
                idx = idx[:max_inputs]
            out.append(np.asarray(x_generated)[i, idx])
            index_success.append(len(idx) >= 1)
        successful = np.concatenate(out, axis=0)
        if return_index_success:
            return successful, np.array(index_success)
        return successful
