from .engine import ConstrainedPGD, round_ints_toward_initial
from .autopgd import AutoPGD

__all__ = ["ConstrainedPGD", "AutoPGD", "round_ints_toward_initial"]
