"""Auto-PGD (Croce & Hein 2020) with the constrained combined loss.

Capability parity with the reference's vendored ART AutoPGD
(``/root/reference/src/attacks/pgd/auto_pgd.py:45-615``): checkpoint schedule
p_{j+1} = p_j + max(p_j - p_{j-1} - 0.03, 0.06), per-sample step halving when
the objective stops improving (rho = 0.75) or when both step and best loss
stagnate, restart from the best point, and momentum iterates with alpha=0.75.
The loss, schedules, and random restarts are inherited from
:class:`ConstrainedPGD` (the reference wires its TF2Classifier into
AutoPGD the same way — ``auto_pgd.py:262-277``).

TPU-first: one ``lax.fori_loop`` carrying (x, x_prev, x_best, f_best, eta,
counters); checkpoint membership is a precomputed static mask, so there is
no Python control flow in the compiled loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .engine import ConstrainedPGD
from ...core.norms import condition_grad, project_ball


def checkpoint_schedule(max_iter: int) -> np.ndarray:
    """Checkpoint iteration indices (AutoPGD paper / ART ``auto_pgd.py:447-457``)."""
    p = [0.0, 0.22]
    while p[-1] < 1.0:
        p.append(p[-1] + max(p[-1] - p[-2] - 0.03, 0.06))
    w = sorted({int(np.ceil(pj * max_iter)) for pj in p if pj <= 1.0})
    return np.array(w, dtype=np.int64)


@dataclass
class AutoPGD(ConstrainedPGD):
    """AutoPGD over the same constrained loss surface as ConstrainedPGD."""

    alpha_momentum: float = 0.75
    rho: float = 0.75

    def _one_run(self, params, x_init, y, x_start, eps, eps_step, max_iter):
        # max_iter is trace-static here (generate guards it equal to
        # self.max_iter): the checkpoint masks below are precomputed numpy
        n = x_init.shape[0]
        ckpts = checkpoint_schedule(self.max_iter)
        is_ckpt = np.zeros(self.max_iter + 1, dtype=bool)
        is_ckpt[ckpts[ckpts <= self.max_iter]] = True
        # interval length since previous checkpoint, for the rho condition
        interval = np.ones(self.max_iter + 1, dtype=np.float32)
        prev = 0
        for c in ckpts:
            if c <= self.max_iter:
                interval[c] = max(c - prev, 1)
                prev = c
        is_ckpt_d = jnp.asarray(is_ckpt)
        interval_d = jnp.asarray(interval)

        # Iteration-independent objective for x_best/step-halving:
        # phase-switching strategies produce incommensurable per-iteration
        # losses, so best-point tracking uses static weights (the reference's
        # ``compute_loss`` line-search mirror likewise has no iteration
        # argument — ``classifier.py:334-412``).
        tw_class, tw_cons = self._static_loss_weights()

        def tracking_loss(x):
            loss_class, cons = self._loss_terms(params, x, y, jnp.int32(0))
            return tw_class * loss_class + tw_cons * (-cons)

        def step_to(x, grad, eta):
            z = x + eta[:, None] * grad
            z = jnp.clip(z, *self.clip)
            z = x_init + project_ball(z - x_init, eps, self.norm)
            return jnp.clip(z, *self.clip)

        f0 = tracking_loss(x_start)
        # effective reference init: auto_pgd.py:441's 2*eps_step is dead,
        # overwritten by eps_step at :459 before the loop
        eta0 = jnp.full((n,), eps_step, x_init.dtype)

        carry0 = dict(
            x=x_start,
            x_prev=x_start,
            x_best=x_start,
            f_best=f0,
            f_prev=f0,
            eta=eta0,
            eta_prev_ckpt=eta0,
            fbest_prev_ckpt=f0,
            improved=jnp.zeros((n,), jnp.float32),
            hist=self._hist_init(n, x_init.dtype),
        )

        def body(i, c):
            grad, per, loss_class, cons, g = self._grad_and_terms(
                params, c["x"], y, i, self.max_iter
            )
            hist = (
                self._hist_record(c["hist"], i, per, loss_class, cons, g, grad)
                if self.record_loss
                else c["hist"]
            )
            grad = jnp.where(jnp.isnan(grad), 0.0, grad)
            grad = jnp.where(self._mutable, grad, 0.0)
            grad = condition_grad(grad, self.norm)

            z = step_to(c["x"], grad, c["eta"])
            alpha = jnp.where(i == 0, 1.0, self.alpha_momentum)
            x_new = c["x"] + alpha * (z - c["x"]) + (1 - alpha) * (
                c["x"] - c["x_prev"]
            )
            x_new = jnp.clip(x_new, *self.clip)
            x_new = x_init + project_ball(x_new - x_init, eps, self.norm)
            x_new = jnp.clip(x_new, *self.clip)
            if "repair" in self.loss_evaluation:
                x_new = jnp.where(
                    self._mutable, self._repair(x_new).astype(x_new.dtype), x_new
                )

            f_new = tracking_loss(x_new)
            improved = c["improved"] + (f_new > c["f_prev"])
            better = f_new > c["f_best"]
            x_best = jnp.where(better[:, None], x_new, c["x_best"])
            f_best = jnp.where(better, f_new, c["f_best"])

            # checkpoint: halve eta where progress stalled, restart at best
            at_ckpt = is_ckpt_d[i + 1]
            cond1 = improved < self.rho * interval_d[i + 1]
            cond2 = (c["eta_prev_ckpt"] == c["eta"]) & (
                c["fbest_prev_ckpt"] == f_best
            )
            halve = at_ckpt & (cond1 | cond2)
            eta = jnp.where(halve, c["eta"] / 2.0, c["eta"])
            x_next = jnp.where(halve[:, None], x_best, x_new)

            return dict(
                x=x_next,
                x_prev=c["x"],
                x_best=x_best,
                f_best=f_best,
                f_prev=f_new,
                eta=eta,
                eta_prev_ckpt=jnp.where(at_ckpt, eta, c["eta_prev_ckpt"]),
                fbest_prev_ckpt=jnp.where(at_ckpt, f_best, c["fbest_prev_ckpt"]),
                improved=jnp.where(at_ckpt, 0.0, improved),
                hist=hist,
            )

        out = jax.lax.fori_loop(0, self.max_iter, body, carry0)
        return out["x_best"], out["hist"]
