"""Constrained PGD — gradient attack with the constraint set in the loss.

Capability parity with the reference's PGDTF2 + TF2Classifier pair
(``/root/reference/src/attacks/pgd/atk.py:74-265``,
``pgd/classifier.py:96-332``): combined cross-entropy + constraint-violation
loss with every ``loss_evaluation`` strategy (flip, constraints,
constraints+flip, +alternate, +constraints half-split, +manual) and
``constraints_optim`` reduction (sum / alternating single / fixed single),
adaptive ε-step schedule, mutable-feature masking, NaN-grad zeroing, Lp
norm conditioning + ε-ball projection, optional in-graph constraint repair,
and random restarts.

TPU-first: the reference crosses numpy↔TF per iteration inside ART's Python
loop; here the entire attack — all iterations, all restarts — is one jitted
``lax.fori_loop`` whose iteration-dependent loss strategy is a branchless
weight schedule, so XLA fuses the whole thing.

The attack operates in the classifier's scaled input space (the runner
scales candidates first — ``united/01_pgd_united.py:124-129``); the
constraint loss unscales in-graph (``pgd/classifier.py:82-105``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ...core.constraints import ConstraintSet
from ...core.norms import condition_grad, is_inf, project_ball
from ...models.io import Surrogate
from ...models.scalers import MinMaxParams
from ...observability.gaps import emit_window_trace, get_gap_tracker
from ...observability.ledger import LedgeredJit, get_ledger


@dataclass
class ConstrainedPGD:
    """PGD in scaled feature space with domain constraints folded in."""

    classifier: Surrogate
    constraints: ConstraintSet
    scaler: MinMaxParams  # classifier input scaler (attack space = scaled)
    eps: float = 0.3
    eps_step: float = 0.1
    max_iter: int = 100
    norm: Any = np.inf
    loss_evaluation: str = "flip"
    constraints_optim: str = "sum"
    ctr_id: int = 0
    alternate_frequency: int = 5
    targeted: bool = False
    num_random_init: int = 0
    clip: tuple = (0.0, 1.0)
    seed: int = 0
    dtype: Any = jnp.float32
    #: "reduced" records per-iteration [loss, loss_class, cons_sum] columns,
    #: "full" appends the per-constraint violations (parity with the
    #: reference's TF2Classifier history, ``classifier.py:276-296``);
    #: exposed as ``loss_history`` (N, max_iter, C) after ``generate``.
    record_loss: str | None = None
    #: with ``record_loss``, also record the per-sample L2 norm of the raw
    #: loss gradient each iteration (parity with the reference's TensorBoard
    #: grad-norm stream, ``atk.py:201-226``) as an extra column after
    #: cons_sum and before any "full" per-constraint columns.
    record_grad_norm: bool = False
    #: shard the batch (states) axis over a device mesh. Every op in the
    #: attack is per-sample, so XLA partitions the whole fori_loop with zero
    #: collectives — the same data-parallel axis as the MoEvA engine's.
    mesh: jax.sharding.Mesh | None = None
    states_axis: str = "states"

    def __post_init__(self):
        self._mutable = jnp.asarray(
            np.asarray(self.constraints.get_mutable_mask(), dtype=bool)
        )
        self._jit_attack = None
        self.loss_history: np.ndarray | None = None
        #: per-restart quality history of the most recent ``generate``
        #: (None without restarts): ``restart_success`` is the (R, N)
        #: cumulative per-sample success mask after each restart (monotone
        #: rows — the restart loop keeps first successes) and
        #: ``restart_flip_frac`` its per-restart batch fraction. The mask
        #: is per-row so a caller that padded the batch (runners pad to a
        #: mesh multiple) can recompute unbiased fractions over its real
        #: rows. Always computed inside the compiled program (the restart
        #: loop already evaluates the success mask), so reading it costs
        #: nothing extra.
        self.quality_history: dict | None = None
        #: number of times the attack program was (re)traced — one trace per
        #: distinct executable. ε/ε-step are runtime arguments, so an ε sweep
        #: over a cached engine keeps this at 1 (grid observability reads it).
        self.trace_count = 0
        #: ledger keys (and per-key dispatch counts) of the executables the
        #: most recent ``generate`` dispatched — serving joins them with
        #: its device_run span for per-span roofline attribution
        self.last_run_executables: list[str] = []
        self.last_run_dispatch_counts: dict[str, int] = {}

    def _ledger_identity(self) -> dict:
        """Compile-time identity of this engine's executables for the cost
        ledger: everything the engine-cache key encodes, human-readable."""
        from ..sharding import describe_mesh

        return {
            "engine": type(self).__name__,
            "cache_key": getattr(self, "cache_key", None),
            # stable domain identity for the persistent AOT cache: the
            # constraint formulas are traced into the executable, and the
            # engine-cache slot id above is id()-derived (process noise);
            # spec-compiled domains discriminate by spec hash (ledger_tag)
            "constraints": self.constraints.ledger_tag,
            "n_constraints": int(self.constraints.n_constraints),
            "loss_evaluation": self.loss_evaluation,
            "constraints_optim": self.constraints_optim,
            "norm": str(self.norm),
            "num_random_init": self.num_random_init,
            "record_loss": self.record_loss,
            "mesh": describe_mesh(self.mesh),
        }

    # -- loss ---------------------------------------------------------------
    def _loss_weights(self, i, dtype, max_iter):
        """Iteration schedule for (class, constraints) loss weights
        (``classifier.py:234-259``)."""
        le = self.loss_evaluation
        if "constraints+flip+manual" in le:
            w_class = (i < 100).astype(dtype)
            return w_class, 1.0 - w_class
        if "constraints+flip+constraints" in le:
            w_class = (i < max_iter // 2).astype(dtype)
            return w_class, 1.0 - w_class
        if "constraints+flip+alternate" in le:
            w_class = ((i // self.alternate_frequency) % 2).astype(dtype)
            return w_class, 1.0 - w_class
        if "constraints+flip" in le:
            return 1.0, 1.0
        if "constraints" in le:
            return 0.0, 1.0
        return 1.0, 0.0  # flip

    def _loss_terms(self, params, x, y, i, with_g: bool = False):
        """Per-sample (class, constraint) loss terms, pre-weighting; with
        ``with_g`` also the raw per-constraint violations (for history)."""
        logits = Surrogate(self.classifier.model, params).logits(x)
        y1h = jax.nn.one_hot(y, logits.shape[-1], dtype=logits.dtype)
        loss_class = -(y1h * jax.nn.log_softmax(logits)).sum(-1)  # CE
        if self.targeted:
            loss_class = -loss_class

        g = self.constraints.evaluate_smooth(self.scaler.inverse(x))
        if "alt_constraints" in self.constraints_optim:
            k = g.shape[-1]
            cons = jnp.take_along_axis(
                g, jnp.full(g.shape[:-1] + (1,), i % k), axis=-1
            )[..., 0]
        elif "single_constraints" in self.constraints_optim:
            cons = g[..., self.ctr_id]
        else:
            cons = g.sum(-1)
        if with_g:
            return loss_class, cons, g
        return loss_class, cons

    def _static_loss_weights(self):
        """Iteration-independent weights: phase-switching strategies collapse
        to the combined loss (for best-point tracking in AutoPGD)."""
        le = self.loss_evaluation
        if "constraints+flip" in le:
            return 1.0, 1.0
        if "constraints" in le:
            return 0.0, 1.0
        return 1.0, 0.0

    def _grad_and_terms(self, params, x, y, i, max_iter):
        """Gradient of the iteration-weighted ascent loss plus its per-sample
        components ``(grad, per, loss_class, cons, g)`` — the single shared
        definition for both PGD and AutoPGD steps (and their history)."""

        def loss_with_aux(xx):
            loss_class, cons, g = self._loss_terms(params, xx, y, i, with_g=True)
            w_class, w_cons = self._loss_weights(i, loss_class.dtype, max_iter)
            # violations must shrink while CE grows, hence the minus
            per = w_class * loss_class + w_cons * (-cons)
            return per.sum(), (per, loss_class, cons, g)

        grad, (per, loss_class, cons, g) = jax.grad(
            loss_with_aux, has_aux=True
        )(x)
        return grad, per, loss_class, cons, g

    # -- attack -------------------------------------------------------------
    def _repair(self, x):
        return self.scaler.transform(
            self.constraints.repair(self.scaler.inverse(x))
        )

    def _step_size(self, i, dtype, eps, eps_step, max_iter):
        if "adaptive_eps_step" in self.loss_evaluation:
            # eps * 10^-(i // (max_iter//7) + 1) — atk.py:129-135
            power = (i // jnp.maximum(max_iter // 7, 1) + 1).astype(dtype)
            return eps * 10.0 ** (-power)
        return eps_step

    def hist_column_names(self) -> list[str]:
        """Recorded-history column layout, the single source of truth for
        consumers (runners/streaming): [loss, loss_class, cons_sum]
        (+ grad_norm under ``record_grad_norm``) + per-constraint violations
        for "full" (``classifier.py:276-296``)."""
        if not self.record_loss:
            return []
        names = ["loss", "loss_class", "cons_sum"]
        if self.record_grad_norm:
            names.append("grad_norm")
        if "full" in self.record_loss:
            names += [f"g{i + 1}" for i in range(self.constraints.n_constraints)]
        return names

    def _hist_columns(self) -> int:
        return len(self.hist_column_names())

    def _hist_init(self, n, dtype):
        if self.record_loss:
            return jnp.zeros((self.max_iter, n, self._hist_columns()), dtype)
        return jnp.zeros((), dtype)

    def _hist_record(self, hist, i, per, loss_class, cons, g, grad):
        cols = [per, loss_class, cons]
        if self.record_grad_norm:
            finite = jnp.nan_to_num(grad, nan=0.0, posinf=0.0, neginf=0.0)
            cols.append(jnp.sqrt((finite * finite).sum(-1)))
        stacked = jnp.column_stack(
            cols + [g] if "full" in self.record_loss else cols
        )
        return hist.at[i].set(stacked.astype(hist.dtype))

    def _one_run(self, params, x_init, y, x_start, eps, eps_step, max_iter):
        """Full iteration loop from ``x_start``; returns ``(x_adv, hist)``
        where hist is (max_iter, N, C) per-iteration loss components, or a
        scalar when recording is off (subclasses override). ``eps``,
        ``eps_step``, and (without history recording) ``max_iter`` are
        runtime scalars, not trace constants — every (ε, budget) in a sweep
        reuses the same compiled program."""

        def body(i, carry):
            x, hist = carry
            grad, per, loss_class, cons, g = self._grad_and_terms(
                params, x, y, i, max_iter
            )
            if self.record_loss:
                hist = self._hist_record(hist, i, per, loss_class, cons, g, grad)
            grad = jnp.where(jnp.isnan(grad), 0.0, grad)
            grad = jnp.where(self._mutable, grad, 0.0)
            grad = condition_grad(grad, self.norm)

            x = x + self._step_size(i, x.dtype, eps, eps_step, max_iter) * grad
            x = jnp.clip(x, *self.clip)
            x = x_init + project_ball(x - x_init, eps, self.norm)
            x = jnp.clip(x, *self.clip)
            if "repair" in self.loss_evaluation:
                x = jnp.where(self._mutable, self._repair(x).astype(x.dtype), x)
            return x, hist

        return jax.lax.fori_loop(
            0,
            max_iter,
            body,
            (x_start, self._hist_init(x_init.shape[0], x_init.dtype)),
        )

    def _random_start(self, key, x_init, eps):
        k_dir, k_rad = jax.random.split(key)
        if is_inf(self.norm):
            pert = eps * jax.random.uniform(
                k_dir, x_init.shape, x_init.dtype, -1.0, 1.0
            )
        else:
            d = jax.random.normal(k_dir, x_init.shape, x_init.dtype)
            d = d / (jnp.sqrt((d * d).sum(-1, keepdims=True)) + 1e-12)
            radius = eps * jax.random.uniform(
                k_rad, x_init.shape[:-1] + (1,), x_init.dtype
            ) ** (1.0 / x_init.shape[-1])
            pert = d * radius
        return jnp.clip(
            x_init + jnp.where(self._mutable, pert, 0.0), *self.clip
        )

    def _runtime_max_iter(self) -> bool:
        """True when the iteration budget can be a runtime argument of the
        compiled program (a dynamic ``fori_loop`` trip count): plain
        ConstrainedPGD without history recording. AutoPGD's checkpoint masks
        and the recorded-history buffer are shaped by ``max_iter`` at trace
        time, so those programs keep it baked (one executable per budget)."""
        return type(self) is ConstrainedPGD and not self.record_loss

    def _build(self):
        def attack(params, x_init, y, key, eps, eps_step, max_iter):
            self.trace_count += 1  # body runs once per (re)trace
            # No restarts: return the attacked batch as-is (ART PGD semantics —
            # success filtering only arbitrates BETWEEN multiple restarts).
            if self.num_random_init == 0:
                x_adv, hist = self._one_run(
                    params, x_init, y, x_init, eps, eps_step, max_iter
                )
                return x_adv, hist, jnp.zeros((0, x_init.shape[0]), bool)

            def restart(r, carry):
                best_x, best_success, best_hist, succ_hist = carry
                x_start = self._random_start(
                    jax.random.fold_in(key, r), x_init, eps
                )
                x_adv, hist = self._one_run(
                    params, x_init, y, x_start, eps, eps_step, max_iter
                )
                probs = Surrogate(self.classifier.model, params).predict_proba(x_adv)
                success = probs.argmax(-1) != y  # untargeted flip
                if self.targeted:
                    success = probs.argmax(-1) == y
                take = success & ~best_success
                best_x = jnp.where(take[:, None], x_adv, best_x)
                if self.record_loss:
                    # history follows the restart whose result was kept;
                    # still-unsuccessful samples track their latest attempt
                    upd = take | ~(best_success | success)
                    best_hist = jnp.where(upd[None, :, None], hist, best_hist)
                else:
                    best_hist = hist
                best_success = best_success | success
                # per-restart quality history: the cumulative per-sample
                # success mask after this restart (already computed for
                # the keep/replace arbitration — recording it is free);
                # per-row so padded batches can be trimmed by the caller
                succ_hist = succ_hist.at[r].set(best_success)
                return best_x, best_success, best_hist, succ_hist

            best, _, hist, succ_hist = jax.lax.fori_loop(
                0,
                self.num_random_init,
                restart,
                (
                    x_init,
                    jnp.zeros(x_init.shape[0], bool),
                    self._hist_init(x_init.shape[0], x_init.dtype),
                    jnp.zeros(
                        (self.num_random_init, x_init.shape[0]), bool
                    ),
                ),
            )
            return best, hist, succ_hist

        return attack

    def generate(
        self,
        x_scaled: np.ndarray,
        y: np.ndarray,
        *,
        eps: float | None = None,
        eps_step: float | None = None,
        max_iter: int | None = None,
    ) -> np.ndarray:
        """Attack scaled candidates ``x_scaled`` with true labels ``y``.

        ``eps``/``eps_step``/``max_iter`` default to the constructor values
        but are fed to the compiled program as runtime scalars where the
        program allows it (see :meth:`_runtime_max_iter`): sweeping ε — and,
        for plain PGD without history, the budget — over one engine instance
        dispatches the same executable (no retrace, no recompile)."""
        if eps is None:
            eps = self.eps
        if eps_step is None:
            eps_step = self.eps_step
        if max_iter is None:
            max_iter = self.max_iter
        runtime_iters = self._runtime_max_iter()
        if not runtime_iters and int(max_iter) != self.max_iter:
            raise ValueError(
                f"max_iter={max_iter} differs from the trace-static budget "
                f"{self.max_iter}: this program bakes its iteration count "
                "(AutoPGD / history recording); build an engine per budget"
            )
        if self._jit_attack is None:
            # the baked-budget programs take max_iter as a static arg so the
            # jitted callable's signature stays uniform across both modes.
            # LedgeredJit compiles AOT and dispatches the same executable the
            # jit cache would have — the cost ledger observes every compile
            # (identity, cost/memory analysis, wall-clock) as it happens.
            static = () if runtime_iters else (6,)
            self._jit_attack = LedgeredJit(
                jax.jit(self._build(), static_argnums=static),
                producer="pgd_attack",
                identity=self._ledger_identity,
                describe_args=lambda params, x, *rest: {
                    "rows": int(x.shape[0]),
                    "max_iter": None
                    if runtime_iters
                    else (int(rest[-1]) if rest else self.max_iter),
                },
                static_argnums=static,
            )
        mi = (
            jnp.asarray(max_iter, jnp.int32)
            if runtime_iters
            else int(max_iter)
        )
        args = (
            self.classifier.params,
            jnp.asarray(x_scaled, self.dtype),
            jnp.asarray(y, jnp.int32),
            jax.random.PRNGKey(self.seed),
            jnp.asarray(eps, self.dtype),
            jnp.asarray(eps_step, self.dtype),
        )
        if self.mesh is not None:
            from ..sharding import shard_states_args

            params, x_dev, y_dev, key, eps_d, step_d = args
            repl_in = (params, key, eps_d, step_d) + (
                (mi,) if runtime_iters else ()
            )
            repl_out, (x_dev, y_dev) = shard_states_args(
                self.mesh, self.states_axis, repl_in, (x_dev, y_dev)
            )
            params, key, eps_d, step_d = repl_out[:4]
            if runtime_iters:
                mi = repl_out[4]
            args = (params, x_dev, y_dev, key, eps_d, step_d)
        t0 = time.perf_counter()
        out, hist, succ_curve = self._jit_attack(*args, mi)
        # device-run end, read at the sync point the first device_get
        # below would block on anyway (no new sync — just a clock read at
        # the wait/fetch split): the gap ledger needs device-busy separate
        # from the host-side fetch/decode tail, which the ledger's own
        # run attribution deliberately folds in (roofline semantics
        # unchanged below)
        jax.block_until_ready(out)
        t_run_end = time.perf_counter()
        # ONE coalesced device→host fetch for all three result leaves
        # (roofline satellite): the former per-leaf device_get calls were
        # three sequential round trips — measurable when the accelerator
        # sits behind a network tunnel. The unused leaves are scalar
        # zeros, so the coalesced fetch moves no extra bytes.
        out_h, hist_h, succ_h = jax.device_get((out, hist, succ_curve))
        # (N, max_iter, C) — runners add the reference's unit axis on save
        # (01_pgd_united.py:196-199).
        self.loss_history = (
            np.swapaxes(np.asarray(hist_h), 0, 1)
            if self.record_loss
            else None
        )
        if self.num_random_init:
            succ = np.asarray(succ_h, bool)
            self.quality_history = {
                "restart_success": succ,
                "restart_flip_frac": succ.mean(axis=1).tolist(),
            }
        else:
            self.quality_history = None
        x_out = np.asarray(out_h)
        # roofline attribution: this fetch is the dispatch's sync point, so
        # dispatch->fetched wall-clock (compile excluded) is the run time of
        # exactly one executable
        entry = self._jit_attack.last_entry
        self.last_run_executables = [entry.key] if entry is not None else []
        self.last_run_dispatch_counts = (
            {entry.key: 1} if entry is not None else {}
        )
        t_end = time.perf_counter()
        compile_s = self._jit_attack.last_call_compile_s
        run_s = t_end - t0 - compile_s
        if entry is not None:
            get_ledger().add_run_seconds(entry.key, run_s)
        # dispatch-gap ledger: one window per generate. Device busy runs
        # from the post-compile enqueue to the block_until_ready instant;
        # the fetch/bookkeeping tail after it is the window's gap — the
        # host-side idle the overlap ratio exists to surface (the
        # ledger's run_s above keeps the fetch folded in, its documented
        # roofline semantics).
        window = get_gap_tracker().record_window(
            producer="pgd",
            engine=getattr(self, "cache_key", None),
            start=t0,
            end=t_end,
            dispatches=[
                (
                    t0 + compile_s,
                    max(t_run_end - t0 - compile_s, 0.0),
                    compile_s,
                    entry.key if entry is not None else None,
                )
            ],
        )
        emit_window_trace(getattr(self, "trace", None), window)
        if self.mesh is not None and self.mesh.size > 1:
            # per-device balance at the same sync point: PGD runs every
            # row to the full budget, so the engine's view is uniform —
            # rows per device is the padded batch split evenly (runners
            # pad to a mesh multiple before dispatch; pad rows are wasted
            # lockstep work but the engine cannot tell them apart)
            from ...observability.mesh import get_mesh_capture

            n = self.mesh.size
            get_mesh_capture().record_balance(
                [x_scaled.shape[0] / n] * n, run_s
            )
        return x_out


def round_ints_toward_initial(
    x_adv_unscaled: np.ndarray, x_init_unscaled: np.ndarray, feature_types
) -> np.ndarray:
    """Directional integer rounding (``united/01_pgd_united.py:130-137``):
    int features moved up are floored, moved down are ceiled — never
    overshooting past the original value. Softmax (simplex) features are
    continuous and stay untouched."""
    int_mask = np.array(
        [str(t) not in ("real", "softmax") for t in feature_types]
    )
    x = x_adv_unscaled.copy()
    up = x > x_init_unscaled
    vals = np.where(up, np.floor(x), np.ceil(x))
    x[..., int_mask] = vals[..., int_mask]
    return x
