from .engine import LinearRows, SatAttack

__all__ = ["SatAttack", "LinearRows"]
