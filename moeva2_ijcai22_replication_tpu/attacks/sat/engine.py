"""SAT/MIP attack: provably constraint-satisfying candidates via MILP.

Capability parity with the reference's Gurobi attack
(``/root/reference/src/attacks/sat/sat.py:21-231``): per initial state a
typed mixed-integer program — continuous/integer variables from the feature
schema, immutability as bound fixes, an ε-box in min-max-scaled space
(``:63-124``), domain constraints from a per-use-case builder (``:147``),
hot start from a prior gradient attack (``:126-130``), and fallback to the
initial state when infeasible (``:184-185``).

Solver: scipy's HiGHS ``milp`` (no Gurobi license assumption). Documented
fidelity limits vs the reference:

- HiGHS is linear-only, so each domain supplies *linearised* constraint rows
  (see ``domains/*_sat.py``). Discrete nonlinearities are searched as MILP
  *modes*: a builder may append auxiliary binary variables (``n_extra_bin``)
  and big-M rows — LCLD's term ∈ {36, 60} amortisation switch is a genuine
  mode search, matching the reference's indicator+pow constraints
  (``lcld_constraints_sat.py:25-36``), and LCLD's mutable ratio denominators
  are grid-searched over the ε-box (``domains/lcld_sat.py``). Builders
  accepting a third parameter receive the ε-intersected feature box for
  exactly this purpose. Immutable nonlinear participants (dates, pub_rec)
  are pinned at hot-start values — exact by immutability — with every
  zero/degenerate pin detected and mapped to the infeasible fallback.
- The L2 ε-ball (Gurobi pow-constraint, ``sat.py:98-124``) is solved
  EXACTLY by outer approximation (``l2_cut_rounds``, the default): the
  program is relaxed to the circumscribed box (every feature gets the full
  ε radius), and each incumbent outside the true scaled-L2 ball adds the
  ball's supporting hyperplane at that direction — a plain linear row — and
  re-solves. An accepted incumbent lies inside the true ball and minimised
  the objective over a superset of a (1 − 1e-3)-shrunk ball (L2_CUT_MARGIN),
  so it is optimal over the exact ball to within a 0.1% radial margin;
  within the linear solver this closes the reference's quadratic-constraint
  capability for low-dimensional repair displacements (where Kelley
  converges in a few cuts — the LCLD family). High-dimensional
  displacements (botnet's coordinated sum-equality chains) can flatline
  above the ball — frequently because no in-ball repair exists, which
  tangent cuts cannot prove — and are abandoned after two stalled rounds.
  When the cut loop exits without an in-ball incumbent the engine falls
  back to the previous inscribed-box program:
  a per-feature box with Σ radius² = ε² (solutions remain valid L2 members,
  the search space is just smaller), directional — radii follow the
  hot-start displacement, so a PGD-steered repair keeps almost the full ε
  budget on the features the gradient attack actually moved (uniform ε/√D
  only in the no-hot-start case).
- Gurobi's solution pool (PoolSolutions=n_sample, ``sat.py:167-173``) is
  emulated with no-good cuts over the program's binary variables (one-hot
  members, mode binaries): each re-solve excludes all previous binary
  assignments, so ``n_sample > 1`` returns *distinct* candidates, ordered by
  distance. When the binary space is exhausted the pool is padded with the
  last solution (the reference pads with ``x_init`` when Gurobi finds none).

Unlike the reference's pure feasibility program, the objective minimises the
scaled L1 distance to the hot start (or initial state) — "closest repair"
— which is a strict improvement in result quality at equal validity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ...core.constraints import ConstraintSet
from ...core.norms import is_inf, validate_norm
from ...models.scalers import MinMaxParams

SAFETY_DELTA = 1e-7  # sat.py:18
#: relative radial margin of the L2 cutting planes: cuts are tangent to a
#: (1 − margin)-shrunk ball so the cutting-plane incumbents — which approach
#: the cut ball's boundary FROM OUTSIDE — land strictly inside the true
#: ε-ball after a few rounds instead of converging to it asymptotically.
#: Accepted solutions are validated against the full ε (− SAFETY_DELTA), so
#: the margin costs at most 0.1% of the radius — vs the inscribed box's
#: (1 − 1/√m) sacrifice on concentrated directions.
L2_CUT_MARGIN = 1e-3


@dataclass
class LinearRows:
    """Sparse-ish linear constraint rows over the feature variables:
    lo <= sum_j coefs[j] * x[cols[j]] <= hi, plus hard variable pins.

    ``n_extra_bin`` auxiliary {0,1} variables are appended after the
    ``n_features`` feature variables; rows may reference them by index
    ``n_features + k`` (mode switches for big-M constructions).
    ``feasible=False`` short-circuits the solve: the builder proved the
    program unsatisfiable (e.g. a zero pinned denominator), so the engine
    takes the reference's infeasible fallback (``sat.py:184-185``)."""

    rows: list  # [(cols: np.ndarray, coefs: np.ndarray, lo: float, hi: float)]
    fixes: dict  # {var_index: value} — variables pinned to constants
    n_extra_bin: int = 0
    feasible: bool = True


@dataclass
class SatAttack:
    constraints: ConstraintSet
    #: (x_init, hot, box) -> LinearRows, box = the ε-intersected (xl, xu)
    sat_rows_builder: Callable[[np.ndarray, np.ndarray, tuple], LinearRows]
    min_max_scaler: MinMaxParams
    eps: float
    norm: Any = np.inf
    n_sample: int = 1
    n_jobs: int = 1
    time_limit: float | None = 30.0
    #: iterative grid refinement for builders that search nonlinear
    #: participants over candidate grids (LCLD's ratio denominators): after a
    #: successful solve the builder is re-invoked with the incumbent solution
    #: as ``focus`` and a geometrically shrinking ``window`` (¼, ¹⁄₁₆, … of
    #: the box per round), re-gridding around the incumbent. The incumbent's
    #: grid values are always kept, so each round's program contains the
    #: previous optimum and the objective improves monotonically — after r
    #: rounds the effective denominator resolution is box/4^(r+1) per round
    #: chain vs the reference's continuous nonconvex search
    #: (``sat.py:167-173`` NonConvex=2). Ignored for builders without a
    #: ``focus`` parameter (botnet: fully linear, nothing to refine).
    refine_rounds: int = 0
    #: outer-approximation rounds for the exact L2 ball (L2 norm only): the
    #: ε-box is relaxed to the circumscribed box and out-of-ball incumbents
    #: add supporting-hyperplane cuts until one lands inside the ball (then
    #: optimal over it up to L2_CUT_MARGIN) or the rounds run out (then the
    #: inscribed directional box is solved instead — the guaranteed-valid
    #: fallback).
    #: 0 disables the cut path entirely.
    l2_cut_rounds: int = 12

    def __post_init__(self):
        validate_norm(self.norm)
        import inspect

        try:
            self._builder_refines = "focus" in inspect.signature(
                self.sat_rows_builder
            ).parameters
        except (TypeError, ValueError):
            self._builder_refines = False
        schema = self.constraints.schema
        # int/ohe features become MILP integer variables; real and softmax
        # (simplex) features stay continuous
        self._int_mask = np.array(
            [str(t) not in ("real", "softmax") for t in schema.types]
        )
        # the softmax sub-vector's Σ=1 simplex row is part of the type's
        # meaning, so the engine adds it itself — like integer typing, it is
        # derived from the schema, not left to the domain builders
        self._softmax_idx = np.flatnonzero(
            [str(t) == "softmax" for t in schema.types]
        )
        self._mutable = np.asarray(schema.mutable, dtype=bool)
        self._scale = np.asarray(self.min_max_scaler.scale)
        self._min = np.asarray(self.min_max_scaler.min_)

    # -- per-state program --------------------------------------------------
    def _box_radii(self, x_init: np.ndarray, hot: np.ndarray) -> np.ndarray:
        """Per-feature half-widths of the ε-box in scaled space (sat.py:85-97).

        L∞ is the box itself. The L2 ball (Gurobi quadratic pow-constraint,
        ``sat.py:98-124``) has no linear encoding, so it is inscribed by a
        box with Σ radius² = ε² — every solution remains a valid L2 member.
        The budget goes only to features the MILP can actually move (mutable,
        nonzero scale; pinned dims contribute zero displacement, so weighting
        them would only shrink everyone else), and the box is *directional*:
        radii follow the hot-start displacement |hot − x_init| with a 10%
        uniform floor so unmoved features keep room. Displacements below
        ε/100 are treated as zero — PGD converging at x_init must not let
        float noise steer the box — degrading to the uniform inscribed
        box ε/√m over the m movable features.
        """
        d = x_init.shape[0]
        if is_inf(self.norm):
            return np.full(d, self.eps)
        movable = self._mutable & (self._scale != 0)
        if not movable.any():
            return np.full(d, self.eps / np.sqrt(d))
        delta = np.abs((hot - x_init) * self._scale)
        delta = np.where(movable & (delta > self.eps / 100.0), delta, 0.0)
        if delta.max() > 0:
            weights = np.where(movable, delta + delta.max() / 10.0, 0.0)
        else:
            weights = movable.astype(float)
        return self.eps * weights / np.linalg.norm(weights)

    def _assemble(self, spec: LinearRows, xl: np.ndarray, xu: np.ndarray, hot: np.ndarray):
        """LinearRows -> the HiGHS program matrices, or None when a hard pin
        falls outside the ε-box ∩ feature bounds (the mode is unreachable
        within the budget: genuinely infeasible, never silently escaped)."""
        d = xl.shape[0]
        xl, xu = xl.copy(), xu.copy()
        rows = list(spec.rows)
        if len(self._softmax_idx):
            rows.append(
                (self._softmax_idx, np.ones(len(self._softmax_idx)), 1.0, 1.0)
            )
        tol = 1e-9
        for i, v in spec.fixes.items():
            if v < xl[i] - tol or v > xu[i] + tol:
                return None
            xl[i] = xu[i] = min(max(v, xl[i]), xu[i])

        # variable layout: [x (d features), z (e mode binaries), p, n (split)]
        e = spec.n_extra_bin
        n_rows = len(rows)
        a_rows, lo_r, hi_r = [], [], []
        for cols, coefs, lo, hi in rows:
            row = np.zeros(d + e)
            row[np.asarray(cols, dtype=int)] = np.asarray(coefs, dtype=float)
            a_rows.append(row)
            lo_r.append(lo)
            hi_r.append(hi)

        a_main = np.array(a_rows) if n_rows else np.zeros((0, d + e))
        # objective: scaled L1 distance to hot start via split variables
        # x = hot + p - n, p,n >= 0; minimise sum(scale * (p + n))
        mut_idx = np.flatnonzero(self._mutable)
        m = len(mut_idx)
        n_var = d + e + 2 * m
        a_split = np.zeros((m, n_var))
        a_split[np.arange(m), mut_idx] = 1.0
        a_split[np.arange(m), d + e + np.arange(m)] = -1.0
        a_split[np.arange(m), d + e + m + np.arange(m)] = 1.0

        a_full = np.zeros((n_rows + m, n_var))
        a_full[:n_rows, : d + e] = a_main
        a_full[n_rows:] = a_split
        lo_full = np.concatenate([lo_r, hot[mut_idx]])
        hi_full = np.concatenate([hi_r, hot[mut_idx]])

        c = np.zeros(n_var)
        w = np.where(self._scale[mut_idx] == 0, 1.0, np.abs(self._scale[mut_idx]))
        c[d + e: d + e + m] = w
        c[d + e + m:] = w

        xl_full = np.concatenate([xl, np.zeros(e), np.zeros(2 * m)])
        xu_full = np.concatenate([xu, np.ones(e), np.full(2 * m, np.inf)])
        integrality = np.concatenate(
            [
                self._int_mask.astype(int),
                np.ones(e, dtype=int),
                np.zeros(2 * m, dtype=int),
            ]
        )

        # Binary variables carry the solution pool's no-good cuts: mode
        # binaries plus any integer feature whose *feasible integer values*
        # are exactly {0, 1} (one-hot members, flags) — judged on the
        # ε-intersected box, not the schema bounds.
        lo_int = np.ceil(xl_full[: d + e] - 1e-9)
        hi_int = np.floor(xu_full[: d + e] + 1e-9)
        is_bin = (integrality[: d + e] == 1) & (lo_int == 0.0) & (hi_int == 1.0)
        return {
            "d": d,
            "e": e,
            "a": a_full,
            "lo": lo_full,
            "hi": hi_full,
            "c": c,
            "xl": xl_full,
            "xu": xu_full,
            "integrality": integrality,
            "bin_idx": np.flatnonzero(is_bin),
        }

    def _solve_pool(self, prog: dict, n_sample: int) -> list[np.ndarray]:
        """Solve, emulating Gurobi's solution pool with no-good cuts over the
        program's binary variables (``sat.py:167-173``)."""
        from scipy import optimize, sparse

        d, e = prog["d"], prog["e"]
        a_full, lo_full, hi_full = prog["a"], prog["lo"], prog["hi"]
        bin_idx = prog["bin_idx"]
        n_var = a_full.shape[1]
        options = {}
        if self.time_limit is not None:
            options["time_limit"] = self.time_limit

        sols: list[np.ndarray] = []
        for _ in range(n_sample):
            cons = optimize.LinearConstraint(
                sparse.csr_matrix(a_full), lo_full, hi_full
            )
            res = optimize.milp(
                prog["c"],
                constraints=cons,
                bounds=optimize.Bounds(prog["xl"], prog["xu"]),
                integrality=prog["integrality"],
                options=options,
            )
            if not res.success or res.x is None:
                break
            out = res.x[:d]
            out = np.where(self._int_mask, np.round(out), out)
            sols.append(out)
            if len(sols) == n_sample or len(bin_idx) == 0:
                break
            # no-good cut: at least one binary flips vs this assignment —
            # sum_{b=0} x_b + sum_{b=1} (1 - x_b) >= 1
            assign = np.round(res.x[: d + e][bin_idx])
            row = np.zeros(n_var)
            row[bin_idx] = np.where(assign > 0.5, -1.0, 1.0)
            a_full = np.vstack([a_full, row[None, :]])
            lo_full = np.concatenate([lo_full, [1.0 - assign.sum()]])
            hi_full = np.concatenate([hi_full, [np.inf]])
        return sols

    def _eps_box(self, x_init: np.ndarray, radius: np.ndarray):
        """Feature bounds ∩ per-feature ε-box (scaled space) with
        immutability pins (sat.py:56-61)."""
        xl, xu = self.constraints.get_feature_min_max(dynamic_input=x_init)
        xl = np.asarray(xl, dtype=float).copy()
        xu = np.asarray(xu, dtype=float).copy()
        s_init = x_init * self._scale + self._min
        nonzero = self._scale != 0
        safe_scale = np.where(nonzero, self._scale, 1.0)
        lo_box = np.where(
            nonzero, (s_init - radius + SAFETY_DELTA - self._min) / safe_scale, xl
        )
        hi_box = np.where(
            nonzero, (s_init + radius - SAFETY_DELTA - self._min) / safe_scale, xu
        )
        xl = np.maximum(xl, lo_box)
        xu = np.minimum(xu, hi_box)
        xl[~self._mutable] = x_init[~self._mutable]
        xu[~self._mutable] = x_init[~self._mutable]
        return xl, xu

    def _l1_objective(self, hot: np.ndarray):
        """The program's objective as a host function — scaled L1 distance to
        the hot start over the mutable features (refinement acceptance)."""
        mut_idx = np.flatnonzero(self._mutable)
        w = np.where(self._scale[mut_idx] == 0, 1.0, np.abs(self._scale[mut_idx]))

        def obj(s):
            return float(w @ np.abs(s[mut_idx] - hot[mut_idx]))

        return obj

    def _ball_cut_rows(self, dirs: list, x_init: np.ndarray) -> list:
        """Supporting hyperplanes of the (1 − L2_CUT_MARGIN)-shrunk scaled-L2
        ε-ball: for a unit direction u (scaled space), u·scale·(x − x_init) ≤
        ρ is valid for every shrunk-ball member and cuts off everything beyond
        the tangent plane (see L2_CUT_MARGIN for why the shrink)."""
        eps_eff = (self.eps - SAFETY_DELTA) * (1.0 - L2_CUT_MARGIN)
        rows = []
        for u in dirs:
            coefs = u * self._scale
            nz = np.flatnonzero(coefs)
            rows.append(
                (nz, coefs[nz], -np.inf, eps_eff + float(coefs[nz] @ x_init[nz]))
            )
        return rows

    def _ball_norm(self, x: np.ndarray, x_init: np.ndarray) -> float:
        return float(np.linalg.norm((x - x_init) * self._scale))

    def _solve_ball(self, assemble, x_init: np.ndarray, n_sample: int, dirs: list):
        """Cutting-plane solve over the exact scaled-L2 ball.

        ``assemble(cut_rows)`` builds the program with the given extra rows;
        ``dirs`` accumulates cut directions across calls (refinement rounds
        reuse every cut already found). Returns in-ball solutions, or [] when
        the loop exhausts ``l2_cut_rounds`` without an in-ball incumbent.
        Each added cut is strictly violated by the incumbent that produced
        it, so incumbents never repeat.

        Stall exit: Kelley converges in a handful of cuts when the repair
        displacement is low-dimensional (the binding subspace is small — the
        LCLD family), but when the nearest feasible repair moves hundreds of
        coordinated features OUTSIDE the ball (botnet sum-equality chains),
        each tangent plane shaves a negligible cap and the incumbent norm
        flatlines above ε — often because no in-ball repair exists at all,
        which a cutting-plane loop cannot prove cheaply. Two consecutive
        rounds without meaningful norm progress abandon the hunt to the
        caller's fallback instead of burning the full round budget.
        """
        eps_tol = self.eps - SAFETY_DELTA
        prev_nrm, stalled = None, 0
        for _ in range(self.l2_cut_rounds):
            prog = assemble(self._ball_cut_rows(dirs, x_init))
            if prog is None:
                return []
            sols = self._solve_pool(prog, 1)
            if not sols:
                return []
            delta = (sols[0] - x_init) * self._scale
            nrm = float(np.linalg.norm(delta))
            if nrm <= eps_tol:
                if n_sample > 1:
                    pool = self._solve_pool(prog, n_sample)
                    sols = [
                        s for s in pool if self._ball_norm(s, x_init) <= eps_tol
                    ] or sols
                return sols
            if prev_nrm is not None and nrm > prev_nrm * (1.0 - 1e-3):
                stalled += 1
                if stalled >= 2:
                    return []
            else:
                stalled = 0
            prev_nrm = nrm
            dirs.append(delta / nrm)
        return []

    def _refine(self, solve, x_init, hot, box, spec, sols):
        """Iterative denominator-grid refinement around the incumbent.

        A refined round's solution is accepted only when its objective does
        not worsen — the incumbent's grid value can fall to the builder's
        near-zero filter, in which case the refined program no longer
        contains the incumbent and its optimum may regress.
        """
        obj = self._l1_objective(hot)
        best = obj(sols[0])
        for r in range(self.refine_rounds):
            spec_r = self.sat_rows_builder(
                x_init, hot, box, focus=sols[0], window=0.25 ** (r + 1)
            )
            if not spec_r.feasible:
                break
            sols_r = solve(spec_r, 1)
            if not sols_r or obj(sols_r[0]) > best + 1e-9:
                break
            spec, sols, best = spec_r, sols_r, obj(sols_r[0])
        return spec, sols

    def _one_generate(self, x_init: np.ndarray, hot: np.ndarray) -> np.ndarray:
        fallback = np.tile(x_init, (self.n_sample, 1))
        d = x_init.shape[0]
        refining = self.refine_rounds > 0 and self._builder_refines

        # -- exact-ball path (L2): circumscribed box + tangent cuts ---------
        if not is_inf(self.norm) and self.l2_cut_rounds > 0:
            xl, xu = self._eps_box(x_init, np.full(d, self.eps))
            box = (xl.copy(), xu.copy())
            spec = self.sat_rows_builder(x_init, hot, box)
            if spec.feasible:
                dirs: list = []  # cuts persist across refinement rounds

                def solve(spec_i, n):
                    return self._solve_ball(
                        lambda cut_rows: self._assemble(
                            LinearRows(
                                rows=list(spec_i.rows) + cut_rows,
                                fixes=spec_i.fixes,
                                n_extra_bin=spec_i.n_extra_bin,
                            ),
                            xl, xu, hot,
                        ),
                        x_init, n, dirs,
                    )

                sols = solve(spec, 1 if refining else self.n_sample)
                if sols:
                    if refining:
                        spec, sols = self._refine(
                            solve, x_init, hot, box, spec, sols
                        )
                        if self.n_sample > 1:
                            sols = solve(spec, self.n_sample) or sols
                    while len(sols) < self.n_sample:
                        sols.append(sols[-1])
                    return np.stack(sols)

        # -- inscribed directional box (L∞, or the cut loop came up dry) ----
        xl, xu = self._eps_box(x_init, self._box_radii(x_init, hot))
        box = (xl.copy(), xu.copy())
        # builders receive the ε-intersected feature box so they can
        # grid-search nonlinear participants inside it
        spec = self.sat_rows_builder(x_init, hot, box)
        if not spec.feasible:
            return fallback

        def solve_box(spec_i, n):
            prog = self._assemble(spec_i, xl, xu, hot)
            return self._solve_pool(prog, n) if prog is not None else []

        sols = solve_box(spec, 1 if refining else self.n_sample)
        if sols and refining:
            spec, sols = self._refine(solve_box, x_init, hot, box, spec, sols)
            if self.n_sample > 1:
                sols = solve_box(spec, self.n_sample) or sols

        if not sols:
            return fallback  # sat.py:184-185
        while len(sols) < self.n_sample:
            sols.append(sols[-1])  # binary space exhausted: pad
        return np.stack(sols)

    # -- public API ---------------------------------------------------------
    def generate(self, x: np.ndarray, hot_start: np.ndarray | None = None) -> np.ndarray:
        """(S, D) initial states -> (S, n_sample, D) repaired candidates."""
        x = np.asarray(x, dtype=float)
        hot = x if hot_start is None else np.asarray(hot_start, dtype=float)
        if hot.shape != x.shape:
            raise ValueError(f"hot_start shape {hot.shape} != x shape {x.shape}")

        if self.n_jobs == 1:
            outs = [self._one_generate(x[i], hot[i]) for i in range(len(x))]
        else:
            from concurrent.futures import ThreadPoolExecutor

            workers = None if self.n_jobs in (-1, 0) else self.n_jobs
            with ThreadPoolExecutor(max_workers=workers) as pool:
                outs = list(
                    pool.map(lambda i: self._one_generate(x[i], hot[i]), range(len(x)))
                )
        return np.stack(outs)
