"""SAT/MIP attack: provably constraint-satisfying candidates via MILP.

Capability parity with the reference's Gurobi attack
(``/root/reference/src/attacks/sat/sat.py:21-231``): per initial state a
typed mixed-integer program — continuous/integer variables from the feature
schema, immutability as bound fixes, an ε-box in min-max-scaled space
(``:63-124``), domain constraints from a per-use-case builder (``:147``),
hot start from a prior gradient attack (``:126-130``), and fallback to the
initial state when infeasible (``:184-185``).

Solver: scipy's HiGHS ``milp`` (no Gurobi license assumption). Documented
fidelity limits vs the reference:

- HiGHS is linear-only, so each domain supplies *linearised* constraint rows
  (see ``domains/*_sat.py``). Discrete nonlinearities are searched as MILP
  *modes*: a builder may append auxiliary binary variables (``n_extra_bin``)
  and big-M rows — LCLD's term ∈ {36, 60} amortisation switch is a genuine
  mode search, matching the reference's indicator+pow constraints
  (``lcld_constraints_sat.py:25-36``), and LCLD's mutable ratio denominators
  are grid-searched over the ε-box (``domains/lcld_sat.py``). Builders
  accepting a third parameter receive the ε-intersected feature box for
  exactly this purpose. Immutable nonlinear participants (dates, pub_rec)
  are pinned at hot-start values — exact by immutability — with every
  zero/degenerate pin detected and mapped to the infeasible fallback.
- The L2 ε-ball (Gurobi pow-constraint, ``sat.py:98-124``) is inscribed by
  a per-feature box with Σ radius² = ε² — solutions remain valid L2
  members, the search space is just smaller. The box is directional: radii
  follow the hot-start displacement, so a PGD-steered repair keeps almost
  the full ε budget on the features the gradient attack actually moved
  (uniform ε/√D only in the no-hot-start case).
- Gurobi's solution pool (PoolSolutions=n_sample, ``sat.py:167-173``) is
  emulated with no-good cuts over the program's binary variables (one-hot
  members, mode binaries): each re-solve excludes all previous binary
  assignments, so ``n_sample > 1`` returns *distinct* candidates, ordered by
  distance. When the binary space is exhausted the pool is padded with the
  last solution (the reference pads with ``x_init`` when Gurobi finds none).

Unlike the reference's pure feasibility program, the objective minimises the
scaled L1 distance to the hot start (or initial state) — "closest repair"
— which is a strict improvement in result quality at equal validity.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ...core.constraints import ConstraintSet
from ...core.norms import is_inf, validate_norm
from ...models.scalers import MinMaxParams

SAFETY_DELTA = 1e-7  # sat.py:18


@dataclass
class LinearRows:
    """Sparse-ish linear constraint rows over the feature variables:
    lo <= sum_j coefs[j] * x[cols[j]] <= hi, plus hard variable pins.

    ``n_extra_bin`` auxiliary {0,1} variables are appended after the
    ``n_features`` feature variables; rows may reference them by index
    ``n_features + k`` (mode switches for big-M constructions).
    ``feasible=False`` short-circuits the solve: the builder proved the
    program unsatisfiable (e.g. a zero pinned denominator), so the engine
    takes the reference's infeasible fallback (``sat.py:184-185``)."""

    rows: list  # [(cols: np.ndarray, coefs: np.ndarray, lo: float, hi: float)]
    fixes: dict  # {var_index: value} — variables pinned to constants
    n_extra_bin: int = 0
    feasible: bool = True


@dataclass
class SatAttack:
    constraints: ConstraintSet
    #: (x_init, hot, box) -> LinearRows, box = the ε-intersected (xl, xu)
    sat_rows_builder: Callable[[np.ndarray, np.ndarray, tuple], LinearRows]
    min_max_scaler: MinMaxParams
    eps: float
    norm: Any = np.inf
    n_sample: int = 1
    n_jobs: int = 1
    time_limit: float | None = 30.0
    #: iterative grid refinement for builders that search nonlinear
    #: participants over candidate grids (LCLD's ratio denominators): after a
    #: successful solve the builder is re-invoked with the incumbent solution
    #: as ``focus`` and a geometrically shrinking ``window`` (¼, ¹⁄₁₆, … of
    #: the box per round), re-gridding around the incumbent. The incumbent's
    #: grid values are always kept, so each round's program contains the
    #: previous optimum and the objective improves monotonically — after r
    #: rounds the effective denominator resolution is box/4^(r+1) per round
    #: chain vs the reference's continuous nonconvex search
    #: (``sat.py:167-173`` NonConvex=2). Ignored for builders without a
    #: ``focus`` parameter (botnet: fully linear, nothing to refine).
    refine_rounds: int = 0

    def __post_init__(self):
        validate_norm(self.norm)
        import inspect

        try:
            self._builder_refines = "focus" in inspect.signature(
                self.sat_rows_builder
            ).parameters
        except (TypeError, ValueError):
            self._builder_refines = False
        schema = self.constraints.schema
        # int/ohe features become MILP integer variables; real and softmax
        # (simplex) features stay continuous
        self._int_mask = np.array(
            [str(t) not in ("real", "softmax") for t in schema.types]
        )
        # the softmax sub-vector's Σ=1 simplex row is part of the type's
        # meaning, so the engine adds it itself — like integer typing, it is
        # derived from the schema, not left to the domain builders
        self._softmax_idx = np.flatnonzero(
            [str(t) == "softmax" for t in schema.types]
        )
        self._mutable = np.asarray(schema.mutable, dtype=bool)
        self._scale = np.asarray(self.min_max_scaler.scale)
        self._min = np.asarray(self.min_max_scaler.min_)

    # -- per-state program --------------------------------------------------
    def _box_radii(self, x_init: np.ndarray, hot: np.ndarray) -> np.ndarray:
        """Per-feature half-widths of the ε-box in scaled space (sat.py:85-97).

        L∞ is the box itself. The L2 ball (Gurobi quadratic pow-constraint,
        ``sat.py:98-124``) has no linear encoding, so it is inscribed by a
        box with Σ radius² = ε² — every solution remains a valid L2 member.
        The budget goes only to features the MILP can actually move (mutable,
        nonzero scale; pinned dims contribute zero displacement, so weighting
        them would only shrink everyone else), and the box is *directional*:
        radii follow the hot-start displacement |hot − x_init| with a 10%
        uniform floor so unmoved features keep room. Displacements below
        ε/100 are treated as zero — PGD converging at x_init must not let
        float noise steer the box — degrading to the uniform inscribed
        box ε/√m over the m movable features.
        """
        d = x_init.shape[0]
        if is_inf(self.norm):
            return np.full(d, self.eps)
        movable = self._mutable & (self._scale != 0)
        if not movable.any():
            return np.full(d, self.eps / np.sqrt(d))
        delta = np.abs((hot - x_init) * self._scale)
        delta = np.where(movable & (delta > self.eps / 100.0), delta, 0.0)
        if delta.max() > 0:
            weights = np.where(movable, delta + delta.max() / 10.0, 0.0)
        else:
            weights = movable.astype(float)
        return self.eps * weights / np.linalg.norm(weights)

    def _assemble(self, spec: LinearRows, xl: np.ndarray, xu: np.ndarray, hot: np.ndarray):
        """LinearRows -> the HiGHS program matrices, or None when a hard pin
        falls outside the ε-box ∩ feature bounds (the mode is unreachable
        within the budget: genuinely infeasible, never silently escaped)."""
        d = xl.shape[0]
        xl, xu = xl.copy(), xu.copy()
        rows = list(spec.rows)
        if len(self._softmax_idx):
            rows.append(
                (self._softmax_idx, np.ones(len(self._softmax_idx)), 1.0, 1.0)
            )
        tol = 1e-9
        for i, v in spec.fixes.items():
            if v < xl[i] - tol or v > xu[i] + tol:
                return None
            xl[i] = xu[i] = min(max(v, xl[i]), xu[i])

        # variable layout: [x (d features), z (e mode binaries), p, n (split)]
        e = spec.n_extra_bin
        n_rows = len(rows)
        a_rows, lo_r, hi_r = [], [], []
        for cols, coefs, lo, hi in rows:
            row = np.zeros(d + e)
            row[np.asarray(cols, dtype=int)] = np.asarray(coefs, dtype=float)
            a_rows.append(row)
            lo_r.append(lo)
            hi_r.append(hi)

        a_main = np.array(a_rows) if n_rows else np.zeros((0, d + e))
        # objective: scaled L1 distance to hot start via split variables
        # x = hot + p - n, p,n >= 0; minimise sum(scale * (p + n))
        mut_idx = np.flatnonzero(self._mutable)
        m = len(mut_idx)
        n_var = d + e + 2 * m
        a_split = np.zeros((m, n_var))
        a_split[np.arange(m), mut_idx] = 1.0
        a_split[np.arange(m), d + e + np.arange(m)] = -1.0
        a_split[np.arange(m), d + e + m + np.arange(m)] = 1.0

        a_full = np.zeros((n_rows + m, n_var))
        a_full[:n_rows, : d + e] = a_main
        a_full[n_rows:] = a_split
        lo_full = np.concatenate([lo_r, hot[mut_idx]])
        hi_full = np.concatenate([hi_r, hot[mut_idx]])

        c = np.zeros(n_var)
        w = np.where(self._scale[mut_idx] == 0, 1.0, np.abs(self._scale[mut_idx]))
        c[d + e: d + e + m] = w
        c[d + e + m:] = w

        xl_full = np.concatenate([xl, np.zeros(e), np.zeros(2 * m)])
        xu_full = np.concatenate([xu, np.ones(e), np.full(2 * m, np.inf)])
        integrality = np.concatenate(
            [
                self._int_mask.astype(int),
                np.ones(e, dtype=int),
                np.zeros(2 * m, dtype=int),
            ]
        )

        # Binary variables carry the solution pool's no-good cuts: mode
        # binaries plus any integer feature whose *feasible integer values*
        # are exactly {0, 1} (one-hot members, flags) — judged on the
        # ε-intersected box, not the schema bounds.
        lo_int = np.ceil(xl_full[: d + e] - 1e-9)
        hi_int = np.floor(xu_full[: d + e] + 1e-9)
        is_bin = (integrality[: d + e] == 1) & (lo_int == 0.0) & (hi_int == 1.0)
        return {
            "d": d,
            "e": e,
            "a": a_full,
            "lo": lo_full,
            "hi": hi_full,
            "c": c,
            "xl": xl_full,
            "xu": xu_full,
            "integrality": integrality,
            "bin_idx": np.flatnonzero(is_bin),
        }

    def _solve_pool(self, prog: dict, n_sample: int) -> list[np.ndarray]:
        """Solve, emulating Gurobi's solution pool with no-good cuts over the
        program's binary variables (``sat.py:167-173``)."""
        from scipy import optimize, sparse

        d, e = prog["d"], prog["e"]
        a_full, lo_full, hi_full = prog["a"], prog["lo"], prog["hi"]
        bin_idx = prog["bin_idx"]
        n_var = a_full.shape[1]
        options = {}
        if self.time_limit is not None:
            options["time_limit"] = self.time_limit

        sols: list[np.ndarray] = []
        for _ in range(n_sample):
            cons = optimize.LinearConstraint(
                sparse.csr_matrix(a_full), lo_full, hi_full
            )
            res = optimize.milp(
                prog["c"],
                constraints=cons,
                bounds=optimize.Bounds(prog["xl"], prog["xu"]),
                integrality=prog["integrality"],
                options=options,
            )
            if not res.success or res.x is None:
                break
            out = res.x[:d]
            out = np.where(self._int_mask, np.round(out), out)
            sols.append(out)
            if len(sols) == n_sample or len(bin_idx) == 0:
                break
            # no-good cut: at least one binary flips vs this assignment —
            # sum_{b=0} x_b + sum_{b=1} (1 - x_b) >= 1
            assign = np.round(res.x[: d + e][bin_idx])
            row = np.zeros(n_var)
            row[bin_idx] = np.where(assign > 0.5, -1.0, 1.0)
            a_full = np.vstack([a_full, row[None, :]])
            lo_full = np.concatenate([lo_full, [1.0 - assign.sum()]])
            hi_full = np.concatenate([hi_full, [np.inf]])
        return sols

    def _one_generate(self, x_init: np.ndarray, hot: np.ndarray) -> np.ndarray:
        xl, xu = self.constraints.get_feature_min_max(dynamic_input=x_init)
        xl = np.asarray(xl, dtype=float).copy()
        xu = np.asarray(xu, dtype=float).copy()

        radius = self._box_radii(x_init, hot)
        s_init = x_init * self._scale + self._min
        nonzero = self._scale != 0
        lo_box = np.where(
            nonzero, (s_init - radius + SAFETY_DELTA - self._min) / np.where(nonzero, self._scale, 1.0), xl
        )
        hi_box = np.where(
            nonzero, (s_init + radius - SAFETY_DELTA - self._min) / np.where(nonzero, self._scale, 1.0), xu
        )
        xl = np.maximum(xl, lo_box)
        xu = np.minimum(xu, hi_box)

        # immutability as bound pins (sat.py:56-61)
        xl[~self._mutable] = x_init[~self._mutable]
        xu[~self._mutable] = x_init[~self._mutable]
        box = (xl.copy(), xu.copy())

        fallback = np.tile(x_init, (self.n_sample, 1))
        # builders receive the ε-intersected feature box so they can
        # grid-search nonlinear participants inside it
        spec = self.sat_rows_builder(x_init, hot, box)
        if not spec.feasible:
            return fallback
        prog = self._assemble(spec, xl, xu, hot)
        if prog is None:
            return fallback

        refining = self.refine_rounds > 0 and self._builder_refines
        sols = self._solve_pool(prog, 1 if refining else self.n_sample)
        if sols and refining:
            # grid refinement: re-centre the builder's candidate grids on the
            # incumbent with a shrinking window; the incumbent always stays
            # in the refined grid, so each round's optimum is no worse
            for r in range(self.refine_rounds):
                spec_r = self.sat_rows_builder(
                    x_init, hot, box, focus=sols[0], window=0.25 ** (r + 1)
                )
                if not spec_r.feasible:
                    break
                prog_r = self._assemble(spec_r, xl, xu, hot)
                if prog_r is None:
                    break
                sols_r = self._solve_pool(prog_r, 1)
                if not sols_r:
                    break
                prog, sols = prog_r, sols_r
            if self.n_sample > 1:
                sols = self._solve_pool(prog, self.n_sample) or sols

        if not sols:
            return fallback  # sat.py:184-185
        while len(sols) < self.n_sample:
            sols.append(sols[-1])  # binary space exhausted: pad
        return np.stack(sols)

    # -- public API ---------------------------------------------------------
    def generate(self, x: np.ndarray, hot_start: np.ndarray | None = None) -> np.ndarray:
        """(S, D) initial states -> (S, n_sample, D) repaired candidates."""
        x = np.asarray(x, dtype=float)
        hot = x if hot_start is None else np.asarray(hot_start, dtype=float)
        if hot.shape != x.shape:
            raise ValueError(f"hot_start shape {hot.shape} != x shape {x.shape}")

        if self.n_jobs == 1:
            outs = [self._one_generate(x[i], hot[i]) for i in range(len(x))]
        else:
            from concurrent.futures import ThreadPoolExecutor

            workers = None if self.n_jobs in (-1, 0) else self.n_jobs
            with ThreadPoolExecutor(max_workers=workers) as pool:
                outs = list(
                    pool.map(lambda i: self._one_generate(x[i], hot[i]), range(len(x)))
                )
        return np.stack(outs)
