"""States-axis data-parallel sharding shared by the attack engines.

Both attack families scale the same way (SURVEY §2.8): initial states are
embarrassingly parallel, so the batch axis shards over a 1-D device mesh with
zero collectives in the hot loop. This module owns the divisibility contract
and the replicate/shard placements so the engines cannot drift; runners that
face data-dependent candidate counts pad to a mesh multiple with
:func:`..experiments.common.pad_states` and trim afterwards.

The zero-collective contract is machine-checked: ``tools/shard_lint.py``
compiles the hot attack programs on an emulated 8-device mesh and fails on
any hot-loop collective, implicit host↔device transfer at dispatch, or
large array compiled fully replicated when a states-sharded placement was
requested (wired into the tier-1 repo check next to ``bench_diff``).
"""

from __future__ import annotations

import jax


def describe_mesh(mesh) -> dict | None:
    """JSON-ready mesh identity for metrics files and serving responses.

    The mesh shape is part of a run's RNG-affecting execution mode (padding
    to mesh multiples changes batch shapes, and MoEvA's chunk keys fold per
    chunk), so every committed number carries it: ``None`` for single-device
    runs, else ``{"devices", "shape", "axes"}``.
    """
    if mesh is None:
        return None
    return {
        "devices": int(mesh.size),
        "shape": [int(s) for s in mesh.devices.shape],
        "axes": [str(a) for a in mesh.axis_names],
    }


def shard_states_args(mesh, states_axis: str, replicated: tuple, sharded: tuple):
    """Place arrays for a states-sharded attack dispatch.

    ``replicated`` pytrees (params, PRNG keys) land fully replicated;
    ``sharded`` arrays split their leading axis over ``states_axis``.
    Returns ``(replicated, sharded)`` with the same structures.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    n_states = sharded[0].shape[0]
    if n_states % mesh.size != 0:
        raise ValueError(
            f"n_states={n_states} must be divisible by the mesh size "
            f"{mesh.size} to shard the states axis; pad the batch or trim "
            "it to a multiple (runners: experiments.common.pad_states)"
        )
    state_sh = NamedSharding(mesh, P(states_axis))
    repl = NamedSharding(mesh, P())
    rep_out = tuple(
        jax.tree.map(lambda a: jax.device_put(a, repl), r) for r in replicated
    )
    sh_out = tuple(jax.device_put(a, state_sh) for a in sharded)
    return rep_out, sh_out
