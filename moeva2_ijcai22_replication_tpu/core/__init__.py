from .schema import FeatureSchema, ConstraintBounds, OHE_PREFIX
from .codec import Codec, make_codec
from . import codec
from .constraints import ConstraintSet, ConstraintViolationError

__all__ = [
    "FeatureSchema",
    "ConstraintBounds",
    "OHE_PREFIX",
    "Codec",
    "make_codec",
    "codec",
    "ConstraintSet",
    "ConstraintViolationError",
]
