"""Jittable genetic <-> ML <-> normalized feature transforms.

The reference's ``FeatureEncoder`` (``/root/reference/src/attacks/moeva2/feature_encoder.py``)
maintains three representations of a candidate:

- **ML space** ``(D,)``: every feature, as the classifier consumes it;
- **genetic space** ``(L,)``: only mutable features, with each one-hot group
  collapsed to a single categorical gene — bound and one-hot validity hold by
  construction;
- **normalized space**: MinMax over per-feature bounds (sklearn semantics:
  zero-range features get scale 1).

This module re-designs those transforms TPU-first: all group structure is
precomputed into *static padded index tables* so that every transform is a pure
gather/scatter over the last axis — shape-static, differentiable where
meaningful, and freely `vmap`-able over population and initial-state axes.
Dynamic (per-sample) bounds are handled by passing per-state ``(S, D)`` bound
tensors through the same broadcasting code paths.

Genetic layout (matches the reference's, ``feature_encoder.py:97-110``): first
all mutable non-OHE features in ML order, then one categorical gene per mutable
OHE group.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .schema import FeatureSchema, OHE_PREFIX, SOFTMAX_TYPE


class Codec(NamedTuple):
    """Static index tables driving the transforms (all shapes fixed at build).

    Arrays live as device constants inside jitted computations; the codec is a
    pytree so it can be closed over or passed as an argument.
    """

    non_ohe_ml_idx: jnp.ndarray  # (n1,) int32 — ML index of each non-OHE gene
    group_ml_idx: jnp.ndarray  # (G, K) int32 — ML indices per OHE group, padded
    group_pad_mask: jnp.ndarray  # (G, K) bool — True on real (non-pad) entries
    group_sizes: jnp.ndarray  # (G,) int32
    int_mask_gen: jnp.ndarray  # (L,) bool — genes needing integer rounding
    mutable_mask: jnp.ndarray  # (D,) bool
    n_features: int  # static
    gen_length: int  # static
    #: (L,) bool — genes forming the probability-simplex sub-vector
    #: (schema type "softmax"); None means the schema declares none.
    softmax_mask_gen: jnp.ndarray | None = None

    @property
    def n_groups(self) -> int:
        return self.group_ml_idx.shape[0]

    @property
    def n_non_ohe(self) -> int:
        return self.non_ohe_ml_idx.shape[0]


def _pad_group_tables(group_lists: list[list[int]]):
    """Pad ragged index groups into (G, K) tables + validity mask.

    Pad slots repeat the group's first member; pad scatters/gathers are always
    masked out by the companion mask.
    """
    n_groups = len(group_lists)
    max_k = max((len(g) for g in group_lists), default=1)
    idx = np.zeros((n_groups, max_k), dtype=np.int32)
    mask = np.zeros((n_groups, max_k), dtype=bool)
    sizes = np.zeros((n_groups,), dtype=np.int32)
    for gi, members in enumerate(group_lists):
        idx[gi, : len(members)] = members
        idx[gi, len(members):] = members[0] if members else 0
        mask[gi, : len(members)] = True
        sizes[gi] = len(members)
    return idx, mask, sizes


def make_codec(schema: FeatureSchema) -> Codec:
    """Build the codec from a feature schema.

    Mirrors the group discovery of ``FeatureEncoder._create_one_hot_encoders``
    (``feature_encoder.py:58-86``) but materialised as padded index tables.
    Only *mutable* features participate in the genetic space.
    """
    mutable = schema.mutable
    types = [str(t) for t in schema.types]

    # OHE groups among mutable features, in first-seen order.
    groups: dict[str, list[int]] = {}
    non_ohe_ml: list[int] = []
    for i in range(schema.n_features):
        if not mutable[i]:
            continue
        if types[i].startswith(OHE_PREFIX):
            groups.setdefault(types[i], []).append(i)
        else:
            non_ohe_ml.append(i)

    group_lists = list(groups.values())
    n_groups = len(group_lists)
    group_ml_idx, group_pad_mask, group_sizes = _pad_group_tables(group_lists)

    # int: integer genes + collapsed categorical (OHE) genes; softmax genes
    # are continuous simplex members, neither int nor real-plain
    int_mask = np.array(
        [types[i] == "int" for i in non_ohe_ml] + [True] * n_groups, dtype=bool
    )
    softmax_mask = np.array(
        [types[i] == SOFTMAX_TYPE for i in non_ohe_ml] + [False] * n_groups,
        dtype=bool,
    )

    return Codec(
        non_ohe_ml_idx=jnp.asarray(np.array(non_ohe_ml, dtype=np.int32)),
        group_ml_idx=jnp.asarray(group_ml_idx),
        group_pad_mask=jnp.asarray(group_pad_mask),
        group_sizes=jnp.asarray(group_sizes),
        int_mask_gen=jnp.asarray(int_mask),
        mutable_mask=jnp.asarray(np.asarray(mutable, dtype=bool)),
        n_features=schema.n_features,
        gen_length=len(non_ohe_ml) + n_groups,
        softmax_mask_gen=jnp.asarray(softmax_mask),
    )


# ---------------------------------------------------------------------------
# Transforms. All operate on the trailing axis and broadcast over leading axes.
# ---------------------------------------------------------------------------


def scatter_groups(
    x: jnp.ndarray,
    group_idx: jnp.ndarray,
    pad_mask: jnp.ndarray,
    group_vals: jnp.ndarray,
) -> jnp.ndarray:
    """Scatter per-group value rows ``(..., G, K)`` into feature slots of
    ``x`` ``(..., D)``, dropping padded entries via a sentinel column."""
    d = x.shape[-1]
    batch = jnp.broadcast_shapes(x.shape[:-1], group_vals.shape[:-2])
    flat_idx = jnp.where(pad_mask, group_idx, d).reshape(-1)
    flat_vals = jnp.broadcast_to(
        group_vals, batch + group_vals.shape[-2:]
    ).reshape(batch + (-1,))
    padded = jnp.concatenate(
        [
            jnp.broadcast_to(x, batch + (d,)),
            jnp.zeros(batch + (1,), x.dtype),
        ],
        axis=-1,
    )
    return padded.at[..., flat_idx].set(flat_vals)[..., :d]


def genetic_to_ml(codec: Codec, x_gen: jnp.ndarray, x_init_ml: jnp.ndarray) -> jnp.ndarray:
    """Decode genetic vectors into full ML vectors.

    Immutable features are taken from the initial state; mutable non-OHE genes
    scatter to their ML slots; categorical genes expand to one-hot groups.
    Parity: ``FeatureEncoder.genetic_to_ml`` (``feature_encoder.py:112-130``).

    ``x_gen``: (..., L); ``x_init_ml``: broadcastable to (..., D).
    """
    n1 = codec.n_non_ohe
    batch = jnp.broadcast_shapes(x_gen.shape[:-1], x_init_ml.shape[:-1])
    out = jnp.broadcast_to(x_init_ml, batch + (codec.n_features,))

    # Non-OHE mutable genes.
    out = out.at[..., codec.non_ohe_ml_idx].set(
        jnp.broadcast_to(x_gen[..., :n1], batch + (n1,))
    )

    if codec.n_groups:
        # Categorical genes -> one-hot rows.  (..., G, K)
        cats = jnp.round(x_gen[..., n1:])
        onehot = (cats[..., None] == jnp.arange(codec.group_ml_idx.shape[1])).astype(
            out.dtype
        )
        out = scatter_groups(out, codec.group_ml_idx, codec.group_pad_mask, onehot)
    return out


def harden_onehot(
    x: jnp.ndarray, group_idx: jnp.ndarray, pad_mask: jnp.ndarray
) -> jnp.ndarray:
    """Snap every one-hot group in ``x`` to a hard argmax one-hot."""
    if group_idx.shape[0] == 0:
        return x
    vals = jnp.where(pad_mask, x[..., group_idx], -jnp.inf)
    winner = jnp.argmax(vals, axis=-1)  # (..., G)
    hard = (winner[..., None] == jnp.arange(group_idx.shape[1])).astype(x.dtype)
    hard = jnp.where(pad_mask, hard, 0.0)
    return scatter_groups(x, group_idx, pad_mask, hard)


def ml_to_genetic(codec: Codec, x_ml: jnp.ndarray) -> jnp.ndarray:
    """Encode ML vectors into the genetic representation.

    Parity: ``FeatureEncoder.ml_to_genetic`` (``feature_encoder.py:126-127``);
    one-hot groups collapse to argmax (the reference's OneHotEncoder inverse).
    """
    parts = [x_ml[..., codec.non_ohe_ml_idx]]
    if codec.n_groups:
        vals = x_ml[..., codec.group_ml_idx]  # (..., G, K)
        vals = jnp.where(codec.group_pad_mask, vals, -jnp.inf)
        parts.append(jnp.argmax(vals, axis=-1).astype(x_ml.dtype))
    return jnp.concatenate(parts, axis=-1)


def genetic_bounds(codec: Codec, xl_ml: jnp.ndarray, xu_ml: jnp.ndarray):
    """Per-gene (xl, xu) from per-feature ML bounds (may carry leading axes).

    Parity: ``FeatureEncoder.get_min_max_genetic`` (``feature_encoder.py:145-163``):
    categorical genes range over [0, group_size - 1].
    """
    xl_ml = jnp.asarray(xl_ml)
    if not jnp.issubdtype(xl_ml.dtype, jnp.floating):
        xl_ml = xl_ml.astype(jnp.result_type(float))
    xu_ml = jnp.asarray(xu_ml, dtype=xl_ml.dtype)
    batch = xl_ml.shape[:-1]
    cat_lo = jnp.broadcast_to(
        jnp.zeros((codec.n_groups,), xl_ml.dtype), batch + (codec.n_groups,)
    )
    cat_hi = jnp.broadcast_to(
        (codec.group_sizes - 1).astype(xu_ml.dtype), batch + (codec.n_groups,)
    )
    xl = jnp.concatenate([xl_ml[..., codec.non_ohe_ml_idx], cat_lo], axis=-1)
    xu = jnp.concatenate([xu_ml[..., codec.non_ohe_ml_idx], cat_hi], axis=-1)
    return xl, xu


def minmax_normalize(x: jnp.ndarray, xl: jnp.ndarray, xu: jnp.ndarray) -> jnp.ndarray:
    """sklearn-MinMaxScaler-semantics normalisation to [0, 1].

    Zero-range features use scale 1 (``sklearn _handle_zeros_in_scale``), so a
    degenerate feature maps to 0 — matching ``FeatureEncoder.normalise``.
    """
    rng = xu - xl
    scale = jnp.where(rng == 0, 1.0, rng)
    return (x - xl) / scale


def minmax_denormalize(x: jnp.ndarray, xl: jnp.ndarray, xu: jnp.ndarray) -> jnp.ndarray:
    rng = xu - xl
    scale = jnp.where(rng == 0, 1.0, rng)
    return x * scale + xl


def round_int_genes(codec: Codec, x_gen: jnp.ndarray) -> jnp.ndarray:
    """Round integer-typed genes (incl. categoricals) to the nearest integer."""
    return jnp.where(codec.int_mask_gen, jnp.round(x_gen), x_gen)


def clip_genetic(x_gen: jnp.ndarray, xl_gen: jnp.ndarray, xu_gen: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(x_gen, xl_gen, xu_gen)


def ohe_distance(codec: Codec, x_ml: jnp.ndarray) -> jnp.ndarray:
    """Sum over *mutable* groups of |1 - sum(group members)|.

    NOTE: the reference's post-hoc oracle (``get_one_hot_encoding_constraints``,
    ``moeva2/utils.py:43-54``) sums over ALL OHE groups in the type mask,
    mutable or not — for that use :func:`all_ohe_groups_distance` with
    :func:`full_ohe_tables`. This codec-level variant only sees the mutable
    groups that exist in the genetic space.
    """
    if codec.n_groups == 0:
        return jnp.zeros(x_ml.shape[:-1], x_ml.dtype)
    vals = x_ml[..., codec.group_ml_idx]  # (..., G, K)
    vals = jnp.where(codec.group_pad_mask, vals, 0.0)
    return jnp.abs(1.0 - vals.sum(axis=-1)).sum(axis=-1)


def all_ohe_groups_distance(groups_idx: jnp.ndarray, pad_mask: jnp.ndarray, x_ml: jnp.ndarray) -> jnp.ndarray:
    """Same as :func:`ohe_distance` but over an explicit (G, K) index table —
    used when immutable OHE groups must be included (full type-mask parity)."""
    vals = jnp.where(pad_mask, x_ml[..., groups_idx], 0.0)
    return jnp.abs(1.0 - vals.sum(axis=-1)).sum(axis=-1)


def full_ohe_tables(schema: FeatureSchema):
    """(G, K) padded index table + mask over ALL OHE groups (incl. immutable)."""
    groups = [list(g) for g in schema.ohe_groups()]
    if not groups:
        return (
            jnp.zeros((0, 1), dtype=jnp.int32),
            jnp.zeros((0, 1), dtype=bool),
        )
    idx, mask, _ = _pad_group_tables(groups)
    return jnp.asarray(idx), jnp.asarray(mask)
