"""Constraint-set API: pure jnp violation kernels + repair projections.

The reference's abstract ``Constraints`` (``/root/reference/src/attacks/moeva2/constraints.py:8-77``)
exposes violation evaluation (dual numpy/TF paths), feature metadata, and an
in-graph repair (``fix_features_types``). Here the design is TPU-first: a single
pure ``jax.numpy`` kernel ``(..., D) -> (..., K)`` serves every consumer —
the evolutionary attack's objective (vmapped over states x population), the
gradient attack's loss (differentiated), and post-hoc evaluation — with two
thresholding flavours:

- ``evaluate``: hard oracle semantics — violations ``<= tol`` snap to exactly 0
  (parity with the numpy path, e.g. ``lcld_constraints.py:221``);
- ``evaluate_smooth``: ``max(g - tol, 0)`` — the differentiable flavour used in
  gradient losses (parity with the TF path, ``lcld_constraints.py:155``).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp
import numpy as np

from .schema import ConstraintBounds, FeatureSchema

DEFAULT_TOL = 1e-3


class ConstraintViolationError(ValueError):
    pass


class ConstraintSet:
    """A use case's relational feature constraints.

    Subclasses implement ``_raw`` returning *unthresholded* violation
    magnitudes ``(..., K)`` from ML-space inputs ``(..., D)`` as pure jnp.
    """

    #: number of constraints K
    n_constraints: int = 0
    tol: float = DEFAULT_TOL

    def __init__(self, schema: FeatureSchema, bounds: ConstraintBounds | None = None):
        self.schema = schema
        self.constraint_bounds = bounds
        self._norm_cmin = None
        self._norm_inv_rng = None
        if bounds is not None:
            if self.n_constraints and bounds.n_constraints != self.n_constraints:
                raise ValueError(
                    f"{type(self).__name__} defines {self.n_constraints} constraints "
                    f"but the constraint-bounds file has {bounds.n_constraints} rows "
                    "(base vs augmented constraints.csv mix-up?)"
                )
            # numpy f64 constants: exact under the f64 post-hoc evaluator,
            # converted per the active x64 mode when traced
            rng = np.asarray(bounds.cmax, np.float64) - np.asarray(bounds.cmin, np.float64)
            self._norm_cmin = np.asarray(bounds.cmin, np.float64)
            self._norm_inv_rng = 1.0 / np.where(rng == 0, 1.0, rng)

    # -- to implement ------------------------------------------------------
    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def repair(self, x: jnp.ndarray) -> jnp.ndarray:
        """Project candidates toward constraint satisfaction (in-graph).

        Parity: ``Constraints.fix_features_types``. Default: identity (the
        botnet reference behaviour, ``botnet_constraints.py:14-15``).
        """
        return x

    # -- provided ----------------------------------------------------------
    def evaluate(self, x: jnp.ndarray) -> jnp.ndarray:
        """Hard-thresholded violations: value if > tol else exactly 0."""
        g = self._raw(x)
        return jnp.where(g > self.tol, g, 0.0)

    def evaluate_smooth(self, x: jnp.ndarray) -> jnp.ndarray:
        """Differentiable violations: clip(g - tol, 0, inf)."""
        return jnp.clip(self._raw(x) - self.tol, 0.0, jnp.inf)

    def normalise(self, g: jnp.ndarray) -> jnp.ndarray:
        if self.constraint_bounds is None:
            return g
        return (g - self._norm_cmin) * self._norm_inv_rng

    def check_constraints_error(self, x: np.ndarray) -> None:
        """Raise if any sample violates any constraint.

        Parity: ``Constraints.check_constraints_error`` (``constraints.py:73-77``).
        """
        g = np.asarray(self.evaluate(jnp.asarray(x)))
        n_bad = int((g > 0).sum())
        if n_bad > 0:
            raise ConstraintViolationError(
                f"{n_bad} constraint violations across {int((g.sum(-1) > 0).sum())} "
                f"samples (max violation {float(np.nanmax(g)):.6g})."
            )

    # -- metadata (delegates to the schema) --------------------------------
    @property
    def ledger_tag(self) -> str:
        """Cache/ledger identity of this constraint set.

        Hand-written domains identify by class name (byte-identical to the
        pre-IR ledger keys); spec-compiled domains override the instance
        attribute with ``spec:<name>:<hash12>`` so two processes serving the
        same spec revision share AOT executables while a spec edit is a new
        identity, never a stale hit.
        """
        return getattr(self, "_ledger_tag", None) or type(self).__name__

    def get_mutable_mask(self) -> np.ndarray:
        return np.asarray(self.schema.mutable)

    def get_feature_type(self) -> np.ndarray:
        return np.asarray(self.schema.types)

    def get_feature_min_max(self, dynamic_input=None):
        return self.schema.bounds(dynamic_input)

    def get_nb_constraints(self) -> int:
        return self.n_constraints


class FunctionalConstraintSet(ConstraintSet):
    """Wrap a plain function ``(x) -> (..., K)`` as a ConstraintSet."""

    def __init__(
        self,
        schema: FeatureSchema,
        fn: Callable[[jnp.ndarray], jnp.ndarray],
        n_constraints: int,
        bounds: ConstraintBounds | None = None,
        repair_fn: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
    ):
        super().__init__(schema, bounds)
        self._fn = fn
        self.n_constraints = n_constraints
        self._repair_fn = repair_fn

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._fn(x)

    def repair(self, x: jnp.ndarray) -> jnp.ndarray:
        if self._repair_fn is None:
            return x
        return self._repair_fn(x)
