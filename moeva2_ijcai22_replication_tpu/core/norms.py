"""Shared Lp-norm kernels: one home for norm-membership dispatch.

Consumers: the MoEvA2 objective (f2 distance), the post-hoc
ObjectiveCalculator, and the PGD family (gradient conditioning + ε-ball
projection). The reference spreads these across ART utilities and
``get_scaler_from_norm`` (``moeva2/utils.py:11-22``); supported norms are
2 and inf everywhere (``default_problem.py:80-91`` raises otherwise).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_INF_ALIASES = (np.inf, "inf", "linf")
_L2_ALIASES = (2, "2", 2.0)
_L1_ALIASES = (1, "1", 1.0)


def is_inf(norm) -> bool:
    return norm in _INF_ALIASES


def is_l2(norm) -> bool:
    return norm in _L2_ALIASES


def validate_norm(norm):
    if not (is_inf(norm) or is_l2(norm)):
        raise NotImplementedError(f"Unsupported norm: {norm!r} (use 2 or inf)")
    return norm


def lp_distance(diff: jnp.ndarray, norm) -> jnp.ndarray:
    """Per-row Lp norm over the trailing axis."""
    if is_inf(norm):
        return jnp.abs(diff).max(-1)
    if is_l2(norm):
        return jnp.sqrt((diff * diff).sum(-1))
    raise NotImplementedError(f"Unsupported norm: {norm!r}")


def project_ball(delta: jnp.ndarray, eps, norm) -> jnp.ndarray:
    """Project perturbations into the ε-ball (ART ``_projection`` parity)."""
    if is_inf(norm):
        return jnp.clip(delta, -eps, eps)
    if is_l2(norm):
        nrm = jnp.sqrt((delta * delta).sum(-1, keepdims=True))
        return delta * jnp.minimum(1.0, eps / (nrm + 1e-12))
    raise NotImplementedError(f"Unsupported norm: {norm!r}")


def condition_grad(grad: jnp.ndarray, norm) -> jnp.ndarray:
    """Norm-condition gradients for the ascent step (``atk.py:239-261``)."""
    tol = 1e-7
    if is_inf(norm):
        return jnp.sign(grad)
    if norm in _L1_ALIASES:
        return grad / (jnp.abs(grad).sum(-1, keepdims=True) + tol)
    if is_l2(norm):
        return grad / (jnp.sqrt((grad * grad).sum(-1, keepdims=True)) + tol)
    raise NotImplementedError(f"Unsupported norm: {norm!r}")
