"""Feature and constraint schema loading.

Parses the ``features.csv`` / ``constraints.csv`` schema the reference defines
(columns ``feature,type,mutable,min,max[,augmentation]``, type in
{real, int, oheN, softmax}; min/max may be the literal string ``"dynamic"``
meaning the bound is resolved per input sample). ``softmax`` marks genes that
together form one probability simplex — the genetic operators renormalise the
sub-vector after every crossover/mutation (the reference registers dedicated
operators for this type, ``softmax_crossover.py:9-42``,
``softmax_mutation.py:8-71``, though none of its shipped datasets use it).

Reference parity: the provisioning logic of the per-use-case ``Constraints``
subclasses (``/root/reference/src/examples/lcld/lcld_constraints.py:237-279``,
``botnet_constraints.py:190-232``). The ``bounds`` resolution with a dynamic
input mirrors ``get_feature_min_max(dynamic_input)``.
"""

from __future__ import annotations

import csv
from dataclasses import dataclass

import numpy as np

OHE_PREFIX = "ohe"
SOFTMAX_TYPE = "softmax"


def _parse_bool(value: str) -> bool:
    return str(value).strip().upper() in ("TRUE", "1", "YES")


@dataclass(frozen=True)
class FeatureSchema:
    """Static description of one tabular use case's feature space."""

    names: tuple
    types: np.ndarray  # (D,) object: "real" | "int" | "ohe<N>" | "softmax"
    mutable: np.ndarray  # (D,) bool
    raw_min: np.ndarray  # (D,) object: float or "dynamic"
    raw_max: np.ndarray  # (D,) object: float or "dynamic"
    augmentation: np.ndarray  # (D,) bool — augmented (derived XOR) feature flag

    def __post_init__(self):
        # The type strings are semantically load-bearing across independent
        # consumers (codec genetics, MILP variable typing, PGD rounding): an
        # unrecognised string must fail here, at load, not drift into
        # contradictory per-consumer defaults.
        import re

        bad = [
            (n, t)
            for n, t in zip(self.names, self.types)
            if not re.fullmatch(rf"real|int|{SOFTMAX_TYPE}|{OHE_PREFIX}\d+", str(t))
        ]
        if bad:
            raise ValueError(
                f"unknown feature type(s) {bad}; expected real, int, "
                f"{SOFTMAX_TYPE}, or {OHE_PREFIX}<N>"
            )

    @property
    def n_features(self) -> int:
        return len(self.names)

    @property
    def min_dynamic(self) -> np.ndarray:
        return np.array([str(v) == "dynamic" for v in self.raw_min])

    @property
    def max_dynamic(self) -> np.ndarray:
        return np.array([str(v) == "dynamic" for v in self.raw_max])

    @property
    def has_dynamic_bounds(self) -> bool:
        return bool(self.min_dynamic.any() or self.max_dynamic.any())

    def bounds(self, dynamic_input: np.ndarray | None = None):
        """Resolve (xl, xu) float bounds; dynamic entries come from the input.

        With no dynamic input, dynamic entries resolve to 0.0 (the reference's
        behaviour, which it warns about). ``dynamic_input`` may be a single
        sample ``(D,)`` or a batch ``(S, D)`` — bounds broadcast accordingly.
        """
        min_dyn = self.min_dynamic
        max_dyn = self.max_dynamic
        xl = np.zeros(self.n_features)
        xu = np.zeros(self.n_features)
        xl[~min_dyn] = np.asarray(self.raw_min[~min_dyn], dtype=float)
        xu[~max_dyn] = np.asarray(self.raw_max[~max_dyn], dtype=float)
        if dynamic_input is not None:
            dynamic_input = np.asarray(dynamic_input, dtype=float)
            if dynamic_input.ndim == 1:
                xl = xl.copy()
                xu = xu.copy()
                xl[min_dyn] = dynamic_input[min_dyn]
                xu[max_dyn] = dynamic_input[max_dyn]
            else:
                xl = np.broadcast_to(xl, dynamic_input.shape).copy()
                xu = np.broadcast_to(xu, dynamic_input.shape).copy()
                xl[:, min_dyn] = dynamic_input[:, min_dyn]
                xu[:, max_dyn] = dynamic_input[:, max_dyn]
        return xl, xu

    def ohe_groups(self) -> list[np.ndarray]:
        """Index groups of one-hot-encoded features, in first-seen order."""
        seen: dict[str, list[int]] = {}
        for i, t in enumerate(self.types):
            t = str(t)
            if t.startswith(OHE_PREFIX):
                seen.setdefault(t, []).append(i)
        return [np.array(v) for v in seen.values()]

    @classmethod
    def from_csv(cls, path: str) -> "FeatureSchema":
        names, types, mutable, rmin, rmax, aug = [], [], [], [], [], []
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                names.append(row["feature"])
                types.append(row["type"])
                mutable.append(_parse_bool(row["mutable"]))
                rmin.append(_coerce_bound(row["min"]))
                rmax.append(_coerce_bound(row["max"]))
                aug.append(_parse_bool(row.get("augmentation", "FALSE")))
        return cls(
            names=tuple(names),
            types=np.array(types, dtype=object),
            mutable=np.array(mutable, dtype=bool),
            raw_min=np.array(rmin, dtype=object),
            raw_max=np.array(rmax, dtype=object),
            augmentation=np.array(aug, dtype=bool),
        )


def _coerce_bound(value: str):
    value = str(value).strip()
    if value == "dynamic":
        return "dynamic"
    return float(value)


@dataclass(frozen=True)
class ConstraintBounds:
    """Per-constraint (min, max) used to normalise violation magnitudes.

    Reference parity: ``constraints.csv`` consumed by ``_provision_constraints_min_max``
    + the MinMax scaler over them (``lcld_constraints.py:27-30,275-279``).
    """

    cmin: np.ndarray
    cmax: np.ndarray

    @property
    def n_constraints(self) -> int:
        return len(self.cmin)

    def normalise(self, g: np.ndarray) -> np.ndarray:
        rng = self.cmax - self.cmin
        rng = np.where(rng == 0, 1.0, rng)
        return (g - self.cmin) / rng

    @classmethod
    def from_csv(cls, path: str) -> "ConstraintBounds":
        cmin, cmax = [], []
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                cmin.append(float(row["min"]))
                cmax.append(float(row["max"]))
        return cls(cmin=np.array(cmin), cmax=np.array(cmax))
