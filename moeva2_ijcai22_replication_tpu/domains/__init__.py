"""Use-case domain plugins and their registry.

Parity: the reference's project-name -> constraint-class lookup
(``/root/reference/src/experiments/united/utils.py:12-30``).
"""

from .lcld import LcldConstraints, LcldAugmentedConstraints
from .botnet import BotnetConstraints, BotnetAugmentedConstraints

CONSTRAINTS_REGISTRY = {
    "lcld": LcldConstraints,
    "botnet": BotnetConstraints,
    "lcld_augmented": LcldAugmentedConstraints,
    "botnet_augmented": BotnetAugmentedConstraints,
}


def get_constraints_class(project_name: str):
    try:
        return CONSTRAINTS_REGISTRY[project_name]
    except KeyError:
        raise ValueError(
            f"Unknown project {project_name!r}; known: {sorted(CONSTRAINTS_REGISTRY)}"
        ) from None


__all__ = [
    "LcldConstraints",
    "LcldAugmentedConstraints",
    "BotnetConstraints",
    "BotnetAugmentedConstraints",
    "CONSTRAINTS_REGISTRY",
    "get_constraints_class",
]
