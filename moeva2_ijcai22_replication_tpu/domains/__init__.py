"""Use-case domain plugins and their registry.

Parity: the reference's project-name -> constraint-class lookup
(``/root/reference/src/experiments/united/utils.py:12-30``) — extended into
a real registry serving three origins:

- ``handwritten`` — the original jnp classes (``lcld``, ``botnet``, and
  their augmented variants). Their registry names, classes, and ledger
  identities are unchanged.
- ``spec`` — domains compiled from the declarative constraint IR
  (:mod:`.ir`): the committed re-expressions ``lcld_spec``/``botnet_spec``
  (bit-compatible with their hand-written twins) and data-only domains
  like ``phishing`` that exist *only* as a spec.
- ``generated`` — seeded synthetic families, ``family<seed>``, compiled on
  first lookup from :func:`.ir.generate_family`.

:func:`domain_origin` reports ``{origin, spec_hash}`` per registered name —
the provenance record ``/healthz`` exposes per served domain.
"""

from __future__ import annotations

import os
import re

from .lcld import LcldConstraints, LcldAugmentedConstraints
from .botnet import BotnetConstraints, BotnetAugmentedConstraints
from .ir import compile_spec, generate_family, load_spec, spec_hash

SPEC_DIR = os.path.join(os.path.dirname(__file__), "specs")

CONSTRAINTS_REGISTRY = {
    "lcld": LcldConstraints,
    "botnet": BotnetConstraints,
    "lcld_augmented": LcldAugmentedConstraints,
    "botnet_augmented": BotnetAugmentedConstraints,
}

#: committed spec-front domains: registry name -> spec file under SPEC_DIR
SPEC_DOMAINS = {
    "lcld_spec": "lcld.yaml",
    "botnet_spec": "botnet.yaml",
    "phishing": os.path.join("phishing", "constraints.csv"),
}

_GENERATED_RE = re.compile(r"family(\d+)$")


def spec_domain_dir(project_name: str) -> str:
    """Directory of a committed spec domain's package data (where a data-only
    domain like phishing keeps its ``features.csv``/``constraints.csv``)."""
    rel = SPEC_DOMAINS[project_name]
    return os.path.dirname(os.path.join(SPEC_DIR, rel)) or SPEC_DIR


def register_spec_domain(name: str, spec_path: str) -> type:
    """Compile a spec file and register it under ``name`` (idempotent for an
    unchanged spec; recompiles — new class, new ledger identity — when the
    file changed)."""
    cls = compile_spec(load_spec(spec_path, name=name))
    CONSTRAINTS_REGISTRY[name] = cls
    return cls


def get_constraints_class(project_name: str):
    try:
        return CONSTRAINTS_REGISTRY[project_name]
    except KeyError:
        pass
    if project_name in SPEC_DOMAINS:
        return register_spec_domain(
            project_name, os.path.join(SPEC_DIR, SPEC_DOMAINS[project_name])
        )
    m = _GENERATED_RE.fullmatch(project_name)
    if m:
        _, _, spec, _ = generate_family(int(m.group(1)))
        cls = compile_spec(spec)
        cls.origin = "generated"
        CONSTRAINTS_REGISTRY[project_name] = cls
        return cls
    raise ValueError(
        f"Unknown project {project_name!r}; known: "
        f"{sorted(set(CONSTRAINTS_REGISTRY) | set(SPEC_DOMAINS))} "
        "(plus generated family<seed> domains)"
    ) from None


def domain_origin(project_name: str) -> dict:
    """Provenance of a registered domain: ``{"origin": handwritten|spec|
    generated, "spec_hash": <sha256> | None}``."""
    cls = get_constraints_class(project_name)
    origin = getattr(cls, "origin", "handwritten")
    spec = getattr(cls, "spec", None)
    return {
        "origin": origin,
        "spec_hash": spec_hash(spec) if spec is not None else None,
    }


__all__ = [
    "LcldConstraints",
    "LcldAugmentedConstraints",
    "BotnetConstraints",
    "BotnetAugmentedConstraints",
    "CONSTRAINTS_REGISTRY",
    "SPEC_DOMAINS",
    "SPEC_DIR",
    "domain_origin",
    "get_constraints_class",
    "register_spec_domain",
    "spec_domain_dir",
]
