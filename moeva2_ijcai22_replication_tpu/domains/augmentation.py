"""XOR feature augmentation — the "engineering defense".

For the top-k important features, every pair (i, j) contributes a derived
binary feature ``XOR(x_i >= mean_i, x_j >= mean_j)``. Appending these to the
dataset makes the corresponding consistency constraints learnable.

Parity: ``augment_data`` (``/root/reference/src/experiments/botnet/features.py:6-21``)
and the consistency terms ``constraints_augmented_np/tf``
(``/root/reference/src/examples/utils.py:7-56``).
"""

from __future__ import annotations

from itertools import combinations
from math import comb
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


def pair_table(important_features: np.ndarray):
    """Static (P, 2) index and (P, 2) threshold tables over all pairs.

    ``important_features``: (k, 2) rows of [feature_index, threshold_mean].
    """
    k = important_features.shape[0]
    pairs = list(combinations(range(k), 2))
    idx = np.array(
        [[int(important_features[i, 0]), int(important_features[j, 0])] for i, j in pairs],
        dtype=np.int32,
    )
    thr = np.array(
        [[important_features[i, 1], important_features[j, 1]] for i, j in pairs]
    )
    return idx, thr


def n_pairs(important_features: np.ndarray) -> int:
    return comb(important_features.shape[0], 2)


def xor_features(x: jnp.ndarray, important_features: np.ndarray) -> jnp.ndarray:
    """Compute the (…, P) XOR pair features from base features."""
    idx, thr = pair_table(important_features)
    above = x[..., jnp.asarray(idx)] >= jnp.asarray(thr)  # (..., P, 2)
    return jnp.logical_xor(above[..., 0], above[..., 1]).astype(x.dtype)


def augment(x: jnp.ndarray, important_features: np.ndarray) -> jnp.ndarray:
    """Append XOR pair features along the last axis (any leading shape)."""
    return jnp.concatenate([x, xor_features(x, important_features)], axis=-1)


def consistency_terms(x: jnp.ndarray, important_features: np.ndarray) -> jnp.ndarray:
    """|x_aug - XOR(...)| per pair: the augmented-constraint violation terms.

    The augmented features are assumed to occupy the LAST P columns of ``x``
    (reference layout). Returns (…, P). For repeated evaluation (constraint
    kernels), prefer a prebuilt :class:`PairTables`.
    """
    return PairTables.build(important_features).consistency_terms(x)


class PairTables(NamedTuple):
    """Precomputed pair index/threshold tables for hot-loop use."""

    idx: jnp.ndarray  # (P, 2) int32
    thr: jnp.ndarray  # (P, 2)

    @classmethod
    def build(cls, important_features: np.ndarray) -> "PairTables":
        idx, thr = pair_table(important_features)
        return cls(idx=jnp.asarray(idx), thr=jnp.asarray(thr))

    @property
    def n_pairs(self) -> int:
        return self.idx.shape[0]

    def xor_features(self, x: jnp.ndarray) -> jnp.ndarray:
        above = x[..., self.idx] >= self.thr  # (..., P, 2)
        return jnp.logical_xor(above[..., 0], above[..., 1]).astype(x.dtype)

    def consistency_terms(self, x: jnp.ndarray) -> jnp.ndarray:
        return jnp.abs(x[..., -self.n_pairs :] - self.xor_features(x))
