"""CTU-13 botnet netflow domain: 756 features, 360 relational constraints.

All constraints are gathers + sums over static port-group index tables, so the
whole kernel is a handful of fused gathers on device.

Reference parity: ``/root/reference/src/examples/botnet/botnet_constraints.py``
(numpy oracle :117-173, group tables from ``feat_idx.pickle`` :26-31,
per-port builders :271-309). Constraint order matches the oracle:
[g1, g2] + 34 bytes/pkts-ratio terms + 108 (max<=sum) + 108 (min<=sum)
+ 108 (min<=max).

Quirk preserved on purpose: the reference sizes the bytes/pkts ratio loop by
``len("bytes_out_sum_s_idx") - 2 == 17`` — i.e. only the first 17 of 18 ports —
which is what makes the advertised total 2 + 34 + 324 = 360. We replicate that
count for metric parity.
"""

from __future__ import annotations

import os
import pickle

import jax.numpy as jnp
import numpy as np

from ..core.constraints import ConstraintSet
from ..core.schema import ConstraintBounds, FeatureSchema
from . import augmentation

_SUM_KEYS = ["bytes_out_sum_{0}_idx", "pkts_out_sum_{0}_idx", "duration_sum_{0}_idx"]
_MAX_KEYS = ["bytes_out_max_{0}_idx", "pkts_out_max_{0}_idx", "duration_max_{0}_idx"]
_MIN_KEYS = ["bytes_out_min_{0}_idx", "pkts_out_min_{0}_idx", "duration_min_{0}_idx"]
_RATIO_PORTS = 17  # reference's string-length quirk; see module docstring


class BotnetConstraints(ConstraintSet):
    n_constraints = 360

    def __init__(
        self,
        features_path: str,
        constraints_path: str,
        important_features_path: str | None = None,
    ):
        schema = FeatureSchema.from_csv(features_path)
        bounds = ConstraintBounds.from_csv(constraints_path)
        super().__init__(schema, bounds)

        data_dir = os.path.dirname(features_path)
        with open(os.path.join(data_dir, "feat_idx.pickle"), "rb") as f:
            self.feat_idx = {k: np.asarray(v) for k, v in pickle.load(f).items()}

        if important_features_path is None:
            important_features_path = os.path.join(
                data_dir, "important_features_19.npy"
            )
        self.important_features = (
            np.load(important_features_path)
            if os.path.exists(important_features_path)
            else None
        )
        self._build_tables()

    def _build_tables(self) -> None:
        fi = self.feat_idx

        # Global sum-equality groups (per direction s/d):
        # sum over {icmp,udp,tcp} port sums must equal sum over bytes_{in,out}.
        self._flow_idx = {}
        for side in ("s", "d"):
            self._flow_idx[side] = (
                jnp.asarray(
                    np.concatenate(
                        [
                            fi[f"icmp_sum_{side}_idx"],
                            fi[f"udp_sum_{side}_idx"],
                            fi[f"tcp_sum_{side}_idx"],
                        ]
                    )
                ),
                jnp.asarray(
                    np.concatenate(
                        [fi[f"bytes_in_sum_{side}_idx"], fi[f"bytes_out_sum_{side}_idx"]]
                    )
                ),
            )

        # bytes/pkts ratio <= 1500 per port (first _RATIO_PORTS ports, s then d).
        bytes_idx, pkts_idx = [], []
        for side in ("s", "d"):
            bytes_idx.append(fi[f"bytes_out_sum_{side}_idx"][:_RATIO_PORTS])
            pkts_idx.append(fi[f"pkts_out_sum_{side}_idx"][:_RATIO_PORTS])
        self._ratio_bytes = jnp.asarray(np.concatenate(bytes_idx))
        self._ratio_pkts = jnp.asarray(np.concatenate(pkts_idx))

        # Ordering constraints lower <= upper, flattened over (kind, side, port).
        def ordering(upper_tpls, lower_tpls):
            lo, up = [], []
            for side in ("s", "d"):
                for u_tpl, l_tpl in zip(upper_tpls, lower_tpls):
                    up.append(fi[u_tpl.format(side)])
                    lo.append(fi[l_tpl.format(side)])
            return jnp.asarray(np.concatenate(lo)), jnp.asarray(np.concatenate(up))

        self._orderings = [
            ordering(_SUM_KEYS, _MAX_KEYS),  # max <= sum
            ordering(_SUM_KEYS, _MIN_KEYS),  # min <= sum
            ordering(_MAX_KEYS, _MIN_KEYS),  # min <= max
        ]

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        terms = []
        for side in ("s", "d"):
            flows, byts = self._flow_idx[side]
            terms.append(
                jnp.abs(x[..., flows].sum(-1) - x[..., byts].sum(-1))[..., None]
            )

        b = x[..., self._ratio_bytes]
        p = x[..., self._ratio_pkts]
        ratio = jnp.where(p != 0, b / jnp.where(p != 0, p, 1.0), 0.0) - 1500.0
        terms.append(ratio)

        for lo, up in self._orderings:
            terms.append(x[..., lo] - x[..., up])

        return jnp.concatenate(terms, axis=-1)


class BotnetAugmentedConstraints(BotnetConstraints):
    """Botnet + C(19,2)=171 XOR-consistency constraints (531 total)."""

    n_constraints = 531

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.important_features is None:
            raise FileNotFoundError(
                "BotnetAugmentedConstraints requires important_features_19.npy "
                "(pass important_features_path or place it next to features.csv)"
            )
        self._pairs = augmentation.PairTables.build(self.important_features)

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        base = super()._raw(x)
        return jnp.concatenate([base, self._pairs.consistency_terms(x)], axis=-1)

    def repair(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError(
            "Repair is undefined for the augmented botnet domain (reference parity)."
        )
