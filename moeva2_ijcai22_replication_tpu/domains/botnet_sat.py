"""Botnet constraints as MILP rows — fully linear, no mode fixing needed.

Reference semantics: ``/root/reference/src/examples/botnet/botnet_constraints_sat.py``
(LinExpr sum-equalities, per-port orderings, bytes <= 1500·pkts). The group
index tables come from the same ``feat_idx.pickle`` the evaluation kernel
uses, including its 17-port ratio quirk (see ``domains/botnet.py``).
"""

from __future__ import annotations

import numpy as np

from ..attacks.sat.engine import LinearRows
from .botnet import BotnetConstraints

SLACK = 1e-4


def make_botnet_sat_builder(constraints: BotnetConstraints):
    fi = constraints.feat_idx

    static_rows = []

    # g1/g2: sum(icmp+udp+tcp) == sum(bytes_in + bytes_out) per direction
    for side in ("s", "d"):
        flows = np.concatenate(
            [fi[f"icmp_sum_{side}_idx"], fi[f"udp_sum_{side}_idx"], fi[f"tcp_sum_{side}_idx"]]
        )
        byts = np.concatenate(
            [fi[f"bytes_in_sum_{side}_idx"], fi[f"bytes_out_sum_{side}_idx"]]
        )
        cols = np.concatenate([flows, byts])
        coefs = np.concatenate([np.ones(len(flows)), -np.ones(len(byts))])
        static_rows.append((cols, coefs, -SLACK, SLACK))

    # bytes <= 1500 * pkts per port (reference's 17-port loop)
    ratio_bytes = np.asarray(constraints._ratio_bytes)
    ratio_pkts = np.asarray(constraints._ratio_pkts)
    for b, p in zip(ratio_bytes, ratio_pkts):
        static_rows.append(([int(b), int(p)], [1.0, -1500.0], -np.inf, 0.0))

    # orderings lower <= upper
    for lo_idx, up_idx in constraints._orderings:
        for lo, up in zip(np.asarray(lo_idx), np.asarray(up_idx)):
            static_rows.append(([int(lo), int(up)], [1.0, -1.0], -np.inf, 0.0))

    def build(
        x_init: np.ndarray, hot: np.ndarray, box: tuple | None = None
    ) -> LinearRows:
        # box unused: every botnet constraint is already linear, nothing to
        # grid-search (the builder protocol passes it to all domains).
        # rows is a fresh list per call: the engine may append state-specific
        # rows (e.g. the softmax simplex row) to the returned spec.
        return LinearRows(rows=list(static_rows), fixes={})

    return build
