"""Constraint-expression IR and domain compiler: domain-as-data.

One declarative spec (YAML or a ``constraints.csv`` grown an ``expr``
column) compiles into everything the pipeline previously required three
hand-written implementations for:

- a vectorized jnp penalty-terms kernel (:func:`compile_spec` ->
  :class:`SpecConstraintSet`), bit-compatible with the hand-written domains
  it re-expresses;
- a HiGHS MILP row builder for the SAT/repair attack
  (:func:`make_spec_sat_builder`);
- an in-graph constructive repair projection derived from the defining
  equalities (:mod:`.repair_backend`, wired into the compiled class).

See ``DESIGN.md`` § "Constraint IR & domain compiler" and the README's
five-step onboarding walkthrough.
"""

from .expr import Constraint, Env, SpecError, parse_constraint, parse_expr
from .generator import generate_family, sample_family, write_family
from .jnp_backend import SpecConstraintSet, compile_spec, compile_spec_path
from .milp_backend import SpecMilpError, make_spec_sat_builder
from .ops import finite_div, months, safe_div
from .spec import (
    ConstraintSpec,
    ResolvedSpec,
    load_spec,
    load_spec_csv,
    load_spec_yaml,
    resolve_spec,
    spec_hash,
    validate_spec,
)

__all__ = [
    "Constraint",
    "ConstraintSpec",
    "Env",
    "ResolvedSpec",
    "SpecConstraintSet",
    "SpecError",
    "SpecMilpError",
    "compile_spec",
    "compile_spec_path",
    "finite_div",
    "generate_family",
    "load_spec",
    "load_spec_csv",
    "load_spec_yaml",
    "make_spec_sat_builder",
    "months",
    "parse_constraint",
    "parse_expr",
    "resolve_spec",
    "safe_div",
    "sample_family",
    "spec_hash",
    "validate_spec",
    "write_family",
]
