"""Constraint-expression AST, parser, and evaluators.

The expression language covers exactly the operator inventory the reference
uses (SURVEY §2.4): arithmetic ``+ - * / ^``, ``abs``, guarded ratios
(``safe_div``/``finite_div``), YYYYMM date arithmetic (``months``), feature
aggregates (``sum(@group)``) and elementwise column-group terms
(``@group``), with comparisons ``<=``/``==`` and membership
``in {v1, v2, ...}`` at the constraint level.

Three consumers share the AST: the jnp backend evaluates it with ``jnp``
(tracing a kernel), the tests evaluate it with ``numpy`` (the oracle twin),
and the MILP backend walks it symbolically (``milp_backend``). Canonical
serialization (:func:`canon`) is the round-trip/normal form the spec hash
is computed over, so formatting differences never change a cache identity.

Bit-exactness contract: evaluation emits the same per-element op sequence
the hand-written kernels use — ``a <= b`` becomes ``a - b``, ``a == b``
becomes ``|a - b|``, ``x in {v1..vk}`` becomes ``|(v1-x)·...·(vk-x)|``
(left-associated), groups gather through one concatenated index array —
so a compiled spec reproduces ``lcld_constraint_terms`` /
``BotnetConstraints._raw`` bit for bit.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from . import ops


class SpecError(ValueError):
    """A spec failed to parse, resolve, or type-check."""


# -- AST ---------------------------------------------------------------------


@dataclass(frozen=True)
class Num:
    value: float


@dataclass(frozen=True)
class Feat:
    name: str


@dataclass(frozen=True)
class Group:
    name: str


@dataclass(frozen=True)
class Neg:
    arg: object


@dataclass(frozen=True)
class Bin:
    op: str  # + - * / ^
    lhs: object
    rhs: object


@dataclass(frozen=True)
class Call:
    fn: str  # abs | months | safe_div | finite_div | sum
    args: tuple


#: function name -> arity
FUNCTIONS = {"abs": 1, "months": 1, "safe_div": 3, "finite_div": 3, "sum": 1}


@dataclass(frozen=True)
class Constraint:
    """One named constraint: ``le``/``eq`` relate two expressions,
    ``member`` restricts a feature expression to a finite value set."""

    name: str
    kind: str  # le | eq | member
    lhs: object
    rhs: object  # expr for le/eq; tuple[float, ...] for member


# -- parser ------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>(?:\d+\.?\d*|\.\d+)(?:[eE][-+]?\d+)?)"
    r"|(?P<name>[A-Za-z_][A-Za-z0-9_]*)"
    r"|(?P<group>@[A-Za-z_][A-Za-z0-9_]*)"
    r"|(?P<op><=|==|[-+*/^(),{}]))"
)


def _tokenize(text: str) -> list[tuple[str, str]]:
    tokens, pos = [], 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                break
            raise SpecError(f"cannot tokenize {text[pos:]!r} in {text!r}")
        pos = m.end()
        for kind in ("num", "name", "group", "op"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    tokens.append(("end", ""))
    return tokens


class _Parser:
    """Recursive descent over the token list. Precedence (loose to tight):
    comparison, ``+ -``, ``* /`` (left-assoc), unary ``-``, ``^``
    (right-assoc), atoms."""

    def __init__(self, text: str):
        self.text = text
        self.tokens = _tokenize(text)
        self.i = 0

    def peek(self):
        return self.tokens[self.i]

    def next(self):
        tok = self.tokens[self.i]
        self.i += 1
        return tok

    def expect(self, value: str):
        kind, val = self.next()
        if val != value:
            raise SpecError(f"expected {value!r}, got {val!r} in {self.text!r}")

    def parse_constraint(self, name: str) -> Constraint:
        lhs = self.parse_expr()
        kind, val = self.next()
        if val == "<=":
            rhs = self.parse_expr()
            out = Constraint(name, "le", lhs, rhs)
        elif val == "==":
            rhs = self.parse_expr()
            out = Constraint(name, "eq", lhs, rhs)
        elif kind == "name" and val == "in":
            self.expect("{")
            values = [self._member_value()]
            while self.peek()[1] == ",":
                self.next()
                values.append(self._member_value())
            self.expect("}")
            out = Constraint(name, "member", lhs, tuple(values))
        else:
            raise SpecError(
                f"expected <=, == or 'in' after expression in {self.text!r}"
            )
        if self.peek()[0] != "end":
            raise SpecError(f"trailing tokens after constraint in {self.text!r}")
        return out

    def _member_value(self) -> float:
        neg = False
        if self.peek()[1] == "-":
            self.next()
            neg = True
        kind, val = self.next()
        if kind != "num":
            raise SpecError(f"membership sets are numeric literals: {self.text!r}")
        v = float(val)
        return -v if neg else v

    def parse_expr(self):
        node = self.parse_term()
        while self.peek()[1] in ("+", "-"):
            op = self.next()[1]
            node = Bin(op, node, self.parse_term())
        return node

    def parse_term(self):
        node = self.parse_unary()
        while self.peek()[1] in ("*", "/"):
            op = self.next()[1]
            node = Bin(op, node, self.parse_unary())
        return node

    def parse_unary(self):
        if self.peek()[1] == "-":
            self.next()
            return Neg(self.parse_unary())
        return self.parse_power()

    def parse_power(self):
        base = self.parse_atom()
        if self.peek()[1] == "^":
            self.next()
            return Bin("^", base, self.parse_unary())
        return base

    def parse_atom(self):
        kind, val = self.next()
        if kind == "num":
            return Num(float(val))
        if kind == "group":
            return Group(val[1:])
        if kind == "name":
            if self.peek()[1] == "(":
                if val not in FUNCTIONS:
                    raise SpecError(f"unknown function {val!r} in {self.text!r}")
                self.next()
                args = [self.parse_expr()]
                while self.peek()[1] == ",":
                    self.next()
                    args.append(self.parse_expr())
                self.expect(")")
                if len(args) != FUNCTIONS[val]:
                    raise SpecError(
                        f"{val}() takes {FUNCTIONS[val]} args, got {len(args)} "
                        f"in {self.text!r}"
                    )
                return Call(val, tuple(args))
            return Feat(val)
        if val == "(":
            node = self.parse_expr()
            self.expect(")")
            return node
        raise SpecError(f"unexpected token {val!r} in {self.text!r}")


def parse_expr(text: str):
    p = _Parser(text)
    node = p.parse_expr()
    if p.peek()[0] != "end":
        raise SpecError(f"trailing tokens in expression {text!r}")
    return node


def parse_constraint(name: str, text: str) -> Constraint:
    return _Parser(text).parse_constraint(name)


# -- canonical serialization -------------------------------------------------

_PREC = {"+": 1, "-": 1, "*": 2, "/": 2, "neg": 3, "^": 4}


def _canon(node, parent_prec: int = 0, right_of_same: bool = False) -> str:
    if isinstance(node, Num):
        return repr(node.value)
    if isinstance(node, Feat):
        return node.name
    if isinstance(node, Group):
        return f"@{node.name}"
    if isinstance(node, Call):
        return f"{node.fn}({', '.join(_canon(a) for a in node.args)})"
    if isinstance(node, Neg):
        inner = _canon(node.arg, _PREC["neg"])
        text = f"-{inner}"
        return f"({text})" if parent_prec > _PREC["neg"] or right_of_same else text
    if isinstance(node, Bin):
        prec = _PREC[node.op]
        if node.op == "^":  # right-assoc: parenthesize a binop base
            lhs = _canon(node.lhs, prec + 1)
            rhs = _canon(node.rhs, prec)
            text = f"{lhs}{node.op}{rhs}"
        else:
            lhs = _canon(node.lhs, prec)
            rhs = _canon(node.rhs, prec, right_of_same=True)
            text = f"{lhs} {node.op} {rhs}"
        if prec < parent_prec or (right_of_same and prec == parent_prec):
            return f"({text})"
        return text
    raise SpecError(f"cannot serialize {node!r}")


def canon_expr(node) -> str:
    return _canon(node)


def canon_constraint(c: Constraint) -> str:
    if c.kind == "le":
        return f"{_canon(c.lhs)} <= {_canon(c.rhs)}"
    if c.kind == "eq":
        return f"{_canon(c.lhs)} == {_canon(c.rhs)}"
    if c.kind == "member":
        return f"{_canon(c.lhs)} in {{{', '.join(repr(v) for v in c.rhs)}}}"
    raise SpecError(f"unknown constraint kind {c.kind!r}")


# -- structural queries ------------------------------------------------------


def walk(node):
    yield node
    if isinstance(node, Bin):
        yield from walk(node.lhs)
        yield from walk(node.rhs)
    elif isinstance(node, Neg):
        yield from walk(node.arg)
    elif isinstance(node, Call):
        for a in node.args:
            yield from walk(a)


def features_of(node) -> set:
    return {n.name for n in walk(node) if isinstance(n, Feat)}


def groups_of(node) -> set:
    return {n.name for n in walk(node) if isinstance(n, Group)}


def constraint_features(c: Constraint) -> set:
    out = features_of(c.lhs)
    if c.kind != "member":
        out |= features_of(c.rhs)
    return out


# -- numeric evaluation ------------------------------------------------------


class Env:
    """Name resolution for evaluation: feature name -> column index, group
    name -> concatenated numpy index array."""

    def __init__(self, columns: dict, groups: dict):
        self.columns = dict(columns)
        self.groups = dict(groups)

    def col(self, name: str) -> int:
        try:
            return self.columns[name]
        except KeyError:
            raise SpecError(f"undefined feature {name!r}") from None

    def group(self, name: str):
        try:
            return self.groups[name]
        except KeyError:
            raise SpecError(f"undefined group {name!r}") from None


def eval_expr(node, x, env: Env, xp):
    """Evaluate to ``(value, width)``: width 0 = python-float literal (weak
    scalar), 1 = per-row scalar array ``(...)``, k>1 = per-row vector
    ``(..., k)``. Mixed scalar-array/vector operands expand via
    ``[..., None]``; literals broadcast natively (matching the hand-written
    kernels' use of bare python constants)."""
    if isinstance(node, Num):
        return node.value, 0
    if isinstance(node, Feat):
        return x[..., env.col(node.name)], 1
    if isinstance(node, Group):
        idx = env.group(node.name)
        return x[..., idx], len(idx)
    if isinstance(node, Neg):
        v, w = eval_expr(node.arg, x, env, xp)
        return -v, w
    if isinstance(node, Bin):
        a, wa = eval_expr(node.lhs, x, env, xp)
        b, wb = eval_expr(node.rhs, x, env, xp)
        a, b, w = _align(a, wa, b, wb)
        if node.op == "+":
            return a + b, w
        if node.op == "-":
            return a - b, w
        if node.op == "*":
            return a * b, w
        if node.op == "/":
            return a / b, w
        if node.op == "^":
            return xp.power(a, b), w
        raise SpecError(f"unknown operator {node.op!r}")
    if isinstance(node, Call):
        if node.fn == "sum":
            v, w = eval_expr(node.args[0], x, env, xp)
            if w < 2:
                raise SpecError("sum() takes a @group argument")
            return v.sum(-1), 1
        if node.fn == "abs":
            v, w = eval_expr(node.args[0], x, env, xp)
            return xp.abs(v), w
        if node.fn == "months":
            v, w = eval_expr(node.args[0], x, env, xp)
            return ops.months(v), w
        if node.fn in ("safe_div", "finite_div"):
            n, wn = eval_expr(node.args[0], x, env, xp)
            d, wd = eval_expr(node.args[1], x, env, xp)
            s = node.args[2]
            if not isinstance(s, (Num, Neg)):
                raise SpecError(f"{node.fn}() sentinel must be a literal")
            sval = s.value if isinstance(s, Num) else -s.arg.value
            n, d, w = _align(n, wn, d, wd)
            fn = ops.safe_div if node.fn == "safe_div" else ops.finite_div
            return fn(n, d, sval), w
        raise SpecError(f"unknown function {node.fn!r}")
    raise SpecError(f"cannot evaluate {node!r}")


def _align(a, wa, b, wb):
    """Broadcast a width-1 scalar array against a width-k vector."""
    if wa == wb or wa == 0 or wb == 0:
        return a, b, max(wa, wb)
    if wa == 1:
        return a[..., None], b, wb
    if wb == 1:
        return a, b[..., None], wa
    raise SpecError(f"group width mismatch: {wa} vs {wb}")


def eval_term(c: Constraint, x, env: Env, xp):
    """One constraint's unthresholded violation term ``(value, width)`` —
    the exact op sequences of the hand-written kernels."""
    if c.kind == "le":
        a, wa = eval_expr(c.lhs, x, env, xp)
        b, wb = eval_expr(c.rhs, x, env, xp)
        a, b, w = _align(a, wa, b, wb)
        return a - b, w
    if c.kind == "eq":
        a, wa = eval_expr(c.lhs, x, env, xp)
        b, wb = eval_expr(c.rhs, x, env, xp)
        a, b, w = _align(a, wa, b, wb)
        return xp.abs(a - b), w
    if c.kind == "member":
        v, w = eval_expr(c.lhs, x, env, xp)
        prod = c.rhs[0] - v
        for val in c.rhs[1:]:
            prod = prod * (val - v)
        return xp.abs(prod), w
    raise SpecError(f"unknown constraint kind {c.kind!r}")
