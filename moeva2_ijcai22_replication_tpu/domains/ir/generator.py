"""Seeded synthetic domain-family generator.

Draws random-but-feasible constraint specs from the IR's operator inventory
— ordering chains, linear definitions, guarded ratios, YYYYMM month
arithmetic, memberships — so tests and benchmarks can sweep *families* of
domains instead of the two hand-written ones. Everything is derived from a
``numpy`` Generator seeded explicitly: the same seed reproduces the same
schema, the same spec (same :func:`~.spec.spec_hash`), and the same data.

Feasibility is by construction: base features are sampled uniformly in
bounds, ordering columns are sorted into place, and the compiled repair
projection (:mod:`.repair_backend`) snaps memberships and re-derives the
defined features — so the sampler needs no rejection loop and acceptance is
total.
"""

from __future__ import annotations

import csv
import os

import numpy as np

from ...core.schema import FeatureSchema
from .spec import ConstraintSpec, resolve_spec
from .expr import parse_constraint


def generate_family(seed: int, n_base: int = 8):
    """-> (feature_rows, constraint_rows, ConstraintSpec).

    ``feature_rows`` / ``constraint_rows`` are ready for
    ``features.csv`` / ``constraints.csv`` (the CSV spec front, ``expr``
    column included), so a generated family round-trips through the same
    loader path as a committed domain.
    """
    rng = np.random.default_rng(seed)
    n_base = max(int(n_base), 6)

    feats = []  # (name, lo, hi)
    for i in range(n_base):
        hi = float(rng.choice([10.0, 100.0, 1000.0]))
        feats.append((f"f{i}", 0.0, hi))
    # two YYYYMM date features feeding the month-arithmetic operator
    feats.append(("date_a", 200001.0, 202012.0))
    feats.append(("date_b", 200001.0, 202012.0))
    date_a, date_b = n_base, n_base + 1

    constraints = []

    # ordering chain over a random base subset (sampler sorts these columns)
    chain_len = int(rng.integers(3, min(5, n_base) + 1))
    chain = sorted(rng.choice(n_base, size=chain_len, replace=False).tolist())
    for a, b in zip(chain, chain[1:]):
        constraints.append((f"ord_{a}_{b}", f"f{a} <= f{b}"))

    # membership on one non-chain base feature
    pool = [i for i in range(n_base) if i not in chain]
    if pool:
        m = int(rng.choice(pool))
        hi = feats[m][2]
        k = int(rng.integers(2, 4))
        values = sorted(
            float(v) for v in rng.choice(int(hi), size=k, replace=False)
        )
        constraints.append(
            (f"member_{m}", f"f{m} in {{{', '.join(repr(v) for v in values)}}}")
        )

    # derived features: linear definition, guarded ratio, month difference
    d0 = len(feats)
    i, j = (int(v) for v in rng.choice(n_base, size=2, replace=False))
    c = float(np.round(rng.uniform(0.5, 3.0), 2))
    feats.append((f"d{0}", 0.0, feats[i][2] + c * feats[j][2]))
    constraints.append((f"def_lin", f"d0 == f{i} + {c!r}*f{j}"))

    i, j = (int(v) for v in rng.choice(n_base, size=2, replace=False))
    feats.append((f"d{1}", 0.0, feats[i][2]))
    constraints.append((f"def_ratio", f"d1 == safe_div(f{i}, f{j}, 0.0)"))

    feats.append((f"d{2}", -260.0, 260.0))
    constraints.append(
        (f"def_months", "d2 == months(date_a) - months(date_b)")
    )
    derived = [d0, d0 + 1, d0 + 2]

    feature_rows = [
        {
            "feature": name,
            "type": "real",
            "mutable": "TRUE",
            "min": repr(lo),
            "max": repr(hi),
            "augmentation": "",
        }
        for name, lo, hi in feats
    ]
    constraint_rows = [
        {"constraint": name, "min": "0", "max": "1", "expr": expr}
        for name, expr in constraints
    ]
    spec = ConstraintSpec(
        name=f"family{seed}",
        constraints=tuple(
            parse_constraint(name, expr) for name, expr in constraints
        ),
    )
    return feature_rows, constraint_rows, spec, {
        "chain": chain,
        "derived": derived,
    }


def write_family(out_dir: str, seed: int, n_base: int = 8) -> str:
    """Materialize a generated family as ``features.csv``/``constraints.csv``
    under ``out_dir`` (created); returns ``out_dir``."""
    feature_rows, constraint_rows, _, _ = generate_family(seed, n_base)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "features.csv"), "w", newline="") as f:
        w = csv.DictWriter(
            f,
            fieldnames=["feature", "type", "mutable", "min", "max", "augmentation"],
        )
        w.writeheader()
        w.writerows(feature_rows)
    with open(os.path.join(out_dir, "constraints.csv"), "w", newline="") as f:
        w = csv.DictWriter(f, fieldnames=["constraint", "min", "max", "expr"])
        w.writeheader()
        w.writerows(constraint_rows)
    return out_dir


def sample_family(
    n: int, seed: int, n_base: int = 8
) -> tuple:
    """-> (x, schema, spec): ``n`` feasible rows of the seeded family.

    Uniform in bounds -> ordering columns sorted into place -> compiled
    repair snaps memberships and re-derives defined features. No rejection
    loop needed.
    """
    import jax.numpy as jnp

    from ...core.codec import full_ohe_tables
    from .repair_backend import compile_repair

    feature_rows, _, spec, meta = generate_family(seed, n_base)
    nf = len(feature_rows)
    schema = FeatureSchema(
        names=tuple(r["feature"] for r in feature_rows),
        types=np.array([r["type"] for r in feature_rows], dtype=object),
        mutable=np.ones(nf, dtype=bool),
        raw_min=np.array([float(r["min"]) for r in feature_rows], dtype=object),
        raw_max=np.array([float(r["max"]) for r in feature_rows], dtype=object),
        augmentation=np.zeros(nf, dtype=bool),
    )
    rng = np.random.default_rng(seed + 1)
    xl, xu = schema.bounds()
    xl = np.asarray(xl, dtype=float).reshape(-1)
    xu = np.asarray(xu, dtype=float).reshape(-1)
    x = rng.uniform(xl, xu, size=(n, len(xl)))
    chain = meta["chain"]
    x[:, chain] = np.sort(x[:, chain], axis=1)
    resolved = resolve_spec(spec, schema)
    ohe_idx, ohe_mask = full_ohe_tables(schema)
    repair = compile_repair(resolved, schema, ohe_idx, ohe_mask)
    x = np.asarray(repair(jnp.asarray(x)), dtype=float)
    return x, schema, spec
