"""jnp compiler backend: spec -> vectorized penalty-terms kernel.

:func:`compile_spec` turns a :class:`~.spec.ConstraintSpec` into a
:class:`~...core.constraints.ConstraintSet` subclass whose ``_raw`` emits
the same per-element op sequences as the hand-written kernels (see
:mod:`.expr`), so a committed spec reproduces ``lcld_constraint_terms`` /
``BotnetConstraints._raw`` bit for bit on the same inputs.

Trace stability / cache identity: every compiled class is a distinct Python
type, but the engines' ``_ledger_identity`` and the AOT-cache keys
discriminate by :attr:`ConstraintSet.ledger_tag` — which compiled sets
override with ``spec:<name>:<hash12>`` — so two processes serving the same
spec revision share executables while a spec edit is a new identity, not a
silent stale hit.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from ...core.codec import full_ohe_tables
from ...core.constraints import ConstraintSet
from ...core.schema import ConstraintBounds, FeatureSchema
from .expr import eval_term
from .repair_backend import compile_repair
from .spec import ConstraintSpec, ResolvedSpec, load_spec, resolve_spec


def raw_terms(x, resolved: ResolvedSpec, xp):
    """Unthresholded violation magnitudes ``(..., n_terms)``.

    All-scalar specs stack (the lcld shape); mixed scalar/group specs
    concatenate with scalars expanded ``[..., None]`` (the botnet shape) —
    matching the hand-written kernels' assembly, and either way leaving the
    per-element values untouched.
    """
    vals = []
    for c, w in zip(resolved.spec.constraints, resolved.widths):
        v, vw = eval_term(c, x, resolved.env, xp)
        if vw == 0:  # degenerate literal-only constraint: broadcast per row
            v = v + 0.0 * x[..., 0]
        vals.append((v, max(vw, 1)))
    if all(w == 1 for _, w in vals):
        return xp.stack([v for v, _ in vals], axis=-1)
    return xp.concatenate(
        [v[..., None] if w == 1 else v for v, w in vals], axis=-1
    )


class SpecConstraintSet(ConstraintSet):
    """A constraint set compiled from a declarative spec.

    Constructor signature matches the hand-written domain classes
    (``(features_path, constraints_path)``), so the registry and
    ``load_constraints`` treat compiled and hand-written domains uniformly.
    ``constraints_path`` may be None/"" for spec families without committed
    violation-normalisation bounds.
    """

    #: the compiled spec — set per subclass by :func:`compile_spec`
    spec: ConstraintSpec = None
    origin = "spec"

    def __init__(
        self,
        features_path: str,
        constraints_path: str | None = None,
        important_features_path: str | None = None,
    ):
        if self.spec is None:
            raise TypeError(
                "SpecConstraintSet is abstract; build a subclass with "
                "compile_spec(spec)"
            )
        schema = FeatureSchema.from_csv(features_path)
        bounds = (
            ConstraintBounds.from_csv(constraints_path)
            if constraints_path
            else None
        )
        data_dir = os.path.dirname(os.path.abspath(features_path))
        resolved = resolve_spec(self.spec, schema, data_dir)
        # instance attr must exist before super().__init__ runs its
        # bounds-row count check (n_constraints is group-resolution
        # dependent, so it cannot be a class attribute)
        self.n_constraints = resolved.n_terms
        super().__init__(schema, bounds)
        self.resolved = resolved
        self._ledger_tag = f"spec:{self.spec.name}:{resolved.hash[:12]}"
        self.important_features = (
            np.load(important_features_path)
            if important_features_path and os.path.exists(important_features_path)
            else None
        )
        self._ohe_idx, self._ohe_mask = full_ohe_tables(schema)
        self._repair_fn = compile_repair(
            resolved, schema, self._ohe_idx, self._ohe_mask
        )

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        return raw_terms(x, self.resolved, jnp)

    def raw_numpy(self, x: np.ndarray) -> np.ndarray:
        """The numpy oracle twin of ``_raw`` — same AST, numpy ufuncs."""
        with np.errstate(divide="ignore", invalid="ignore"):
            return np.asarray(raw_terms(np.asarray(x), self.resolved, np))

    def repair(self, x: jnp.ndarray) -> jnp.ndarray:
        return self._repair_fn(x)


def compile_spec(spec: ConstraintSpec) -> type:
    """Spec -> ConstraintSet subclass (instantiate with the usual
    ``(features_path, constraints_path)``)."""
    return type(
        f"Spec_{spec.name}",
        (SpecConstraintSet,),
        {"spec": spec, "__module__": __name__},
    )


def compile_spec_path(path: str, name: str | None = None) -> type:
    return compile_spec(load_spec(path, name=name))
