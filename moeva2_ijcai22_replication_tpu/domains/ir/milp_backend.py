"""MILP compiler backend: spec -> HiGHS ``LinearRows`` builder.

Generalizes the hand-written linearizations (``domains/lcld_sat.py``,
``domains/botnet_sat.py``) into one compiler over the IR:

- **pins**: immutable features are fixed at the hot-start value; a
  *pin-propagation fixpoint* then derives every feature a defining equality
  forces to a constant (the month-difference pattern: pinned dates make
  ``g7`` constant, which makes ``g8``/``g9`` linear). A propagated division
  by zero (zero month difference) flags the program infeasible — exactly
  ``lcld_sat``'s ``diff == 0`` escape.
- **affine rows**: constraints affine in the surviving variables emit plain
  rows — ``<=`` one-sided, ``==`` two-sided at ``±SLACK`` (inside the
  evaluator's 1e-3 snap), ``abs(E) <= c`` as a two-sided band.
- **membership modes**: ``f in {v1..vk}`` on a searched feature becomes
  one-hot mode binaries (``f = Σ v_k z_k``, ``Σ z_k = 1``); a constraint
  that is nonlinear only through such a feature (the term/amortisation
  pattern) is re-extracted per mode with big-M activation rows.
- **denominator grids**: ``r == n / d`` (and the guarded
  ``safe_div``/``finite_div`` forms) with a searched denominator reuses the
  ``lcld_sat`` denominator-grid pattern — candidate pins over the ε-box
  selected by one-hot binaries, with ``focus``/``window`` re-gridding for
  the engine's refinement rounds. Guarded ratios additionally get a
  *sentinel mode* (denominator pinned 0, ratio pinned to the sentinel)
  whenever 0 lies in the box, so the ``pub_rec == 0`` branch stays
  reachable without the hand-written special case.
- **guarded ratio bounds**: ``safe_div(n, d, s) <= C`` with ``d >= 0`` and
  ``s <= C`` cross-multiplies to ``n − C·d <= 0`` (the botnet 1500-ratio
  row; conservative at ``d = 0``).
- **anchored fallback**: residual nonlinearities whose value the
  hand-written builders also freeze at the initial point (the
  ``(1+r)^term`` amortisation factor with a *mutable* rate) are evaluated
  numerically at the anchor (x_init + pins) — products of two non-constant
  affines anchor the **right** operand, so specs should keep the searched
  variable leftmost (the committed lcld spec does).

Anything outside this inventory raises :class:`SpecMilpError` with the
constraint name — ``tools/domain_lint.py`` builds every committed spec once
to prove it compiles.
"""

from __future__ import annotations

import numpy as np

from ...attacks.sat.engine import LinearRows
from ...core.constraints import DEFAULT_TOL
from ..lcld_sat import SLACK, _denominator_grid
from . import expr as E
from .spec import ResolvedSpec


class SpecMilpError(ValueError):
    """A constraint shape the MILP backend cannot linearize."""


class _NonAffine(Exception):
    pass


class _Infeasible(Exception):
    pass


class _RatioMode(Exception):
    """num / den with a searched bare-feature denominator."""

    def __init__(self, den_col: int, num: "_Affine", sentinel: float | None):
        super().__init__(den_col)
        self.den_col = den_col
        self.num = num
        self.sentinel = sentinel


class _Affine:
    __slots__ = ("const", "coefs")

    def __init__(self, const: float = 0.0, coefs: dict | None = None):
        self.const = float(const)
        self.coefs = coefs or {}

    @property
    def is_const(self) -> bool:
        return not self.coefs

    def add(self, other: "_Affine", sign: float = 1.0) -> "_Affine":
        coefs = dict(self.coefs)
        for c, v in other.coefs.items():
            coefs[c] = coefs.get(c, 0.0) + sign * v
        coefs = {c: v for c, v in coefs.items() if v != 0.0}
        return _Affine(self.const + sign * other.const, coefs)

    def scale(self, k: float) -> "_Affine":
        return _Affine(self.const * k, {c: v * k for c, v in self.coefs.items()})


def _sentinel_value(node) -> float:
    if isinstance(node, E.Num):
        return node.value
    if isinstance(node, E.Neg) and isinstance(node.arg, E.Num):
        return -node.arg.value
    raise SpecMilpError("guarded-division sentinel must be a literal")


class _Extractor:
    """Affine extraction under pins, with optional element context for group
    constraints and optional anchored numeric fallback."""

    def __init__(self, env, pins: dict, elem: int | None, anchor=None):
        self.env = env
        self.pins = pins
        self.elem = elem
        self.anchor = anchor  # (D,) numpy row with pins applied, or None

    def _numeric(self, node) -> float:
        if self.anchor is None:
            raise _NonAffine(node)
        with np.errstate(divide="ignore", invalid="ignore"):
            v, w = E.eval_expr(node, self.anchor[None, :], self.env, np)
        v = np.asarray(v, dtype=float)
        if w > 1:
            v = v[..., self.elem]
        v = float(np.ravel(v)[0])
        if not np.isfinite(v):
            raise _Infeasible(f"anchored value of {E.canon_expr(node)} not finite")
        return v

    def _col(self, col: int) -> _Affine:
        if col in self.pins:
            return _Affine(self.pins[col])
        return _Affine(0.0, {col: 1.0})

    def run(self, node) -> _Affine:
        if isinstance(node, E.Num):
            return _Affine(node.value)
        if isinstance(node, E.Feat):
            return self._col(self.env.col(node.name))
        if isinstance(node, E.Group):
            idx = self.env.group(node.name)
            if self.elem is None:
                raise SpecMilpError(
                    f"group @{node.name} outside an elementwise constraint"
                )
            return self._col(int(idx[self.elem]))
        if isinstance(node, E.Neg):
            return self.run(node.arg).scale(-1.0)
        if isinstance(node, E.Bin):
            return self._bin(node)
        if isinstance(node, E.Call):
            return self._call(node)
        raise _NonAffine(node)

    def _bin(self, node: E.Bin) -> _Affine:
        if node.op in ("+", "-"):
            return self.run(node.lhs).add(
                self.run(node.rhs), 1.0 if node.op == "+" else -1.0
            )
        if node.op == "*":
            a, b = self.run(node.lhs), self.run(node.rhs)
            if a.is_const:
                return b.scale(a.const)
            if b.is_const:
                return a.scale(b.const)
            # two searched factors: anchor the right operand (spec
            # convention: searched variable leftmost)
            return a.scale(self._numeric(node.rhs))
        if node.op == "/":
            num = self.run(node.lhs)
            den = self.run(node.rhs)
            if den.is_const:
                if den.const == 0.0:
                    raise _Infeasible(
                        f"division by pinned zero in {E.canon_expr(node)}"
                    )
                return num.scale(1.0 / den.const)
            den_col = self._bare_col(node.rhs)
            if den_col is not None and self.anchor is None:
                raise _RatioMode(den_col, num, None)
            return num.scale(1.0 / self._numeric(node.rhs))
        if node.op == "^":
            a, b = self.run(node.lhs), self.run(node.rhs)
            if a.is_const and b.is_const:
                return _Affine(a.const**b.const)
            return _Affine(self._numeric(node))
        raise _NonAffine(node)

    def _call(self, node: E.Call) -> _Affine:
        if node.fn == "sum":
            arg = node.args[0]
            if not isinstance(arg, E.Group):
                raise SpecMilpError("sum() takes a @group argument")
            out = _Affine(0.0)
            for col in self.env.group(arg.name):
                out = out.add(self._col(int(col)))
            return out
        if node.fn in ("abs", "months"):
            a = self.run(node.args[0])
            if a.is_const:
                import math

                from . import ops

                return _Affine(
                    math.fabs(a.const)
                    if node.fn == "abs"
                    else float(ops.months(float(a.const)))
                )
            return _Affine(self._numeric(node))
        if node.fn in ("safe_div", "finite_div"):
            sentinel = _sentinel_value(node.args[2])
            num = self.run(node.args[0])
            den = self.run(node.args[1])
            if den.is_const:
                if den.const == 0.0:
                    return _Affine(sentinel)
                return num.scale(1.0 / den.const)
            den_col = self._bare_col(node.args[1])
            if den_col is not None and self.anchor is None:
                raise _RatioMode(den_col, num, sentinel)
            return _Affine(self._numeric(node))
        raise _NonAffine(node)

    def _bare_col(self, node) -> int | None:
        if isinstance(node, E.Feat):
            return self.env.col(node.name)
        if isinstance(node, E.Group) and self.elem is not None:
            return int(self.env.group(node.name)[self.elem])
        return None


def make_spec_sat_builder(constraints_set, grid_points: int = 5):
    """``SpecConstraintSet`` instance -> ``build(x_init, hot, box=None,
    focus=None, window=1.0) -> LinearRows`` (the ``SatAttack`` builder
    protocol, including the focus/window refinement contract)."""
    resolved: ResolvedSpec = constraints_set.resolved
    schema = constraints_set.schema
    env = resolved.env
    spec = resolved.spec
    d = schema.n_features
    mutable = np.asarray(schema.mutable)
    ohe_groups = [np.asarray(g) for g in schema.ohe_groups()]
    tol = getattr(constraints_set, "tol", DEFAULT_TOL)

    def build(
        x_init: np.ndarray,
        hot: np.ndarray,
        box: tuple | None = None,
        focus: np.ndarray | None = None,
        window: float = 1.0,
    ) -> LinearRows:
        x_init = np.asarray(x_init, dtype=float)
        hot = np.asarray(hot, dtype=float)
        rows: list = []
        fixes: dict = {}
        state = {"n_bin": 0}

        xl_s, xu_s = schema.bounds(dynamic_input=x_init[None, :])
        xl_s = np.asarray(xl_s, dtype=float).reshape(-1)
        xu_s = np.asarray(xu_s, dtype=float).reshape(-1)
        maxabs = np.maximum(np.abs(xl_s), np.abs(xu_s))
        if box is not None:
            box_lo, box_hi = np.asarray(box[0]), np.asarray(box[1])
        else:
            box_lo = np.minimum(x_init, hot)
            box_hi = np.maximum(x_init, hot)

        pins = {int(j): float(hot[j]) for j in np.nonzero(~mutable)[0]}

        # -- membership modes ------------------------------------------------
        member_modes: dict = {}  # col -> list of (value, z_index)
        for c in spec.constraints:
            if c.kind != "member" or not isinstance(c.lhs, E.Feat):
                continue
            col = env.col(c.lhs.name)
            if col in pins:
                if min(abs(pins[col] - v) for v in c.rhs) > tol:
                    return LinearRows(rows=[], fixes={}, feasible=False)
                continue
            if col in member_modes:
                continue
            base = d + state["n_bin"]
            state["n_bin"] += len(c.rhs)
            zs = list(range(base, base + len(c.rhs)))
            rows.append((zs, np.ones(len(zs)), 1.0, 1.0))
            rows.append(
                (
                    [col] + zs,
                    np.concatenate([[1.0], -np.asarray(c.rhs, dtype=float)]),
                    0.0,
                    0.0,
                )
            )
            member_modes[col] = list(zip((float(v) for v in c.rhs), zs))

        # -- pin-propagation fixpoint ---------------------------------------
        try:
            changed = True
            while changed:
                changed = False
                for c in spec.constraints:
                    if c.kind != "eq":
                        continue
                    for feat, other in ((c.lhs, c.rhs), (c.rhs, c.lhs)):
                        if not isinstance(feat, E.Feat):
                            continue
                        col = env.col(feat.name)
                        if col in member_modes:
                            continue
                        try:
                            a = _Extractor(env, pins, None).run(other)
                        except (_NonAffine, _RatioMode, SpecMilpError):
                            continue
                        if not a.is_const:
                            continue
                        if col in pins:
                            if abs(pins[col] - a.const) > tol:
                                raise _Infeasible(
                                    f"{c.name}: pinned value contradiction"
                                )
                        else:
                            pins[col] = a.const
                            changed = True
                        break
        except _Infeasible:
            return LinearRows(rows=[], fixes={}, feasible=False)

        anchor = x_init.copy()
        for col, v in pins.items():
            anchor[col] = v

        def bound_of(a: _Affine) -> float:
            return (
                abs(a.const)
                + sum(abs(v) * maxabs[c] for c, v in a.coefs.items())
                + 1.0
            )

        def emit(a: _Affine, lo: float, hi: float, z_gate: int | None = None):
            """Row for ``a ∈ [lo, hi]`` (inf-open sides allowed), optionally
            big-M gated on binary ``z_gate`` being 1."""
            if a.is_const and z_gate is None:
                if not (lo - tol <= a.const <= hi + tol):
                    raise _Infeasible(f"constant term {a.const} outside bounds")
                return
            cols = list(a.coefs)
            coefs = [a.coefs[c] for c in cols]
            row_lo = lo - a.const if np.isfinite(lo) else -np.inf
            row_hi = hi - a.const if np.isfinite(hi) else np.inf
            if z_gate is None:
                rows.append((cols, coefs, row_lo, row_hi))
                return
            big = bound_of(a) + max(
                abs(v) for v in (lo, hi) if np.isfinite(v)
            )
            if np.isfinite(row_hi):
                rows.append(
                    (cols + [z_gate], coefs + [big], -np.inf, row_hi + big)
                )
            if np.isfinite(row_lo):
                rows.append(
                    (cols + [z_gate], coefs + [-big], row_lo - big, np.inf)
                )

        def extract(node, elem, mode_pins=None, anchored=False):
            p = dict(pins)
            anchor_row = anchor
            if mode_pins:
                p.update(mode_pins)
                anchor_row = anchor.copy()
                for col, v in mode_pins.items():
                    anchor_row[col] = v
            return _Extractor(
                env, p, elem, anchor=anchor_row if anchored else None
            ).run(node)

        def ratio_modes(c, lhs_aff: _Affine, rm: _RatioMode, elem):
            """Denominator-grid mode search for ``lhs == num / den``
            (``lcld_sat.denominator_modes`` generalized), plus a sentinel
            mode for guarded ratios when 0 is inside the box."""
            den = rm.den_col
            if focus is None:
                grid = _denominator_grid(
                    hot[den], x_init[den], box_lo[den], box_hi[den],
                    n=grid_points,
                )
            else:
                v_star = float(focus[den])
                half = window * (box_hi[den] - box_lo[den]) / 2.0
                grid = _denominator_grid(
                    v_star,
                    v_star,
                    max(box_lo[den], v_star - half),
                    min(box_hi[den], v_star + half),
                    n=grid_points,
                )
            with_sentinel = (
                rm.sentinel is not None and box_lo[den] <= 0.0 <= box_hi[den]
            )
            if not grid and not with_sentinel:
                raise _Infeasible(f"{c.name}: empty denominator grid")
            values = ([0.0] if with_sentinel else []) + list(grid)
            base = d + state["n_bin"]
            state["n_bin"] += len(values)
            zs = list(range(base, base + len(values)))
            rows.append((zs, np.ones(len(zs)), 1.0, 1.0))
            rows.append(
                (
                    [den] + zs,
                    np.concatenate([[1.0], -np.asarray(values)]),
                    0.0,
                    0.0,
                )
            )
            for v, z_k in zip(values, zs):
                if v == 0.0:
                    # sentinel mode: ratio takes the guard value
                    emit(
                        lhs_aff.add(_Affine(rm.sentinel), -1.0),
                        -SLACK,
                        SLACK,
                        z_gate=z_k,
                    )
                else:
                    emit(
                        lhs_aff.add(rm.num.scale(1.0 / v), -1.0),
                        -SLACK,
                        SLACK,
                        z_gate=z_k,
                    )

        def member_var_of(c) -> tuple | None:
            feats = E.constraint_features(c)
            hits = [
                (env.col(f), member_modes[env.col(f)])
                for f in sorted(feats)
                if env.col(f) in member_modes
            ]
            return hits[0] if len(hits) == 1 else None

        def emit_le(c, elem):
            # guarded-ratio bound: safe_div(n, d, s) <= C cross-multiplies
            lhs, rhs = c.lhs, c.rhs
            if isinstance(lhs, E.Call) and lhs.fn in ("safe_div", "finite_div"):
                rhs_aff = extract(rhs, elem)
                if rhs_aff.is_const:
                    sentinel = _sentinel_value(lhs.args[2])
                    try:
                        den_aff = extract(lhs.args[1], elem)
                    except (_NonAffine, _RatioMode):
                        den_aff = None
                    if den_aff is not None and not den_aff.is_const:
                        den_lo = (
                            den_aff.const
                            + sum(
                                v * (xl_s[cc] if v > 0 else xu_s[cc])
                                for cc, v in den_aff.coefs.items()
                            )
                        )
                        if den_lo >= 0.0 and sentinel <= rhs_aff.const + tol:
                            num_aff = extract(lhs.args[0], elem)
                            emit(
                                num_aff.add(
                                    den_aff.scale(rhs_aff.const), -1.0
                                ),
                                -np.inf,
                                0.0,
                            )
                            return
            # abs band: abs(E) <= c
            if isinstance(lhs, E.Call) and lhs.fn == "abs":
                rhs_aff = extract(rhs, elem)
                if rhs_aff.is_const:
                    _emit_band(c, lhs.args[0], rhs_aff.const, elem)
                    return
            _emit_general(c, elem, kind="le")

        def _emit_band(c, inner, half_width: float, elem):
            """|inner| <= half_width, with membership-mode fallback."""
            try:
                a = extract(inner, elem)
                emit(a, -half_width, half_width)
                return
            except _RatioMode:
                raise SpecMilpError(
                    f"{c.name}: searched denominator inside abs-band "
                    "unsupported"
                ) from None
            except _NonAffine:
                pass
            mv = member_var_of(c)
            if mv is None:
                a = extract(inner, elem, anchored=True)
                emit(a, -half_width, half_width)
                return
            col, modes = mv
            for v, z_k in modes:
                a = extract(inner, elem, mode_pins={col: v}, anchored=True)
                emit(a, -half_width, half_width, z_gate=z_k)

        def _emit_general(c, elem, kind: str):
            lo, hi = (
                (-SLACK, SLACK) if kind == "eq" else (-np.inf, 0.0)
            )
            try:
                if kind == "eq":
                    lhs_aff = extract(c.lhs, elem)
                    try:
                        rhs_aff = extract(c.rhs, elem)
                    except _RatioMode as rm:
                        ratio_modes(c, lhs_aff, rm, elem)
                        return
                    a = lhs_aff.add(rhs_aff, -1.0)
                else:
                    a = extract(c.lhs, elem).add(extract(c.rhs, elem), -1.0)
                emit(a, lo, hi)
                return
            except _RatioMode as rm:
                if kind == "eq":
                    try:
                        rhs_aff = extract(c.rhs, elem)
                    except (_NonAffine, _RatioMode):
                        raise SpecMilpError(
                            f"{c.name}: both sides nonlinear"
                        ) from None
                    ratio_modes(c, rhs_aff, rm, elem)
                    return
                raise SpecMilpError(
                    f"{c.name}: searched denominator in <= unsupported"
                ) from None
            except _NonAffine:
                pass
            mv = member_var_of(c)
            if mv is None:
                a = extract(c.lhs, elem, anchored=True).add(
                    extract(c.rhs, elem, anchored=True), -1.0
                )
                emit(a, lo, hi)
                return
            col, modes = mv
            for v, z_k in modes:
                a = extract(
                    c.lhs, elem, mode_pins={col: v}, anchored=True
                ).add(
                    extract(c.rhs, elem, mode_pins={col: v}, anchored=True),
                    -1.0,
                )
                emit(a, lo, hi, z_gate=z_k)

        try:
            for c, width in zip(spec.constraints, resolved.widths):
                if c.kind == "member":
                    if isinstance(c.lhs, E.Feat):
                        continue  # handled by member_modes / pin check
                    raise SpecMilpError(
                        f"{c.name}: membership on a compound expression"
                    )
                for elem in range(width) if width > 1 else (None,):
                    if c.kind == "le":
                        emit_le(c, elem)
                    else:
                        _emit_general(c, elem, kind="eq")
        except _Infeasible:
            return LinearRows(rows=[], fixes={}, feasible=False)

        # derived-constant features the equalities force (pins minus the
        # immutables the engine already fixes through its bounds)
        for col, v in pins.items():
            if mutable[col]:
                fixes[col] = v
        for col in np.nonzero(~mutable)[0]:
            fixes[int(col)] = float(hot[col])

        for g in ohe_groups:
            rows.append((g, np.ones(len(g)), 1.0, 1.0))

        return LinearRows(rows=rows, fixes=fixes, n_extra_bin=state["n_bin"])

    return build
