"""Shared numeric operator definitions for the constraint IR.

Every operator the reference's constraint language uses more than once is
defined exactly once here, with a numpy/jnp dispatch on the input type —
the jnp backend, the numpy twin evaluator, the feasible-sample generators,
and the hand-written kernels all call the same definitions, so "the jnp
kernel and the numpy oracle agree" is true by construction rather than by
parallel maintenance.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _xp(value):
    """numpy for host values (arrays, numpy scalars, python numbers), jnp
    for traced/device arrays — keeps host-side generators in float64 while
    kernels trace under the active jax dtype mode."""
    if isinstance(value, (np.ndarray, np.generic, float, int)):
        return np
    return jnp


def months(date_feature):
    """YYYYMM integer-coded date -> month count: floor(f/100)*12 + f mod 100.

    The reference defines this twice (``lcld_constraints.py`` numpy oracle
    and TF twin); this repo previously did too (``domains/lcld.py`` jnp,
    ``domains/synth.py`` numpy). This is now the only definition.
    """
    xp = _xp(date_feature)
    return xp.floor(date_feature / 100.0) * 12.0 + xp.mod(date_feature, 100.0)


def safe_div(num, den, sentinel):
    """Guarded division: ``num / den`` where ``den != 0``, else ``sentinel``.

    Exactly the botnet ratio guard (``domains/botnet.py``): one mask, the
    denominator substituted by 1 under the mask so the division itself never
    produces inf/nan on the guarded lanes.
    """
    xp = _xp(den)
    ok = den != 0
    return xp.where(ok, num / xp.where(ok, den, 1.0), sentinel)


def finite_div(num, den, sentinel):
    """``safe_div`` plus a non-finite snap: any inf/nan result also maps to
    ``sentinel``. Exactly the LCLD g10 masked-array dance
    (``domains/lcld.py``): 0/0 from float noise must not leak a nan into the
    violation term."""
    xp = _xp(den)
    ok = den != 0
    ratio = xp.where(ok, num / xp.where(ok, den, 1.0), sentinel)
    return xp.where(xp.isfinite(ratio), ratio, sentinel)
