"""Repair compiler backend: spec -> in-graph constructive projection.

Derives the repair the hand-written domains implement by hand
(``LcldConstraints.repair``) from the spec's defining constraints:

1. **membership snap** — ``f in {v1..vk}`` on a mutable feature snaps to the
   nearest member (threshold at the midpoints, the reference's term
   36/60-at-48 rule generalized);
2. **equality re-derivation** — ``f == E`` (and the tolerance-equality form
   ``abs(f - E) <= c``) with a mutable bare-feature ``f`` not appearing in
   ``E`` becomes the assignment ``f := E``, applied in dependency order so a
   derived feature (the month difference) lands before its dependents (the
   per-month ratios); cyclic or self-referential equalities are left to the
   MILP/GA search rather than guessed at;
3. **one-hot hardening** — every OHE group snaps to its argmax
   (``core.codec.harden_onehot``), exactly the hand-written final step.

Everything emitted is pure jnp, so PGD can trace the repair in-graph
(``loss_evaluation`` with "repair") like any hand-written projection.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...core.codec import harden_onehot
from . import expr as E
from .expr import eval_expr
from .spec import ResolvedSpec, _width_of


def _assignment_of(c: E.Constraint, env) -> tuple | None:
    """``(feature_name, expr)`` when the constraint defines a feature."""
    if c.kind == "eq":
        for feat, other in ((c.lhs, c.rhs), (c.rhs, c.lhs)):
            if (
                isinstance(feat, E.Feat)
                and feat.name not in E.features_of(other)
                and not E.groups_of(other)
                and _width_of(other, env) <= 1
            ):
                return feat.name, other
    if c.kind == "le":
        # tolerance equality: abs(f - E) <= c  ->  f := E
        lhs = c.lhs
        if (
            isinstance(lhs, E.Call)
            and lhs.fn == "abs"
            and isinstance(lhs.args[0], E.Bin)
            and lhs.args[0].op == "-"
            and not E.features_of(c.rhs)
        ):
            diff = lhs.args[0]
            for feat, other in ((diff.lhs, diff.rhs), (diff.rhs, diff.lhs)):
                if (
                    isinstance(feat, E.Feat)
                    and feat.name not in E.features_of(other)
                    and not E.groups_of(other)
                    and _width_of(other, env) <= 1
                ):
                    return feat.name, other
    return None


def _topo_assignments(assignments: dict) -> list:
    """Kahn order over feature-dependency edges; members of a dependency
    cycle are dropped (left to the search) rather than applied in an
    arbitrary order."""
    deps = {
        f: {d for d in E.features_of(expr) if d in assignments}
        for f, expr in assignments.items()
    }
    order, ready = [], [f for f, d in deps.items() if not d]
    done = set()
    while ready:
        f = ready.pop()
        order.append(f)
        done.add(f)
        ready.extend(
            g
            for g, d in deps.items()
            if g not in done and g not in ready and d <= done
        )
    return order


def membership_snaps(resolved: ResolvedSpec, schema) -> list:
    """``(column, sorted_values)`` for each mutable single-feature
    membership constraint."""
    mutable = schema.mutable
    out = []
    for c in resolved.spec.constraints:
        if c.kind == "member" and isinstance(c.lhs, E.Feat):
            col = resolved.env.col(c.lhs.name)
            if mutable[col]:
                out.append((col, tuple(sorted(c.rhs))))
    return out


def compile_repair(resolved: ResolvedSpec, schema, ohe_idx, ohe_mask):
    env = resolved.env
    mutable = schema.mutable
    snaps = membership_snaps(resolved, schema)

    assignments: dict = {}
    for c in resolved.spec.constraints:
        found = _assignment_of(c, env)
        if found is not None:
            name, expr_node = found
            if mutable[env.col(name)] and name not in assignments:
                assignments[name] = expr_node
    order = _topo_assignments(assignments)

    def repair(x: jnp.ndarray) -> jnp.ndarray:
        for col, values in snaps:
            v = x[..., col]
            snapped = values[0]
            for k in range(1, len(values)):
                mid = (values[k - 1] + values[k]) / 2.0
                snapped = jnp.where(v < mid, snapped, values[k])
            x = x.at[..., col].set(snapped + 0.0 * v)
        for name in order:
            value, _ = eval_expr(assignments[name], x, env, jnp)
            x = x.at[..., env.col(name)].set(value)
        return harden_onehot(x, ohe_idx, ohe_mask)

    return repair
