"""Declarative constraint specs: data model, loaders, hashing, resolution.

A :class:`ConstraintSpec` is the IR root: named constraints (parsed
expressions, :mod:`.expr`) plus optional column-group definitions. Two
loader fronts produce it:

- **CSV front** (:func:`load_spec_csv`): a ``constraints.csv`` that grows an
  ``expr`` column next to the reference's ``constraint,min,max`` — the
  existing :class:`~...core.schema.ConstraintBounds` reader ignores the
  extra column, so one file serves both the normaliser and the compiler.
- **YAML front** (:func:`load_spec_yaml`): inline specs with group
  definitions — groups concatenate parts that name either schema features
  or keys of a ``feat_idx.pickle`` (the botnet port-group tables), each
  part optionally sliced (``take``), preserving the hand-written kernels'
  exact gather order.

The **spec hash** (:func:`spec_hash`) is a sha256 over the canonical
serialization — expressions are re-printed from the AST, so whitespace and
formatting never change the identity, while any semantic edit does. It is
the cache/ledger discriminator for compiled domains (``spec:<name>:<hash>``)
and the revision fingerprint /healthz exposes.
"""

from __future__ import annotations

import csv
import hashlib
import json
import os
import pickle
from dataclasses import dataclass

import numpy as np

from .expr import (
    Constraint,
    Env,
    SpecError,
    canon_constraint,
    constraint_features,
    groups_of,
    parse_constraint,
)


@dataclass(frozen=True)
class GroupPart:
    """One segment of a concatenated column group: either schema feature
    names or a ``feat_idx.pickle`` key, optionally sliced to ``take``
    leading entries (the botnet ratio family's first-17-ports quirk)."""

    key: str | None = None
    features: tuple = ()
    take: int | None = None


@dataclass(frozen=True)
class GroupDef:
    name: str
    parts: tuple


@dataclass(frozen=True)
class ConstraintSpec:
    name: str
    constraints: tuple
    groups: tuple = ()
    feat_idx_file: str | None = None

    def canonical(self) -> dict:
        """Formatting-independent normal form (the hashed identity)."""
        return {
            "name": self.name,
            "constraints": [
                [c.name, c.kind, canon_constraint(c)] for c in self.constraints
            ],
            "groups": [
                [
                    g.name,
                    [
                        {
                            "key": p.key,
                            "features": list(p.features),
                            "take": p.take,
                        }
                        for p in g.parts
                    ],
                ]
                for g in self.groups
            ],
            "feat_idx_file": self.feat_idx_file,
        }


def spec_hash(spec: ConstraintSpec) -> str:
    blob = json.dumps(spec.canonical(), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


# -- loaders -----------------------------------------------------------------


def _parse_group_part(raw) -> GroupPart:
    if isinstance(raw, str):
        return GroupPart(key=raw)
    take = raw.get("take")
    if take is not None:
        take = int(take)
    features = raw.get("features") or ()
    return GroupPart(
        key=raw.get("key"), features=tuple(features), take=take
    )


def load_spec_yaml(path: str) -> ConstraintSpec:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f)
    name = doc.get("name") or os.path.splitext(os.path.basename(path))[0]
    constraints = tuple(
        parse_constraint(row["name"], row["expr"]) for row in doc["constraints"]
    )
    groups = tuple(
        GroupDef(gname, tuple(_parse_group_part(p) for p in parts))
        for gname, parts in (doc.get("groups") or {}).items()
    )
    return ConstraintSpec(
        name=name,
        constraints=constraints,
        groups=groups,
        feat_idx_file=doc.get("feat_idx"),
    )


def load_spec_csv(path: str, name: str | None = None) -> ConstraintSpec:
    """CSV front: ``constraint,min,max,expr`` rows (``min``/``max`` belong to
    :class:`ConstraintBounds`; only ``constraint`` + ``expr`` matter here)."""
    constraints = []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            expr = (row.get("expr") or "").strip()
            if not expr:
                raise SpecError(
                    f"{path}: row {row.get('constraint')!r} has no expr column "
                    "(a spec-front constraints.csv must carry one per row)"
                )
            constraints.append(parse_constraint(row["constraint"], expr))
    if name is None:
        name = os.path.basename(os.path.dirname(os.path.abspath(path))) or "spec"
    return ConstraintSpec(name=name, constraints=tuple(constraints))


def load_spec(path: str, name: str | None = None) -> ConstraintSpec:
    ext = os.path.splitext(path)[1].lower()
    if ext in (".yaml", ".yml"):
        spec = load_spec_yaml(path)
    elif ext == ".csv":
        spec = load_spec_csv(path, name=name)
    else:
        raise SpecError(f"unknown spec extension {ext!r} for {path}")
    if name:
        spec = ConstraintSpec(
            name=name,
            constraints=spec.constraints,
            groups=spec.groups,
            feat_idx_file=spec.feat_idx_file,
        )
    return spec


# -- resolution --------------------------------------------------------------


@dataclass
class ResolvedSpec:
    """A spec bound to one schema/data-dir: name resolution done, group
    index arrays materialized, per-constraint term widths known."""

    spec: ConstraintSpec
    env: Env
    widths: tuple  # per-constraint term counts
    n_terms: int
    hash: str


def _resolve_groups(spec: ConstraintSpec, columns: dict, data_dir: str | None):
    feat_idx = None
    if spec.feat_idx_file:
        if data_dir is None:
            raise SpecError(
                f"spec {spec.name!r} needs feat_idx {spec.feat_idx_file!r} "
                "but no data dir was given"
            )
        with open(os.path.join(data_dir, spec.feat_idx_file), "rb") as f:
            feat_idx = {k: np.asarray(v) for k, v in pickle.load(f).items()}
    groups = {}
    for g in spec.groups:
        segs = []
        for p in g.parts:
            if p.key is not None:
                if feat_idx is None:
                    raise SpecError(
                        f"group {g.name!r} references feat_idx key {p.key!r} "
                        f"but spec {spec.name!r} declares no feat_idx file"
                    )
                if p.key not in feat_idx:
                    raise SpecError(
                        f"group {g.name!r}: unknown feat_idx key {p.key!r}"
                    )
                seg = feat_idx[p.key]
            else:
                missing = [f for f in p.features if f not in columns]
                if missing:
                    raise SpecError(
                        f"group {g.name!r}: undefined feature(s) {missing}"
                    )
                seg = np.array([columns[f] for f in p.features])
            if p.take is not None:
                seg = seg[: p.take]
            segs.append(np.asarray(seg))
        groups[g.name] = np.concatenate(segs) if segs else np.array([], int)
    return groups


def _width_of(node, env: Env) -> int:
    from . import expr as E

    if isinstance(node, E.Num):
        return 0
    if isinstance(node, E.Feat):
        env.col(node.name)  # raises on undefined features
        return 1
    if isinstance(node, E.Group):
        return len(env.group(node.name))
    if isinstance(node, E.Neg):
        return _width_of(node.arg, env)
    if isinstance(node, E.Bin):
        return _combine(_width_of(node.lhs, env), _width_of(node.rhs, env))
    if isinstance(node, E.Call):
        if node.fn == "sum":
            w = _width_of(node.args[0], env)
            if w < 2:
                raise SpecError("sum() takes a @group argument")
            return 1
        if node.fn in ("safe_div", "finite_div"):
            return _combine(
                _width_of(node.args[0], env), _width_of(node.args[1], env)
            )
        return _width_of(node.args[0], env)
    raise SpecError(f"cannot type {node!r}")


def _combine(wa: int, wb: int) -> int:
    if wa == wb or wa == 0 or wb == 0 or wa == 1 or wb == 1:
        return max(wa, wb)
    raise SpecError(f"group width mismatch: {wa} vs {wb}")


def _constraint_width(c: Constraint, env: Env) -> int:
    if c.kind == "member":
        w = _width_of(c.lhs, env)
    else:
        w = _combine(_width_of(c.lhs, env), _width_of(c.rhs, env))
    return max(w, 1)  # a literal-only constraint still emits one term


def resolve_spec(
    spec: ConstraintSpec, schema, data_dir: str | None = None
) -> ResolvedSpec:
    columns = {name: i for i, name in enumerate(schema.names)}
    if len(columns) != len(schema.names):
        raise SpecError(f"spec {spec.name!r}: schema has duplicate feature names")
    groups = _resolve_groups(spec, columns, data_dir)
    env = Env(columns, groups)
    widths = tuple(_constraint_width(c, env) for c in spec.constraints)
    return ResolvedSpec(
        spec=spec,
        env=env,
        widths=widths,
        n_terms=int(sum(widths)),
        hash=spec_hash(spec),
    )


def validate_spec(spec: ConstraintSpec, schema) -> list:
    """Static lint findings (strings). Empty = clean. Checks: undefined
    features, non-guarded ``/`` denominators that can reach zero under the
    schema bounds, membership values outside feature bounds, and duplicate
    constraint names."""
    from . import expr as E

    findings = []
    columns = {name: i for i, name in enumerate(schema.names)}
    seen = set()
    for c in spec.constraints:
        if c.name in seen:
            findings.append(f"{c.name}: duplicate constraint name")
        seen.add(c.name)
        for feat in sorted(constraint_features(c)):
            if feat not in columns:
                findings.append(f"{c.name}: undefined feature {feat!r}")
        nodes = list(E.walk(c.lhs))
        if c.kind != "member":
            nodes += list(E.walk(c.rhs))
        for node in nodes:
            if isinstance(node, E.Bin) and node.op == "/":
                den = node.rhs
                if isinstance(den, E.Feat) and den.name in columns:
                    i = columns[den.name]
                    lo, hi = schema.raw_min[i], schema.raw_max[i]
                    spans_zero = (
                        str(lo) == "dynamic"
                        or str(hi) == "dynamic"
                        or float(lo) <= 0.0 <= float(hi)
                    )
                    if spans_zero:
                        findings.append(
                            f"{c.name}: non-guarded denominator {den.name!r} "
                            "can reach 0 under its bounds — use "
                            "safe_div/finite_div"
                        )
        if c.kind == "member" and isinstance(c.lhs, E.Feat):
            i = columns.get(c.lhs.name)
            if i is not None:
                lo, hi = schema.raw_min[i], schema.raw_max[i]
                if str(lo) != "dynamic" and str(hi) != "dynamic":
                    bad = [
                        v for v in c.rhs if not float(lo) <= v <= float(hi)
                    ]
                    if bad:
                        findings.append(
                            f"{c.name}: membership value(s) {bad} outside "
                            f"bounds [{lo}, {hi}] of {c.lhs.name!r}"
                        )
    return findings
