"""LCLD (LendingClub loan data) domain: 47 features, 10 relational constraints.

One pure jnp kernel serves evaluation and differentiation; the hard/smooth
thresholding split lives in :class:`~..core.constraints.ConstraintSet`.

Reference parity (formula-for-formula, not line-for-line):
``/root/reference/src/examples/lcld/lcld_constraints.py`` — numpy oracle at
:168-223, TF twin at :75-157, repair at :40-73; augmented variant at
``lcld_augmented_constraints.py`` (10 base + C(5,2)=10 XOR-consistency terms).

Feature indices used (see ``data/lcld/features.csv``):
0 loan_amnt, 1 term, 2 int_rate, 3 installment, 6 annual_inc, 7 issue_d,
9 earliest_cr_line, 10 open_acc, 11 pub_rec, 14 total_acc,
16 pub_rec_bankruptcies, 20..25 derived ratio features.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from ..core.codec import full_ohe_tables, harden_onehot
from ..core.constraints import ConstraintSet
from ..core.schema import ConstraintBounds, FeatureSchema
from . import augmentation

N_BASE_FEATURES = 47


# single-sourced in the IR operator library (same definition, numpy/jnp
# dispatched); kept under the old name — domains/lcld_sat.py imports it
from .ir.ops import months as _months


def _installment(loan_amnt, term, int_rate):
    """Amortised monthly payment: L*r*(1+r)^t / ((1+r)^t - 1), r = rate/1200."""
    r = int_rate / 1200.0
    growth = jnp.power(1.0 + r, term)
    return loan_amnt * r * growth / (growth - 1.0)


def lcld_constraint_terms(x: jnp.ndarray) -> jnp.ndarray:
    """Unthresholded violation magnitudes, shape (..., 10)."""
    g1 = jnp.abs(x[..., 3] - _installment(x[..., 0], x[..., 1], x[..., 2])) - 0.099999
    # open_acc <= total_acc ; pub_rec_bankruptcies <= pub_rec
    g2 = x[..., 10] - x[..., 14]
    g3 = x[..., 16] - x[..., 11]
    # term must be one of {36, 60}
    g4 = jnp.abs((36.0 - x[..., 1]) * (60.0 - x[..., 1]))
    # derived-ratio equalities
    g5 = jnp.abs(x[..., 20] - x[..., 0] / x[..., 6])
    g6 = jnp.abs(x[..., 21] - x[..., 10] / x[..., 14])
    g7 = jnp.abs(x[..., 22] - (_months(x[..., 7]) - _months(x[..., 9])))
    g8 = jnp.abs(x[..., 23] - x[..., 11] / x[..., 22])
    g9 = jnp.abs(x[..., 24] - x[..., 16] / x[..., 22])
    # pub_rec_bankruptcies / pub_rec, with 0-denominator (and any non-finite
    # result) mapped to the sentinel -1 — the reference's masked-array dance.
    denom_ok = x[..., 11] != 0
    ratio = jnp.where(denom_ok, x[..., 16] / jnp.where(denom_ok, x[..., 11], 1.0), -1.0)
    ratio = jnp.where(jnp.isfinite(ratio), ratio, -1.0)
    g10 = jnp.abs(x[..., 25] - ratio)
    return jnp.stack([g1, g2, g3, g4, g5, g6, g7, g8, g9, g10], axis=-1)


class LcldConstraints(ConstraintSet):
    n_constraints = 10

    def __init__(
        self,
        features_path: str,
        constraints_path: str,
        important_features_path: str | None = None,
    ):
        schema = FeatureSchema.from_csv(features_path)
        bounds = ConstraintBounds.from_csv(constraints_path)
        super().__init__(schema, bounds)
        if important_features_path is None:
            important_features_path = os.path.join(
                os.path.dirname(features_path), "important_features.npy"
            )
        self.important_features = (
            np.load(important_features_path)
            if os.path.exists(important_features_path)
            else None
        )
        self._ohe_idx, self._ohe_mask = full_ohe_tables(schema)

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        return lcld_constraint_terms(x)

    def repair(self, x: jnp.ndarray) -> jnp.ndarray:
        """In-graph constructive repair (parity: ``fix_features_types``):
        snap term to {36, 60}, recompute installment by formula, harden every
        one-hot group to its argmax, and re-derive augmented XOR features when
        the input carries them."""
        term = jnp.where(x[..., 1] < (60.0 + 36.0) / 2.0, 36.0, 60.0)
        x = x.at[..., 1].set(term)
        x = x.at[..., 3].set(_installment(x[..., 0], term, x[..., 2]))

        x = harden_onehot(x, self._ohe_idx, self._ohe_mask)

        if x.shape[-1] > N_BASE_FEATURES:
            if self.important_features is None:
                raise FileNotFoundError(
                    "repair() on augmented inputs requires important_features.npy "
                    "to re-derive the XOR features (otherwise they would be left "
                    "stale and constraint-violating)"
                )
            base = x[..., : -augmentation.n_pairs(self.important_features)]
            x = augmentation.augment(base, self.important_features)
        return x


class LcldAugmentedConstraints(LcldConstraints):
    """LCLD + XOR-consistency constraints on the augmented features (10+10)."""

    n_constraints = 20

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.important_features is None:
            raise FileNotFoundError(
                "LcldAugmentedConstraints requires important_features.npy "
                "(pass important_features_path or place it next to features.csv)"
            )
        self._pairs = augmentation.PairTables.build(self.important_features)

    def _raw(self, x: jnp.ndarray) -> jnp.ndarray:
        base = lcld_constraint_terms(x)
        return jnp.concatenate([base, self._pairs.consistency_terms(x)], axis=-1)
