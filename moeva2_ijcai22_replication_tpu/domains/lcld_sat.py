"""LCLD constraints linearised for the MILP attack.

Reference semantics: ``/root/reference/src/examples/lcld/lcld_constraints_sat.py``
(Gurobi: indicator constraints for term ∈ {36, 60}, ``addGenConstrPow`` for
(1+r)^term, integer div/mod date decomposition, big-M pub_rec guard).

HiGHS formulation:

- **term is searched, not pinned** (parity with the reference's indicator
  constraints, ``lcld_constraints_sat.py:25-36``): an auxiliary binary z
  selects the mode via ``term = 36 + 24·z``, and big-M rows activate the
  matching amortisation equality |installment − c_t·loan_amnt| ≤ 0.0999
  (int_rate is immutable, so both c_36 and c_60 are constants — the
  (1+r)^term power never has to live inside the MILP).
- the ratio denominators annual_inc, total_acc, pub_rec and both date
  features are pinned at hot-start values, so g5/g6/g8/g9/g10 are linear and
  g7 fixes the month-difference feature to a constant. The pins on issue_d,
  earliest_cr_line and pub_rec are **exact** (those features are immutable
  in the schema, so every attack leaves them at the initial value anyway);
  the only genuine search-power loss vs the reference's nonconvex bilinear
  rows is the two mutable denominators annual_inc and total_acc. Every pin
  that lands on a zero denominator (annual_inc, total_acc, or a zero month
  difference) makes the corresponding equality unsatisfiable — the builder
  flags the program infeasible instead of emitting inf coefficients.
- one-hot groups: integral 0/1 members summing to 1.

The MILP searches term, loan_amnt, installment, open_acc,
pub_rec_bankruptcies, the derived ratios, and every one-hot group.
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema
from ..attacks.sat.engine import LinearRows
from .lcld import _months

SLACK = 1e-4  # inside the evaluator's 1e-3 snap tolerance


def _amortisation_factor(rate_pct: float, term: float) -> float:
    """c such that installment = c · loan_amnt (r = rate/1200); r → 0 limits
    to the interest-free 1/term."""
    r = rate_pct / 1200.0
    if r <= 0.0:
        return 1.0 / term
    growth = (1.0 + r) ** term
    return r * growth / (growth - 1.0)


def make_lcld_sat_builder(schema: FeatureSchema):
    ohe_groups = [np.asarray(g) for g in schema.ohe_groups()]
    d = schema.n_features

    def build(x_init: np.ndarray, hot: np.ndarray) -> LinearRows:
        rows = []
        fixes = {}

        # g1 + g4: term mode search. z = extra binary at index d;
        # term = 36 + 24·z keeps g4 exact for both assignments.
        z = d
        rows.append(([1, z], [1.0, -24.0], 36.0, 36.0))
        c36 = _amortisation_factor(x_init[2], 36.0)
        c60 = _amortisation_factor(x_init[2], 60.0)
        xl_s, xu_s = schema.bounds(dynamic_input=x_init[None, :])
        xl_s, xu_s = np.asarray(xl_s).reshape(-1), np.asarray(xu_s).reshape(-1)
        big_m = (
            max(abs(xu_s[3]), abs(xl_s[3]))
            + max(c36, c60) * max(abs(xu_s[0]), abs(xl_s[0]))
            + 1.0
        )
        # mode 36 (z = 0): |installment − c36·loan| ≤ 0.0999 + M·z
        rows.append(([3, 0, z], [1.0, -c36, -big_m], -np.inf, 0.0999))
        rows.append(([3, 0, z], [1.0, -c36, big_m], -0.0999, np.inf))
        # mode 60 (z = 1): |installment − c60·loan| ≤ 0.0999 + M·(1 − z)
        rows.append(([3, 0, z], [1.0, -c60, big_m], -np.inf, 0.0999 + big_m))
        rows.append(([3, 0, z], [1.0, -c60, -big_m], -0.0999 - big_m, np.inf))

        # g2/g3: orderings
        rows.append(([10, 14], [1.0, -1.0], -np.inf, 0.0))
        rows.append(([16, 11], [1.0, -1.0], -np.inf, 0.0))

        # pin the nonlinear participants at hot-start values
        fixes[6] = hot[6]  # annual_inc (g5 denominator)
        fixes[14] = hot[14]  # total_acc (g6 denominator)
        fixes[7] = hot[7]  # issue_d (g7 months)
        fixes[9] = hot[9]  # earliest_cr_line (g7 months)
        fixes[11] = hot[11]  # pub_rec (g3/g8/g10 denominator)
        diff = float(_months(fixes[7]) - _months(fixes[9]))
        # zero pinned denominators make g5/g6/g8/g9 unsatisfiable — flag
        # infeasible rather than emitting inf coefficients
        if fixes[6] == 0 or fixes[14] == 0 or diff == 0:
            return LinearRows(rows=[], fixes={}, feasible=False)

        # g5: ratio_loan_income == loan / annual_inc
        rows.append(([20, 0], [1.0, -1.0 / fixes[6]], -SLACK, SLACK))
        # g6: ratio_open_total == open_acc / total_acc
        rows.append(([21, 10], [1.0, -1.0 / fixes[14]], -SLACK, SLACK))
        # g7: month difference fixed by the pinned dates
        fixes[22] = diff
        # g8/g9: ratios over the (constant) month difference
        rows.append(([23, 11], [1.0, -1.0 / diff], -SLACK, SLACK))
        rows.append(([24, 16], [1.0, -1.0 / diff], -SLACK, SLACK))
        # g10: pub_rec_bankruptcies / pub_rec, sentinel -1 on zero denominator
        if fixes[11] == 0:
            fixes[25] = -1.0
            fixes[16] = 0.0  # g3 with pub_rec = 0
        else:
            rows.append(([25, 16], [1.0, -1.0 / fixes[11]], -SLACK, SLACK))

        # one-hot validity: each group sums to exactly 1
        for g in ohe_groups:
            rows.append((g, np.ones(len(g)), 1.0, 1.0))

        return LinearRows(rows=rows, fixes=fixes, n_extra_bin=1)

    return build
