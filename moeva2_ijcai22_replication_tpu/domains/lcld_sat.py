"""LCLD constraints linearised for the MILP attack.

Reference semantics: ``/root/reference/src/examples/lcld/lcld_constraints_sat.py``
(Gurobi: indicator constraints for term ∈ {36, 60}, ``addGenConstrPow`` for
(1+r)^term, integer div/mod date decomposition, big-M pub_rec guard).

HiGHS formulation:

- **term is searched, not pinned** (parity with the reference's indicator
  constraints, ``lcld_constraints_sat.py:25-36``): an auxiliary binary z
  selects the mode via ``term = 36 + 24·z``, and big-M rows activate the
  matching amortisation equality |installment − c_t·loan_amnt| ≤ 0.0999
  (int_rate is immutable, so both c_36 and c_60 are constants — the
  (1+r)^term power never has to live inside the MILP).
- **the mutable ratio denominators annual_inc and total_acc are searched,
  not pinned**: each gets a grid of candidate values over its ε-box (the
  hot-start and initial values are included after clamping into the box, so
  in-box pins are never lost) selected by one-hot binaries — the denominator
  variable is the
  exact linear combination Σ vₖ·zₖ, and each mode's ratio equality
  (g5: ratio = loan/annual_inc, g6: ratio = open/total) activates through
  big-M rows with benign magnitudes. This is the same mode-search
  architecture as the term switch; the reference instead hands Gurobi the
  nonconvex bilinear rows directly (``NonConvex=2``), so its search is
  continuous where ours is gridded. ``SatAttack.refine_rounds`` narrows that
  gap iteratively: each round re-grids the denominators around the incumbent
  solution with a ¼-shrinking window (monotone — the incumbent stays in the
  grid), reaching box/64 resolution after two rounds; the residual gap is
  the finite final resolution.
- pub_rec and both date features are pinned at hot-start values — **exact**
  pins, those features are immutable in the schema — so g7 fixes the
  month-difference feature and g8/g9/g10 are linear. A zero month
  difference (or an all-zero denominator grid) makes the corresponding
  equality unsatisfiable — the builder flags the program infeasible instead
  of emitting inf coefficients.
- one-hot groups: integral 0/1 members summing to 1.

The MILP searches term, loan_amnt, installment, open_acc,
pub_rec_bankruptcies, the derived ratios, and every one-hot group.
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema
from ..attacks.sat.engine import LinearRows
from .ir.ops import months as _months

SLACK = 1e-4  # inside the evaluator's 1e-3 snap tolerance


def _amortisation_factor(rate_pct: float, term: float) -> float:
    """c such that installment = c · loan_amnt (r = rate/1200); r → 0 limits
    to the interest-free 1/term."""
    r = rate_pct / 1200.0
    if r <= 0.0:
        return 1.0 / term
    growth = (1.0 + r) ** term
    return r * growth / (growth - 1.0)


def _denominator_grid(
    hot_v: float, init_v: float, lo: float, hi: float, n: int = 5
) -> list:
    """Candidate pins for a searched ratio denominator: hot-start and initial
    values clamped into the ε-box (the directional L2 radii can leave the raw
    hot displacement slightly outside it) plus an n-point spread over the box;
    near-zero values dropped — a tiny |v| would put num_hi/|v| big-Ms in the
    rows and wreck the MILP conditioning — and near-duplicates merged."""
    cand = [
        float(np.clip(hot_v, lo, hi)),
        float(np.clip(init_v, lo, hi)),
    ] + list(np.linspace(lo, hi, n))
    tol = 1e-6 * max(1.0, hi - lo)
    cand = [v for v in cand if abs(v) > tol]
    out: list = []
    for v in sorted(cand):
        if not out or abs(v - out[-1]) > 1e-9 * max(1.0, abs(v)):
            out.append(v)
    return out


def make_lcld_sat_builder(schema: FeatureSchema, grid_points: int = 5):
    """``grid_points`` sets the denominator-grid density (default 5; the
    refinement loop makes denser initial grids unnecessary in production —
    the dense setting exists as a brute-force oracle for tests)."""
    ohe_groups = [np.asarray(g) for g in schema.ohe_groups()]
    d = schema.n_features

    def build(
        x_init: np.ndarray,
        hot: np.ndarray,
        box: tuple | None = None,
        focus: np.ndarray | None = None,
        window: float = 1.0,
    ) -> LinearRows:
        """``focus``/``window`` drive the engine's iterative grid refinement
        (``SatAttack.refine_rounds``): with a focus solution, each searched
        denominator re-grids over ``window``·(box width) centred on the
        incumbent value — which is always kept in the grid, so refinement is
        monotone. Two rounds take the denominator resolution from box/4 to
        box/64, closing most of the gap to Gurobi's continuous nonconvex
        search (``lcld_constraints_sat.py:33-36``)."""
        rows = []
        fixes = {}

        # g1 + g4: term mode search. z = extra binary at index d;
        # term = 36 + 24·z keeps g4 exact for both assignments.
        z = d
        rows.append(([1, z], [1.0, -24.0], 36.0, 36.0))
        c36 = _amortisation_factor(x_init[2], 36.0)
        c60 = _amortisation_factor(x_init[2], 60.0)
        xl_s, xu_s = schema.bounds(dynamic_input=x_init[None, :])
        xl_s, xu_s = np.asarray(xl_s).reshape(-1), np.asarray(xu_s).reshape(-1)
        big_m = (
            max(abs(xu_s[3]), abs(xl_s[3]))
            + max(c36, c60) * max(abs(xu_s[0]), abs(xl_s[0]))
            + 1.0
        )
        # mode 36 (z = 0): |installment − c36·loan| ≤ 0.0999 + M·z
        rows.append(([3, 0, z], [1.0, -c36, -big_m], -np.inf, 0.0999))
        rows.append(([3, 0, z], [1.0, -c36, big_m], -0.0999, np.inf))
        # mode 60 (z = 1): |installment − c60·loan| ≤ 0.0999 + M·(1 − z)
        rows.append(([3, 0, z], [1.0, -c60, big_m], -np.inf, 0.0999 + big_m))
        rows.append(([3, 0, z], [1.0, -c60, -big_m], -0.0999 - big_m, np.inf))

        # g2/g3: orderings
        rows.append(([10, 14], [1.0, -1.0], -np.inf, 0.0))
        rows.append(([16, 11], [1.0, -1.0], -np.inf, 0.0))

        # exact pins: issue_d / earliest_cr_line / pub_rec are immutable, so
        # the hot-start value IS the only admissible value
        fixes[7] = hot[7]  # issue_d (g7 months)
        fixes[9] = hot[9]  # earliest_cr_line (g7 months)
        fixes[11] = hot[11]  # pub_rec (g3/g8/g10 denominator)
        diff = float(_months(fixes[7]) - _months(fixes[9]))
        if diff == 0:  # g8/g9 unsatisfiable: zero month difference
            return LinearRows(rows=[], fixes={}, feasible=False)

        # g5/g6: mutable denominators searched over a candidate grid. For a
        # denominator feature j with grid v_1..v_K and one-hot binaries z_k:
        #   x_j = Σ v_k z_k  (exact linear selection),  Σ z_k = 1,
        #   |ratio − numerator / v_k| ≤ SLACK + M_k (1 − z_k)  per mode.
        if box is not None:
            box_lo, box_hi = np.asarray(box[0]), np.asarray(box[1])
        else:  # standalone callers without a box: search hot ∪ init only
            box_lo = np.minimum(x_init, hot)
            box_hi = np.maximum(x_init, hot)
        n_bin = 1  # the term binary z at index d

        def denominator_modes(den: int, ratio: int, num_cols, num_coefs, num_hi):
            """Append mode-search rows for ratio == numerator / x_den, where
            the numerator is the linear form num_cols·num_coefs (|·| ≤ num_hi).
            Returns False when no admissible denominator value exists."""
            nonlocal n_bin
            if focus is None:
                grid = _denominator_grid(
                    hot[den], x_init[den], box_lo[den], box_hi[den],
                    n=grid_points,
                )
            else:
                v_star = float(focus[den])
                half = window * (box_hi[den] - box_lo[den]) / 2.0
                grid = _denominator_grid(
                    v_star,
                    v_star,
                    max(box_lo[den], v_star - half),
                    min(box_hi[den], v_star + half),
                    n=grid_points,
                )
            if not grid:
                return False
            base = d + n_bin
            n_bin += len(grid)
            zs = list(range(base, base + len(grid)))
            rows.append((zs, np.ones(len(grid)), 1.0, 1.0))  # Σ z_k = 1
            rows.append(  # x_den = Σ v_k z_k
                ([den] + zs, np.concatenate([[1.0], -np.asarray(grid)]), 0.0, 0.0)
            )
            for v, z_k in zip(grid, zs):
                big = (
                    max(abs(xu_s[ratio]), abs(xl_s[ratio]))
                    + num_hi / abs(v)
                    + 1.0
                )
                coefs = [1.0] + [-c / v for c in num_coefs]
                rows.append(
                    (([ratio] + list(num_cols) + [z_k]), coefs + [big], -np.inf, SLACK + big)
                )
                rows.append(
                    (([ratio] + list(num_cols) + [z_k]), coefs + [-big], -SLACK - big, np.inf)
                )
            return True

        # g5: ratio_loan_income == loan_amnt / annual_inc
        ok5 = denominator_modes(6, 20, [0], [1.0], max(abs(xu_s[0]), abs(xl_s[0])))
        # g6: ratio_open_total == open_acc / total_acc
        ok6 = denominator_modes(14, 21, [10], [1.0], max(abs(xu_s[10]), abs(xl_s[10])))
        if not (ok5 and ok6):  # every candidate denominator was zero/out-of-box
            return LinearRows(rows=[], fixes={}, feasible=False)

        # g7: month difference fixed by the pinned dates
        fixes[22] = diff
        # g8/g9: ratios over the (constant) month difference
        rows.append(([23, 11], [1.0, -1.0 / diff], -SLACK, SLACK))
        rows.append(([24, 16], [1.0, -1.0 / diff], -SLACK, SLACK))
        # g10: pub_rec_bankruptcies / pub_rec, sentinel -1 on zero denominator
        if fixes[11] == 0:
            fixes[25] = -1.0
            fixes[16] = 0.0  # g3 with pub_rec = 0
        else:
            rows.append(([25, 16], [1.0, -1.0 / fixes[11]], -SLACK, SLACK))

        # one-hot validity: each group sums to exactly 1
        for g in ohe_groups:
            rows.append((g, np.ones(len(g)), 1.0, 1.0))

        return LinearRows(rows=rows, fixes=fixes, n_extra_bin=n_bin)

    return build
