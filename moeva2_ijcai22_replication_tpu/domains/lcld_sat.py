"""LCLD constraints linearised for the MILP attack.

Reference semantics: ``/root/reference/src/examples/lcld/lcld_constraints_sat.py``
(Gurobi: indicator constraints for term ∈ {36, 60}, ``addGenConstrPow`` for
(1+r)^term, integer div/mod date decomposition, big-M pub_rec guard).

HiGHS stand-in: the nonlinear participants are pinned at hot-start values
("mode fixing"), making every remaining constraint linear:

- term snaps to the nearer of {36, 60} (g4 exact); int_rate is immutable, so
  the amortisation factor c = r(1+r)^t/((1+r)^t − 1) is a constant and g1
  becomes |installment − c·loan_amnt| <= 0.0999 — linear.
- the ratio denominators annual_inc, total_acc, pub_rec and both date
  features are pinned, so g5/g6/g8/g9/g10 are linear and g7 fixes the
  month-difference feature to a constant.
- one-hot groups: integral 0/1 members summing to 1.

The MILP still searches loan_amnt, installment, open_acc,
pub_rec_bankruptcies, the derived ratios, and every one-hot group — the
features the repair actually needs to move.
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema
from ..attacks.sat.engine import LinearRows
from .lcld import _months

SLACK = 1e-4  # inside the evaluator's 1e-3 snap tolerance


def make_lcld_sat_builder(schema: FeatureSchema):
    ohe_groups = [np.asarray(g) for g in schema.ohe_groups()]

    def build(x_init: np.ndarray, hot: np.ndarray) -> LinearRows:
        rows = []
        fixes = {}

        # g4: term in {36, 60} — snap to the hot start's nearer mode
        term = 36.0 if abs(hot[1] - 36.0) <= abs(hot[1] - 60.0) else 60.0
        fixes[1] = term

        # g1: installment = loan * c(term, rate); rate immutable → c constant
        r = x_init[2] / 1200.0
        growth = (1.0 + r) ** term
        c = r * growth / (growth - 1.0)
        rows.append(([3, 0], [1.0, -c], -0.0999, 0.0999))

        # g2/g3: orderings
        rows.append(([10, 14], [1.0, -1.0], -np.inf, 0.0))
        rows.append(([16, 11], [1.0, -1.0], -np.inf, 0.0))

        # pin the nonlinear participants at hot-start values
        fixes[6] = hot[6]  # annual_inc (g5 denominator)
        fixes[14] = hot[14]  # total_acc (g6 denominator)
        fixes[7] = hot[7]  # issue_d (g7 months)
        fixes[9] = hot[9]  # earliest_cr_line (g7 months)
        fixes[11] = hot[11]  # pub_rec (g3/g8/g10 denominator)

        # g5: ratio_loan_income == loan / annual_inc
        rows.append(([20, 0], [1.0, -1.0 / fixes[6]], -SLACK, SLACK))
        # g6: ratio_open_total == open_acc / total_acc
        rows.append(([21, 10], [1.0, -1.0 / fixes[14]], -SLACK, SLACK))
        # g7: month difference fixed by the pinned dates
        diff = float(_months(fixes[7]) - _months(fixes[9]))
        fixes[22] = diff
        # g8/g9: ratios over the (constant) month difference
        rows.append(([23, 11], [1.0, -1.0 / diff], -SLACK, SLACK))
        rows.append(([24, 16], [1.0, -1.0 / diff], -SLACK, SLACK))
        # g10: pub_rec_bankruptcies / pub_rec, sentinel -1 on zero denominator
        if fixes[11] == 0:
            fixes[25] = -1.0
            fixes[16] = 0.0  # g3 with pub_rec = 0
        else:
            rows.append(([25, 16], [1.0, -1.0 / fixes[11]], -SLACK, SLACK))

        # one-hot validity: each group sums to exactly 1
        for g in ohe_groups:
            rows.append((g, np.ones(len(g)), 1.0, 1.0))

        return LinearRows(rows=rows, fixes=fixes)

    return build
