"""Synthetic constraint-satisfying sample generators.

The reference ships real candidate sets for botnet (387 x 756) but none for
LCLD (its LCLD candidates are produced by a defense pipeline over the raw
LendingClub dataset, which is not redistributed). This module constructs LCLD
samples that satisfy all 10 relational constraints *by construction* — usable
as attack seeds, test fixtures, and benchmark inputs.

Schema: ``data/lcld/features.csv`` (see ``domains/lcld.py`` for the index map).
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema


def _random_date(rng, lo_yyyymm: int, hi_yyyymm: int, size) -> np.ndarray:
    """Uniform YYYYMM dates with valid months."""
    lo_m = (lo_yyyymm // 100) * 12 + lo_yyyymm % 100
    hi_m = (hi_yyyymm // 100) * 12 + hi_yyyymm % 100
    months = rng.integers(lo_m, hi_m + 1, size=size)
    # months here are absolute counts with month in 1..12 encoded as offset
    year, month = (months - 1) // 12, (months - 1) % 12 + 1
    return (year * 100 + month).astype(np.float64)


def _months(f: np.ndarray) -> np.ndarray:
    return np.floor(f / 100) * 12 + f % 100


def synth_lcld_schema(out_dir: str) -> dict:
    """Write a self-contained LCLD schema pair (``features.csv`` +
    ``constraints.csv``) and return their paths.

    The reference's schema files are not redistributed; this one is derived
    entirely from committed code — indices 0..25 are the named numeric
    features of ``domains/lcld.py``'s constraint kernel, 26..46 three
    one-hot groups (4+14+3), bounds covering :func:`synth_lcld`'s generator
    ranges. It makes dataset-free consumers (the serving bench/tests)
    runnable anywhere; committed experiment numbers keep using the
    reference schema.
    """
    import os

    rows = [
        ("loan_amnt", "real", "TRUE", 1000, 40000),
        ("term", "int", "TRUE", 36, 60),
        ("int_rate", "real", "TRUE", 5.31, 30.99),
        ("installment", "real", "TRUE", 0, 3500),
        ("grade", "int", "TRUE", 0, 7),
        ("emp_length", "int", "TRUE", 0, 10),
        ("annual_inc", "real", "TRUE", 10000, 300000),
        ("issue_d", "int", "FALSE", 201203, 201812),
        ("dti", "real", "TRUE", 0, 45),
        ("earliest_cr_line", "int", "FALSE", 198001, 201812),
        ("open_acc", "int", "TRUE", 0, 80),
        ("pub_rec", "int", "TRUE", 0, 10),
        ("revol_bal", "real", "TRUE", 0, 100000),
        ("revol_util", "real", "TRUE", 0, 150),
        ("total_acc", "int", "TRUE", 0, 80),
        ("mort_acc", "int", "TRUE", 0, 10),
        ("pub_rec_bankruptcies", "int", "TRUE", 0, 10),
        ("fico_score", "real", "TRUE", 600, 850),
        ("initial_list_status_w", "int", "TRUE", 0, 1),
        ("application_type_joint", "int", "TRUE", 0, 1),
        ("ratio_loan_income", "real", "TRUE", 0, 4),
        ("ratio_open_total", "real", "TRUE", 0, 1),
        ("month_since_cr_line", "real", "TRUE", 0, 400),
        ("ratio_pubrec_month", "real", "TRUE", 0, 1),
        ("ratio_bankrupt_month", "real", "TRUE", 0, 1),
        ("ratio_bankrupt_pubrec", "real", "TRUE", -1, 1),
    ]
    for g, k in (("ohe0", 4), ("ohe1", 14), ("ohe2", 3)):
        for j in range(k):
            rows.append((f"{g}_{j}", g, "TRUE", 0, 1))
    assert len(rows) == 47
    os.makedirs(out_dir, exist_ok=True)
    features = os.path.join(out_dir, "features.csv")
    with open(features, "w") as f:
        f.write("feature,type,mutable,min,max,augmentation\n")
        for name, t, mut, lo, hi in rows:
            f.write(f"{name},{t},{mut},{lo},{hi},FALSE\n")
    constraints = os.path.join(out_dir, "constraints.csv")
    with open(constraints, "w") as f:
        f.write("constraint,min,max\n")
        for i in range(10):
            f.write(f"g{i + 1},0,1\n")
    return {"features": features, "constraints": constraints}


def synth_lcld(
    n: int, schema: FeatureSchema, seed: int = 0, label_rate: float = 0.5
) -> np.ndarray:
    """Generate ``n`` LCLD samples satisfying all 10 constraints exactly."""
    rng = np.random.default_rng(seed)
    d = schema.n_features
    x = np.zeros((n, d))

    x[:, 0] = rng.uniform(1000, 40000, n)  # loan_amnt
    x[:, 1] = rng.choice([36.0, 60.0], n)  # term
    x[:, 2] = rng.uniform(5.31, 30.99, n)  # int_rate
    r = x[:, 2] / 1200.0
    growth = (1.0 + r) ** x[:, 1]
    x[:, 3] = x[:, 0] * r * growth / (growth - 1.0)  # installment
    x[:, 4] = rng.integers(1, 8, n)  # grade
    x[:, 5] = rng.integers(0, 11, n)  # emp_length
    x[:, 6] = rng.uniform(20000, 300000, n)  # annual_inc
    x[:, 7] = _random_date(rng, 201203, 201812, n)  # issue_d
    x[:, 8] = rng.uniform(0, 40, n)  # dti
    # earliest_cr_line at least 36 months before issue_d (bound of feature 22)
    issue_m = _months(x[:, 7])
    offset = rng.integers(36, 300, n).astype(np.float64)
    ecl_m = issue_m - offset
    year, month = (ecl_m - 1) // 12, (ecl_m - 1) % 12 + 1
    x[:, 9] = year * 100 + month  # earliest_cr_line
    x[:, 14] = np.round(rng.uniform(2, 80, n))  # total_acc
    x[:, 10] = np.round(rng.uniform(1, x[:, 14]))  # open_acc <= total_acc
    x[:, 11] = np.round(rng.uniform(0, 5, n) * (rng.random(n) < 0.3))  # pub_rec
    x[:, 12] = rng.uniform(0, 100000, n)  # revol_bal
    x[:, 13] = rng.uniform(0, 150, n)  # revol_util
    x[:, 15] = np.round(rng.uniform(0, 10, n))  # mort_acc
    x[:, 16] = np.round(rng.uniform(0, x[:, 11]))  # pub_rec_bankruptcies <= pub_rec
    x[:, 17] = rng.uniform(662, 847.5, n)  # fico_score
    x[:, 18] = rng.integers(0, 2, n)  # initial_list_status_w
    x[:, 19] = rng.integers(0, 2, n)  # application_type_Joint App

    diff = issue_m - _months(x[:, 9])
    x[:, 20] = x[:, 0] / x[:, 6]
    x[:, 21] = x[:, 10] / x[:, 14]
    x[:, 22] = diff
    x[:, 23] = x[:, 11] / diff
    x[:, 24] = x[:, 16] / diff
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(x[:, 11] == 0, -1.0, x[:, 16] / np.where(x[:, 11] == 0, 1, x[:, 11]))
    x[:, 25] = ratio

    # One-hot groups: pick one member per group uniformly.
    for group in schema.ohe_groups():
        choice = rng.integers(0, len(group), n)
        x[np.arange(n)[:, None], np.asarray(group)[None, :]] = 0.0
        x[np.arange(n), np.asarray(group)[choice]] = 1.0

    return x
