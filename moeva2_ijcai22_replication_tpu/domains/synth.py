"""Synthetic constraint-satisfying sample generators.

The reference ships real candidate sets for botnet (387 x 756) but none for
LCLD (its LCLD candidates are produced by a defense pipeline over the raw
LendingClub dataset, which is not redistributed). This module constructs LCLD
samples that satisfy all 10 relational constraints *by construction* — usable
as attack seeds, test fixtures, and benchmark inputs.

Schema: ``data/lcld/features.csv`` (see ``domains/lcld.py`` for the index map).
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema


def _random_date(rng, lo_yyyymm: int, hi_yyyymm: int, size) -> np.ndarray:
    """Uniform YYYYMM dates with valid months."""
    lo_m = (lo_yyyymm // 100) * 12 + lo_yyyymm % 100
    hi_m = (hi_yyyymm // 100) * 12 + hi_yyyymm % 100
    months = rng.integers(lo_m, hi_m + 1, size=size)
    # months here are absolute counts with month in 1..12 encoded as offset
    year, month = (months - 1) // 12, (months - 1) % 12 + 1
    return (year * 100 + month).astype(np.float64)


def _months(f: np.ndarray) -> np.ndarray:
    return np.floor(f / 100) * 12 + f % 100


def synth_lcld(
    n: int, schema: FeatureSchema, seed: int = 0, label_rate: float = 0.5
) -> np.ndarray:
    """Generate ``n`` LCLD samples satisfying all 10 constraints exactly."""
    rng = np.random.default_rng(seed)
    d = schema.n_features
    x = np.zeros((n, d))

    x[:, 0] = rng.uniform(1000, 40000, n)  # loan_amnt
    x[:, 1] = rng.choice([36.0, 60.0], n)  # term
    x[:, 2] = rng.uniform(5.31, 30.99, n)  # int_rate
    r = x[:, 2] / 1200.0
    growth = (1.0 + r) ** x[:, 1]
    x[:, 3] = x[:, 0] * r * growth / (growth - 1.0)  # installment
    x[:, 4] = rng.integers(1, 8, n)  # grade
    x[:, 5] = rng.integers(0, 11, n)  # emp_length
    x[:, 6] = rng.uniform(20000, 300000, n)  # annual_inc
    x[:, 7] = _random_date(rng, 201203, 201812, n)  # issue_d
    x[:, 8] = rng.uniform(0, 40, n)  # dti
    # earliest_cr_line at least 36 months before issue_d (bound of feature 22)
    issue_m = _months(x[:, 7])
    offset = rng.integers(36, 300, n).astype(np.float64)
    ecl_m = issue_m - offset
    year, month = (ecl_m - 1) // 12, (ecl_m - 1) % 12 + 1
    x[:, 9] = year * 100 + month  # earliest_cr_line
    x[:, 14] = np.round(rng.uniform(2, 80, n))  # total_acc
    x[:, 10] = np.round(rng.uniform(1, x[:, 14]))  # open_acc <= total_acc
    x[:, 11] = np.round(rng.uniform(0, 5, n) * (rng.random(n) < 0.3))  # pub_rec
    x[:, 12] = rng.uniform(0, 100000, n)  # revol_bal
    x[:, 13] = rng.uniform(0, 150, n)  # revol_util
    x[:, 15] = np.round(rng.uniform(0, 10, n))  # mort_acc
    x[:, 16] = np.round(rng.uniform(0, x[:, 11]))  # pub_rec_bankruptcies <= pub_rec
    x[:, 17] = rng.uniform(662, 847.5, n)  # fico_score
    x[:, 18] = rng.integers(0, 2, n)  # initial_list_status_w
    x[:, 19] = rng.integers(0, 2, n)  # application_type_Joint App

    diff = issue_m - _months(x[:, 9])
    x[:, 20] = x[:, 0] / x[:, 6]
    x[:, 21] = x[:, 10] / x[:, 14]
    x[:, 22] = diff
    x[:, 23] = x[:, 11] / diff
    x[:, 24] = x[:, 16] / diff
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(x[:, 11] == 0, -1.0, x[:, 16] / np.where(x[:, 11] == 0, 1, x[:, 11]))
    x[:, 25] = ratio

    # One-hot groups: pick one member per group uniformly.
    for group in schema.ohe_groups():
        choice = rng.integers(0, len(group), n)
        x[np.arange(n)[:, None], np.asarray(group)[None, :]] = 0.0
        x[np.arange(n), np.asarray(group)[choice]] = 1.0

    return x
