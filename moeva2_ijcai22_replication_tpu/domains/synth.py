"""Synthetic constraint-satisfying sample generators.

The reference ships real candidate sets for botnet (387 x 756) but none for
LCLD (its LCLD candidates are produced by a defense pipeline over the raw
LendingClub dataset, which is not redistributed). This module constructs LCLD
samples that satisfy all 10 relational constraints *by construction* — usable
as attack seeds, test fixtures, and benchmark inputs.

Schema: ``data/lcld/features.csv`` (see ``domains/lcld.py`` for the index map).
"""

from __future__ import annotations

import numpy as np

from ..core.schema import FeatureSchema


def _random_date(rng, lo_yyyymm: int, hi_yyyymm: int, size) -> np.ndarray:
    """Uniform YYYYMM dates with valid months."""
    lo_m = (lo_yyyymm // 100) * 12 + lo_yyyymm % 100
    hi_m = (hi_yyyymm // 100) * 12 + hi_yyyymm % 100
    months = rng.integers(lo_m, hi_m + 1, size=size)
    # months here are absolute counts with month in 1..12 encoded as offset
    year, month = (months - 1) // 12, (months - 1) % 12 + 1
    return (year * 100 + month).astype(np.float64)


# single-sourced in the IR operator library (numpy path: float64-exact)
from .ir.ops import months as _months


def synth_lcld_schema(out_dir: str) -> dict:
    """Write a self-contained LCLD schema pair (``features.csv`` +
    ``constraints.csv``) and return their paths.

    The reference's schema files are not redistributed; this one is derived
    entirely from committed code — indices 0..25 are the named numeric
    features of ``domains/lcld.py``'s constraint kernel, 26..46 three
    one-hot groups (4+14+3), bounds covering :func:`synth_lcld`'s generator
    ranges. It makes dataset-free consumers (the serving bench/tests)
    runnable anywhere; committed experiment numbers keep using the
    reference schema.
    """
    import os

    rows = [
        ("loan_amnt", "real", "TRUE", 1000, 40000),
        ("term", "int", "TRUE", 36, 60),
        ("int_rate", "real", "TRUE", 5.31, 30.99),
        ("installment", "real", "TRUE", 0, 3500),
        ("grade", "int", "TRUE", 0, 7),
        ("emp_length", "int", "TRUE", 0, 10),
        ("annual_inc", "real", "TRUE", 10000, 300000),
        ("issue_d", "int", "FALSE", 201203, 201812),
        ("dti", "real", "TRUE", 0, 45),
        ("earliest_cr_line", "int", "FALSE", 198001, 201812),
        ("open_acc", "int", "TRUE", 0, 80),
        ("pub_rec", "int", "TRUE", 0, 10),
        ("revol_bal", "real", "TRUE", 0, 100000),
        ("revol_util", "real", "TRUE", 0, 150),
        ("total_acc", "int", "TRUE", 0, 80),
        ("mort_acc", "int", "TRUE", 0, 10),
        ("pub_rec_bankruptcies", "int", "TRUE", 0, 10),
        ("fico_score", "real", "TRUE", 600, 850),
        ("initial_list_status_w", "int", "TRUE", 0, 1),
        ("application_type_joint", "int", "TRUE", 0, 1),
        ("ratio_loan_income", "real", "TRUE", 0, 4),
        ("ratio_open_total", "real", "TRUE", 0, 1),
        ("month_since_cr_line", "real", "TRUE", 0, 400),
        ("ratio_pubrec_month", "real", "TRUE", 0, 1),
        ("ratio_bankrupt_month", "real", "TRUE", 0, 1),
        ("ratio_bankrupt_pubrec", "real", "TRUE", -1, 1),
    ]
    for g, k in (("ohe0", 4), ("ohe1", 14), ("ohe2", 3)):
        for j in range(k):
            rows.append((f"{g}_{j}", g, "TRUE", 0, 1))
    assert len(rows) == 47
    os.makedirs(out_dir, exist_ok=True)
    features = os.path.join(out_dir, "features.csv")
    with open(features, "w") as f:
        f.write("feature,type,mutable,min,max,augmentation\n")
        for name, t, mut, lo, hi in rows:
            f.write(f"{name},{t},{mut},{lo},{hi},FALSE\n")
    constraints = os.path.join(out_dir, "constraints.csv")
    with open(constraints, "w") as f:
        f.write("constraint,min,max\n")
        for i in range(10):
            f.write(f"g{i + 1},0,1\n")
    return {"features": features, "constraints": constraints}


def synth_lcld(
    n: int, schema: FeatureSchema, seed: int = 0, label_rate: float = 0.5
) -> np.ndarray:
    """Generate ``n`` LCLD samples satisfying all 10 constraints exactly."""
    rng = np.random.default_rng(seed)
    d = schema.n_features
    x = np.zeros((n, d))

    x[:, 0] = rng.uniform(1000, 40000, n)  # loan_amnt
    x[:, 1] = rng.choice([36.0, 60.0], n)  # term
    x[:, 2] = rng.uniform(5.31, 30.99, n)  # int_rate
    r = x[:, 2] / 1200.0
    growth = (1.0 + r) ** x[:, 1]
    x[:, 3] = x[:, 0] * r * growth / (growth - 1.0)  # installment
    x[:, 4] = rng.integers(1, 8, n)  # grade
    x[:, 5] = rng.integers(0, 11, n)  # emp_length
    x[:, 6] = rng.uniform(20000, 300000, n)  # annual_inc
    x[:, 7] = _random_date(rng, 201203, 201812, n)  # issue_d
    x[:, 8] = rng.uniform(0, 40, n)  # dti
    # earliest_cr_line at least 36 months before issue_d (bound of feature 22)
    issue_m = _months(x[:, 7])
    offset = rng.integers(36, 300, n).astype(np.float64)
    ecl_m = issue_m - offset
    year, month = (ecl_m - 1) // 12, (ecl_m - 1) % 12 + 1
    x[:, 9] = year * 100 + month  # earliest_cr_line
    x[:, 14] = np.round(rng.uniform(2, 80, n))  # total_acc
    x[:, 10] = np.round(rng.uniform(1, x[:, 14]))  # open_acc <= total_acc
    x[:, 11] = np.round(rng.uniform(0, 5, n) * (rng.random(n) < 0.3))  # pub_rec
    x[:, 12] = rng.uniform(0, 100000, n)  # revol_bal
    x[:, 13] = rng.uniform(0, 150, n)  # revol_util
    x[:, 15] = np.round(rng.uniform(0, 10, n))  # mort_acc
    x[:, 16] = np.round(rng.uniform(0, x[:, 11]))  # pub_rec_bankruptcies <= pub_rec
    x[:, 17] = rng.uniform(662, 847.5, n)  # fico_score
    x[:, 18] = rng.integers(0, 2, n)  # initial_list_status_w
    x[:, 19] = rng.integers(0, 2, n)  # application_type_Joint App

    diff = issue_m - _months(x[:, 9])
    x[:, 20] = x[:, 0] / x[:, 6]
    x[:, 21] = x[:, 10] / x[:, 14]
    x[:, 22] = diff
    x[:, 23] = x[:, 11] / diff
    x[:, 24] = x[:, 16] / diff
    with np.errstate(divide="ignore", invalid="ignore"):
        ratio = np.where(x[:, 11] == 0, -1.0, x[:, 16] / np.where(x[:, 11] == 0, 1, x[:, 11]))
    x[:, 25] = ratio

    # One-hot groups: pick one member per group uniformly.
    for group in schema.ohe_groups():
        choice = rng.integers(0, len(group), n)
        x[np.arange(n)[:, None], np.asarray(group)[None, :]] = 0.0
        x[np.arange(n), np.asarray(group)[choice]] = 1.0

    return x


# -- botnet -------------------------------------------------------------------

_BOTNET_PORTS = 18
_BOTNET_KINDS = ("bytes_out", "pkts_out", "duration")
_BOTNET_STATS = ("sum", "max", "min")


def synth_botnet_schema(out_dir: str) -> dict:
    """Write a self-contained botnet schema (``features.csv`` +
    ``constraints.csv`` + ``feat_idx.pickle``) and return the paths.

    The reference's CTU-13 schema is not redistributed; this one reproduces
    its *structure* exactly — 756 features, the 18-port group tables
    ``domains/botnet.py`` gathers through (9 stat keys + 3 protocol-sum keys
    + 1 bytes_in key per direction), 360 constraint rows — from committed
    code alone, so dataset-free consumers (serving, the IR equivalence
    tests) run anywhere. Committed experiment numbers keep using the
    reference schema.
    """
    import os
    import pickle

    names: list = []
    feat_idx: dict = {}

    def alloc(key: str, count: int, prefix: str) -> None:
        base = len(names)
        names.extend(f"{prefix}_p{j}" for j in range(count))
        feat_idx[key] = np.arange(base, base + count, dtype=np.int64)

    for side in ("s", "d"):
        for kind in _BOTNET_KINDS:
            for stat in _BOTNET_STATS:
                alloc(f"{kind}_{stat}_{side}_idx", _BOTNET_PORTS, f"{kind}_{stat}_{side}")
        for proto in ("icmp", "udp", "tcp"):
            alloc(f"{proto}_sum_{side}_idx", _BOTNET_PORTS, f"{proto}_sum_{side}")
        alloc(f"bytes_in_sum_{side}_idx", _BOTNET_PORTS, f"bytes_in_sum_{side}")
    while len(names) < 756:
        names.append(f"ctx_{len(names)}")
    assert len(names) == 756

    os.makedirs(out_dir, exist_ok=True)
    features = os.path.join(out_dir, "features.csv")
    with open(features, "w") as f:
        f.write("feature,type,mutable,min,max,augmentation\n")
        for name in names:
            hi = 1.0 if name.startswith("ctx_") else 1e7
            f.write(f"{name},real,TRUE,0,{hi},FALSE\n")
    constraints = os.path.join(out_dir, "constraints.csv")
    with open(constraints, "w") as f:
        f.write("constraint,min,max\n")
        for i in range(360):
            f.write(f"c{i},0,1\n")
    idx_path = os.path.join(out_dir, "feat_idx.pickle")
    with open(idx_path, "wb") as f:
        pickle.dump(feat_idx, f)
    return {"features": features, "constraints": constraints, "feat_idx": idx_path}


def synth_botnet(n: int, schema: FeatureSchema, seed: int = 0) -> np.ndarray:
    """Generate ``n`` botnet samples satisfying all 360 constraints exactly.

    Construction: per (kind, side, port) three draws sorted into
    min <= median <= max with sum = min+median+max (>= max, so every
    ordering holds); bytes_out triples rescaled under 1500·pkts_out (MTU
    ratio); protocol port sums constructed so Σflows == Σbytes_in +
    Σbytes_out per direction EXACTLY.

    Every constrained value is quantized to a multiple of 1/16 with
    magnitude far below 2**18, so values, triple sums, and the 54-term
    flow-identity sums are all exactly representable in float32 in any
    summation order: the equalities hold bit-exactly under the engines'
    f32 casts (the serving request path validates in f32), not just in
    the f64 sampler.
    """
    rng = np.random.default_rng(seed)
    d = schema.n_features
    x = np.zeros((n, d))
    x[:, :] = rng.uniform(0.0, 1.0, (n, d))  # filler/ctx features

    cols = {name: i for i, name in enumerate(schema.names)}

    def q16(v: np.ndarray) -> np.ndarray:
        """Quantize to 1/16 steps (monotone, so orderings survive)."""
        return np.floor(v * 16.0) / 16.0

    def block(prefix: str) -> np.ndarray:
        return np.array(
            [cols[f"{prefix}_p{j}"] for j in range(_BOTNET_PORTS)], dtype=np.int64
        )

    for side in ("s", "d"):
        triples = {}
        for kind in _BOTNET_KINDS:
            scale = {"bytes_out": 3000.0, "pkts_out": 40.0, "duration": 60.0}[kind]
            draws = np.sort(
                rng.uniform(0.0, scale, (n, _BOTNET_PORTS, 3)), axis=-1
            )
            # sparsify: some ports saw no traffic at all
            draws *= (rng.random((n, _BOTNET_PORTS, 1)) < 0.7)
            triples[kind] = q16(draws)
        # MTU: bytes_out_sum <= 1500 * pkts_out_sum, preserved under the
        # triple's internal ordering by scaling the whole triple; the
        # re-quantize after scaling only shrinks bytes, keeping the bound
        b_sum = triples["bytes_out"].sum(-1)
        p_sum = triples["pkts_out"].sum(-1)
        with np.errstate(divide="ignore", invalid="ignore"):
            factor = np.where(
                (p_sum > 0) & (b_sum > 1500.0 * p_sum),
                np.where(b_sum > 0, 1500.0 * p_sum / np.where(b_sum > 0, b_sum, 1.0), 1.0),
                1.0,
            )
        triples["bytes_out"] = q16(triples["bytes_out"] * factor[..., None])
        # pkts==0 ports pass via the sentinel, but only if bytes==0 too is
        # not required — the guard passes any bytes; keep them anyway.
        for kind in _BOTNET_KINDS:
            mn, md, mx = (triples[kind][..., k] for k in range(3))
            x[:, block(f"{kind}_min_{side}")] = mn
            x[:, block(f"{kind}_max_{side}")] = mx
            x[:, block(f"{kind}_sum_{side}")] = mn + md + mx
        # flow-volume identity: Σ proto sums == Σ bytes_in + Σ bytes_out.
        # target is a multiple of 1/16; quantizing the scaled flows only
        # undershoots, and the residual (also a multiple of 1/16) lands on
        # the first flow — the identity is exact, not approximately scaled
        x[:, block(f"bytes_in_sum_{side}")] = q16(
            rng.uniform(0.0, 2000.0, (n, _BOTNET_PORTS))
        )
        target = (
            x[:, block(f"bytes_in_sum_{side}")].sum(-1)
            + x[:, block(f"bytes_out_sum_{side}")].sum(-1)
        )
        flows = rng.uniform(0.1, 100.0, (n, 3 * _BOTNET_PORTS))
        flows = q16(flows * (target / flows.sum(-1))[:, None])
        flows[:, 0] += target - flows.sum(-1)
        x[:, block(f"icmp_sum_{side}")] = flows[:, :_BOTNET_PORTS]
        x[:, block(f"udp_sum_{side}")] = flows[:, _BOTNET_PORTS : 2 * _BOTNET_PORTS]
        x[:, block(f"tcp_sum_{side}")] = flows[:, 2 * _BOTNET_PORTS :]
    return x


# -- phishing -----------------------------------------------------------------


def synth_phishing(n: int, schema: FeatureSchema, seed: int = 0) -> np.ndarray:
    """Generate ``n`` samples of the spec-only phishing/URL domain
    (``domains/specs/phishing/``) satisfying all 10 constraints exactly.

    The domain has no hand-written kernel — the committed CSV spec is its
    single definition — so this sampler builds rows constraint-first:
    lengths split hostname+path <= url, punctuation counts summed into
    n_punct, ratios derived by the same guarded division the kernel uses.
    """
    rng = np.random.default_rng(seed)
    cols = {name: i for i, name in enumerate(schema.names)}
    x = np.zeros((n, schema.n_features))

    url = np.round(rng.uniform(30, 300, n))
    host = np.round(rng.uniform(4, 25, n))
    path = np.round(rng.uniform(0, url - host))
    dots = np.round(rng.uniform(1, 10, n))
    hyphens = np.round(rng.uniform(0, 5, n))
    slash = np.round(rng.uniform(1, 8, n))
    digits = np.round(rng.uniform(0, 0.3 * url))
    special = np.round(rng.uniform(0, 0.2 * url))

    x[:, cols["length_url"]] = url
    x[:, cols["length_hostname"]] = host
    x[:, cols["length_path"]] = path
    x[:, cols["nb_dots"]] = dots
    x[:, cols["nb_hyphens"]] = hyphens
    x[:, cols["nb_slash"]] = slash
    x[:, cols["nb_digits"]] = digits
    x[:, cols["nb_special"]] = special
    x[:, cols["n_subdomains"]] = np.minimum(np.round(rng.uniform(0, 4, n)), dots)
    x[:, cols["https"]] = rng.integers(0, 2, n)
    x[:, cols["n_punct"]] = dots + hyphens + slash
    x[:, cols["ratio_digits_url"]] = digits / url
    x[:, cols["ratio_special_url"]] = special / url
    x[:, cols["ratio_hostname_url"]] = host / url
    return x
