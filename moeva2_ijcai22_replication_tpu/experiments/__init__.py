"""Experiment entry points (L4) and research-question orchestration (L5).

Parity targets under ``/root/reference/src``:

- :mod:`.moeva`   — ``experiments/united/04_moeva.py`` (MoEvA2 runner)
- :mod:`.pgd`     — ``experiments/united/01_pgd_united.py`` (PGD/AutoPGD/SAT)
- :mod:`.rq`      — ``run_rq1.py`` / ``run_rq2.py`` / ``run_rq3.py`` grids
- :mod:`.run_all` — ``run_all.sh``
- :mod:`.defense` — ``experiments/{lcld,botnet}/01_train_robust.py`` pipelines
- :mod:`.rq4`     — ``experiments/lcld/03_train_robust_rq4.py`` iteration

Runners are plain functions ``run(config) -> metrics | None`` so grids
compose in-process within one JAX runtime; each module also has a CLI
(``python -m moeva2_ijcai22_replication_tpu.experiments.moeva -c … -p …``)
mirroring the reference's subprocess interface.
"""

from . import common  # noqa: F401
