"""Shared runner plumbing: artifact loading, skip-if-done, SAT registry.

Mirrors the setup blocks both reference entry points share
(``04_moeva.py:41-64``, ``01_pgd_united.py:50-77``).
"""

from __future__ import annotations

import os

import numpy as np

from ..domains import get_constraints_class
from ..domains.botnet_sat import make_botnet_sat_builder
from ..domains.lcld_sat import make_lcld_sat_builder
from ..models.scalers import MinMaxParams, load_joblib_scaler
from ..utils.config import get_dict_hash
from ..utils.in_out import load_model


def setup_jax_cache(config: dict | None = None) -> None:
    """Point XLA's persistent compilation cache at a per-repo directory so
    every runner invocation of the same jitted attack program after the first
    loads its executable from disk instead of recompiling (~tens of seconds
    per program shape; an rq grid revisits the same handful of shapes across
    many processes). ``system.jax_cache_dir: ""`` disables."""
    import jax

    cache_dir = ".jax_cache"
    if config is not None:
        cache_dir = config.get("system", {}).get("jax_cache_dir", cache_dir)
    if not cache_dir:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # never let cache plumbing break an experiment
        print(f"persistent compilation cache unavailable: {e}")


def metrics_path_for(config: dict, mid_fix: str) -> str:
    out_dir = config["dirs"]["results"]
    return f"{out_dir}/metrics_{mid_fix}_{get_dict_hash(config)}.json"


def should_skip(config: dict, mid_fix: str) -> bool:
    """Config-hash idempotency (``04_moeva.py:31-36``): a metrics file for
    this exact config means the experiment already ran."""
    path = metrics_path_for(config, mid_fix)
    if os.path.exists(path):
        print(
            f"Configuration with hash {get_dict_hash(config)} already "
            "executed. Skipping"
        )
        return True
    return False


def load_constraints(config: dict):
    """Constraint plugin from the registry, with optional explicit
    important-features path (``04_moeva.py:43-53``)."""
    cls = get_constraints_class(config["project_name"])
    kwargs = {}
    if config["paths"].get("important_features"):
        kwargs["important_features_path"] = config["paths"]["important_features"]
    return cls(
        config["paths"]["features"], config["paths"]["constraints"], **kwargs
    )


def load_candidates(config: dict) -> np.ndarray:
    """Candidate set, sliced to the configured window; ``n_initial_state=-1``
    keeps everything (``04_moeva.py:55-58``)."""
    x = np.load(config["paths"]["x_candidates"])
    offset, count = config["initial_state_offset"], config["n_initial_state"]
    return x if count == -1 else x[offset : offset + count]


def load_scaler(config: dict) -> MinMaxParams:
    return load_joblib_scaler(config["paths"]["ml_scaler"])


def load_surrogate(config: dict):
    model = load_model(config["paths"]["model"])
    from ..models.io import Surrogate

    if not isinstance(model, Surrogate):
        raise TypeError(
            f"{config['paths']['model']} is not a device-runnable surrogate; "
            "attack runners need a Keras/Flax artifact"
        )
    return model


def get_sat_builder(project_name: str, constraints):
    """Project-name -> MILP row builder (parity:
    ``united/utils.py:28-30``'s STR_TO_SAT_CONSTRAINTS)."""
    if project_name.startswith("lcld"):
        return make_lcld_sat_builder(constraints.schema)
    if project_name.startswith("botnet"):
        return make_botnet_sat_builder(constraints)
    raise ValueError(f"No SAT constraint builder for project {project_name!r}")


def evaluation_constraints(config: dict, attack_constraints):
    """RQ2's evaluation override: success is judged under a different
    constraint set than the attack used (``04_moeva.py:116-120``)."""
    ev = config.get("evaluation")
    if not ev:
        return attack_constraints
    cls = get_constraints_class(ev["project_name"])
    return cls(config["paths"]["features"], ev["constraints"])


def build_mesh(config: dict):
    """Optional states-axis mesh from config ``system.mesh_devices``:
    -1 = all visible devices, 0/absent = single device."""
    n = int(config.get("system", {}).get("mesh_devices", 0) or 0)
    if n == 0:
        return None
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n > 0:
        if n > len(devices):
            raise ValueError(
                f"system.mesh_devices={n} but only {len(devices)} devices "
                "are visible"
            )
        devices = devices[:n]
    return Mesh(np.array(devices), ("states",))


def pad_states(x: np.ndarray, mesh) -> tuple[np.ndarray, int]:
    """Pad the leading (states) axis to a mesh-size multiple.

    Candidate counts are data-dependent (e.g. the 387-row botnet set), so
    runners pad with copies of the last row before a mesh-sharded attack and
    trim every per-state result back to ``n_orig`` rows afterwards. Returns
    ``(x_padded, n_orig)``; a no-op without a mesh or when already aligned.
    """
    n = x.shape[0]
    if mesh is None or n % mesh.size == 0:
        return x, n
    pad = (-n) % mesh.size
    return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)]), n
