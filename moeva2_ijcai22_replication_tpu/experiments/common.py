"""Shared runner plumbing: artifact loading, skip-if-done, SAT registry.

Mirrors the setup blocks both reference entry points share
(``04_moeva.py:41-64``, ``01_pgd_united.py:50-77``) — with one grid-scale
difference: the loaders are memoized (:class:`ArtifactCache`, keyed by
resolved paths + mtime/size) and runners can reuse attack-engine instances
across grid points (:func:`cached_engine`), so an in-process sweep reads
constraints / candidates / scalers / surrogate weights from disk once per
grid and shares compiled executables instead of rebuilding per point.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from ..domains import get_constraints_class
from ..domains.botnet_sat import make_botnet_sat_builder
from ..domains.lcld_sat import make_lcld_sat_builder
from ..models.scalers import MinMaxParams, load_joblib_scaler
from ..utils.config import get_dict_hash
from ..utils.in_out import load_model


class ArtifactCache:
    """Path-keyed memoizer for on-disk experiment artifacts.

    An entry is valid while every file it was built from keeps its
    (mtime_ns, size) stamp; a touched or rewritten file invalidates exactly
    that entry on the next lookup. Hit/miss counters feed the grid report.

    Lookups are serialized: the grid pipeline's background writer (point A's
    evaluation) and the launching thread (point B's setup) — and serving
    resolves — hit this process-wide cache concurrently, and a racing miss
    must not build twice (a replaced constraints object would change a later
    ``id()``-keyed engine-cache key and force a spurious recompile).
    """

    def __init__(self):
        self._entries: dict = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        #: stamp-invalidated rebuilds (a file changed on disk under a live
        #: key) — the cache's only eviction mode, counted for /healthz
        self.evictions = 0

    @staticmethod
    def _stamp(paths: tuple) -> tuple:
        return tuple(
            (p, st.st_mtime_ns, st.st_size)
            for p, st in ((p, os.stat(p)) for p in paths)
        )

    def get(self, kind: str, paths, extra, builder):
        """Return ``builder()``'s result memoized under ``(kind, paths,
        extra)``, rebuilt when any of ``paths`` changed on disk."""
        paths = tuple(os.path.abspath(p) for p in paths)
        key = (kind, paths, extra)
        stamp = self._stamp(paths)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[0] == stamp:
                self.hits += 1
                return entry[1]
            if entry is not None:
                self.evictions += 1
            self.misses += 1
            t0 = time.perf_counter()
            value = builder()
            # cold-start decomposition: artifact build/load wall-clock is
            # a named phase of the process's cold path (misses only — a
            # hit is the amortisation working)
            from ..observability.coldstart import get_coldstart

            get_coldstart().record_phase(
                "artifact_build", time.perf_counter() - t0
            )
            self._entries[key] = (stamp, value)
            return value

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "entries": len(self._entries),
        }

    def clear(self):
        self._entries.clear()


#: process-wide artifact cache: one disk read per artifact per grid (module
#: level so subprocess-mode grid points — one process per point — still work,
#: they just never hit).
ARTIFACTS = ArtifactCache()


class EngineCache:
    """Static-config-keyed attack-engine instances.

    An engine owns its jitted program, so reusing the instance across grid
    points reuses the traced/compiled executable in-process (the persistent
    XLA cache only amortises across processes). Keys must contain every
    constructor argument that shapes the compiled program; run-identity
    knobs that only feed host-side dispatch (seed, checkpoint paths, MoEvA's
    ``n_gen``) are reassigned on the cached instance per point.
    """

    #: recompile causes kept (bounded; the key space is client-controlled)
    MAX_CAUSES = 32

    def __init__(self):
        self._engines: dict = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        #: structured "why did this miss build a new engine" records:
        #: which key fields differed from the nearest existing entry
        self.recompile_causes: list[dict] = []

    def get(self, key: tuple, builder, fields: tuple | None = None):
        """``fields`` optionally names the key's positions so a miss can be
        explained field-by-field (the /healthz recompile-cause view)."""
        # serialized like ArtifactCache.get: a racing miss must not build
        # two engine instances for one key (each would trace its own
        # executables — exactly the duplication this cache exists to prevent)
        with self._lock:
            engine = self._engines.get(key)
            if engine is not None:
                self.hits += 1
                return engine
            self.misses += 1
            cause = self._recompile_cause(key, fields)
            if cause is not None:
                self.recompile_causes.append(cause)
                del self.recompile_causes[: -self.MAX_CAUSES]
            engine = builder()
            # stable per-process identity for the cost ledger: entries
            # compiled by this engine carry it, joining executables back
            # to their cache slot (best-effort — not every cached value
            # accepts attributes)
            try:
                engine.cache_key = f"{key[0]}:{get_dict_hash(repr(key))[:12]}"
            except AttributeError:
                pass
            self._engines[key] = engine
            return engine

    def _recompile_cause(self, key: tuple, fields: tuple | None) -> dict | None:
        """Diff the missing key against the nearest cached key of the same
        family and name the fields that differed — "budget 100 -> 1000"
        explains a rebuild faster than two opaque tuples. None on a cold
        miss (nothing comparable cached). The nearest-diff algorithm is
        shared with the executable ledger's recompile causes."""
        from ..observability.ledger import nearest_identity_diff

        names = list(fields or ())

        def as_identity(k: tuple) -> dict:
            return {
                (names[i] if i < len(names) else f"field_{i}"): repr(k[i])
                for i in range(len(k))
            }

        cause = nearest_identity_diff(
            (
                (None, as_identity(k))
                for k in self._engines
                if k[0] == key[0] and len(k) == len(key)
            ),
            as_identity(key),
        )
        if cause is None:
            return None
        return {"family": str(key[0]), "changed": cause["changed"]}

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "engines": len(self._engines),
            "traces": sum(
                getattr(e, "trace_count", 0) for e in self._engines.values()
            ),
        }

    def clear(self):
        self._engines.clear()


#: process-wide engine cache (same lifetime rationale as ARTIFACTS).
ENGINES = EngineCache()


def setup_jax_cache(config: dict | None = None) -> None:
    """Point XLA's persistent compilation cache at a per-repo directory so
    every runner invocation of the same jitted attack program after the first
    loads its executable from disk instead of recompiling (~tens of seconds
    per program shape; an rq grid revisits the same handful of shapes across
    many processes). ``system.jax_cache_dir: ""`` disables.

    Also applies ``system.cost_ledger``, ``system.mesh_telemetry``,
    ``system.gap_telemetry`` (all default on), and ``system.aot_cache``
    (the serialized-executable tier — default ``<jax_cache_dir>/aot``
    whenever the jax cache is on, so warm processes skip
    trace+lower+compile entirely; ``""`` disables): this is the one
    process-level setup hook every runner and bench path already calls —
    which also makes it the cold-start ledger's "imports are done" marker."""
    from ..observability.aotcache import configure_aot_cache
    from ..observability.coldstart import configure_coldstart
    from ..observability.gaps import configure_gap_tracker
    from ..observability.ledger import configure_ledger
    from ..observability.mesh import configure_mesh_capture

    configure_ledger(config)
    configure_mesh_capture(config)
    configure_gap_tracker(config)
    coldstart = configure_coldstart(config)
    coldstart.note_import_complete()
    import jax

    cache_dir = ".jax_cache"
    if config is not None:
        cache_dir = config.get("system", {}).get("jax_cache_dir", cache_dir)
    # the AOT dir defaults INSIDE the jax cache dir so both tiers share
    # one volume/symlink layout (the bench grid symlinks .jax_cache into
    # its working dirs and gets the serialized executables for free);
    # created eagerly so the jax-cache entry census counts it from start
    aot = configure_aot_cache(
        config, default_dir=os.path.join(cache_dir, "aot") if cache_dir else None
    )
    if aot.enabled:
        try:
            os.makedirs(aot.path, exist_ok=True)
        except OSError:
            pass
    if not cache_dir:
        coldstart.configure_cache(None, False)
        return
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        coldstart.configure_cache(cache_dir, True)
    except Exception as e:  # never let cache plumbing break an experiment
        # a swallowed failure must still be observable: a counted recorder
        # event plus structured state (dir + fallback + error) that the
        # cold-start ledger carries onto /healthz ``build.jax_cache`` —
        # every later compile in this process is a silent cache miss, and
        # that is exactly the cold-start regression the gap/cold telemetry
        # exists to attribute
        from ..observability.trace import default_recorder

        default_recorder().count("jax_cache_setup_failures")
        coldstart.configure_cache(cache_dir, False, error=repr(e))
        print(f"persistent compilation cache unavailable: {e}")


def metrics_path_for(config: dict, mid_fix: str) -> str:
    out_dir = config["dirs"]["results"]
    return f"{out_dir}/metrics_{mid_fix}_{get_dict_hash(config)}.json"


def should_skip(config: dict, mid_fix: str, pipeline=None) -> bool:
    """Config-hash idempotency (``04_moeva.py:31-36``): a metrics file for
    this exact config means the experiment already ran. Under a grid pipeline
    the metrics write may still sit in the background writer's queue, so a
    queued-but-unwritten hash also skips (idempotency must not depend on
    writer latency)."""
    path = metrics_path_for(config, mid_fix)
    if os.path.exists(path) or (pipeline is not None and pipeline.is_pending(path)):
        print(
            f"Configuration with hash {get_dict_hash(config)} already "
            "executed. Skipping"
        )
        return True
    return False


def load_constraints(config: dict):
    """Constraint plugin from the registry, with optional explicit
    important-features path (``04_moeva.py:43-53``). Memoized: every grid
    point naming the same CSVs shares one constraints object."""
    project = config["project_name"]
    spec_path = config.get("spec")
    paths = [config["paths"]["features"], config["paths"]["constraints"]]
    important = config["paths"].get("important_features")
    if important:
        paths.append(important)
    if spec_path:
        # domain-as-data: the constraint class is compiled from the named
        # spec file rather than looked up; the file rides in the mtime+size
        # cache key, so editing a spec invalidates the memoized domain
        paths.append(spec_path)

    def build():
        if spec_path:
            from ..domains.ir import compile_spec_path

            cls = compile_spec_path(spec_path, name=project)
        else:
            cls = get_constraints_class(project)
        kwargs = (
            {"important_features_path": important} if important else {}
        )
        return cls(paths[0], paths[1], **kwargs)

    return ARTIFACTS.get("constraints", paths, (project, bool(important)), build)


def load_candidates(config: dict) -> np.ndarray:
    """Candidate set, sliced to the configured window; ``n_initial_state=-1``
    keeps everything (``04_moeva.py:55-58``). The full ``np.load`` is
    memoized per file; slicing is per-config (views of the cached array —
    runners treat candidates as read-only)."""
    path = config["paths"]["x_candidates"]
    x = ARTIFACTS.get("candidates", [path], None, lambda: np.load(path))
    offset, count = config["initial_state_offset"], config["n_initial_state"]
    return x if count == -1 else x[offset : offset + count]


def load_scaler(config: dict) -> MinMaxParams:
    path = config["paths"]["ml_scaler"]
    return ARTIFACTS.get("scaler", [path], None, lambda: load_joblib_scaler(path))


def load_surrogate(config: dict):
    path = config["paths"]["model"]

    def build():
        model = load_model(path)
        from ..models.io import Surrogate

        if not isinstance(model, Surrogate):
            raise TypeError(
                f"{path} is not a device-runnable surrogate; "
                "attack runners need a Keras/Flax artifact"
            )
        return model

    return ARTIFACTS.get("surrogate", [path], None, build)


def get_sat_builder(project_name: str, constraints):
    """Project-name -> MILP row builder (parity:
    ``united/utils.py:28-30``'s STR_TO_SAT_CONSTRAINTS).

    Spec-compiled domains route to the IR's MILP backend — one compiler for
    every spec — before the hand-written prefix matches, so ``lcld_spec``
    gets its own linearization rather than the hand-written twin's."""
    from ..domains.ir import SpecConstraintSet, make_spec_sat_builder

    if isinstance(constraints, SpecConstraintSet):
        return make_spec_sat_builder(constraints)
    if project_name.startswith("lcld"):
        return make_lcld_sat_builder(constraints.schema)
    if project_name.startswith("botnet"):
        return make_botnet_sat_builder(constraints)
    raise ValueError(f"No SAT constraint builder for project {project_name!r}")


def evaluation_constraints(config: dict, attack_constraints):
    """RQ2's evaluation override: success is judged under a different
    constraint set than the attack used (``04_moeva.py:116-120``)."""
    ev = config.get("evaluation")
    if not ev:
        return attack_constraints
    paths = [config["paths"]["features"], ev["constraints"]]
    return ARTIFACTS.get(
        "constraints",
        paths,
        (ev["project_name"], False),
        lambda: get_constraints_class(ev["project_name"])(paths[0], paths[1]),
    )


def build_mesh(config: dict):
    """Optional states-axis mesh from config ``system.mesh_devices``:
    -1 = all visible devices, 0/absent = single device."""
    n = int(config.get("system", {}).get("mesh_devices", 0) or 0)
    if n == 0:
        return None
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n > 0:
        if n > len(devices):
            raise ValueError(
                f"system.mesh_devices={n} but only {len(devices)} devices "
                "are visible"
            )
        devices = devices[:n]
    return Mesh(np.array(devices), ("states",))


#: The one batch-shape menu for every fixed-shape dispatch path: the serving
#: microbatcher's bucket sizes AND the MoEvA early-exit compaction targets.
#: Power-of-two keeps the compile surface logarithmic in the largest batch
#: while padding waste stays < 2x; every production mesh size (1/2/4/8)
#: divides every entry, so bucketed batches satisfy the states-axis
#: divisibility contract (``attacks/sharding.py``) without re-padding.
DEFAULT_BUCKET_SIZES = (8, 16, 32, 64, 128, 256)


class RequestTooLarge(ValueError):
    """A row count exceeds the largest bucket; it can never dispatch."""


class BucketMenu:
    """The fixed menu of allowed batch shapes.

    Shared source of truth for every fixed-shape dispatch path (serving
    batches, MoEvA active-set compaction): small and power-of-two so the
    compile surface stays bounded (one program per size actually used)
    while padding waste stays < 2x; every size must be a mesh-size multiple
    so bucketed batches satisfy the states-axis divisibility contract
    (``attacks/sharding.py``) without re-padding.
    """

    def __init__(self, sizes=DEFAULT_BUCKET_SIZES, mesh_size: int = 1):
        sizes = sorted(int(s) for s in sizes)
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket menu must be non-empty positive: {sizes}")
        if len(set(sizes)) != len(sizes):
            raise ValueError(f"bucket menu has duplicates: {sizes}")
        bad = [s for s in sizes if s % mesh_size]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} are not multiples of the mesh size "
                f"{mesh_size}; the states-axis sharding contract requires "
                "mesh-aligned batch shapes"
            )
        self.sizes = tuple(sizes)
        self.max_size = sizes[-1]

    def bucket_for(self, n_rows: int) -> int:
        """Smallest menu size that fits ``n_rows``."""
        for s in self.sizes:
            if n_rows <= s:
                return s
        raise RequestTooLarge(
            f"{n_rows} rows exceed the largest bucket {self.max_size}"
        )

    def shrink_bucket(self, n_rows: int, current: int) -> int | None:
        """Smallest menu size that fits ``n_rows`` and is strictly below the
        ``current`` batch shape — the compaction question ("is repacking the
        active set worth a smaller executable?"). None when no menu size
        improves on ``current`` (including ``n_rows`` above the menu)."""
        for s in self.sizes:
            if n_rows <= s:
                return s if s < current else None
        return None


def pad_states(
    x: np.ndarray, mesh, bucket: int | None = None
) -> tuple[np.ndarray, int]:
    """Pad the leading (states) axis to a mesh-size multiple.

    Candidate counts are data-dependent (e.g. the 387-row botnet set), so
    runners pad with copies of the last row before a mesh-sharded attack and
    trim every per-state result back to ``n_orig`` rows afterwards. Returns
    ``(x_padded, n_orig)``; a no-op without a mesh or when already aligned.

    With ``bucket``, pads to exactly ``bucket`` rows instead of the nearest
    mesh multiple — the serving microbatcher's fixed-shape dispatch mode
    (one compiled program per bucket size). ``bucket`` must be >= the row
    count and itself a mesh multiple, so the two contracts compose.
    """
    n = x.shape[0]
    if bucket is not None:
        if bucket < n:
            raise ValueError(f"bucket={bucket} smaller than n_states={n}")
        if mesh is not None and bucket % mesh.size != 0:
            raise ValueError(
                f"bucket={bucket} must be a multiple of the mesh size "
                f"{mesh.size} (serving bucket menus must be mesh-aligned)"
            )
        if bucket == n:
            return x, n
        pad = bucket - n
        return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)]), n
    if mesh is None or n % mesh.size == 0:
        return x, n
    pad = (-n) % mesh.size
    return np.concatenate([x, np.repeat(x[-1:], pad, axis=0)]), n
