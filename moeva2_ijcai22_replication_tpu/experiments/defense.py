"""Defense pipeline: augmentation + adversarial retraining artifact family.

Parity: ``/root/reference/src/experiments/lcld/01_train_robust.py:31-491``
and ``botnet/01_train_robust.py`` — the staged, artifact-memoized workflow
that produces every model/candidate artifact the attack experiments consume:

1.  min-max scaler from feature bounds ∪ data (floor/ceil envelope)
2.  base surrogate ``nn`` + AUROC gate
3.  top-k important mutable features (the reference uses SHAP DeepExplainer
    on a class-balanced subsample; here: gradient×input attribution on the
    same balanced subsample — the deep-net analog that runs as one jitted
    program on device)
4.  XOR-augmented dataset + ``features_augmented.csv`` /
    ``constraints_augmented.csv`` (reference CSV schema)
5.  augmented scaler + augmented surrogate ``nn_augmented``
6.  adversarial candidate filter (label-1, correctly classified,
    constraint-satisfying)
7.  MoEvA attack on train candidates → best successful adversarial per
    state → ``nn_moeva`` adversarial retraining
8.  targeted PGD attack → ``nn_gradient`` (LCLD; the botnet reference
    generates gradient adversarials but trains no gradient model)
9.  common candidate set: test points correctly classified by every
    defended model → ``x_candidates_common[_augmented].npy``

Every stage is keyed on its output artifact (load-if-exists), so a crashed
run resumes where it stopped — the reference's recovery model.
"""

from __future__ import annotations

import os

import numpy as np

from ..attacks.moeva import Moeva2
from ..attacks.objective import ObjectiveCalculator
from ..attacks.pgd import ConstrainedPGD, round_ints_toward_initial
from ..domains import augmentation
from ..models.io import Surrogate, load_classifier, save_classifier
from ..models.mlp import botnet_mlp, lcld_mlp
from ..models.scalers import from_sklearn_minmax
from ..models.train import auroc, fit_mlp
from ..utils.config import parse_config
from . import common

#: per-project pipeline knobs (reference: nb_important_features=5 at
#: lcld/01_train_robust.py:36 vs 19 at botnet/01_train_robust.py:36;
#: balanced subsample 5000/300 per class at :99 / :98; epochs/batch from the
#: model modules; the botnet reference trains no gradient-defended model and
#: skips the constraint filter on common candidates)
PROJECT_DEFAULTS = {
    "lcld": dict(
        model_fn=lcld_mlp, nb_important=5, balanced_n=5000, epochs=100,
        batch_size=512, augmented_suffix="", gradient_model=True,
        common_requires_constraints=True,
    ),
    "botnet": dict(
        model_fn=botnet_mlp, nb_important=19, balanced_n=300, epochs=3,
        batch_size=256, augmented_suffix="_19", gradient_model=False,
        common_requires_constraints=False,
    ),
}


def _memo_npy(path, fn):
    if os.path.exists(path):
        print(f"{path} exists loading...")
        return np.load(path)
    out = fn()
    np.save(path, out)
    return out


def _memo_model(path, fn) -> Surrogate:
    if os.path.exists(path):
        print(f"{path} exists loading...")
        return load_classifier(path)
    sur = fn()
    save_classifier(sur, path)  # format follows the path's suffix
    return sur


def make_trainer(model_fn, knobs: dict, seed: int):
    """Keras-fit-parity trainer: 10% stratified val split, ES patience 25
    (lcld/model.py:23-42) — shared by the defense and RQ4 pipelines."""

    def train(x_s, y) -> Surrogate:
        from sklearn.model_selection import train_test_split

        x_tr, x_val, y_tr, y_val = train_test_split(
            x_s, y, test_size=0.1, random_state=42, stratify=y
        )
        return fit_mlp(
            model_fn(), x_tr, y_tr, x_val, y_val,
            epochs=knobs["epochs"], batch_size=knobs["batch_size"],
            patience=25, seed=seed,
        ).surrogate

    return train


def proba1(sur: Surrogate, scaler, x: np.ndarray) -> np.ndarray:
    """P(class=1) under a (sklearn-)scaled forward pass."""
    return np.asarray(sur.predict_proba(scaler.transform(x)))[:, 1]


def moeva_attack(model, constraints, ml_scaler, config, x_cand) -> np.ndarray:
    """MoEvA over internally-computed candidates; pads the states axis to the
    mesh size (candidate counts are data-dependent) and trims the result."""
    mesh = common.build_mesh(config)
    x_run, n = common.pad_states(x_cand, mesh)
    result = Moeva2(
        classifier=model, constraints=constraints, ml_scaler=ml_scaler,
        norm=config["norm"], n_gen=config["budget"],
        n_pop=config["n_pop"], n_offsprings=config["n_offsprings"],
        seed=config["seed"], mesh=mesh,
        assoc_block=config.get("assoc_block") or None,
        max_states_per_call=config.get("max_states_per_call") or None,
    ).generate(x_run, 1)
    return result.x_ml[:n]


def fit_envelope_scaler(schema_df, x_all: np.ndarray):
    """sklearn MinMaxScaler over floor/ceil of feature bounds ∪ data
    (01_train_robust.py:55-65; 'dynamic' bounds resolve to the data)."""
    from sklearn.preprocessing import MinMaxScaler

    x_min = schema_df["min"].to_numpy(dtype=object).copy()
    x_max = schema_df["max"].to_numpy(dtype=object).copy()
    dyn_min = x_min == "dynamic"
    dyn_max = x_max == "dynamic"
    x_min[dyn_min] = x_all.min(0)[dyn_min]
    x_max[dyn_max] = x_all.max(0)[dyn_max]
    x_min = np.minimum(x_min.astype(float), x_all.min(0))
    x_max = np.maximum(x_max.astype(float), x_all.max(0))
    return MinMaxScaler().fit(
        np.stack([np.floor(x_min), np.ceil(x_max)])
    )


def importance_gradient_x_input(
    surrogate: Surrogate,
    scaler,
    x: np.ndarray,
    y: np.ndarray,
    mutable_mask: np.ndarray,
    k: int,
    balanced_n: int,
    seed: int = 42,
) -> np.ndarray:
    """Top-k important mutable features as (k, 2) [index, train-mean].

    Reference: SHAP DeepExplainer values for class 0, mean |value| per
    feature, on a RandomUnderSampler({0: n, 1: n}) subsample
    (01_train_robust.py:98-115). Equivalent here: |gradient×(x - background
    mean)| of the class-0 probability — DeepSHAP's single-reference linear
    approximation — over the same balanced subsample, one jitted batch.
    """
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    parts = []
    for cls in (0, 1):
        idx = np.flatnonzero(y == cls)
        take = min(balanced_n, len(idx))
        parts.append(rng.choice(idx, size=take, replace=False))
    sub = np.concatenate(parts)
    xs = np.asarray(scaler.transform(x[sub]))
    background = xs.mean(0)

    def p0(xrow):
        return surrogate.predict_proba(xrow[None, :])[0, 0]

    grads = jax.jit(jax.vmap(jax.grad(p0)))(jnp.asarray(xs))
    attr = np.mean(np.abs(np.asarray(grads) * (xs - background)), axis=0)

    mutable_idx = np.flatnonzero(mutable_mask)
    order = np.argsort(attr[mutable_mask])[::-1]
    top = mutable_idx[order][:k]
    return np.column_stack([top, x[:, top].mean(0)])


def augmented_schema_rows(schema_df, constraints_df, n_new: int):
    """Append the reference's augmented-feature rows to both CSV frames
    (01_train_robust.py:134-156)."""
    import pandas as pd

    feat_rows = pd.DataFrame(
        [
            {
                "feature": f"augmented_{i}",
                "type": "int",
                "mutable": True,
                "min": 0.0,
                "max": 1.0,
                "augmentation": True,
            }
            for i in range(n_new)
        ]
    )
    cons_rows = pd.DataFrame(
        [{"min": 0.0, "max": 1.0, "augmentation": True} for _ in range(n_new)]
    )
    if "augmentation" not in schema_df.columns:
        schema_df = schema_df.assign(augmentation=False)
    if "augmentation" not in constraints_df.columns:
        constraints_df = constraints_df.assign(augmentation=False)
    return (
        pd.concat([schema_df, feat_rows], ignore_index=True),
        pd.concat([constraints_df, cons_rows], ignore_index=True),
    )


def run(config: dict) -> dict:
    """Execute the defense pipeline; returns the artifact-path map."""
    import joblib
    import pandas as pd

    common.setup_jax_cache(config)

    project = config["project_name"]
    knobs = dict(PROJECT_DEFAULTS[project.split("_")[0]])
    knobs.update(config.get("defense", {}))
    threshold = config["misclassification_threshold"]
    data_dir = config["dirs"]["data"]
    models_dir = config["dirs"]["models"]
    os.makedirs(data_dir, exist_ok=True)
    os.makedirs(models_dir, exist_ok=True)
    suffix = knobs["augmented_suffix"]

    # ----- LOAD (01_train_robust.py:41-46)
    x_train = np.load(config["paths"]["x_train"])
    x_test = np.load(config["paths"]["x_test"])
    y_train = np.load(config["paths"]["y_train"])
    y_test = np.load(config["paths"]["y_test"])
    schema_df = pd.read_csv(config["paths"]["features"])
    constraints_df = pd.read_csv(config["paths"]["constraints"])
    train = make_trainer(knobs["model_fn"], knobs, config["seed"])

    # ----- SCALER (:50-66)
    scaler_path = f"{models_dir}/scaler.joblib"
    if os.path.exists(scaler_path):
        scaler = joblib.load(scaler_path)
    else:
        scaler = fit_envelope_scaler(
            schema_df, np.concatenate([x_train, x_test])
        )
        joblib.dump(scaler, scaler_path)

    # ----- BASE MODEL + AUROC (:70-90)
    model = _memo_model(
        f"{models_dir}/nn.msgpack",
        lambda: train(scaler.transform(x_train), y_train),
    )
    y_proba = proba1(model, scaler, x_test)
    y_pred = (y_proba >= threshold).astype(int)
    print(f"AUROC: {auroc(y_proba, y_test)}")

    # ----- IMPORTANT FEATURES (:94-116)
    important_features = _memo_npy(
        f"{data_dir}/important_features{suffix}.npy",
        lambda: importance_gradient_x_input(
            model, scaler, x_train, y_train,
            schema_df["mutable"].to_numpy(dtype=bool),
            knobs["nb_important"], knobs["balanced_n"],
        ),
    )

    # ----- AUGMENT DATASET (:120-160)
    feats_aug_path = f"{data_dir}/features_augmented{suffix}.csv"
    cons_aug_path = f"{data_dir}/constraints_augmented{suffix}.csv"
    x_train_augmented = _memo_npy(
        f"{data_dir}/x_train_augmented.npy",
        lambda: np.asarray(augmentation.augment(x_train, important_features)),
    )
    x_test_augmented = _memo_npy(
        f"{data_dir}/x_test_augmented.npy",
        lambda: np.asarray(augmentation.augment(x_test, important_features)),
    )
    n_new = x_train_augmented.shape[1] - x_train.shape[1]
    if not os.path.exists(feats_aug_path):
        feats_aug, cons_aug = augmented_schema_rows(
            schema_df, constraints_df, n_new
        )
        feats_aug.to_csv(feats_aug_path, index=False)
        cons_aug.to_csv(cons_aug_path, index=False)

    # ----- AUGMENTED SCALER (:164-179)
    scaler_aug_path = f"{models_dir}/scaler_augmented{suffix}.joblib"
    if os.path.exists(scaler_aug_path):
        scaler_augmented = joblib.load(scaler_aug_path)
    else:
        from sklearn.preprocessing import MinMaxScaler

        scaler_augmented = MinMaxScaler().fit(
            np.stack(
                [
                    np.concatenate([scaler.data_min_, np.zeros(n_new)]),
                    np.concatenate([scaler.data_max_, np.ones(n_new)]),
                ]
            )
        )
        joblib.dump(scaler_augmented, scaler_aug_path)

    # ----- AUGMENTED MODEL (:183-205)
    model_augmented = _memo_model(
        f"{models_dir}/nn_augmented{suffix}.msgpack",
        lambda: train(scaler_augmented.transform(x_train_augmented), y_train),
    )
    p_augmented = proba1(model_augmented, scaler_augmented, x_test_augmented)
    y_pred_augmented = (p_augmented >= threshold).astype(int)
    print(f"AUROC: {auroc(p_augmented, y_test)}")

    # ----- ADVERSARIAL CANDIDATES (:208-224)
    constraints = common.load_constraints(config)
    correct = (
        proba1(model, scaler, x_train) >= threshold
    ).astype(int) == y_train
    cand_mask = (y_train == 1) & correct
    x_cand = x_train[cand_mask]
    satisfied = (
        np.asarray(constraints.evaluate(x_cand)).max(-1) <= 0
    )
    x_cand = x_cand[satisfied]
    print(f"{x_cand.shape} candidates.")

    ml_scaler = from_sklearn_minmax(scaler)
    calc = ObjectiveCalculator(
        classifier=model,
        constraints=constraints,
        thresholds={"f1": threshold, "f2": config["eps"]},
        min_max_scaler=ml_scaler,
        ml_scaler=ml_scaler,
        minimize_class=1,
        norm=config["norm"],
    )

    # ----- MOEVA ADVERSARIALS + RETRAINING (:230-293, :411-437)
    x_train_moeva = _memo_npy(
        f"{data_dir}/x_train_moeva.npy",
        lambda: moeva_attack(model, constraints, ml_scaler, config, x_cand),
    )
    adv_moeva_path = f"{data_dir}/x_train_adv_moeva.npy"
    adv_moeva_index_path = f"{data_dir}/x_train_adv_moeva_index.npy"
    if os.path.exists(adv_moeva_path):
        x_adv_moeva = np.load(adv_moeva_path)
        adv_moeva_index = np.load(adv_moeva_index_path)
    else:
        x_adv_moeva, adv_moeva_index = calc.get_successful_attacks(
            x_cand, x_train_moeva, preferred_metrics="misclassification",
            order="asc", max_inputs=1, return_index_success=True,
        )
        print(f"Success rate: {x_adv_moeva.shape[0] / x_train_moeva.shape[0]}")
        np.save(adv_moeva_path, x_adv_moeva)
        np.save(adv_moeva_index_path, adv_moeva_index)

    # ----- GRADIENT ADVERSARIALS (:297-397)
    adv_grad_path = f"{data_dir}/x_train_adv_gradient.npy"
    adv_grad_index_path = f"{data_dir}/x_train_adv_gradient_index.npy"
    if os.path.exists(adv_grad_path):
        x_adv_gradient = np.load(adv_grad_path)
        adv_gradient_index = np.load(adv_grad_index_path)
    else:
        pgd = ConstrainedPGD(
            classifier=model, constraints=constraints, scaler=ml_scaler,
            eps=config["eps"] - 0.000001, eps_step=0.1,
            max_iter=int(config["budget"]), norm=config["norm"],
            loss_evaluation=config.get("loss_evaluation", "flip"),
            constraints_optim=config.get("constraints_optim", "sum"),
            # LCLD attacks toward class 0 (targeted y=[1,0] one-hots,
            # :358-364); botnet runs the untargeted variant (:361-366).
            targeted=knobs["gradient_model"],
            seed=config["seed"],
            mesh=common.build_mesh(config),
        )
        # candidate counts are data-dependent: pad to a mesh multiple, trim
        x_run, n_orig = common.pad_states(np.asarray(x_cand), pgd.mesh)
        y_att = np.zeros(x_run.shape[0], dtype=np.int64)
        x_att = np.asarray(
            ml_scaler.inverse(pgd.generate(ml_scaler.transform(x_run), y_att))
        )[:n_orig]
        x_att = round_ints_toward_initial(
            x_att, x_cand, constraints.get_feature_type()
        )
        x_adv_gradient, adv_gradient_index = calc.get_successful_attacks(
            x_cand, x_att[:, None, :], preferred_metrics="misclassification",
            order="asc", max_inputs=1, return_index_success=True,
        )
        print(f"Success rate: {x_adv_gradient.shape[0] / x_att.shape[0]}")
        np.save(adv_grad_path, x_adv_gradient)
        np.save(adv_grad_index_path, adv_gradient_index)

    # ----- COMMON SUCCESS MASKS (:401-409) — LCLD only: the LCLD reference
    # retrains each model on adversarials whose initial state BOTH attacks
    # defeated; the botnet reference retrains on all MoEvA successes
    # (botnet/01_train_robust.py:275).
    if knobs["gradient_model"]:
        both = adv_moeva_index & adv_gradient_index
        if not both.any():
            # On the bootstrapped family the gradient attack can come back
            # EMPTY (the paper's own finding: constrained PGD rarely beats
            # LCLD validity) — the strict LCLD intersection then retrains on
            # zero adversarials and silently ships base weights as the
            # "defended" models (observed round 5: nn_moeva was md5-equal to
            # nn.msgpack). Fall back to the botnet reference's semantics
            # (retrain on every MoEvA success, botnet/01_train_robust.py:275)
            # so nn_moeva is a real defense artifact; nn_gradient still
            # honestly degenerates to base when there are no gradient
            # adversarials at all.
            print(
                "WARNING: both-attacks intersection is empty; retraining "
                "nn_moeva on all MoEvA successes (botnet semantics)"
            )
            moeva_mask = np.ones(len(x_adv_moeva), dtype=bool)
            # the botnet-semantics fallback is for nn_moeva only: nn_gradient
            # keeps the LCLD intersection semantics and retrains on zero
            # adversarials (honestly degenerating to base weights) — both
            # when the gradient attack found nothing (x_adv_gradient is
            # empty) and when its successes are merely disjoint from MoEvA's
            gradient_mask = np.zeros(len(x_adv_gradient), dtype=bool)
        else:
            moeva_mask = both[adv_moeva_index]
            gradient_mask = both[adv_gradient_index]
    else:
        moeva_mask = np.ones(len(x_adv_moeva), dtype=bool)
        gradient_mask = np.ones(len(x_adv_gradient), dtype=bool)

    # ----- ADVERSARIAL RETRAINING (:411-466)
    model_moeva = _memo_model(
        f"{models_dir}/nn_moeva.msgpack",
        lambda: train(
            scaler.transform(
                np.concatenate([x_train, x_adv_moeva[moeva_mask]])
            ),
            np.concatenate([y_train, np.ones(moeva_mask.sum(), dtype=y_train.dtype)]),
        ),
    )
    p_adv_moeva = proba1(model_moeva, scaler, x_test)
    y_pred_adv_moeva = (p_adv_moeva >= threshold).astype(int)
    print(f"AUROC: {auroc(p_adv_moeva, y_test)}")

    y_pred_adv_gradient = None
    if knobs["gradient_model"]:
        model_gradient = _memo_model(
            f"{models_dir}/nn_gradient.msgpack",
            lambda: train(
                scaler.transform(
                    np.concatenate([x_train, x_adv_gradient[gradient_mask]])
                ),
                np.concatenate(
                    [y_train, np.ones(gradient_mask.sum(), dtype=y_train.dtype)]
                ),
            ),
        )
        p_adv_gradient = proba1(model_gradient, scaler, x_test)
        y_pred_adv_gradient = (p_adv_gradient >= threshold).astype(int)
        print(f"AUROC: {auroc(p_adv_gradient, y_test)}")

    # ----- COMMON CANDIDATE SET (:468-491)
    cand_path = f"{data_dir}/x_candidates_common.npy"
    cand_aug_path = f"{data_dir}/x_candidates_common_augmented.npy"
    if not (os.path.exists(cand_path) and os.path.exists(cand_aug_path)):
        index = (
            (y_test == 1)
            & (y_test == y_pred)
            & (y_test == y_pred_augmented)
            & (y_test == y_pred_adv_moeva)
        )
        if knobs["common_requires_constraints"]:
            index &= np.asarray(constraints.evaluate(x_test)).max(-1) <= 0
        if y_pred_adv_gradient is not None:
            index &= y_pred == y_pred_adv_gradient
        np.save(cand_path, x_test[index])
        np.save(cand_aug_path, x_test_augmented[index])
    x_candidates = np.load(cand_path)
    print(f"Candidates: {x_candidates.shape}.")

    return {
        "scaler": scaler_path,
        "nn": f"{models_dir}/nn.msgpack",
        "nn_augmented": f"{models_dir}/nn_augmented{suffix}.msgpack",
        "nn_moeva": f"{models_dir}/nn_moeva.msgpack",
        "nn_gradient": (
            f"{models_dir}/nn_gradient.msgpack" if knobs["gradient_model"] else None
        ),
        "important_features": f"{data_dir}/important_features{suffix}.npy",
        "x_candidates_common": cand_path,
        "x_candidates_common_augmented": cand_aug_path,
    }


if __name__ == "__main__":
    run(parse_config())
