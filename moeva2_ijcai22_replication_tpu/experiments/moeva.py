"""MoEvA2 experiment runner.

Parity: ``/root/reference/src/experiments/united/04_moeva.py:27-147`` —
config-hash skip, constraint check, timed attack, result artifacts
(populations npy, optional history), augmented-feature reconstruction,
per-ε success rates, and ``metrics_moeva_{hash}.json``. The attack itself
runs as one jitted program over all initial states (optionally sharded over
a device mesh via ``system.mesh_devices``) instead of a joblib process pool.

Grid-scale execution (docs/DESIGN.md §"Grid execution pipeline"): the
``Moeva2`` engine is cached across grid points keyed by its static config —
seed / budget / checkpoint path are host-side dispatch knobs reassigned per
point, so a budget sweep shares one engine (and its compiled ``init``
program; each distinct budget adds one ``segment`` trace) — and, when a
:class:`..experiments.pipeline.GridPipeline` is passed, per-ε evaluation and
artifact serialization run on the grid's background writer while the device
starts the next point's attack. Mid-run checkpointing happens inside
``generate`` on the launching thread, before finalize is queued, so crash
recovery semantics are unchanged.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..attacks.moeva import Moeva2
from ..attacks.objective import O_COLUMNS, ObjectiveCalculator
from ..attacks.sharding import describe_mesh
from ..domains import augmentation
from ..observability import (
    Trace,
    get_gap_tracker,
    get_ledger,
    get_mesh_capture,
    quality_block,
    recorder_for,
    telemetry_block,
    trim_quality,
)
from ..utils.config import get_dict_hash, parse_config, save_config
from ..utils.in_out import json_to_file, save_to_file
from ..utils.observability import PhaseTimer, maybe_profile
from ..utils.streaming import stream_for
from . import common


def _cached_engine(config, surrogate, constraints, scaler):
    """Engine instance shared across grid points with the same static
    config. ``n_gen``/``seed``/checkpointing only steer host-side dispatch
    (the per-segment scan length is a jit static argument), so they are
    per-point attributes, not key material."""
    mesh_devices = int(config.get("system", {}).get("mesh_devices", 0) or 0)
    # field names travel with the key so a cache miss can be explained
    # field-by-field (the recompile-cause view on /healthz)
    fields = (
        "engine", "surrogate", "constraints", "scaler", "norm", "n_pop",
        "n_offsprings", "init", "init_eps", "init_ratio", "archive_size",
        "assoc_block", "max_states_per_call", "save_history", "mesh_devices",
    )
    key = (
        "moeva",
        id(surrogate),
        id(constraints),
        id(scaler),
        str(config["norm"]),
        config["n_pop"],
        config["n_offsprings"],
        config.get("init", "tile"),
        config.get("init_eps", 0.1),
        config.get("init_ratio", 0.5),
        config.get("archive_size", 0),
        config.get("assoc_block") or None,
        config.get("max_states_per_call") or None,
        config.get("save_history") or None,
        mesh_devices,
    )

    def build():
        return Moeva2(
            classifier=surrogate,
            constraints=constraints,
            ml_scaler=scaler,
            norm=config["norm"],
            n_gen=config["budget"],
            n_pop=config["n_pop"],
            n_offsprings=config["n_offsprings"],
            init=config.get("init", "tile"),
            init_eps=config.get("init_eps", 0.1),
            init_ratio=config.get("init_ratio", 0.5),
            archive_size=config.get("archive_size", 0),
            # association formulation (None = one-shot einsum; an int =
            # blocked scan with that direction-block size, bit-identical)
            assoc_block=config.get("assoc_block") or None,
            max_states_per_call=config.get("max_states_per_call") or None,
            save_history=config.get("save_history") or None,
            mesh=common.build_mesh(config),
        )

    return common.ENGINES.get(key, build, fields=fields)


def run(config: dict, pipeline=None):
    """Execute one MoEvA2 experiment; returns the metrics dict, or None when
    the config hash already has results (skip-if-done) — or when ``pipeline``
    is given, in which case evaluation/serialization are deferred to the
    grid's background writer (drained by the grid runner before it returns)."""
    common.setup_jax_cache(config)
    out_dir = config["dirs"]["results"]
    config_hash = get_dict_hash(config)
    mid_fix = f"{config['attack_name']}"
    metrics_path = common.metrics_path_for(config, mid_fix)
    if common.should_skip(config, mid_fix, pipeline):
        if pipeline is not None:
            pipeline.point(mid_fix, config_hash, None, skipped=True)
        return None

    os.makedirs(out_dir, exist_ok=True)
    print(config)
    # run-scoped trace: spans on when the config sets ``system.trace_log``
    # (JSONL sink shared by every run in the process); otherwise the trace
    # is None and the timers/engine emit nothing beyond cheap counters
    recorder = recorder_for(config)
    trace = (
        Trace(recorder, trace_id=f"run-{config_hash[:12]}", name=mid_fix)
        if recorder.spans_enabled
        else None
    )
    timer = PhaseTimer(trace=trace)
    # cost-ledger window: the metrics' telemetry.cost reports THIS run's
    # executables/compiles, not the process lifetime (shared-engine grids);
    # the mesh-balance and dispatch-gap marks scope telemetry.mesh and
    # telemetry.gaps the same way
    ledger_mark = get_ledger().mark()
    mesh_mark = get_mesh_capture().mark()
    gaps_mark = get_gap_tracker().mark()

    # ----- Load and create necessary objects (04_moeva.py:41-60)
    with timer.phase("setup"):
        constraints = common.load_constraints(config)
        x_initial_states = common.load_candidates(config)
        scaler = common.load_scaler(config)
        surrogate = common.load_surrogate(config)

        # ----- Check constraints (04_moeva.py:64)
        constraints.check_constraints_error(x_initial_states)

        moeva = _cached_engine(config, surrogate, constraints, scaler)
        # per-point run identity: host-side dispatch knobs on the cached engine
        moeva.n_gen = config["budget"]
        moeva.seed = config["seed"]
        # success-gated early exit (0 = strict/parity mode): a host-side
        # dispatch knob — compaction reuses the shared bucket-menu
        # executables, so it is not engine-cache key material
        moeva.early_stop_check_every = int(
            config.get("early_stop_check_every", 0) or 0
        )
        moeva.early_stop_threshold = float(
            config.get(
                "early_stop_threshold",
                config.get("misclassification_threshold", 0.5),
            )
        )
        moeva.early_stop_eps = float(config.get("early_stop_eps", np.inf))
        # reset like every other host-side knob: a serving layer sharing
        # this cached engine may have pointed it at its own bucket menu
        buckets = config.get("compaction_buckets")
        moeva.compaction_buckets = tuple(buckets) if buckets else None
        # convergence-quality capture: on by default (zero extra device
        # work without gates — the final sample is numpy on fetched
        # arrays); ``quality_every`` adds interior curve points by
        # splitting the scan at a semantics-free cadence
        moeva.record_quality = bool(config.get("record_quality", True))
        moeva.quality_every = int(config.get("quality_every", 0) or 0)
        # per-point observability handle (reset like seed/n_gen: a cached
        # engine may carry the previous point's — or a serving batch's — trace)
        moeva.trace = trace
        # crash recovery: a rerun of this config hash resumes mid-attack
        # from the last ``checkpoint_every``-generation boundary instead of
        # generation 0 (config-hash skip only covers *completed* runs)
        moeva.checkpoint_every = int(config.get("checkpoint_every", 0) or 0)
        moeva.checkpoint_path = f"{out_dir}/checkpoint_{mid_fix}_{config_hash}.npz"

    start_time = time.time()
    with timer.attack(moeva), maybe_profile(
        config.get("system", {}).get("profile_dir")
    ):
        # candidate counts are data-dependent: pad to a mesh multiple, trim
        x_run, n_orig = common.pad_states(x_initial_states, moeva.mesh)
        result = moeva.generate(x_run, 1)
    consumed_time = time.time() - start_time

    x_attacks = result.x_ml[:n_orig]
    if config.get("reconstruction"):
        # Strip the stale augmented columns and recompute them from the
        # attacked base features (04_moeva.py:97-104).
        important = constraints.important_features
        n_pairs = augmentation.n_pairs(important)
        x_attacks = np.asarray(
            augmentation.augment(x_attacks[..., :-n_pairs], important)
        )

    def finalize():
        # ----- Persist populations ((S, P, D) ndarray — results_to_numpy_results)
        with timer.phase("write"):
            save_to_file(
                x_attacks, f"{out_dir}/x_attacks_{mid_fix}_{config_hash}.npy"
            )
            if config.get("save_history") and len(result.history) > 1:
                # (n_gen-1, S, n_off, C) per-generation objective history
                np.save(
                    f"{out_dir}/x_history_{mid_fix}_{config_hash}.npy",
                    np.stack(result.history[1:])[:, :n_orig],
                )

        # ----- Success rates per ε (04_moeva.py:112-131)
        with timer.phase("evaluate"):
            eval_constraints = common.evaluation_constraints(config, constraints)
            calc = ObjectiveCalculator(
                classifier=surrogate,
                constraints=eval_constraints,
                thresholds={
                    "f1": config["misclassification_threshold"],
                    "f2": 0.0,
                },
                min_max_scaler=scaler,
                ml_scaler=scaler,
                minimize_class=1,
                norm=config["norm"],
            )
            # [cv, f1, f2] is ε-independent: evaluate once, re-threshold per ε
            vals = calc.objectives(x_initial_states, x_attacks)
            objective_lists = []
            for eps in config["eps_list"]:
                calc.thresholds = {
                    "f1": config["misclassification_threshold"],
                    "f2": eps,
                }
                df = calc.success_rate_3d_df(x_initial_states, x_attacks, vals)
                objective_lists.append(df.to_dict(orient="records")[0])

        with timer.phase("write"):
            # Comet-equivalent event stream (src/utils/comet.py parity; off by
            # default, enabled by config `streaming`).
            with stream_for(config, mid_fix, config_hash) as stream:
                stream.log_parameters(config)
                stream.log_metric("time", consumed_time)
                for eps, objectives in zip(config["eps_list"], objective_lists):
                    for k, v in objectives.items():
                        stream.log_metric(f"eps{eps}_{k}", v)

        # metrics assembled AFTER the write phase closes so its 'timings'
        # include the artifact-write spans; the metrics JSON itself still
        # lands last, preserving the "metrics exists => siblings exist"
        # invariant should_skip relies on
        metrics = {
            "objectives_list": objective_lists,
            "time": consumed_time,
            # the reference-schema "time" field spans the whole attack call;
            # on a cold engine that includes trace + XLA compile (or a
            # persistent-cache load), so the flag travels with the number
            "includes_compile": "attack_compile" in timer.spans,
            # RNG-affecting execution mode of this number (VERDICT r5 item 8):
            # the chunk size folds per-chunk PRNG keys, the mesh shape sets
            # the padded batch shape
            "execution": {
                "max_states_per_call": moeva.effective_states_chunk(),
                "mesh": describe_mesh(moeva.mesh),
                # early-exit mode of this number: the knob (0 = strict, the
                # bit-identical default) and the generation steps actually
                # executed vs the static budget (summed across state chunks)
                "early_stop_check_every": moeva.early_stop_check_every,
                "gens_executed": int(result.gens_executed),
            },
            "timings": timer.spans,
            "counters": timer.counters,
            # shared record schema: span totals, engine progress events,
            # the device-memory watermark, and the convergence-quality
            # curve travel with the number. ``final`` records the post-hoc
            # f64 judgement (the last ε's o-rates) next to — never instead
            # of — the engine-judged curve.
            "telemetry": telemetry_block(
                timer=timer,
                trace=trace,
                device=moeva.mesh.devices.flat[0]
                if moeva.mesh is not None
                else None,
                ledger_since=ledger_mark,
                gaps_since=gaps_mark,
                # multi-device runs carry telemetry.mesh (per-device
                # roofline + balance + collectives), window-scoped
                mesh=describe_mesh(moeva.mesh),
                mesh_since=mesh_mark,
                quality=quality_block(
                    # drop the mesh-pad duplicate rows (pad_states above)
                    # exactly like x_attacks — padded rates would drift
                    # with mesh size
                    trim_quality(result.quality, n_orig),
                    final={
                        "judged": "post_hoc_f64",
                        "eps": config["eps_list"][-1],
                        "o_rates": [
                            objective_lists[-1].get(k) for k in O_COLUMNS
                        ],
                    }
                    if objective_lists
                    else None,
                ),
            ),
            "config": config,
            "config_hash": config_hash,
        }
        json_to_file(metrics, metrics_path)
        save_config(config, f"{out_dir}/config_{mid_fix}_")
        return metrics

    if pipeline is not None:
        pipeline.point(mid_fix, config_hash, timer)
        pipeline.submit(mid_fix, metrics_path, finalize)
        return None
    return finalize()


if __name__ == "__main__":
    run(parse_config())
