"""PGD / AutoPGD / SAT experiment runner.

Parity: ``/root/reference/src/experiments/united/01_pgd_united.py:29-222`` —
config-hash skip, ε-halving when a SAT pass follows, PGD vs AutoPGD selection
by ``loss_evaluation``, scaled-space attack with mutable-feature masking,
directional integer rounding toward the original, SAT repair with the
gradient output as hot start, reconstruction, success rates, and
``metrics_pgd_{loss}_{hash}.json`` + success-rate CSV.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..attacks.objective import ObjectiveCalculator
from ..attacks.pgd import AutoPGD, ConstrainedPGD, round_ints_toward_initial
from ..attacks.sat import SatAttack
from ..domains import augmentation
from ..utils.config import get_dict_hash, parse_config, save_config
from ..utils.in_out import json_to_file
from ..utils.observability import PhaseTimer, maybe_profile
from ..utils.streaming import stream_for
from . import common


def run(config: dict):
    """Execute one gradient-attack experiment; returns the metrics dict, or
    None when the config hash already has results."""
    common.setup_jax_cache(config)
    out_dir = config["dirs"]["results"]
    config_hash = get_dict_hash(config)
    mid_fix = f"{config['attack_name']}_{config['loss_evaluation']}"
    metrics_path = common.metrics_path_for(config, mid_fix)
    if common.should_skip(config, mid_fix):
        return None

    os.makedirs(out_dir, exist_ok=True)
    print(config)
    timer = PhaseTimer()
    apply_sat = "sat" in config["loss_evaluation"]

    with timer.phase("setup"):
        constraints = common.load_constraints(config)
        x_initial = common.load_candidates(config)
        scaler = common.load_scaler(config)
        surrogate = common.load_surrogate(config)
        constraints.check_constraints_error(x_initial)

    start_time = time.time()
    # Use only half ε if SAT runs after (01_pgd_united.py:97).
    per_attack_eps = config["eps"] / 2 if apply_sat else config["eps"]

    cls = AutoPGD if "autopgd" in config["loss_evaluation"] else ConstrainedPGD
    kwargs = dict(
        classifier=surrogate,
        constraints=constraints,
        scaler=scaler,
        eps=per_attack_eps - 0.000001,
        max_iter=int(config["budget"]),
        norm=config["norm"],
        loss_evaluation=config["loss_evaluation"],
        constraints_optim=config.get("constraints_optim", "sum"),
        seed=config["seed"],
        record_loss=config.get("save_history") or None,
        record_grad_norm=bool(config.get("save_grad_norm")),
        mesh=common.build_mesh(config),
    )
    if cls is AutoPGD:
        # AutoPGD defaults (01_pgd_united.py:99-111)
        kwargs.update(
            eps_step=per_attack_eps / 3,
            num_random_init=config.get("nb_random", 1),
        )
    else:
        kwargs.update(
            eps_step=0.1,
            num_random_init=config.get("nb_random", 0),
        )
    attack = cls(**kwargs)

    with timer.phase("attack"), maybe_profile(
        config.get("system", {}).get("profile_dir")
    ):
        x_scaled = np.asarray(scaler.transform(x_initial))
        # ART infers labels from the classifier's own predictions when no y
        # is given (the reference calls generate(x) label-free).
        y = np.asarray(surrogate.predict_proba(x_scaled)).argmax(-1)
        # candidate counts are data-dependent: pad to a mesh multiple, trim
        x_run, n_orig = common.pad_states(x_scaled, attack.mesh)
        y_run, _ = common.pad_states(y, attack.mesh)
        x_adv_scaled = attack.generate(x_run, y_run)[:n_orig]
        if attack.loss_history is not None:
            attack.loss_history = attack.loss_history[:n_orig]
        x_attacks = np.asarray(scaler.inverse(x_adv_scaled))

        # Directional integer rounding (01_pgd_united.py:130-137).
        x_attacks = round_ints_toward_initial(
            x_attacks, x_initial, constraints.get_feature_type()
        )

        if apply_sat:
            sat = SatAttack(
                constraints,
                common.get_sat_builder(config["project_name"], constraints),
                scaler,
                per_attack_eps,
                np.inf,
                n_sample=1,
                n_jobs=config.get("system", {}).get("n_jobs", 1),
                # iterative denominator-grid refinement (no-op for fully
                # linear domains); 2 rounds ~ box/64 resolution
                refine_rounds=int(config.get("sat_refine_rounds", 2)),
            )
            x_attacks = sat.generate(x_initial, x_attacks)[:, 0, :]

    if config.get("reconstruction"):
        important = constraints.important_features
        n_pairs = augmentation.n_pairs(important)
        x_attacks = np.asarray(
            augmentation.augment(x_attacks[..., :-n_pairs], important)
        )
    consumed_time = time.time() - start_time

    if x_attacks.ndim == 2:
        x_attacks = x_attacks[:, np.newaxis, :]

    with timer.phase("evaluate"):
        eval_constraints = common.evaluation_constraints(config, constraints)
        calc = ObjectiveCalculator(
            classifier=surrogate,
            constraints=eval_constraints,
            thresholds={
                "f1": config["misclassification_threshold"],
                "f2": config["eps"],
            },
            min_max_scaler=scaler,
            ml_scaler=scaler,
            minimize_class=1,
            norm=config["norm"],
        )
        success_rate_df = calc.success_rate_3d_df(x_initial, x_attacks)
    print(success_rate_df)

    np.save(f"{out_dir}/x_attacks_{mid_fix}_{config_hash}.npy", x_attacks)
    if config.get("save_history") and attack.loss_history is not None:
        # (N, max_iter, 1, C) loss-component curves, the reference's saved
        # layout (01_pgd_united.py:196-199; C = 3 for "reduced", 3+K "full").
        np.save(
            f"{out_dir}/x_history_{config_hash}.npy",
            attack.loss_history[:, :, np.newaxis, :],
        )

    metrics = {
        "objectives": success_rate_df.to_dict(orient="records")[0],
        "time": consumed_time,
        "timings": timer.spans,
        "config": config,
        "config_hash": config_hash,
    }
    # Comet-equivalent event stream: run params, final rates, and (when loss
    # history was recorded) the per-iteration loss/grad-norm curves the
    # reference pushed to Comet from inside the loop
    # (pgd/classifier.py:183-217, atk.py:201-226).
    with stream_for(config, mid_fix, config_hash) as stream:
        stream.log_parameters(config)
        stream.log_metric("time", consumed_time)
        for k, v in metrics["objectives"].items():
            stream.log_metric(k, v)
        if attack.loss_history is not None:
            mean_curves = attack.loss_history.mean(axis=0)  # (max_iter, C)
            names = attack.hist_column_names()
            scalar = {"loss", "loss_class", "cons_sum", "grad_norm"}
            for j, name in enumerate(names):
                if name in scalar:  # skip the per-constraint g1..gK columns
                    stream.log_series(f"mean_{name}", mean_curves[:, j])
    success_rate_df.to_csv(
        f"{out_dir}/success_rate_{mid_fix}_{config_hash}.csv", index=False
    )
    json_to_file(metrics, metrics_path)
    save_config(config, f"{out_dir}/config_{mid_fix}_")
    return metrics


if __name__ == "__main__":
    run(parse_config())
