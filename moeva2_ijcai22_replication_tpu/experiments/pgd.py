"""PGD / AutoPGD / SAT experiment runner.

Parity: ``/root/reference/src/experiments/united/01_pgd_united.py:29-222`` —
config-hash skip, ε-halving when a SAT pass follows, PGD vs AutoPGD selection
by ``loss_evaluation``, scaled-space attack with mutable-feature masking,
directional integer rounding toward the original, SAT repair with the
gradient output as hot start, reconstruction, success rates, and
``metrics_pgd_{loss}_{hash}.json`` + success-rate CSV.

Grid-scale execution (docs/DESIGN.md §"Grid execution pipeline"): the attack
engine is cached across grid points keyed by its *static* config — ε and
ε-step are runtime arguments of the compiled program, so an ε sweep at a
fixed loss strategy dispatches one executable — and, when a
:class:`..experiments.pipeline.GridPipeline` is passed, evaluation and all
artifact serialization run on the grid's background writer while the device
starts the next point's attack. Device math is unaffected: pipelining only
reorders host work, so outputs for a fixed config stay bit-identical.
"""

from __future__ import annotations

import os
import time

import numpy as np

from ..attacks.objective import O_COLUMNS, ObjectiveCalculator
from ..attacks.pgd import AutoPGD, ConstrainedPGD, round_ints_toward_initial
from ..attacks.sat import SatAttack
from ..attacks.sharding import describe_mesh
from ..domains import augmentation
from ..observability import (
    Trace,
    get_gap_tracker,
    get_ledger,
    get_mesh_capture,
    quality_block,
    recorder_for,
    telemetry_block,
)
from ..utils.config import get_dict_hash, parse_config, save_config
from ..utils.in_out import json_to_file
from ..utils.observability import PhaseTimer, maybe_profile
from ..utils.streaming import stream_for
from . import common


def _cached_attack(config, surrogate, constraints, scaler):
    """Engine instance shared across grid points with the same static
    config. ε/ε-step/seed — and, for plain PGD without history, the budget —
    are per-point runtime values (`generate` args / host-side attribute), so
    they are deliberately NOT in the key."""
    cls = AutoPGD if "autopgd" in config["loss_evaluation"] else ConstrainedPGD
    num_random_init = config.get("nb_random", 1 if cls is AutoPGD else 0)
    record_loss = config.get("save_history") or None
    record_grad_norm = bool(config.get("save_grad_norm"))
    mesh_devices = int(config.get("system", {}).get("mesh_devices", 0) or 0)
    # AutoPGD / history programs bake the budget (see _runtime_max_iter):
    # those get one engine per budget; plain PGD shares across budgets
    budget_is_static = cls is AutoPGD or bool(record_loss)
    # field names travel with the key so a cache miss can be explained
    # field-by-field (the recompile-cause view on /healthz)
    fields = (
        "engine", "surrogate", "constraints", "scaler", "budget", "norm",
        "loss_evaluation", "constraints_optim", "num_random_init",
        "record_loss", "record_grad_norm", "mesh_devices",
    )
    key = (
        cls.__name__,
        id(surrogate),
        id(constraints),
        id(scaler),
        int(config["budget"]) if budget_is_static else None,
        str(config["norm"]),
        config["loss_evaluation"],
        config.get("constraints_optim", "sum"),
        num_random_init,
        record_loss,
        record_grad_norm,
        mesh_devices,
    )

    def build():
        return cls(
            classifier=surrogate,
            constraints=constraints,
            scaler=scaler,
            max_iter=int(config["budget"]),
            norm=config["norm"],
            loss_evaluation=config["loss_evaluation"],
            constraints_optim=config.get("constraints_optim", "sum"),
            num_random_init=num_random_init,
            record_loss=record_loss,
            record_grad_norm=record_grad_norm,
            mesh=common.build_mesh(config),
        )

    return common.ENGINES.get(key, build, fields=fields)


def run(config: dict, pipeline=None):
    """Execute one gradient-attack experiment; returns the metrics dict, or
    None when the config hash already has results — or when ``pipeline`` is
    given, in which case evaluation/serialization are deferred to the grid's
    background writer (drained by the grid runner before it returns)."""
    common.setup_jax_cache(config)
    out_dir = config["dirs"]["results"]
    config_hash = get_dict_hash(config)
    mid_fix = f"{config['attack_name']}_{config['loss_evaluation']}"
    metrics_path = common.metrics_path_for(config, mid_fix)
    if common.should_skip(config, mid_fix, pipeline):
        if pipeline is not None:
            pipeline.point(mid_fix, config_hash, None, skipped=True)
        return None

    os.makedirs(out_dir, exist_ok=True)
    print(config)
    # run-scoped trace (spans on under ``system.trace_log``, see moeva.py)
    recorder = recorder_for(config)
    trace = (
        Trace(recorder, trace_id=f"run-{config_hash[:12]}", name=mid_fix)
        if recorder.spans_enabled
        else None
    )
    timer = PhaseTimer(trace=trace)
    # cost-ledger window: the metrics' telemetry.cost reports THIS run's
    # executables/compiles, not the process lifetime (shared-engine grids);
    # the mesh-balance and dispatch-gap marks scope telemetry.mesh and
    # telemetry.gaps the same way
    ledger_mark = get_ledger().mark()
    mesh_mark = get_mesh_capture().mark()
    gaps_mark = get_gap_tracker().mark()
    apply_sat = "sat" in config["loss_evaluation"]

    with timer.phase("setup"):
        constraints = common.load_constraints(config)
        x_initial = common.load_candidates(config)
        scaler = common.load_scaler(config)
        surrogate = common.load_surrogate(config)
        constraints.check_constraints_error(x_initial)
        attack = _cached_attack(config, surrogate, constraints, scaler)
        attack.seed = config["seed"]

    start_time = time.time()
    # Use only half ε if SAT runs after (01_pgd_united.py:97).
    per_attack_eps = config["eps"] / 2 if apply_sat else config["eps"]
    eps_run = per_attack_eps - 0.000001
    # AutoPGD defaults (01_pgd_united.py:99-111); plain PGD uses a fixed step.
    eps_step_run = per_attack_eps / 3 if isinstance(attack, AutoPGD) else 0.1

    with timer.attack(attack), maybe_profile(
        config.get("system", {}).get("profile_dir")
    ):
        x_scaled = np.asarray(scaler.transform(x_initial))
        # ART infers labels from the classifier's own predictions when no y
        # is given (the reference calls generate(x) label-free).
        y = np.asarray(surrogate.predict_proba(x_scaled)).argmax(-1)
        # candidate counts are data-dependent: pad to a mesh multiple, trim
        x_run, n_orig = common.pad_states(x_scaled, attack.mesh)
        y_run, _ = common.pad_states(y, attack.mesh)
        x_adv_scaled = attack.generate(
            x_run, y_run, eps=eps_run, eps_step=eps_step_run,
            max_iter=int(config["budget"]),
        )[:n_orig]
        # snapshot per-run engine outputs NOW: a cached engine may be
        # re-dispatched for the next grid point while the writer thread is
        # still finalizing this one
        loss_history = attack.loss_history
        # per-restart flip curve over the REAL rows only: the batch was
        # padded to a mesh multiple above, and pad duplicates would bias
        # the recorded fractions (the engine returns the per-row mask for
        # exactly this trim)
        restart_curve = None
        if attack.quality_history is not None:
            restart_curve = (
                attack.quality_history["restart_success"][:, :n_orig]
                .mean(axis=1)
                .tolist()
            )
        if loss_history is not None:
            loss_history = loss_history[:n_orig]
        hist_names = attack.hist_column_names()
        x_attacks = np.asarray(scaler.inverse(x_adv_scaled))

        # Directional integer rounding (01_pgd_united.py:130-137).
        x_attacks = round_ints_toward_initial(
            x_attacks, x_initial, constraints.get_feature_type()
        )

        if apply_sat:
            sat = SatAttack(
                constraints,
                common.get_sat_builder(config["project_name"], constraints),
                scaler,
                per_attack_eps,
                np.inf,
                n_sample=1,
                n_jobs=config.get("system", {}).get("n_jobs", 1),
                # iterative denominator-grid refinement (no-op for fully
                # linear domains); 2 rounds ~ box/64 resolution
                refine_rounds=int(config.get("sat_refine_rounds", 2)),
            )
            x_attacks = sat.generate(x_initial, x_attacks)[:, 0, :]

    if config.get("reconstruction"):
        important = constraints.important_features
        n_pairs = augmentation.n_pairs(important)
        x_attacks = np.asarray(
            augmentation.augment(x_attacks[..., :-n_pairs], important)
        )
    consumed_time = time.time() - start_time

    if x_attacks.ndim == 2:
        x_attacks = x_attacks[:, np.newaxis, :]

    def finalize():
        with timer.phase("evaluate"):
            eval_constraints = common.evaluation_constraints(config, constraints)
            calc = ObjectiveCalculator(
                classifier=surrogate,
                constraints=eval_constraints,
                thresholds={
                    "f1": config["misclassification_threshold"],
                    "f2": config["eps"],
                },
                min_max_scaler=scaler,
                ml_scaler=scaler,
                minimize_class=1,
                norm=config["norm"],
            )
            success_rate_df = calc.success_rate_3d_df(x_initial, x_attacks)
        print(success_rate_df)

        objectives = success_rate_df.to_dict(orient="records")[0]
        with timer.phase("write"):
            np.save(f"{out_dir}/x_attacks_{mid_fix}_{config_hash}.npy", x_attacks)
            if config.get("save_history") and loss_history is not None:
                # (N, max_iter, 1, C) loss-component curves, the reference's
                # saved layout (01_pgd_united.py:196-199; C = 3 for "reduced",
                # 3+K "full").
                np.save(
                    f"{out_dir}/x_history_{config_hash}.npy",
                    loss_history[:, :, np.newaxis, :],
                )
            # Comet-equivalent event stream: run params, final rates, and
            # (when loss history was recorded) the per-iteration
            # loss/grad-norm curves the reference pushed to Comet from inside
            # the loop (pgd/classifier.py:183-217, atk.py:201-226).
            with stream_for(config, mid_fix, config_hash) as stream:
                stream.log_parameters(config)
                stream.log_metric("time", consumed_time)
                for k, v in objectives.items():
                    stream.log_metric(k, v)
                if loss_history is not None:
                    mean_curves = loss_history.mean(axis=0)  # (max_iter, C)
                    scalar = {"loss", "loss_class", "cons_sum", "grad_norm"}
                    for j, name in enumerate(hist_names):
                        if name in scalar:  # skip per-constraint g1..gK cols
                            stream.log_series(f"mean_{name}", mean_curves[:, j])
            success_rate_df.to_csv(
                f"{out_dir}/success_rate_{mid_fix}_{config_hash}.csv", index=False
            )

        # metrics assembled AFTER the write phase closes so its 'timings'
        # include the artifact-write span; the metrics JSON itself still
        # lands last, preserving the "metrics exists => siblings exist"
        # invariant should_skip relies on
        metrics = {
            "objectives": objectives,
            "time": consumed_time,
            # the reference-schema "time" field spans the whole attack call;
            # on a cold engine that includes trace + XLA compile (or a
            # persistent-cache load), so the flag travels with the number
            "includes_compile": "attack_compile" in timer.spans,
            # RNG-affecting execution mode of this number (VERDICT r5 item 8)
            "execution": {
                "max_states_per_call": None,  # PGD dispatches one batch
                "mesh": describe_mesh(attack.mesh),
            },
            "timings": timer.spans,
            "counters": timer.counters,
            # shared record schema (observability.records); quality = the
            # post-hoc f64 o-rates as the final summary plus the engine's
            # per-restart flip curve when restarts ran
            "telemetry": telemetry_block(
                timer=timer,
                trace=trace,
                device=attack.mesh.devices.flat[0]
                if attack.mesh is not None
                else None,
                ledger_since=ledger_mark,
                gaps_since=gaps_mark,
                # multi-device runs carry telemetry.mesh (per-device
                # roofline + balance + collectives), window-scoped
                mesh=describe_mesh(attack.mesh),
                mesh_since=mesh_mark,
                quality=quality_block(
                    final={
                        "judged": "post_hoc_f64",
                        "eps": config["eps"],
                        "o_rates": [objectives.get(k) for k in O_COLUMNS],
                    },
                    restart_curve=restart_curve,
                    judged="post_hoc_f64",
                ),
            ),
            "config": config,
            "config_hash": config_hash,
        }
        json_to_file(metrics, metrics_path)
        save_config(config, f"{out_dir}/config_{mid_fix}_")
        return metrics

    if pipeline is not None:
        pipeline.point(mid_fix, config_hash, timer)
        pipeline.submit(mid_fix, metrics_path, finalize)
        return None
    return finalize()


if __name__ == "__main__":
    run(parse_config())
