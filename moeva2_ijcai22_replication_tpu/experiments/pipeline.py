"""Grid-level execution pipeline: host/device overlap + grid observability.

One in-process grid sweep (``experiments/rq.py``) used to run every point
strictly sequentially: setup → attack → evaluate → save. The attack is the
only device-bound stage; evaluation kick-off, ``.npy``/metrics/CSV
serialization, and event streaming are host work that can run while the
device executes the *next* point's attack (JAX dispatch is thread-safe and
async). This module provides that overlap:

- :class:`GridPipeline.submit` hands a point's finalize closure (evaluate +
  serialize + stream + metrics write) to a single background writer thread.
  FIFO on one worker gives a strict ordering guarantee: a point's artifacts
  are written in submission order, and within a point the metrics JSON is
  written last — so "metrics file exists" still implies "all sibling
  artifacts exist", which is what ``should_skip``'s config-hash idempotency
  relies on. Queued-but-unwritten hashes are tracked (:meth:`is_pending`)
  so a duplicate grid point skips even before its metrics file lands.
- Writer failures are caught per point (same isolation as a failed attack:
  logged, sweep continues) and surfaced in the grid report.
- :meth:`point` records per-point spans/counters; :meth:`finish` drains the
  writer and assembles the ``grid_report_{hash}.json`` aggregate — points,
  compile-vs-run span totals, artifact/engine cache hit deltas, and the
  number of distinct programs traced (the executable-reuse headline: an
  ε sweep should trace far fewer programs than it has grid points).

MoEvA's mid-run checkpointing is untouched by design: the checkpointer runs
inside ``Moeva2.generate`` on the launching thread, strictly before the
point's finalize is submitted, so a crash mid-attack leaves the same
resumable state as without the pipeline.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time

from ..observability import (
    TraceRecorder,
    get_coldstart,
    get_gap_tracker,
    get_ledger,
    get_mesh_capture,
    quality_block,
    telemetry_block,
    validate_record,
)
from ..utils.config import get_dict_hash
from . import common

logger = logging.getLogger(__name__)


class GridPipeline:
    """Shared execution context for one in-process grid sweep."""

    def __init__(self, recorder=None):
        self._queue: queue.Queue = queue.Queue()
        self._pending: set[str] = set()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._submitted = 0
        self.points: list[dict] = []
        self.write_failures: list[dict] = []
        # per-point quality summaries harvested from the finalize closures'
        # metrics (interior + final only — the full curves live in the
        # per-point metrics JSONs); single writer thread appends, finish()
        # reads after close(), so no lock is needed
        self.point_quality: list[dict] = []
        # unified tracing recorder: the writer-queue depth gauge and grid
        # counters are always-on cheap instruments; with spans enabled
        # (``system.trace_log``) they also land in the event stream. The
        # default is a counters-only recorder OWNED by this grid, so the
        # report's telemetry reflects this sweep, not the whole process
        self.recorder = (
            recorder if recorder is not None else TraceRecorder(spans_enabled=False)
        )
        self._t0 = time.perf_counter()  # monotonic: NTP-step-proof wallclock
        self._artifacts0 = common.ARTIFACTS.stats()
        self._engines0 = common.ENGINES.stats()
        # cost-ledger snapshots: the report scopes the process ledger to
        # this sweep (executables/compile-seconds added BY the grid); the
        # mesh-balance mark scopes telemetry.mesh the same way
        self._ledger0 = get_ledger().summary()
        self._ledger_mark = get_ledger().mark()
        self._mesh_mark = get_mesh_capture().mark()
        # dispatch-gap window: the report's telemetry.gaps covers this
        # sweep's device timeline (incl. the idle seams between points
        # that the background writer exists to fill)
        self._gaps_mark = get_gap_tracker().mark()

    # -- background writer ---------------------------------------------------
    def _worker(self):
        while True:
            item = self._queue.get()
            try:
                if item is None:
                    return
                label, metrics_path, finalize = item
                try:
                    metrics = finalize()
                    self.recorder.count("grid_points_finalized")
                    q = (
                        (metrics.get("telemetry") or {}).get("quality")
                        if isinstance(metrics, dict)
                        else None
                    )
                    if isinstance(q, dict) and (
                        q.get("interior") or q.get("final")
                    ):
                        self.point_quality.append(
                            {
                                "point": label,
                                "interior": q.get("interior"),
                                "final": q.get("final"),
                            }
                        )
                except Exception as e:
                    logger.exception("grid point finalize failed: %s", label)
                    self.write_failures.append({"point": label, "error": repr(e)})
                    self.recorder.count("grid_point_write_failures")
                finally:
                    with self._lock:
                        self._pending.discard(metrics_path)
                    self.recorder.gauge(
                        "grid_writer_queue_depth", self._queue.qsize()
                    )
            finally:
                self._queue.task_done()

    def submit(self, label: str, metrics_path: str, finalize) -> None:
        """Queue a point's finalize closure on the writer thread."""
        with self._lock:
            self._pending.add(metrics_path)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._worker, name="grid-writer", daemon=True
                )
                self._thread.start()
        self._submitted += 1
        self._queue.put((label, metrics_path, finalize))
        self.recorder.gauge("grid_writer_queue_depth", self._queue.qsize())

    def is_pending(self, metrics_path: str) -> bool:
        with self._lock:
            return metrics_path in self._pending

    def drain(self) -> None:
        """Block until every queued finalize has run."""
        self._queue.join()

    def close(self) -> None:
        self.drain()
        if self._thread is not None:
            self._queue.put(None)
            self._thread.join()
            self._thread = None

    # -- observability -------------------------------------------------------
    def point(self, attack: str, config_hash: str, timer, skipped: bool = False):
        """Record one launched grid point; ``timer`` is the point's
        PhaseTimer, read again at :meth:`finish` time so spans added later by
        the writer thread (evaluate/write) are included."""
        self.points.append(
            {
                "attack": attack,
                "config_hash": config_hash,
                "skipped": skipped,
                "_timer": timer,
            }
        )
        self.recorder.count("grid_points_skipped" if skipped else "grid_points")

    @staticmethod
    def _delta(now: dict, before: dict) -> dict:
        return {k: now[k] - before.get(k, 0) for k in now}

    def finish(self, grid_config: dict, out_dirs) -> dict:
        """Drain the writer and write ``grid_report_{hash}.json``."""
        self.close()
        points = []
        for p in self.points:
            timer = p.pop("_timer", None)
            if timer is not None:
                p["spans"] = {k: round(v, 4) for k, v in timer.spans.items()}
                p["counters"] = dict(timer.counters)
            points.append(p)

        def span_total(name):
            return round(
                sum(p.get("spans", {}).get(name, 0.0) for p in points), 3
            )

        launched = [p for p in points if not p["skipped"]]
        # cold-start decomposition at grid scale: the first launched
        # point's attack wall-clock (compiles / persistent-cache loads
        # land there) vs the steady cost of the rest — the grid analogue
        # of bench.py's cold_s/steady_s pair, plus the process-wide
        # startup-phase ledger (import, artifact builds, lower-vs-compile
        # split, per-executable persistent-cache hit/miss counts)
        attack_walls = [
            p.get("spans", {}).get("attack")
            for p in launched
            if isinstance(p.get("spans", {}).get("attack"), (int, float))
        ]
        steady_walls = sorted(attack_walls[1:])
        steady_attack = (
            steady_walls[len(steady_walls) // 2] if steady_walls else None
        )
        cold_block = {
            "first_point_attack_s": (
                round(attack_walls[0], 4) if attack_walls else None
            ),
            "steady_point_attack_s": (
                round(steady_attack, 4) if steady_attack is not None else None
            ),
            "cold_steady_ratio": (
                round(attack_walls[0] / steady_attack, 3)
                if attack_walls and steady_attack
                else None
            ),
            "process": get_coldstart().cold_block(),
        }
        # resolve the grid's mesh identity (config mesh_devices may be -1 =
        # all visible devices): the execution block records the RESOLVED
        # count and multi-device grids carry telemetry.mesh
        try:
            from ..attacks.sharding import describe_mesh

            mesh_desc = describe_mesh(common.build_mesh(grid_config))
        except Exception:
            mesh_desc = None
        report = {
            "grid_config_hash": get_dict_hash(grid_config),
            "grid_wallclock_s": round(time.perf_counter() - self._t0, 3),
            "points_total": len(points),
            "points_launched": len(launched),
            "points_skipped": len(points) - len(launched),
            "distinct_compiled_programs": sum(
                p.get("counters", {}).get("traces", 0) for p in points
            ),
            "attack_compile_s": span_total("attack_compile"),
            "attack_run_s": span_total("attack_run"),
            "setup_s": span_total("setup"),
            "evaluate_s": span_total("evaluate"),
            "write_s": span_total("write"),
            "artifact_cache": self._delta(
                common.ARTIFACTS.stats(), self._artifacts0
            ),
            "engine_cache": self._delta(common.ENGINES.stats(), self._engines0),
            # this grid's executable-cost footprint (satellite of the cost
            # ledger: report next to the cache deltas it explains)
            "ledger": get_ledger().summary_delta(self._ledger0),
            "cold": cold_block,
            "writer": {
                "submitted": self._submitted,
                "failures": self.write_failures,
            },
            # the shared record schema (observability.records): execution
            # mode + telemetry travel with every bench/grid/serving record
            "execution": {
                "pipeline": True,
                "mesh_devices": int(
                    (mesh_desc or {}).get("devices")
                    or (grid_config.get("system") or {}).get(
                        "mesh_devices", 0
                    )
                    or 0
                ),
                "mesh": mesh_desc,
            },
            "telemetry": telemetry_block(
                recorder=self.recorder,
                ledger_since=self._ledger_mark,
                gaps_since=self._gaps_mark,
                mesh=mesh_desc,
                mesh_since=self._mesh_mark,
                # grid-level quality: per-point interior/final summaries
                # (the curves stay in the metrics JSONs they came from)
                quality=dict(
                    quality_block(judged="per_point"),
                    points=self.point_quality,
                ),
            ),
            "points": points,
        }
        validate_record(report, "grid")
        for out_dir in out_dirs:
            try:
                os.makedirs(out_dir, exist_ok=True)
                path = os.path.join(
                    out_dir, f"grid_report_{report['grid_config_hash']}.json"
                )
                with open(path, "w") as f:
                    json.dump(report, f, indent=1)
                report["report_path"] = path
                break
            except OSError as e:
                logger.warning("could not write grid report to %s: %s", out_dir, e)
        return report
