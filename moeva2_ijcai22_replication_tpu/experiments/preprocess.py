"""LCLD raw-data preprocessing: LendingClub CSV → the 47-feature dataset.

Capability parity with ``/root/reference/src/experiments/lcld/00_data_preprocess.py:9-150``
(status filter, investor-known column whitelist, emp_length/grade encodings,
YYYYMM date ints, fico average, the six derived ratio features, one-hot
dummies, ``charged_off`` target). The raw LendingClub CSV is not
redistributed with the reference, so this stage has nothing to run on in CI
— ``domains/synth.py`` generates constraint-valid data instead — but the
transform itself ships so a user with the raw export gets the same dataset.

Reshaped from the reference's 150-line imperative script into declarative
tables (encodings, derived-feature formulas, pinned category lists). Pinning
the categorical levels to the committed ``features.csv`` schema is a
deliberate difference: ``pd.get_dummies`` on a raw sample that happens to
miss a level would silently emit a narrower frame; here the output columns
are the schema's 47, always.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

#: investor-known columns kept from the raw export (``00_data_preprocess.py:33-37``)
KEEP = [
    "annual_inc", "application_type", "dti", "earliest_cr_line", "emp_length",
    "fico_range_high", "fico_range_low", "grade", "home_ownership",
    "initial_list_status", "installment", "int_rate", "issue_d", "loan_amnt",
    "loan_status", "mort_acc", "open_acc", "pub_rec", "pub_rec_bankruptcies",
    "purpose", "revol_bal", "revol_util", "term", "total_acc",
    "verification_status",
]

GRADES = {g: i + 1 for i, g in enumerate("ABCDEFG")}

#: pinned one-hot levels, in the committed features.csv order
OHE_LEVELS = {
    "home_ownership": ["MORTGAGE", "OTHER", "OWN", "RENT"],
    "verification_status": ["Not Verified", "Source Verified", "Verified"],
    "purpose": [
        "car", "credit_card", "debt_consolidation", "educational",
        "home_improvement", "house", "major_purchase", "medical", "moving",
        "other", "renewable_energy", "small_business", "vacation", "wedding",
    ],
}
#: drop-first binaries (``00_data_preprocess.py:116``)
BINARY_LEVELS = {"initial_list_status": "w", "application_type": "Joint App"}


def _date_to_yyyymm(s: pd.Series) -> pd.Series:
    return pd.to_datetime(s).map(
        lambda d: np.nan if pd.isnull(d) else int(d.strftime("%Y%m"))
    )


def _months(d: pd.Series) -> pd.Series:
    return np.floor(d / 100) * 12 + d % 100


def preprocess_lcld(raw: pd.DataFrame) -> pd.DataFrame:
    """Raw LendingClub frame → cleaned frame: the 47 schema features (in
    ``features.csv`` order) + the ``charged_off`` target."""
    missing_raw = [c for c in KEEP + ["loan_status"] if c not in raw.columns]
    if missing_raw:
        raise ValueError(
            f"raw export is missing required columns: {missing_raw} — the "
            "47-feature schema cannot be derived from this file"
        )
    loans = raw.loc[raw["loan_status"].isin(["Fully Paid", "Charged Off"])]
    loans = loans[KEEP].copy()

    # scalar encodings
    loans["term"] = loans["term"].map(lambda s: int(str(s).split()[0]))
    loans["emp_length"] = (
        loans["emp_length"]
        .replace({"10+ years": "10 years", "< 1 year": "0 years"})
        .map(lambda s: s if pd.isnull(s) else int(str(s).split()[0]))
    )
    loans["home_ownership"] = loans["home_ownership"].replace(
        ["NONE", "ANY"], "OTHER"
    )
    loans["grade"] = loans["grade"].map(GRADES)

    # dates as YYYYMM ints; a 1900-01 earliest_cr_line marks missing
    loans["earliest_cr_line"] = _date_to_yyyymm(
        loans["earliest_cr_line"].fillna("1900-01-01")
    ).replace({190001: np.nan})
    loans["issue_d"] = _date_to_yyyymm(loans["issue_d"])

    loans["fico_score"] = (
        loans.pop("fico_range_low") + loans.pop("fico_range_high")
    ) / 2.0

    # binary / one-hot expansions against the pinned level lists; column
    # names keep the raw level verbatim ("application_type_Joint App",
    # "verification_status_Not Verified") — the committed schema's names
    for col, level in BINARY_LEVELS.items():
        loans[f"{col}_{level}"] = (loans.pop(col) == level).astype(np.uint8)
    ohe_frames = {}
    for col, levels in OHE_LEVELS.items():
        vals = loans.pop(col)
        for lv in levels:
            ohe_frames[f"{col}_{lv}"] = (vals == lv).astype(np.uint8)

    # derived features (the constraint formulas' right-hand sides)
    loans["ratio_loan_amnt_annual_inc"] = loans["loan_amnt"] / loans["annual_inc"]
    loans["ratio_open_acc_total_acc"] = loans["open_acc"] / loans["total_acc"]
    diff = _months(loans["issue_d"]) - _months(loans["earliest_cr_line"])
    loans["diff_issue_d_earliest_cr_line"] = diff
    loans["ratio_pub_rec_diff_issue_d_earliest_cr_line"] = loans["pub_rec"] / diff
    loans["ratio_pub_rec_bankruptcies_diff_issue_d_earliest_cr_line"] = (
        loans["pub_rec_bankruptcies"] / diff
    )
    loans["ratio_pub_rec_bankruptcies_pub_rec"] = np.where(
        loans["pub_rec"] > 0,
        loans["pub_rec_bankruptcies"] / loans["pub_rec"].replace({0: 1}),
        -1.0,
    )

    for name, col in ohe_frames.items():
        loans[name] = col
    loans["charged_off"] = (loans.pop("loan_status") == "Charged Off").astype(
        np.uint8
    )
    loans = loans.dropna()

    order = _schema_order()
    missing = [c for c in order if c not in loans.columns]
    if missing:
        raise ValueError(
            f"raw export is missing columns needed for the 47-feature schema: "
            f"{missing} — refusing to emit a silently narrowed dataset"
        )
    return loans[order + ["charged_off"]]


def _schema_order() -> list[str]:
    """The committed features.csv column order (hard-coded so preprocessing
    does not require the schema file; cross-checked by the test suite)."""
    return (
        ["loan_amnt", "term", "int_rate", "installment", "grade", "emp_length",
         "annual_inc", "issue_d", "dti", "earliest_cr_line", "open_acc",
         "pub_rec", "revol_bal", "revol_util", "total_acc", "mort_acc",
         "pub_rec_bankruptcies", "fico_score", "initial_list_status_w",
         "application_type_Joint App", "ratio_loan_amnt_annual_inc",
         "ratio_open_acc_total_acc", "diff_issue_d_earliest_cr_line",
         "ratio_pub_rec_diff_issue_d_earliest_cr_line",
         "ratio_pub_rec_bankruptcies_diff_issue_d_earliest_cr_line",
         "ratio_pub_rec_bankruptcies_pub_rec"]
        + [f"home_ownership_{l}" for l in OHE_LEVELS["home_ownership"]]
        + [f"verification_status_{l}" for l in OHE_LEVELS["verification_status"]]
        + [f"purpose_{l}" for l in OHE_LEVELS["purpose"]]
    )


def run(config: dict):
    raw = pd.read_csv(config["paths"]["raw_data"], low_memory=False)
    out = preprocess_lcld(raw)
    out.to_csv(config["paths"]["dataset"], index=False)
    print(f"Saved dataset {out.shape} -> {config['paths']['dataset']}")
    return out


if __name__ == "__main__":
    from ..utils.config import parse_config

    run(parse_config())
