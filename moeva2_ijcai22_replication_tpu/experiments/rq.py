"""Research-question grid runners (L5 orchestration).

Parity: ``/root/reference/src/run_rq{1,2,3}.py`` — nested loops over
seeds × projects × budgets (× scenari for RQ2, × models for RQ3) composing
layered configs and launching the MoEvA and PGD runners per grid point. The
three reference scripts are the same loop with one optional axis each, so a
single runner handles all of them: the ``scenari`` / ``models`` axes are
driven by config presence.

Launch modes: in-process (default — runner functions are called directly,
sharing one JAX runtime across the grid) or ``use_subprocess=True`` for the
reference's process-isolation semantics (failed points are logged and the
grid continues).

In-process grids run through a :class:`..experiments.pipeline.GridPipeline`
(disable with ``pipeline: false`` in the grid config): artifact loads and
attack engines are shared across points, ε is a runtime argument of the
compiled PGD programs, and each point's evaluation/serialization runs on a
background writer while the device executes the next point's attack. The
pipeline is drained before :meth:`GridRunner.run` returns and writes a
``grid_report_{hash}.json`` aggregate (per-point spans, compile-vs-run
totals, cache hit counters) beside the point results.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import subprocess
import sys

from ..utils.config import load_config_file, merge_config, parse_config

TABULATOR = ">>>"
logger = logging.getLogger(__name__)


def _compose(config_dir: str, base_name: str, project: str, overrides: list[dict]) -> dict:
    """Layered config: {base attack yaml} <- {project static yaml} <- overrides
    (the reference's ``-c attack.yaml -c project.yaml -p/-j …`` stack)."""
    cfg: dict = {}
    merge_config(cfg, load_config_file(os.path.join(config_dir, f"{base_name}.yaml")))
    merge_config(cfg, load_config_file(os.path.join(config_dir, f"{project}.yaml")))
    for o in overrides:
        merge_config(cfg, copy.deepcopy(o))
    return cfg


class GridRunner:
    """Expand the grid and launch one experiment per point."""

    def __init__(self, config: dict, use_subprocess: bool = False):
        self.config = config
        self.use_subprocess = use_subprocess
        self.launch_counter = 0
        self.pipeline = None
        if not use_subprocess and config.get("pipeline", True):
            from ..observability import recorder_for
            from .pipeline import GridPipeline

            # with ``system.trace_log`` the grid shares the sink-backed
            # recorder (points + runs in one event stream); otherwise the
            # pipeline owns a counters-only recorder so the grid report's
            # telemetry reflects this sweep alone
            rec = recorder_for(config)
            self.pipeline = GridPipeline(
                recorder=rec if rec.spans_enabled else None
            )
        self._out_dirs: list[str] = []
        self.report: dict | None = None

    # -- launching ----------------------------------------------------------
    def _launch(self, module: str, cfg: dict) -> None:
        self.launch_counter += 1
        out_dir = cfg.get("dirs", {}).get("results")
        if out_dir and out_dir not in self._out_dirs:
            self._out_dirs.append(out_dir)
        if self.use_subprocess:
            blob = json.dumps(cfg, separators=(",", ":"))
            script = [sys.executable, "-m", module, "-j", blob]
            logger.info(script)
            proc = subprocess.run(script)
            if proc.returncode != 0:
                logger.error(
                    "grid point failed (rc=%d): %s", proc.returncode, script
                )
            return
        logger.info("in-process %s %s", module, cfg.get("attack_name"))
        if module.endswith(".moeva"):
            from . import moeva as runner
        else:
            from . import pgd as runner
        # Same failure isolation as subprocess mode: one bad grid point is
        # logged and the sweep continues (the reference gets this for free
        # from its per-point processes).
        try:
            runner.run(cfg, pipeline=self.pipeline)
        except Exception:
            logger.exception("grid point failed in-process: %s", module)

    def _launch_moeva(self, project: str, overrides: list[dict]) -> None:
        cfg = _compose(
            self.config["config_dir"],
            "moeva",
            project,
            overrides + [{"eps_list": self.config["eps_list"]}],
        )
        self._launch("moeva2_ijcai22_replication_tpu.experiments.moeva", cfg)

    def _launch_pgd(self, project: str, overrides: list[dict]) -> None:
        for eps in self.config["eps_list"]:
            logger.info(f"{TABULATOR * 5} Running eps {eps} ...")
            for loss_evaluation in self.config["loss_evaluations"]:
                logger.info(
                    f"{TABULATOR * 6} Running loss_evaluation {loss_evaluation} ..."
                )
                cfg = _compose(
                    self.config["config_dir"],
                    "pgd",
                    project,
                    overrides + [{"eps": eps, "loss_evaluation": loss_evaluation}],
                )
                self._launch("moeva2_ijcai22_replication_tpu.experiments.pgd", cfg)

    # -- grid ---------------------------------------------------------------
    def _extra_axis(self) -> list[list[dict]]:
        """RQ2's scenari (config-fragment overrides) or RQ3's models (model
        path overrides); RQ1 has the single empty point."""
        if "scenari" in self.config:
            return [[scenario] for scenario in self.config["scenari"]]
        if "models" in self.config:
            return [
                [{"paths": {"model": model}}] for model in self.config["models"]
            ]
        return [[]]

    def run(self) -> int:
        config = self.config
        try:
            for seed in config["seeds"]:
                logger.info(f"{TABULATOR} Running seed {seed} ...")
                for project in config["projects"]:
                    logger.info(f"{TABULATOR * 2} Running project {project} ...")
                    for budget in config["budgets"]:
                        logger.info(f"{TABULATOR * 3} Running budget {budget} ...")
                        for extra in self._extra_axis():
                            overrides = [{"seed": seed, "budget": budget}] + extra
                            if "moeva" in config["attacks"]:
                                logger.info(f"{TABULATOR * 4} Running MoEvA ...")
                                self._launch_moeva(project, overrides)
                            if "pgd" in config["attacks"]:
                                logger.info(f"{TABULATOR * 4} Running pgd ...")
                                self._launch_pgd(project, overrides)
        finally:
            if self.pipeline is not None:
                # drain the background writer (every queued point lands on
                # disk before the grid returns) and publish the aggregate
                self.report = self.pipeline.finish(config, self._out_dirs)
                logger.info(
                    "grid report: %d points (%d launched), %d compiled "
                    "programs, compile %.1fs / run %.1fs, artifact cache "
                    "%s, engine cache %s -> %s",
                    self.report["points_total"],
                    self.report["points_launched"],
                    self.report["distinct_compiled_programs"],
                    self.report["attack_compile_s"],
                    self.report["attack_run_s"],
                    self.report["artifact_cache"],
                    self.report["engine_cache"],
                    self.report.get("report_path", "<unwritten>"),
                )
        return self.launch_counter


def run(config: dict, use_subprocess: bool = False) -> int:
    runner = GridRunner(config, use_subprocess=use_subprocess)
    n = runner.run()
    logger.info(f"{n} run executed.")
    return n


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    run(parse_config())
