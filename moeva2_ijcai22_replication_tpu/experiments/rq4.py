"""RQ4 defense iteration: retrain on the *best* adversarials.

Parity: ``/root/reference/src/experiments/lcld/03_train_robust_rq4.py`` —
consumes the :mod:`.defense` artifact family (scaler, nn, nn_augmented,
important features, augmented data, x_train_moeva, common candidates; raises
FileNotFoundError when missing, like the reference) and produces:

- ``nn_moeva_best``: retrained on the best successful adversarial per state
  under the *relaxed* misclassification threshold f1=1.0 (:164-186);
- a MoEvA attack on the augmented model under augmented constraints →
  ``nn_augmented_moeva_best`` (:237-328);
- the RQ4 candidate sets: common candidates still classified correctly by
  both "best" models (:331-343).
"""

from __future__ import annotations

import os

import numpy as np

from ..attacks.objective import ObjectiveCalculator
from ..domains import get_constraints_class
from ..models.io import load_classifier
from ..models.scalers import from_sklearn_minmax
from ..models.train import auroc
from ..utils.config import parse_config
from . import common
from .defense import (
    PROJECT_DEFAULTS,
    _memo_model,
    _memo_npy,
    make_trainer,
    moeva_attack,
    proba1,
)


def _require(path: str) -> str:
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"{path} missing — run the defense pipeline (experiments.defense) first"
        )
    return path


def run(config: dict) -> dict:
    import joblib

    common.setup_jax_cache(config)
    project = config["project_name"]
    knobs = dict(PROJECT_DEFAULTS[project.split("_")[0]])
    knobs.update(config.get("defense", {}))
    threshold = config["misclassification_threshold"]
    data_dir = config["dirs"]["data"]
    models_dir = config["dirs"]["models"]
    suffix = knobs["augmented_suffix"]

    # ----- LOAD (03_train_robust_rq4.py:41-120 — all load-or-raise)
    x_train = np.load(config["paths"]["x_train"])
    x_test = np.load(config["paths"]["x_test"])
    y_train = np.load(config["paths"]["y_train"])
    y_test = np.load(config["paths"]["y_test"])
    scaler = joblib.load(_require(f"{models_dir}/scaler.joblib"))
    scaler_augmented = joblib.load(
        _require(f"{models_dir}/scaler_augmented{suffix}.joblib")
    )
    model = load_classifier(_require(f"{models_dir}/nn.msgpack"))
    model_augmented = load_classifier(
        _require(f"{models_dir}/nn_augmented{suffix}.msgpack")
    )
    x_train_augmented = np.load(_require(f"{data_dir}/x_train_augmented.npy"))
    x_test_augmented = np.load(_require(f"{data_dir}/x_test_augmented.npy"))
    x_train_moeva = np.load(_require(f"{data_dir}/x_train_moeva.npy"))
    train = make_trainer(knobs["model_fn"], knobs, config["seed"])

    # ----- CANDIDATES (same filter as the defense pipeline, :123-139)
    constraints = common.load_constraints(config)
    cand_mask = (y_train == 1) & (
        (proba1(model, scaler, x_train) >= threshold).astype(int) == y_train
    )
    x_cand = x_train[cand_mask]
    x_cand = x_cand[np.asarray(constraints.evaluate(x_cand)).max(-1) <= 0]

    ml_scaler = from_sklearn_minmax(scaler)

    # ----- BEST MOEVA ADVERSARIALS: f1 threshold 1.0 (:164-186)
    best_path = f"{data_dir}/x_train_best_moeva.npy"
    if os.path.exists(best_path):
        x_best = np.load(best_path)
    else:
        calc = ObjectiveCalculator(
            classifier=model,
            constraints=constraints,
            thresholds={"f1": 1.0, "f2": config["eps"]},
            min_max_scaler=ml_scaler,
            ml_scaler=ml_scaler,
            minimize_class=1,
            norm=config["norm"],
        )
        x_best, idx = calc.get_successful_attacks(
            x_cand, x_train_moeva, preferred_metrics="misclassification",
            order="asc", max_inputs=1, return_index_success=True,
        )
        np.save(f"{data_dir}/x_train_best_moeva_index.npy", idx)
        np.save(best_path, x_best)

    # ----- nn_moeva_best (:191-216)
    model_best = _memo_model(
        f"{models_dir}/nn_moeva_best.msgpack",
        lambda: train(
            scaler.transform(np.concatenate([x_train, x_best])),
            np.concatenate([y_train, np.ones(len(x_best), dtype=y_train.dtype)]),
        ),
    )
    print(f"AUROC: {auroc(proba1(model_best, scaler, x_test), y_test)}")

    # ----- AUGMENTED ATTACK (:218-266)
    aug_constraints = get_constraints_class(f"{project}_augmented")(
        config["paths"]["features_augmented"],
        config["paths"]["constraints_augmented"],
        important_features_path=f"{data_dir}/important_features{suffix}.npy",
    )
    aug_cand_mask = (y_train == 1) & (
        (proba1(model_augmented, scaler_augmented, x_train_augmented) >= threshold)
        .astype(int)
        == y_train
    )
    x_aug_cand = x_train_augmented[aug_cand_mask]
    x_aug_cand = x_aug_cand[
        np.asarray(aug_constraints.evaluate(x_aug_cand)).max(-1) <= 0
    ]
    ml_scaler_aug = from_sklearn_minmax(scaler_augmented)

    x_aug_moeva = _memo_npy(
        f"{data_dir}/x_train_augmented_moeva.npy",
        lambda: moeva_attack(
            model_augmented, aug_constraints, ml_scaler_aug, config, x_aug_cand
        ),
    )

    # ----- BEST AUGMENTED ADVERSARIALS (:269-298; threshold back to config)
    aug_best_path = f"{data_dir}/x_train_augmented_best_moeva.npy"
    if os.path.exists(aug_best_path):
        x_aug_best = np.load(aug_best_path)
    else:
        calc = ObjectiveCalculator(
            classifier=model_augmented,
            constraints=aug_constraints,
            thresholds={"f1": threshold, "f2": config["eps"]},
            min_max_scaler=ml_scaler_aug,
            ml_scaler=ml_scaler_aug,
            minimize_class=1,
            norm=config["norm"],
        )
        x_aug_best, idx = calc.get_successful_attacks(
            x_aug_cand, x_aug_moeva, preferred_metrics="misclassification",
            order="asc", max_inputs=1, return_index_success=True,
        )
        np.save(f"{data_dir}/x_train_augmented_best_moeva_index.npy", idx)
        np.save(aug_best_path, x_aug_best)

    # ----- nn_augmented_moeva_best (:303-328)
    model_aug_best = _memo_model(
        f"{models_dir}/nn_augmented_moeva_best.msgpack",
        lambda: train(
            scaler_augmented.transform(
                np.concatenate([x_train_augmented, x_aug_best])
            ),
            np.concatenate(
                [y_train, np.ones(len(x_aug_best), dtype=y_train.dtype)]
            ),
        ),
    )
    print(f"AUROC: {auroc(proba1(model_aug_best, scaler_augmented, x_test_augmented), y_test)}")

    # ----- RQ4 CANDIDATE SETS (:331-343)
    x_common = np.load(_require(f"{data_dir}/x_candidates_common.npy"))
    x_common_aug = np.load(
        _require(f"{data_dir}/x_candidates_common_augmented.npy")
    )
    still_ok = (proba1(model_best, scaler, x_common) >= threshold).astype(int)
    print(f"Still ok rate: {still_ok.sum() / len(x_common)}")
    still_ok_aug = (
        proba1(model_aug_best, scaler_augmented, x_common_aug) >= threshold
    ).astype(int)
    print(f"Still ok rate: {still_ok_aug.sum() / len(x_common_aug)}")
    final = (still_ok * still_ok_aug) == 1
    rq4_path = f"{data_dir}/x_candidates_rq4_best.npy"
    rq4_aug_path = f"{data_dir}/x_candidates_rq4_augmented_best.npy"
    np.save(rq4_path, x_common[final])
    np.save(rq4_aug_path, x_common_aug[final])
    print(f"{int(final.sum())}")

    return {
        "nn_moeva_best": f"{models_dir}/nn_moeva_best.msgpack",
        "nn_augmented_moeva_best": f"{models_dir}/nn_augmented_moeva_best.msgpack",
        "x_candidates_rq4_best": rq4_path,
        "x_candidates_rq4_augmented_best": rq4_aug_path,
    }


if __name__ == "__main__":
    run(parse_config())
