"""Full experiment suite: RQ1-RQ4 + SM1 (parity: ``run_all.sh``).

Every run is config-hash idempotent, so re-invoking after a crash resumes
where it stopped — the reference's recovery model (SURVEY.md §5).

Usage::

    python -m moeva2_ijcai22_replication_tpu.experiments.run_all [config_dir]
"""

from __future__ import annotations

import logging
import sys

from ..utils.config import load_config_file
from . import common, moeva, rq

logger = logging.getLogger(__name__)

RQ_GRIDS = [
    "rq1.lcld.yaml",
    "rq1.botnet.yaml",
    "rq2.lcld.yaml",
    "rq2.botnet.yaml",
    "rq3.lcld.yaml",
    "rq3.botnet.yaml",
]
RQ4_CONFIGS = ["rq4.lcld.moeva.yaml", "rq4.lcld.moeva_augmented.yaml"]
SM1_GRIDS = [
    "sm1.1.lcld.yaml",
    "sm1.2.lcld.yaml",
    "sm1.1.botnet.yaml",
    "sm1.2.botnet.yaml",
]


def run(config_dir: str = "./config") -> None:
    for grid in RQ_GRIDS:
        logger.info("=== grid %s", grid)
        rq.run(load_config_file(f"{config_dir}/{grid}"))
    for cfg in RQ4_CONFIGS:
        logger.info("=== rq4 %s", cfg)
        moeva.run(load_config_file(f"{config_dir}/{cfg}"))
    for grid in SM1_GRIDS:
        logger.info("=== grid %s", grid)
        rq.run(load_config_file(f"{config_dir}/{grid}"))
    # the artifact/engine caches are process-wide, so the whole suite shares
    # loads and executables ACROSS grids too — surface the aggregate once
    logger.info(
        "suite caches: artifacts %s, engines %s",
        common.ARTIFACTS.stats(),
        common.ENGINES.stats(),
    )


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    run(sys.argv[1] if len(sys.argv) > 1 else "./config")
