from .mlp import MLP, lcld_mlp, botnet_mlp, forward_logits, predict_proba
from .scalers import MinMaxParams, from_sklearn_minmax, load_joblib_scaler
from .io import (
    load_classifier,
    save_classifier,
    save_params,
    load_params,
    save_orbax,
    load_orbax,
)

__all__ = [
    "MLP",
    "lcld_mlp",
    "botnet_mlp",
    "forward_logits",
    "predict_proba",
    "MinMaxParams",
    "from_sklearn_minmax",
    "load_joblib_scaler",
    "load_classifier",
    "save_classifier",
    "save_params",
    "load_params",
    "save_orbax",
    "load_orbax",
]
