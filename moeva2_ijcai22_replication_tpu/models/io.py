"""Model artifact IO: Keras SavedModel import, Flax param (de)serialisation.

The reference persists surrogates as Keras SavedModel directories
(``models/<project>/*.model``, loaded by ``src/utils/in_out.py:111-127``).
To attack those exact committed models from JAX, we import their Dense
kernels/biases into Flax params; topology is inferred from kernel shapes.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any

import jax.numpy as jnp
import numpy as np

from .mlp import MLP, forward_logits, predict_proba


@dataclass
class Surrogate:
    """A classifier = Flax module + params; behaves like the reference's
    duck-typed ``Classifier`` wrapper (``moeva2/classifier.py:4-41``)."""

    model: MLP
    params: Any

    def logits(self, x: jnp.ndarray) -> jnp.ndarray:
        return forward_logits(self.model, self.params, x)

    def predict_proba(self, x: jnp.ndarray) -> jnp.ndarray:
        # Sigmoid-head (1-column) outputs expand to 2 columns, mirroring the
        # reference's duck-typed wrapper (classifier.py:27-28).
        probs = predict_proba(self.model, self.params, x)
        if probs.shape[-1] == 1:
            probs = jnp.concatenate([1.0 - probs, probs], axis=-1)
        return probs


def _dense_stack_from_savedmodel(path: str):
    """Extract ordered (kernel, bias) pairs from a Keras SavedModel dir."""
    import tensorflow as tf

    loaded = tf.saved_model.load(path)
    kernels, biases = [], []
    for v in loaded.variables:
        arr = v.numpy()
        if v.name.endswith("kernel:0"):
            kernels.append(arr)
        elif v.name.endswith("bias:0"):
            biases.append(arr)
    if not kernels or len(kernels) != len(biases):
        raise ValueError(f"Could not extract dense stack from {path}")
    # Order by connectivity: input dim of layer k equals output dim of k-1.
    ordered = [kernels.pop(0)]
    ordered_b = [biases.pop(0)]
    while kernels:
        out_dim = ordered[-1].shape[1]
        for i, k in enumerate(kernels):
            if k.shape[0] == out_dim:
                ordered.append(kernels.pop(i))
                ordered_b.append(biases.pop(i))
                break
        else:
            raise ValueError("Dense layers do not chain; cannot infer topology")
    return ordered, ordered_b


def flax_params_from_dense_stack(kernels, biases):
    return {
        "params": {
            f"Dense_{i}": {"kernel": jnp.asarray(k), "bias": jnp.asarray(b)}
            for i, (k, b) in enumerate(zip(kernels, biases))
        }
    }


def load_keras_model(path: str) -> Surrogate:
    kernels, biases = _dense_stack_from_savedmodel(path)
    hidden = tuple(k.shape[1] for k in kernels[:-1])
    model = MLP(hidden=hidden, n_classes=kernels[-1].shape[1])
    return Surrogate(model=model, params=flax_params_from_dense_stack(kernels, biases))


def load_classifier(path: str) -> Surrogate:
    """Dispatch on artifact type (parity: ``in_out.load_model``)."""
    if path.rstrip("/").endswith(".orbax"):
        return load_orbax(path)
    if path.endswith(".model") or os.path.isdir(path):
        return load_keras_model(path)
    if path.endswith((".msgpack", ".flax")):
        return load_params(path)
    raise ValueError(f"Unknown model artifact: {path}")


def save_classifier(surrogate: Surrogate, path: str) -> None:
    """Save-side counterpart of :func:`load_classifier`: dispatch on the
    same suffix convention so a memoized artifact always reloads with the
    format it was written in (``.orbax`` -> orbax directory, anything else
    -> flax msgpack)."""
    if path.rstrip("/").endswith(".orbax"):
        save_orbax(surrogate, path)
    else:
        save_params(surrogate, path)


def _topology_meta(surrogate: Surrogate) -> np.ndarray:
    """Topology header shared by every params format: hidden sizes then
    n_classes, one int64 vector."""
    return np.array(
        list(surrogate.model.hidden) + [surrogate.model.n_classes], dtype=np.int64
    )


def save_orbax(surrogate: Surrogate, path: str) -> None:
    """Orbax checkpoint of the surrogate (SURVEY §5's suggested TPU-native
    model format; directory path, conventionally ``*.orbax``).

    Same content as :func:`save_params` (topology meta + params pytree) in
    the ecosystem-standard format — multi-host-safe, shard-aware, and
    readable by any orbax consumer without this package.
    """
    import orbax.checkpoint as ocp

    meta = _topology_meta(surrogate)
    # StandardCheckpointer saves asynchronously: the context manager joins
    # the background write before returning, so the artifact is durable
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            os.path.abspath(path),
            {"meta": meta, "params": surrogate.params},
            force=True,
        )


def load_orbax(path: str) -> Surrogate:
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        raw = ckptr.restore(os.path.abspath(path))
    meta = np.asarray(raw["meta"])
    hidden, n_classes = tuple(int(v) for v in meta[:-1]), int(meta[-1])
    return Surrogate(model=MLP(hidden=hidden, n_classes=n_classes), params=raw["params"])


def save_params(surrogate: Surrogate, path: str) -> None:
    from flax import serialization

    meta = _topology_meta(surrogate)
    with open(path, "wb") as f:
        np.save(f, meta, allow_pickle=False)
        f.write(serialization.to_bytes(surrogate.params))


def load_params(path: str) -> Surrogate:
    from flax import serialization

    with open(path, "rb") as f:
        meta = np.load(f)
        hidden, n_classes = tuple(int(v) for v in meta[:-1]), int(meta[-1])
        model = MLP(hidden=hidden, n_classes=n_classes)
        raw = f.read()
    template = _empty_params_like(model)
    params = serialization.from_bytes(template, raw)
    return Surrogate(model=model, params=params)


def _empty_params_like(model: MLP):
    # from_bytes needs a matching tree structure; leaf shapes come from bytes.
    names = [f"Dense_{i}" for i in range(len(model.hidden) + 1)]
    return {"params": {n: {"kernel": jnp.zeros(()), "bias": jnp.zeros(())} for n in names}}
