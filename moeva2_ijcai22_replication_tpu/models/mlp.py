"""Flax surrogate classifiers.

The reference's surrogates are tiny Keras Sequential MLPs
(``/root/reference/src/experiments/lcld/model.py:9-20``: 64-32-16-2 relu+softmax;
``botnet/model.py:9-24``: 64-64-32-2). Here they are Flax modules whose forward
pass is a plain function of (params, x) — freely jit/vmap/grad-able and
shardable. Probabilities come from a softmax head; use ``forward_logits`` in
losses for numerical stability.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class MLP(nn.Module):
    """Dense relu stack with a linear (logit) head."""

    hidden: Sequence[int]
    n_classes: int = 2

    @nn.compact
    def __call__(self, x):
        for width in self.hidden:
            x = nn.relu(nn.Dense(width)(x))
        return nn.Dense(self.n_classes)(x)


def lcld_mlp() -> MLP:
    return MLP(hidden=(64, 32, 16))


def botnet_mlp() -> MLP:
    return MLP(hidden=(64, 64, 32))


def forward_logits(model: MLP, params, x: jnp.ndarray) -> jnp.ndarray:
    return model.apply(params, x)


def predict_proba(model: MLP, params, x: jnp.ndarray) -> jnp.ndarray:
    logits = forward_logits(model, params, x)
    if logits.shape[-1] == 1:  # sigmoid head
        return jax.nn.sigmoid(logits)
    return jax.nn.softmax(logits, axis=-1)


def init_params(model: MLP, n_features: int, seed: int = 0):
    return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, n_features)))
