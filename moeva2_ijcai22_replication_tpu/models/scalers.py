"""MinMax feature scaling as jittable parameter structs.

The reference scales classifier inputs with fitted sklearn ``MinMaxScaler``
objects persisted as ``scaler.joblib`` (``/root/reference/src/experiments/
lcld/01_train_robust.py:50-66``). We represent a fitted scaler as a small
pytree so transforms run in-graph on device, and provide importers from
sklearn objects / joblib files.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class MinMaxParams(NamedTuple):
    """Constants are kept as numpy float64 and embedded at trace time, so the
    same scaler is exact under the f64 post-hoc evaluator and compact f32
    inside device attack loops (conversion follows the active x64 mode)."""

    scale: np.ndarray  # multiply
    min_: np.ndarray  # then add  (sklearn's X * scale_ + min_)

    def transform(self, x: jnp.ndarray) -> jnp.ndarray:
        return x * self.scale + self.min_

    def inverse(self, x: jnp.ndarray) -> jnp.ndarray:
        return (x - self.min_) / self.scale


def fit_minmax(x_min: np.ndarray, x_max: np.ndarray) -> MinMaxParams:
    """Fit to explicit per-feature bounds (sklearn zero-range semantics)."""
    rng = np.asarray(x_max, dtype=np.float64) - np.asarray(x_min, dtype=np.float64)
    scale = 1.0 / np.where(rng == 0, 1.0, rng)
    return MinMaxParams(
        scale=scale, min_=-np.asarray(x_min, dtype=np.float64) * scale
    )


def from_sklearn_minmax(scaler) -> MinMaxParams:
    return MinMaxParams(
        scale=np.asarray(scaler.scale_, dtype=np.float64),
        min_=np.asarray(scaler.min_, dtype=np.float64),
    )


def load_joblib_scaler(path: str) -> MinMaxParams:
    import joblib

    return from_sklearn_minmax(joblib.load(path))
