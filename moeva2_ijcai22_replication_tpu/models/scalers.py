"""MinMax feature scaling as jittable parameter structs.

The reference scales classifier inputs with fitted sklearn ``MinMaxScaler``
objects persisted as ``scaler.joblib`` (``/root/reference/src/experiments/
lcld/01_train_robust.py:50-66``). We represent a fitted scaler as a small
pytree so transforms run in-graph on device, and provide importers from
sklearn objects / joblib files.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class MinMaxParams(NamedTuple):
    scale: jnp.ndarray  # multiply
    min_: jnp.ndarray  # then add  (sklearn's X * scale_ + min_)

    def transform(self, x: jnp.ndarray) -> jnp.ndarray:
        return x * self.scale + self.min_

    def inverse(self, x: jnp.ndarray) -> jnp.ndarray:
        return (x - self.min_) / self.scale


def fit_minmax(x_min: np.ndarray, x_max: np.ndarray) -> MinMaxParams:
    """Fit to explicit per-feature bounds (sklearn zero-range semantics)."""
    rng = np.asarray(x_max, dtype=float) - np.asarray(x_min, dtype=float)
    scale = 1.0 / np.where(rng == 0, 1.0, rng)
    return MinMaxParams(
        scale=jnp.asarray(scale), min_=jnp.asarray(-np.asarray(x_min) * scale)
    )


def from_sklearn_minmax(scaler) -> MinMaxParams:
    return MinMaxParams(
        scale=jnp.asarray(np.asarray(scaler.scale_)),
        min_=jnp.asarray(np.asarray(scaler.min_)),
    )


def load_joblib_scaler(path: str) -> MinMaxParams:
    import joblib

    return from_sklearn_minmax(joblib.load(path))
