"""Flax training loop for the surrogate MLPs.

Capability parity with the reference's Keras training
(``/root/reference/src/experiments/lcld/model.py:23-42``: Adam, categorical
cross-entropy, EarlyStopping(patience=25) on val loss, class weights) —
re-designed as a jitted optax train step whose batch axis shards over a
device mesh (data parallel; XLA inserts the gradient psums).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .io import Surrogate
from .mlp import MLP


def ce_loss(model: MLP, params, x, y, class_weight=None, sample_weight=None):
    """Weighted softmax cross-entropy; ``y`` is integer labels.

    ``sample_weight`` gives the weighted mean Σwℓ/Σw (Keras semantics), so
    zero-weight padding rows contribute nothing — mesh batches pad with
    weight 0 instead of double-counting duplicated samples.
    """
    logits = model.apply(params, x)
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, y)
    if class_weight is not None:
        losses = losses * class_weight[y]
    if sample_weight is None:
        return losses.mean()
    w = sample_weight.astype(losses.dtype)
    return (losses * w).sum() / jnp.maximum(w.sum(), 1e-12)


def make_train_step(model: MLP, tx: optax.GradientTransformation, class_weight=None):
    """One SGD step: pure function of (params, opt_state, batch, weights)."""

    def step(params, opt_state, x, y, w=None):
        loss, grads = jax.value_and_grad(
            lambda p: ce_loss(model, p, x, y, class_weight, w)
        )(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return step


@dataclass
class FitResult:
    surrogate: Surrogate
    history: list  # [(epoch, train_loss, val_loss)]
    best_val_loss: float


def fit_mlp(
    model: MLP,
    x_train: np.ndarray,
    y_train: np.ndarray,
    x_val: np.ndarray | None = None,
    y_val: np.ndarray | None = None,
    epochs: int = 100,
    batch_size: int = 512,
    learning_rate: float = 1e-3,
    patience: int = 25,
    class_weight: dict | None = None,
    seed: int = 0,
    mesh: jax.sharding.Mesh | None = None,
    batch_axis: str = "dp",
    verbose: bool = False,
) -> FitResult:
    """Train with early stopping on validation loss (Keras-fit parity).

    With ``mesh``, batches are sharded over ``batch_axis`` and parameters
    replicated — the jitted step then runs data-parallel with XLA-inserted
    gradient reductions.
    """
    n, d = x_train.shape
    params = model.init(jax.random.PRNGKey(seed), jnp.zeros((1, d)))
    tx = optax.adam(learning_rate)
    opt_state = tx.init(params)

    cw = None
    if class_weight is not None:
        n_classes = max(class_weight) + 1
        cw = jnp.asarray([class_weight.get(i, 1.0) for i in range(n_classes)])

    step = jax.jit(make_train_step(model, tx, cw))
    val_loss_fn = jax.jit(lambda p, x, y: ce_loss(model, p, x, y, cw))

    shard = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        shard = NamedSharding(mesh, P(batch_axis))
        repl = NamedSharding(mesh, P())
        params = jax.device_put(params, repl)
        opt_state = jax.device_put(opt_state, repl)

    rng = np.random.default_rng(seed)
    steps_per_epoch = max(1, -(-n // batch_size))  # include the partial batch
    best_val = np.inf
    best_params = params
    since_best = 0
    history = []

    for epoch in range(epochs):
        perm = rng.permutation(n)
        epoch_loss = 0.0
        for i in range(steps_per_epoch):
            idx = perm[i * batch_size : (i + 1) * batch_size]
            w = np.ones(len(idx), dtype=np.float32)
            # Pad short/uneven batches with weight-0 rows so every sample
            # contributes exactly once per epoch (batch shapes stay static
            # for the jit cache; mesh sharding stays even).
            target = batch_size
            if mesh is not None:
                target += (-target) % mesh.size
            pad = target - len(idx)
            if pad:
                idx = np.concatenate([idx, np.zeros(pad, dtype=idx.dtype)])
                w = np.concatenate([w, np.zeros(pad, dtype=np.float32)])
            xb = jnp.asarray(x_train[idx])
            yb = jnp.asarray(y_train[idx])
            wb = jnp.asarray(w)
            if shard is not None:
                xb = jax.device_put(xb, shard)
                yb = jax.device_put(yb, shard)
                wb = jax.device_put(wb, shard)
            params, opt_state, loss = step(params, opt_state, xb, yb, wb)
            epoch_loss += float(loss)
        epoch_loss /= steps_per_epoch

        if x_val is not None:
            vl = float(val_loss_fn(params, jnp.asarray(x_val), jnp.asarray(y_val)))
        else:
            vl = epoch_loss
        history.append((epoch, epoch_loss, vl))
        if verbose:
            print(f"epoch {epoch}: train {epoch_loss:.4f} val {vl:.4f}")

        if vl < best_val:
            best_val, best_params, since_best = vl, params, 0
        else:
            since_best += 1
            if since_best >= patience:
                break

    return FitResult(
        surrogate=Surrogate(model=model, params=jax.device_get(best_params)),
        history=history,
        best_val_loss=float(best_val),
    )


def auroc(probs_pos: np.ndarray, y: np.ndarray) -> float:
    """AUROC via the rank statistic (the reference prints Keras' AUC)."""
    # midranks for ties
    _, inv, counts = np.unique(probs_pos, return_inverse=True, return_counts=True)
    cum = np.cumsum(counts)
    start = cum - counts
    ranks = ((start + cum + 1) / 2.0)[inv]
    pos = y == 1
    n_pos, n_neg = pos.sum(), (~pos).sum()
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))
