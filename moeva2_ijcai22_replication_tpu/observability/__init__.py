"""Unified tracing & telemetry subsystem.

One id-correlated event stream for all three execution paths — the serving
request lifecycle, the grid pipeline, and the MoEvA engine's early-exit
gates — recorded into a bounded ring plus an optional append-only JSONL
sink (config ``system.trace_log``), with exporters to Chrome/Perfetto
trace-event JSON (``observability.export`` / ``tools/trace_export.py``)
and Prometheus text exposition (``observability.prom`` behind
``/metrics?format=prom``).

Contract: cheap counters/gauges are always on; spans/events are opt-in
(``TraceRecorder.spans_enabled``), and with them off every instrumented
path is a no-op — zero extra device dispatches, zero extra compiles
(pinned by the tier-1 overhead smoke). The executable cost ledger
(``observability.ledger``) rides the same contract from the other side:
it observes every compile (identity, XLA cost/memory analysis, wall-clock)
at the AOT capture point the engines already dispatch through, so
ledger-on and ledger-off runs are bit-identical. ``PhaseTimer`` and
``ServiceMetrics`` (``utils/observability.py``) are thin facades over this
recorder, so grid reports, bench records, and serving metadata share one
event stream; ``records.telemetry_block`` / ``records.validate_record``
keep every committed record carrying the shared ``execution`` +
``telemetry`` schema.
"""

from .aotcache import (
    AOT_CACHE,
    AotExecutableCache,
    backend_fingerprint,
    configure_aot_cache,
    get_aot_cache,
)
from .capacity import CapacityModel
from .coldstart import (
    COLD_KEYS,
    COLDSTART,
    ColdStartLedger,
    configure_coldstart,
    get_coldstart,
    validate_cold,
)
from .fleetrace import (
    TRACE_HEADER,
    TRACE_VERSION,
    clock_offset,
    format_trace_context,
    merge_fleet_events,
    merge_fleet_traces,
    parse_trace_context,
)
from .flightrec import FlightRecorder, load_flight_dump
from .gaps import (
    GAPS,
    GAPS_KEYS,
    DispatchWindow,
    GapTracker,
    configure_gap_tracker,
    emit_window_trace,
    get_gap_tracker,
    join_gaps_to_spans,
    spans_from_recorder,
    spans_from_trace,
    validate_gaps,
)
from .incidents import (
    INCIDENT_KEYS,
    INCIDENT_KINDS,
    IncidentDetector,
    incidents_block,
    validate_incidents,
)
from .ledger import (
    LEDGER,
    CostLedger,
    LedgeredJit,
    LedgerEntry,
    configure_ledger,
    current_ledger_context,
    get_ledger,
    ledger_context,
)
from .mesh import (
    HOT_LOOP_PRODUCERS,
    MESH,
    MESH_KEYS,
    MeshCapture,
    configure_mesh_capture,
    get_mesh_capture,
    mesh_block,
    mesh_snapshot,
    probe_collectives,
    probe_shardings,
    validate_mesh,
)
from .quality import (
    DEFAULT_INTERIOR_BUDGETS,
    QUALITY_KEYS,
    interior_summary,
    merge_chunk_quality,
    quality_block,
    sample_from_per_state,
    trim_quality,
    validate_quality,
)
from .records import (
    REQUIRED_RECORD_KEYS,
    build_identity,
    telemetry_block,
    validate_record,
)
from .slo import (
    DEFAULT_LATENCY_BUCKETS,
    SHED_CAUSES,
    SLO_KEYS,
    STAGES,
    Histogram,
    SloTracker,
    detect_knee,
    merge_histogram_snapshots,
    merge_slo_snapshots,
    slo_block,
    validate_slo,
)
from .trace import (
    Trace,
    TraceRecorder,
    all_device_memory_stats,
    current_trace,
    default_recorder,
    device_memory_stats,
    maybe_span,
    recorder_for,
    use_trace,
)

__all__ = [
    "AOT_CACHE",
    "AotExecutableCache",
    "COLD_KEYS",
    "COLDSTART",
    "DEFAULT_INTERIOR_BUDGETS",
    "DEFAULT_LATENCY_BUCKETS",
    "GAPS",
    "GAPS_KEYS",
    "HOT_LOOP_PRODUCERS",
    "INCIDENT_KEYS",
    "INCIDENT_KINDS",
    "LEDGER",
    "MESH",
    "MESH_KEYS",
    "QUALITY_KEYS",
    "REQUIRED_RECORD_KEYS",
    "SHED_CAUSES",
    "SLO_KEYS",
    "STAGES",
    "TRACE_HEADER",
    "TRACE_VERSION",
    "CapacityModel",
    "ColdStartLedger",
    "CostLedger",
    "DispatchWindow",
    "FlightRecorder",
    "GapTracker",
    "Histogram",
    "IncidentDetector",
    "LedgerEntry",
    "LedgeredJit",
    "MeshCapture",
    "SloTracker",
    "Trace",
    "TraceRecorder",
    "all_device_memory_stats",
    "backend_fingerprint",
    "build_identity",
    "clock_offset",
    "configure_aot_cache",
    "configure_coldstart",
    "configure_gap_tracker",
    "configure_ledger",
    "configure_mesh_capture",
    "current_ledger_context",
    "current_trace",
    "default_recorder",
    "detect_knee",
    "device_memory_stats",
    "emit_window_trace",
    "format_trace_context",
    "get_aot_cache",
    "get_coldstart",
    "get_gap_tracker",
    "get_ledger",
    "get_mesh_capture",
    "incidents_block",
    "interior_summary",
    "join_gaps_to_spans",
    "ledger_context",
    "load_flight_dump",
    "maybe_span",
    "merge_chunk_quality",
    "merge_fleet_events",
    "merge_fleet_traces",
    "merge_histogram_snapshots",
    "merge_slo_snapshots",
    "mesh_block",
    "mesh_snapshot",
    "parse_trace_context",
    "probe_collectives",
    "probe_shardings",
    "quality_block",
    "recorder_for",
    "sample_from_per_state",
    "slo_block",
    "spans_from_recorder",
    "spans_from_trace",
    "telemetry_block",
    "trim_quality",
    "use_trace",
    "validate_cold",
    "validate_gaps",
    "validate_incidents",
    "validate_mesh",
    "validate_quality",
    "validate_record",
]
