"""Persistent AOT executable cache: skip trace+lower+compile entirely.

The jax persistent compilation cache (``setup_jax_cache``) only amortises
the *XLA compile* — every new process still pays tracing and lowering for
all ~400 entries (the PR-9 cold ledger measured trace_lower as a first-
class cold phase), and the cache lookup itself happens inside
``lower().compile()``. This module caches one level higher: the
**serialized executable** (``jax.experimental.serialize_executable``) of
every :class:`~.ledger.LedgeredJit` compile, keyed by the program's full
dispatch identity, so a warm process deserializes the finished binary and
never traces, lowers, or compiles at all.

Key scheme (sha256 over a canonical JSON string):

- producer (``pgd_attack``, ``moeva_segment``, …) and the LedgeredJit
  compile-time identity (engine cache key, rows, scan length, loss
  strategy, mesh description) — the same identity the cost ledger
  records, minus the *ambient* :func:`~.ledger.ledger_context` attrs
  (batch composition varies per dispatch and must not fragment the key);
- the executable-cache key itself: static argument values, sorted
  kwargs, the dynamic arguments' pytree structure, and every leaf's
  (shape, dtype, weak_type, sharding) signature.

Fingerprint scheme (stored INSIDE each entry, checked on load — a
foreign file must be *found and rejected*, with a counted event, not
silently never looked up): jax version, backend name, device kind, PJRT
platform version, and visible device count. Any mismatch invalidates the
entry (stale jax upgrade, foreign backend, different mesh topology) and
the compile falls through to the normal path, overwriting the entry.

Degradation contract (the satellite): corrupt, truncated, stale, or
foreign cache files log a counted recorder event
(``aot_cache_load_failures``, with a per-reason split in
:meth:`AotExecutableCache.state`, surfaced on /healthz
``build.jax_cache.aot``) and fall back to a fresh compile — the cache
must never take an attack down. Stores are equally best-effort (a full
disk degrades to plain compiles) and atomic (tmp + rename), so a reader
never sees a half-written entry.

Disabled by default: an unconfigured cache has no directory and both
:meth:`load` and :meth:`store` are no-ops. ``setup_jax_cache`` wires
config ``system.aot_cache`` (default: ``<jax_cache_dir>/aot`` whenever
the jax persistent cache is on; ``""`` disables).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time

#: envelope schema version — bump on any layout change so old entries
#: reject cleanly (counted as ``stale``) instead of unpickling garbage.
ENVELOPE_VERSION = 1


def backend_fingerprint() -> dict:
    """Identity of the compilation target an executable is only valid
    for. Serialized executables embed device ids and backend-specific
    binary code: loading one on a different jax/backend/topology is
    undefined, so every field here gates the load. The ``package`` and
    ``code`` fields make invalidation deliberately COARSE across
    commits: constraint formulas are *code traced into the program* (not
    runtime arguments like the model weights), so an executable is only
    trusted within the checkout that built it — serving replicas, grid
    reruns, and repeated bench invocations of one deployment share a
    commit and still amortise fully."""
    import jax

    from .. import __version__
    from .records import git_describe

    try:
        dev = jax.devices()[0]
        platform_version = getattr(dev.client, "platform_version", None)
        device_kind = getattr(dev, "device_kind", None)
    except Exception:
        platform_version = device_kind = None
    code = git_describe()
    if code is None or code.endswith("-dirty"):
        # `git describe --dirty` cannot distinguish two DIFFERENT dirty
        # states of one commit — a dirty-tree edit to a constraint
        # formula would otherwise reuse a stale executable with the old
        # formula baked in (the jax cache keys on traced HLO and is
        # immune; this tier keys above tracing, so it must carry its own
        # source identity). Stamp the package source instead: sorted
        # (path, mtime_ns, size) of every .py file — the ArtifactCache
        # validity discipline, no file reads, ~1 ms once per process.
        code = f"{code}+{_source_stamp()}"
    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        "platform_version": platform_version,
        "device_count": jax.device_count(),
        "package": __version__,
        "code": code,
    }


def _source_stamp() -> str:
    """Cheap content-identity of the package source tree: sha256 over
    the sorted (relative path, mtime_ns, size) of every ``.py`` file.
    Conservative by design — a touched-but-identical file invalidates
    (a spurious recompile), an edited file always invalidates (never a
    stale executable)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rows = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in filenames:
            if not f.endswith(".py"):
                continue
            p = os.path.join(dirpath, f)
            try:
                st = os.stat(p)
            except OSError:
                continue
            rows.append((os.path.relpath(p, root), st.st_mtime_ns, st.st_size))
    rows.sort()
    return hashlib.sha256(repr(rows).encode()).hexdigest()[:16]


class AotExecutableCache:
    """Disk-backed serialized-executable store for :class:`LedgeredJit`."""

    def __init__(self, path: str | None = None):
        self._lock = threading.Lock()
        self.path = path
        self._fingerprint: dict | None = None
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.load_failures = 0
        self.store_failures = 0
        #: load failures by reason: corrupt / fingerprint / deserialize
        self.failure_reasons: dict[str, int] = {}
        self.last_load_s = 0.0

    @property
    def enabled(self) -> bool:
        return bool(self.path)

    def configure(self, path: str | None) -> None:
        """Point the cache at ``path`` (None/"" disables). Counters are
        process facts and survive reconfiguration."""
        with self._lock:
            self.path = path or None
            self._fingerprint = None  # re-read lazily against the new dir

    def fingerprint(self) -> dict:
        if self._fingerprint is None:
            self._fingerprint = backend_fingerprint()
        return self._fingerprint

    # -- keying --------------------------------------------------------------
    @staticmethod
    def cache_key(producer: str, identity: dict, exec_key) -> str:
        """Stable cross-process key: producer + compile identity + the
        LedgeredJit executable-cache key (statics, kwargs, treedef, leaf
        avals). Everything is rendered through a canonical JSON string
        (``default=repr`` for treedefs and other non-JSON leaves)."""
        static, kwargs, treedef, leaves = exec_key
        # the engine-cache slot id (identity["cache_key"]) hashes id()s
        # of in-process artifact objects — stable within a process, noise
        # across processes — so it must not fragment a DISK key. The
        # stable parts of the identity (engine family, domain/constraint
        # class, knobs, mesh, rows/length) plus the full aval signature
        # carry the discrimination; model WEIGHTS are runtime arguments,
        # so weight-independent executable sharing is correct by
        # construction.
        identity = {k: v for k, v in identity.items() if k != "cache_key"}
        doc = {
            "producer": producer,
            "identity": identity,
            "static": repr(static),
            "kwargs": repr(kwargs),
            "treedef": str(treedef),
            "leaves": repr(leaves),
        }
        blob = json.dumps(doc, sort_keys=True, default=repr)
        return hashlib.sha256(blob.encode()).hexdigest()

    def _entry_path(self, key: str) -> str:
        return os.path.join(self.path, f"{key}.aotx")

    def _count_failure(self, reason: str, path: str | None = None) -> None:
        with self._lock:
            self.load_failures += 1
            self.failure_reasons[reason] = (
                self.failure_reasons.get(reason, 0) + 1
            )
        # the satellite contract: a swallowed deserialization failure must
        # still be a counted, scrapeable event (PR-9 setup-failure style)
        try:
            from .trace import default_recorder

            default_recorder().count("aot_cache_load_failures")
        except Exception:
            pass
        # self-healing: a rejected entry stays rejected (corrupt bytes,
        # stale fingerprint, undeserializable blob), and the recompile
        # that follows may legitimately skip the re-store (a jax-cache
        # hit) — without the discard every future process would pay the
        # same counted failure forever
        if path is not None:
            try:
                os.remove(path)
            except OSError:
                pass

    # -- load/store ----------------------------------------------------------
    def load(self, key: str):
        """Deserialized ``jax.stages.Compiled`` for ``key``, or None on a
        miss. Every failure mode — unreadable file, corrupt pickle, wrong
        envelope version, fingerprint mismatch, deserialization error —
        counts a ``aot_cache_load_failures`` event and returns None (the
        caller compiles as if the cache did not exist)."""
        if not self.enabled:
            return None
        path = self._entry_path(key)
        t0 = time.perf_counter()
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
            return None
        except OSError:
            self._count_failure("corrupt", path)
            return None
        try:
            env = pickle.loads(raw)
            if (
                not isinstance(env, dict)
                or env.get("v") != ENVELOPE_VERSION
                or not isinstance(env.get("payload"), bytes)
            ):
                raise ValueError("bad envelope")
        except Exception:
            self._count_failure("corrupt", path)
            return None
        if env.get("fingerprint") != self.fingerprint():
            # stale jax / foreign backend / different topology: found and
            # honestly rejected — the recompile below overwrites the entry
            self._count_failure("fingerprint", path)
            return None
        try:
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                env["payload"], env["in_tree"], env["out_tree"]
            )
        except Exception:
            self._count_failure("deserialize", path)
            return None
        with self._lock:
            self.hits += 1
            self.last_load_s = time.perf_counter() - t0
        return compiled

    def store(self, key: str, compiled, *, producer: str | None = None) -> bool:
        """Serialize ``compiled`` under ``key`` (atomic tmp+rename);
        best-effort — an unserializable executable or a full disk counts
        a store failure and returns False, never raises."""
        if not self.enabled:
            return False
        try:
            from jax.experimental import serialize_executable

            payload, in_tree, out_tree = serialize_executable.serialize(
                compiled
            )
            env = {
                "v": ENVELOPE_VERSION,
                "fingerprint": self.fingerprint(),
                "producer": producer,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
            blob = pickle.dumps(env)
            os.makedirs(self.path, exist_ok=True)
            tmp = self._entry_path(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                fh.write(blob)
            os.replace(tmp, self._entry_path(key))
        except Exception:
            with self._lock:
                self.store_failures += 1
            return False
        with self._lock:
            self.stores += 1
        return True

    # -- introspection -------------------------------------------------------
    def entries(self) -> int | None:
        if not self.enabled:
            return None
        try:
            return sum(
                1 for e in os.scandir(self.path) if e.name.endswith(".aotx")
            )
        except FileNotFoundError:
            return 0
        except OSError:
            return None

    def state(self) -> dict:
        """The /healthz ``build.jax_cache.aot`` view (also embedded in the
        cold ledger's ``persistent_cache`` block): dir, entry count, and
        the hit/store/failure counters with the per-reason failure split."""
        with self._lock:
            return {
                "dir": self.path,
                "enabled": self.enabled,
                "entries": self.entries(),
                "hits": self.hits,
                "misses": self.misses,
                "stores": self.stores,
                "load_failures": self.load_failures,
                "load_failure_reasons": dict(self.failure_reasons),
                "store_failures": self.store_failures,
            }

    def reset(self) -> None:
        """Drop counters and detach the directory (tests only)."""
        with self._lock:
            self.path = None
            self._fingerprint = None
            self.hits = self.misses = self.stores = 0
            self.load_failures = self.store_failures = 0
            self.failure_reasons = {}
            self.last_load_s = 0.0


#: THE process cache — LedgeredJit consults it the way it consults the
#: process CostLedger; unconfigured (no dir) it is a pair of no-ops.
AOT_CACHE = AotExecutableCache()


def get_aot_cache() -> AotExecutableCache:
    return AOT_CACHE


def configure_aot_cache(
    config: dict | None, default_dir: str | None = None
) -> AotExecutableCache:
    """Apply config ``system.aot_cache``: an explicit directory, ``""`` to
    disable, or absent → ``default_dir`` (``setup_jax_cache`` passes
    ``<jax_cache_dir>/aot`` so the serialized executables ride the same
    volume/symlink layout as the jax persistent cache)."""
    if os.environ.get("MOEVA2_AOT_CACHE_DISABLE"):
        # hermetic-test / CI escape: an AOT hit legitimately skips
        # tracing, which would make trace-count-based assertions depend
        # on what a PREVIOUS test session left on disk. Only this config
        # path honors the switch — tests driving the cache explicitly use
        # AotExecutableCache.configure directly.
        AOT_CACHE.configure(None)
        return AOT_CACHE
    path = (config or {}).get("system", {}).get("aot_cache", None)
    if path is None:
        path = default_dir
    AOT_CACHE.configure(path or None)
    return AOT_CACHE
