"""Ledger-backed capacity model: what a replica can sustain, per domain.

PR 5's cost ledger knows two numbers nothing else in the stack knows:
the XLA cost model's FLOPs per compiled executable (what a dispatch
*asks* the device for) and the attributed run seconds per executable
(what the device *delivered*). Joining them over the serving layer's
batch stream yields an honest capacity model, the missing half of
ROADMAP item 4's admission-control story:

- **predicted FLOPs per request** per traffic class — a class is
  ``(loss strategy, bucket, budget)`` within a domain, exactly the
  coordinates that select a compiled program — from the ledger entries
  of the executables each batch actually dispatched, divided by the
  requests that rode the batch;
- **achieved FLOP/s** — model FLOPs over attributed run seconds across
  the window (the roofline's achieved rate, aggregated per domain);
- **max sustainable QPS** = achieved FLOP/s / predicted FLOPs per
  request — the rate at which device time alone saturates. By
  construction this equals window requests / window run seconds; both
  factors are published so the formula (and its degradation when the
  cost model is absent) stays auditable;
- **utilization & headroom** — attributed device seconds over the
  window's wall span: how much of the replica the current offered load
  already consumes, and what fraction remains;
- **calibration error** — mean |predicted - actual| / actual run
  seconds per batch, where predicted = batch FLOPs / window achieved
  FLOP/s. Zero means FLOPs are a faithful time predictor across classes
  (admission control can price requests in FLOPs); large means classes
  sit at different roofline points (low arithmetic-intensity programs
  run memory-bound) and FLOPs alone under-prices some traffic — the
  caveat docs/DESIGN.md § SLO & capacity spells out.

Feeding is host-side only (the serving dispatch closures call
:meth:`CapacityModel.note_batch` with numbers they already computed for
the trace spans), windowed per domain (``serving.capacity_window``
batches) so the published capacity reflects recent traffic, not a cold
start's. Compile-bearing dispatches are excluded — their wall-clock is
compile, not capacity. No flops available (model-less backend) degrades
to ``basis: "run_seconds"``: max QPS stays (requests / run seconds),
prediction and calibration go None rather than wrong.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass


@dataclass
class _BatchObs:
    """One pure-run batch dispatch, as the capacity model sees it."""

    t: float  #: monotonic completion time (window wall-span basis)
    klass: str  #: traffic class: "{strategy}|b{bucket}|g{budget}"
    requests: int
    rows: int
    run_s: float
    flops: float | None  #: ledger model FLOPs for the dispatch set
    #: QoS class name -> requests that rode the batch (None pre-QoS)
    qos: dict | None = None


class CapacityModel:
    """Windowed per-domain capacity aggregation over serving batches."""

    def __init__(
        self,
        window: int = 256,
        clock=time.monotonic,
    ):
        self.window = int(window)
        self.clock = clock
        self._lock = threading.Lock()
        self._by_domain: dict[str, collections.deque] = {}

    @staticmethod
    def class_key(strategy, bucket, budget) -> str:
        return f"{strategy}|b{bucket}|g{budget}"

    # -- ingestion -----------------------------------------------------------
    def note_batch(
        self,
        domain: str,
        *,
        strategy,
        bucket,
        budget,
        requests: int,
        rows: int,
        run_s: float,
        flops: float | None,
        qos_classes: dict | None = None,
    ) -> None:
        """Fold one pure-run batch dispatch into the domain's window.
        Callers must not feed compile-bearing dispatches (their duration
        is compile wall-clock, not sustainable capacity). ``qos_classes``
        (optional) is the batch's QoS census — class name -> requests —
        surfaced per domain as ``by_qos_class``."""
        if run_s <= 0 or requests < 1:
            return
        obs = _BatchObs(
            t=self.clock(),
            klass=self.class_key(strategy, bucket, budget),
            requests=int(requests),
            rows=int(rows),
            run_s=float(run_s),
            flops=float(flops) if flops else None,
            qos=dict(qos_classes) if qos_classes else None,
        )
        with self._lock:
            dq = self._by_domain.get(domain)
            if dq is None:
                dq = self._by_domain[domain] = collections.deque(
                    maxlen=self.window
                )
            dq.append(obs)

    # -- admission hints -----------------------------------------------------
    def retry_after_s(
        self, queued_rows: int, *, floor_s: float = 0.001, cap_s: float = 30.0
    ) -> float | None:
        """Honest ``Retry-After`` for a queue-full rejection: the predicted
        seconds for ``queued_rows`` to drain at the windowed sustainable
        row rate (per-domain ``rows / run_s``, summed — the same window
        that backs ``max_sustainable_qps``). None when no capacity window
        is live yet (the caller falls back to its static hint); clamped to
        [``floor_s``, ``cap_s``] so a tiny backlog over a fast device never
        advertises a zero and a mispredicted window never advertises
        minutes."""
        with self._lock:
            per_domain = {d: list(dq) for d, dq in self._by_domain.items()}
        rows_per_s = 0.0
        for obs in per_domain.values():
            run_s = sum(o.run_s for o in obs)
            rows = sum(o.rows for o in obs)
            if run_s > 0 and rows > 0:
                rows_per_s += rows / run_s
        if rows_per_s <= 0:
            return None
        return min(max(float(queued_rows) / rows_per_s, floor_s), cap_s)

    # -- export --------------------------------------------------------------
    def domain_block(self, domain: str) -> dict | None:
        """The per-domain capacity block /healthz publishes."""
        with self._lock:
            dq = self._by_domain.get(domain)
            obs = list(dq) if dq else []
        if not obs:
            return None
        requests = sum(o.requests for o in obs)
        rows = sum(o.rows for o in obs)
        run_s = sum(o.run_s for o in obs)
        with_flops = [o for o in obs if o.flops is not None]
        flops_total = sum(o.flops for o in with_flops)
        run_s_flops = sum(o.run_s for o in with_flops)
        req_flops = sum(o.requests for o in with_flops)

        predicted_flops_per_request = (
            flops_total / req_flops if flops_total and req_flops else None
        )
        achieved_flops_s = (
            flops_total / run_s_flops if flops_total and run_s_flops > 0 else None
        )
        # max QPS: achieved FLOP/s over predicted FLOPs/request when the
        # cost model is present (algebraically requests/run_s over the
        # flops-bearing subset); the run-seconds rate otherwise
        if achieved_flops_s is not None and predicted_flops_per_request:
            max_qps = achieved_flops_s / predicted_flops_per_request
            basis = "ledger_flops"
        else:
            max_qps = requests / run_s
            basis = "run_seconds"

        # utilization: attributed device seconds over the window's wall
        # span — first dispatch START (its completion time minus its own
        # run) to last completion. One batch spans no wall time —
        # utilization needs a window, not a point.
        span = (obs[-1].t - obs[0].t) + obs[0].run_s
        utilization = min(run_s / span, 1.0) if len(obs) > 1 and span > 0 else None

        # calibration: does the FLOPs model predict where run time went?
        calibration = None
        if achieved_flops_s is not None:
            errs = []
            for o in with_flops:
                predicted_s = o.flops / achieved_flops_s
                errs.append(abs(predicted_s - o.run_s) / o.run_s)
            if errs:
                calibration = {
                    "mean_abs_rel_err": round(sum(errs) / len(errs), 4),
                    "max_abs_rel_err": round(max(errs), 4),
                    "n": len(errs),
                }

        per_class: dict = {}
        for o in obs:
            c = per_class.setdefault(
                o.klass,
                {"dispatches": 0, "requests": 0, "run_s": 0.0, "flops": 0.0,
                 "flops_known": 0, "requests_flops": 0},
            )
            c["dispatches"] += 1
            c["requests"] += o.requests
            c["run_s"] += o.run_s
            if o.flops is not None:
                c["flops"] += o.flops
                c["flops_known"] += 1
                c["requests_flops"] += o.requests
        for c in per_class.values():
            # denominator is the requests on flops-BEARING dispatches only
            # (mirroring the domain-level req_flops): a class mixing
            # flops-less observations in must not dilute the per-request
            # prediction admission control prices traffic with
            c["predicted_flops_per_request"] = (
                round(c["flops"] / c["requests_flops"], 1)
                if c["flops"] and c["requests_flops"]
                else None
            )
            c["mean_run_s"] = round(c["run_s"] / c["dispatches"], 6)
            c["run_s"] = round(c["run_s"], 6)
            del c["flops"], c["requests_flops"]

        # QoS census over the window: who the served capacity went to
        by_qos: dict = {}
        for o in obs:
            if o.qos:
                for name, n in o.qos.items():
                    q = by_qos.setdefault(
                        name, {"requests": 0, "batches": 0}
                    )
                    q["requests"] += int(n)
                    q["batches"] += 1

        return {
            "window_batches": len(obs),
            "window_limit": self.window,
            # freshness: seconds since the window's LAST batch completed
            # (this model's clock domain). A wedged replica keeps serving
            # its old capacity numbers on /healthz forever — the router
            # discounts any block whose age says the window no longer
            # describes current traffic, instead of routing into it.
            "age_s": round(self.clock() - obs[-1].t, 3),
            # wall span the window covers (first dispatch start to last
            # completion): age + span bound when the window's traffic ran
            "window_span_s": round(
                (obs[-1].t - obs[0].t) + obs[0].run_s, 3
            ),
            "requests": requests,
            "rows": rows,
            "run_s": round(run_s, 6),
            "basis": basis,
            "predicted_flops_per_request": (
                round(predicted_flops_per_request, 1)
                if predicted_flops_per_request
                else None
            ),
            "achieved_flops_s": (
                round(achieved_flops_s, 1) if achieved_flops_s else None
            ),
            "max_sustainable_qps": round(max_qps, 2),
            "utilization": (
                round(utilization, 4) if utilization is not None else None
            ),
            "headroom": (
                round(1.0 - utilization, 4) if utilization is not None else None
            ),
            "calibration": calibration,
            "per_class": per_class,
            **({"by_qos_class": by_qos} if by_qos else {}),
        }

    def snapshot(self) -> dict:
        """All domains' capacity blocks — the /healthz ``capacity`` key."""
        with self._lock:
            domains = list(self._by_domain)
        return {
            "window": self.window,
            "by_domain": {
                d: blk
                for d in sorted(domains)
                if (blk := self.domain_block(d)) is not None
            },
        }
