"""Startup-phase ledger: where a process's cold seconds actually go.

ROADMAP item 2 names cold start as a first-class perf sink (r05: 17.4 s
cold vs 10.5 s steady; 406 jax cache entries rebuilt per process) but the
observability stack so far reports cold wall-clock as ONE number
(``cold_s``). This module decomposes it — the prerequisite for spending
the optimisation budget (persistent AOT cache, warmup overlap) on the
right phase:

- **Phases** — a process-wide :class:`ColdStartLedger` accumulating named
  phase seconds: ``import`` (package import + process setup, measured
  from this module's import instant to the first ``setup_jax_cache``
  call — the one process-level hook every runner/bench/serving path
  already makes), ``artifact_build`` (ArtifactCache misses' builder
  wall-clock), ``trace_lower`` and ``xla_compile`` (the
  :class:`~.ledger.LedgeredJit` compile split), ``device_warmup``
  (explicit warmup dispatches the producers bracket), plus
  ``time_to_first_dispatch_s`` — module-import epoch to the first
  compiled-program dispatch.

- **Persistent-cache accounting** — every AOT compile is classified
  against ``setup_jax_cache``'s directory: ``hit`` (loaded from the
  persistent cache — jax's ``/jax/compilation_cache/cache_hits``
  monitoring event, registered when available), ``miss_stored`` (a real
  XLA compile whose entry landed in the cache dir — new files appeared),
  ``miss_uncached`` (compiled but below the persistence threshold, or
  classified by the monitoring miss event), ``unknown`` (no signal
  either way), ``disabled`` (no cache dir configured) — plus the
  serialized-executable tier's verdicts (``observability.aotcache``):
  ``aot_hit`` (the finished executable was deserialized from the AOT
  cache — trace, lower, AND compile all skipped; the only cold cost is
  the ``aot_load`` phase) and ``aot_stored`` (a real compile whose
  serialized executable landed on disk for the next process). The cache-dir
  entry counts (start / now / added) surface the "N entries rebuilt per
  process" number directly. Classification is best-effort and documented
  approximate: monitoring deltas are process-global, so a concurrent
  compile on another thread can mislabel one entry — the aggregate
  hit/miss counters stay exact.

- **Cache health** — ``setup_jax_cache`` reports its outcome here
  (dir, enabled, error) instead of swallowing failures in a bare print;
  /healthz ``build`` surfaces the state and the failure is a counted
  recorder event.

Capture rides ``system.gap_telemetry`` (one knob for the
device-utilization + cold-start pair): a few dict writes per *compile*
and per artifact build — never per dispatch — so on/off adds zero
compiles/dispatches and results stay bit-identical (tier-1 smoke in
``tests/test_gaps.py``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

#: as close to process start as importing this package gets: the
#: observability package imports this module at its own import, which the
#: engines/runners pull in before any device work.
_IMPORT_EPOCH = time.perf_counter()

#: bounded per-executable classification rows (serving uptime).
MAX_EXECUTABLES = 256


def _cache_dir_entries(path: str | None) -> int | None:
    if not path:
        return None
    try:
        return sum(1 for _ in os.scandir(path))
    except FileNotFoundError:
        # configured but not yet created (jax creates it lazily on the
        # first persisted entry): zero entries, not "unknown"
        return 0
    except OSError:
        return None


class ColdStartLedger:
    """Process-wide startup-phase + persistent-cache accounting."""

    def __init__(self, enabled: bool = True, clock=None):
        self._lock = threading.Lock()
        self.enabled = enabled
        self.clock = clock or time.perf_counter
        self.epoch = _IMPORT_EPOCH
        self.phases: dict[str, float] = {}
        self.phase_counts: dict[str, int] = {}
        self._import_noted = False
        # persistent-cache state (setup_jax_cache reports here)
        self.cache_dir: str | None = None
        self.cache_enabled: bool | None = None
        self.cache_error: str | None = None
        self.cache_entries_start: int | None = None
        # jax monitoring counters (exact process-wide hit/miss totals)
        self._jax_hits = 0
        self._jax_misses = 0
        self._listener_registered = False
        # per-executable classification rows (bounded ring — detail only)
        self.executables: list[dict] = []
        #: UNBOUNDED per-outcome counters: ``by_outcome`` must cover the
        #: whole process, not the last ``MAX_EXECUTABLES`` rows — the
        #: bench_diff --cold warm-start hit rate gates on it, and a
        #: ~400-executable process would otherwise evict its boot-time
        #: aot_hits before the record is assembled
        self.outcome_counts: dict[str, int] = {}
        self._first_dispatch: dict | None = None

    # -- phases --------------------------------------------------------------
    def record_phase(self, name: str, seconds: float) -> None:
        if not self.enabled or seconds is None or seconds < 0:
            return
        with self._lock:
            self.phases[name] = self.phases.get(name, 0.0) + float(seconds)
            self.phase_counts[name] = self.phase_counts.get(name, 0) + 1

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = self.clock()
        try:
            yield
        finally:
            self.record_phase(name, self.clock() - t0)

    def note_import_complete(self) -> None:
        """First call wins: the span from package import to the process's
        setup hook approximates import + python-side init cost."""
        if not self.enabled:
            return
        with self._lock:
            if self._import_noted:
                return
            self._import_noted = True
            self.phases["import"] = self.clock() - self.epoch
            self.phase_counts["import"] = 1

    # -- persistent cache ----------------------------------------------------
    def configure_cache(
        self, cache_dir: str | None, enabled: bool, error: str | None = None
    ) -> None:
        """``setup_jax_cache`` reports its outcome (satellite: no more
        swallowed failures — the state surfaces on /healthz ``build``)."""
        with self._lock:
            self.cache_dir = cache_dir
            self.cache_enabled = bool(enabled)
            self.cache_error = error
            if self.cache_entries_start is None:
                self.cache_entries_start = _cache_dir_entries(cache_dir)
        if enabled and not self._listener_registered:
            self._register_jax_listener()

    def _register_jax_listener(self) -> None:
        """Count jax's own persistent-cache hit/miss monitoring events —
        exact totals, available on jax >= 0.4.30; degrade silently
        otherwise (the dir-diff classification still works)."""
        try:
            from jax import monitoring

            def _listener(event, *args, **kw):
                if event == "/jax/compilation_cache/cache_hits":
                    with self._lock:
                        self._jax_hits += 1
                elif event == "/jax/compilation_cache/cache_misses":
                    with self._lock:
                        self._jax_misses += 1

            monitoring.register_event_listener(_listener)
            self._listener_registered = True
        except Exception:
            pass

    def compile_probe(self) -> dict:
        """Pre-compile snapshot for :meth:`note_compile`'s per-executable
        classification (monitoring counters + cache-dir entry count).
        The counters are returned even with capture off — the AOT cache's
        store guard (:meth:`saw_cache_hit_since`) needs them regardless
        of whether the cold-start *bookkeeping* is enabled."""
        with self._lock:
            out = {"hits": self._jax_hits, "misses": self._jax_misses}
            if self.enabled:
                out["entries"] = _cache_dir_entries(
                    self.cache_dir if self.cache_enabled else None
                )
            return out

    def saw_cache_hit_since(self, probe: dict | None) -> bool:
        """True when jax's persistent-cache monitoring reported a hit
        since ``probe`` (a :meth:`compile_probe`). Best-effort: False
        when monitoring is unavailable, and process-global — a
        concurrent compile on another thread can read as a hit here
        (the consumer, the AOT store guard, then merely skips a store).
        """
        if not probe or not self._listener_registered:
            return False
        with self._lock:
            return self._jax_hits > probe.get("hits", self._jax_hits)

    def note_compile(
        self,
        *,
        producer: str,
        key: str | None,
        lower_s: float,
        compile_s: float,
        probe: dict | None = None,
        aot: bool = True,
        aot_cache: str | None = None,
    ) -> str:
        """Record one AOT compile's phase split and classify it against
        the persistent cache; returns the classification. ``aot_cache``
        is the serialized-executable cache's verdict for this program
        ("hit" = deserialized, trace+lower+compile all skipped — the
        ``lower_s``/``compile_s`` booked here are the load wall-clock,
        charged to ``aot_load``; "stored" = freshly compiled AND
        serialized to disk for the next process)."""
        if not self.enabled:
            return "off"
        if aot_cache == "hit":
            # the whole trace/lower/compile pipeline was skipped: the only
            # cold cost is the deserialize wall-clock, a phase of its own
            self.record_phase("aot_load", lower_s + compile_s)
        else:
            self.record_phase("trace_lower", lower_s)
            self.record_phase("xla_compile", compile_s)
        probe = probe or {}
        with self._lock:
            if aot_cache == "hit":
                outcome = "aot_hit"
            elif not aot:
                outcome = "fallback"
            elif not self.cache_enabled or not self.cache_dir:
                outcome = "disabled"
            elif self._listener_registered:
                if self._jax_hits > probe.get("hits", self._jax_hits):
                    outcome = "hit"
                elif self._jax_misses > probe.get(
                    "misses", self._jax_misses
                ):
                    outcome = "miss_uncached"
                else:
                    outcome = "unknown"
            else:
                outcome = "unknown"
            if outcome in ("miss_uncached", "unknown"):
                entries_now = _cache_dir_entries(self.cache_dir)
                before = probe.get("entries")
                if (
                    entries_now is not None
                    and before is not None
                    and entries_now > before
                ):
                    outcome = "miss_stored"
            if aot_cache == "stored" and outcome in (
                "miss_uncached",
                "miss_stored",
                "unknown",
                "disabled",
            ):
                # a real compile whose finished executable landed in the
                # serialized-executable cache: the NEXT process's aot_hit.
                # A jax-persistent-cache "hit" stays "hit" — the compile
                # itself was already amortised, storing is a side effect.
                outcome = "aot_stored"
            self.executables.append(
                {
                    "key": key,
                    "producer": producer,
                    "lower_s": round(lower_s, 4),
                    "compile_s": round(compile_s, 4),
                    "persistent_cache": outcome,
                }
            )
            del self.executables[:-MAX_EXECUTABLES]
            self.outcome_counts[outcome] = (
                self.outcome_counts.get(outcome, 0) + 1
            )
        return outcome

    def compile_phase_seconds(self) -> float:
        """Total seconds this process spent producing executables — the
        trace/lower + XLA-compile split plus AOT-cache deserializes
        (``aot_load``). THE phase set warmup brackets subtract so their
        ``device_warmup`` phase never double-counts seconds already
        booked per-compile (one definition; the bench and serving
        warmups both read it)."""
        with self._lock:
            return sum(
                self.phases.get(k, 0.0)
                for k in ("trace_lower", "xla_compile", "aot_load")
            )

    def note_dispatch(self) -> None:
        """First compiled-program dispatch of the process (cheap: one
        None-check per call at the LedgeredJit dispatch site)."""
        if not self.enabled or self._first_dispatch is not None:
            return
        with self._lock:
            if self._first_dispatch is None:
                self._first_dispatch = {
                    "since_import_s": round(self.clock() - self.epoch, 4),
                    "wall": time.time(),
                }

    # -- export --------------------------------------------------------------
    def cache_state(self) -> dict:
        """The /healthz ``build.jax_cache`` view: dir, enabled/fallback
        state, the setup error if any, and the entry counts that surface
        the 'N entries rebuilt per process' number."""
        from .aotcache import get_aot_cache

        with self._lock:
            now = _cache_dir_entries(self.cache_dir)
            return {
                "dir": self.cache_dir,
                "enabled": self.cache_enabled,
                "error": self.cache_error,
                "entries_start": self.cache_entries_start,
                "entries_now": now,
                "entries_added": (
                    now - self.cache_entries_start
                    if now is not None and self.cache_entries_start is not None
                    else None
                ),
                # serialized-executable tier (observability.aotcache):
                # dir, entry count, hit/store counters, and the counted
                # load failures with their reasons — the satellite's
                # "surfaced on /healthz build.jax_cache" contract
                "aot": get_aot_cache().state(),
            }

    def cold_block(self) -> dict:
        """The structured ``cold`` breakdown a bench record embeds next
        to ``cold_s`` (and /healthz serves as the replica warmup report):
        phase seconds, per-executable persistent-cache hit/miss counts,
        cache health, and time-to-first-dispatch."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            phases = {k: round(v, 4) for k, v in self.phases.items()}
            counts = dict(self.phase_counts)
            rows = [dict(r) for r in self.executables]
            first = dict(self._first_dispatch) if self._first_dispatch else None
            hits, misses = self._jax_hits, self._jax_misses
            listener = self._listener_registered
            # process-lifetime counters, NOT derived from the bounded
            # rows: eviction must never bias the by_outcome the --cold
            # hit-rate gate reads
            outcome_counts = dict(self.outcome_counts)
        return {
            "enabled": True,
            "phases": phases,
            "phase_counts": counts,
            "persistent_cache": {
                **self.cache_state(),
                "monitoring": listener,
                "hits": hits,
                "misses": misses,
                "by_outcome": outcome_counts,
                "by_executable": rows,
            },
            "first_dispatch": first,
            "time_to_first_dispatch_s": (
                first["since_import_s"] if first else None
            ),
        }

    def reset(self) -> None:
        """Drop all state (tests only). The import epoch and the jax
        listener registration survive — both are process facts."""
        with self._lock:
            self.phases = {}
            self.phase_counts = {}
            self._import_noted = False
            self.cache_dir = None
            self.cache_enabled = None
            self.cache_error = None
            self.cache_entries_start = None
            self._jax_hits = 0
            self._jax_misses = 0
            self.executables = []
            self.outcome_counts = {}
            self._first_dispatch = None


#: keys a capture-on ``cold`` breakdown must carry.
COLD_KEYS = ("phases", "persistent_cache", "time_to_first_dispatch_s")


def validate_cold(block, kind: str = "record") -> dict:
    """Assert a structured ``cold`` breakdown is well-formed; returns it."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's cold breakdown must be a dict, got "
            f"{type(block).__name__}"
        )
    if block.get("enabled") is False:
        return block
    missing = [k for k in COLD_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's cold breakdown is missing {missing}: "
            "assemble it with observability.coldstart.cold_block so the "
            "startup-phase decomposition travels with every cold number"
        )
    return block


#: THE process ledger — the startup path is process-scoped by nature.
COLDSTART = ColdStartLedger()


def get_coldstart() -> ColdStartLedger:
    return COLDSTART


def configure_coldstart(config: dict | None) -> ColdStartLedger:
    """Apply config ``system.gap_telemetry`` (shared knob with the
    dispatch-gap tracker: one switch for the device-utilization +
    cold-start observability pair)."""
    enabled = (config or {}).get("system", {}).get("gap_telemetry", True)
    COLDSTART.enabled = bool(enabled)
    return COLDSTART
