"""Trace events -> Chrome/Perfetto trace-event JSON.

The recorder's event schema (``observability.trace``) is one JSON object
per event; the Chrome trace-event format (consumed by ``chrome://tracing``
and https://ui.perfetto.dev) wants microsecond ``ts``/``dur`` "X" complete
events grouped by pid/tid. The mapping here assigns one pid per trace id
(so every request/run renders as its own process track, with the trace id
as the track name), "X" events for spans, "i" instants for point events,
and "C" counter tracks for gauges. ``device_run`` spans whose attrs carry
a ``devices`` count > 1 (the serving dispatch closures attach it for
mesh-backed engines) fan out onto per-device tracks — tid = device
ordinal + 1 (tid 0 keeps the trace's other spans), named ``device <n>``
— so a multi-device trace stops stacking every device's lockstep
execution on one row; spans without the attr (single-device runs)
render exactly as before, byte for byte.
``tools/trace_export.py`` is the CLI wrapper.
"""

from __future__ import annotations

import json
import warnings
from typing import Iterable


def read_jsonl(path: str, strict: bool = False) -> list[dict]:
    """Load a ``system.trace_log`` JSONL sink (blank lines skipped).

    A live or crashed process leaves the sink with a partial last line
    (the write was cut mid-event); an idle one may leave it empty. Both
    are normal states for an append-only log, so unparseable lines are
    skipped with a warning — an empty event list (rendering to an empty
    Perfetto document) beats a stack trace from ``json.loads``. Pass
    ``strict=True`` to re-raise instead (debugging a corrupt sink)."""
    events = []
    skipped = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                if strict:
                    raise
                skipped += 1
    if skipped:
        warnings.warn(
            f"{path}: skipped {skipped} unparseable JSONL line(s) — "
            "truncated write from a live or crashed recorder?",
            stacklevel=2,
        )
    return events


def to_chrome_trace(events: Iterable[dict]) -> dict:
    """Render recorder events to a Chrome/Perfetto trace-event document."""
    trace_events: list[dict] = []
    pids: dict[str, int] = {}
    named_tids: set = set()

    def pid_for(trace_id) -> int:
        tid = str(trace_id)
        pid = pids.get(tid)
        if pid is None:
            pid = pids[tid] = len(pids) + 1
            trace_events.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": 0,
                    "args": {"name": tid},
                }
            )
        return pid

    def name_device_tid(pid: int, tid: int, ordinal: int) -> None:
        if (pid, tid) in named_tids:
            return
        named_tids.add((pid, tid))
        trace_events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": f"device {ordinal}"},
            }
        )

    t0_wall = None
    for ev in events:
        kind = ev.get("kind")
        ts_us = round(float(ev.get("ts", 0.0)) * 1e6, 1)
        if kind == "meta":
            t0_wall = ev.get("t0_wall", t0_wall)
        elif kind == "span":
            args = dict(ev.get("attrs") or {})
            args["span"] = ev.get("span")
            if ev.get("parent") is not None:
                args["parent"] = ev["parent"]
            devices = args.get("devices")
            if (
                ev.get("name") == "device_run"
                and isinstance(devices, int)
                and devices > 1
            ):
                # multi-device dispatch: one lockstep slice per device
                # ordinal (tid = ordinal) instead of stacking the whole
                # mesh on row 0; per-device HBM rides each slice's args
                pid = pid_for(ev.get("trace", "?"))
                hbm_devices = args.pop("hbm_devices", None)
                for d in range(devices):
                    # tid = ordinal + 1: tid 0 carries the trace's OTHER
                    # spans/instants, so device 0 must not land on it
                    tid = d + 1
                    name_device_tid(pid, tid, d)
                    dev_args = dict(args, device=d)
                    if isinstance(hbm_devices, list) and d < len(
                        hbm_devices
                    ):
                        dev_args["hbm"] = hbm_devices[d]
                    trace_events.append(
                        {
                            "name": ev.get("name", "?"),
                            "ph": "X",
                            "pid": pid,
                            "tid": tid,
                            "ts": ts_us,
                            "dur": round(
                                float(ev.get("dur", 0.0)) * 1e6, 1
                            ),
                            "args": dev_args,
                        }
                    )
                continue
            trace_events.append(
                {
                    "name": ev.get("name", "?"),
                    "ph": "X",
                    "pid": pid_for(ev.get("trace", "?")),
                    "tid": 0,
                    "ts": ts_us,
                    "dur": round(float(ev.get("dur", 0.0)) * 1e6, 1),
                    "args": args,
                }
            )
        elif kind == "event":
            trace_events.append(
                {
                    "name": ev.get("name", "?"),
                    "ph": "i",
                    "s": "t",  # thread-scoped instant
                    "pid": pid_for(ev.get("trace", "?")),
                    "tid": 0,
                    "ts": ts_us,
                    "args": dict(ev.get("attrs") or {}),
                }
            )
        elif kind == "gauge":
            trace_events.append(
                {
                    "name": ev.get("name", "?"),
                    "ph": "C",
                    "pid": pid_for(ev.get("trace", "gauges")),
                    "tid": 0,
                    "ts": ts_us,
                    "args": {"value": ev.get("value", 0.0)},
                }
            )
    doc = {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    if t0_wall is not None:
        doc["otherData"] = {"t0_wall": t0_wall}
    return doc
