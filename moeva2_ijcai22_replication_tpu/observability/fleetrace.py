"""Fleet-wide distributed tracing: context propagation + sink merging.

Single-process tracing (``observability.trace``) dies at the router hop:
the fleet :class:`~..serving.fleet.router.Router` forwards a request over
HTTP and the replica starts a brand-new trace with no memory of which
routing attempt (or which failover chain) produced it. This module is the
cross-process glue:

- **Context header** — the router stamps ``X-Moeva2-Trace`` (a
  W3C-traceparent-shaped triple: trace id, parent span id, hop count) on
  every forwarded/failover attempt; the replica parses it and adopts the
  trace id + remote parent as the *root* of its existing request trace
  (``Trace(root_parent=...)``). The replica's local ``meta.trace`` is
  unharmed — ``Trace.tree()`` treats an unknown parent as a root — but in
  a merged document the replica's request span parents correctly under
  the router's attempt span. The delimiter is ``;`` (not the W3C ``-``)
  because our trace ids legitimately contain dashes
  (``r01:req-3f2a...``).
- **Clock-offset handshake** — each replica's /healthz carries
  ``now_wall``; the :class:`~..serving.fleet.replica.ReplicaManager`
  brackets the poll with its own wall-clock reads and estimates the
  replica↔router offset as ``remote_now − (t_send + t_recv)/2`` (the NTP
  midpoint rule; error bounded by rtt/2). Good to a few ms on one host —
  plenty against span durations of tens of ms, and honest: the rtt rides
  along so a reader can see the bound.
- **Sink merging** — :func:`merge_fleet_traces` aligns N per-replica
  JSONL sinks onto one wall-clock timeline (each sink's meta line anchors
  its monotonic epoch via ``t0_wall``; the measured offset corrects the
  replica's wall clock) and renders one Chrome/Perfetto document whose
  process tracks keep their replica-prefixed trace ids — the end-to-end
  request journey the single-sink exporter could never show.
"""

from __future__ import annotations

import json
import os

from .export import read_jsonl, to_chrome_trace

__all__ = [
    "TRACE_HEADER",
    "TRACE_VERSION",
    "clock_offset",
    "format_trace_context",
    "merge_fleet_events",
    "merge_fleet_traces",
    "parse_trace_context",
    "replica_sink_path",
]

#: the propagation header every router forward/failover attempt carries
TRACE_HEADER = "X-Moeva2-Trace"

#: context format version (leading field, room to evolve the schema)
TRACE_VERSION = "00"


def format_trace_context(
    trace_id: str, parent_span: int | None = None, hop: int = 0
) -> str:
    """Render the ``X-Moeva2-Trace`` value: ``00;<trace>;<parent>;<hop>``.

    ``parent_span`` 0 means "no recorded parent" (a router running
    without a span recorder still propagates identity + hop count)."""
    return (
        f"{TRACE_VERSION};{trace_id};{int(parent_span or 0)};{int(hop)}"
    )


def parse_trace_context(header: str | None) -> dict | None:
    """Parse a context header; None on absent/malformed/foreign-version
    input (propagation is best-effort — a bad header must never fail the
    request it rides on)."""
    if not header:
        return None
    parts = str(header).split(";")
    if len(parts) != 4 or parts[0] != TRACE_VERSION or not parts[1]:
        return None
    try:
        parent = int(parts[2])
        hop = int(parts[3])
    except ValueError:
        return None
    return {
        "trace_id": parts[1],
        "parent_span": parent if parent > 0 else None,
        "hop": hop,
    }


def replica_sink_path(trace_log: str, replica_id: str | None) -> str:
    """Template a shared ``serving.trace_log`` path per replica
    (``out/trace.jsonl`` -> ``out/trace_r01.jsonl``). N replicas share
    ONE config file, and two processes appending to one JSONL would
    corrupt both streams — so ``tools/serve.py`` writes here and the
    fleet merge reads the same paths back."""
    if not replica_id:
        return trace_log
    root, ext = os.path.splitext(trace_log)
    return f"{root}_{replica_id}{ext or '.jsonl'}"


def clock_offset(
    t_send_wall: float, t_recv_wall: float, remote_now_wall: float
) -> dict:
    """NTP-midpoint offset estimate from one request/response bracket:
    the remote clock read is assumed to happen at the midpoint of the
    round trip, so ``offset = remote − midpoint`` and the error is
    bounded by ``rtt/2`` (reported alongside, never hidden)."""
    rtt = max(t_recv_wall - t_send_wall, 0.0)
    midpoint = (t_send_wall + t_recv_wall) / 2.0
    return {
        "offset_s": round(remote_now_wall - midpoint, 6),
        "rtt_s": round(rtt, 6),
    }


def _sink_t0_wall(events: list[dict]) -> float | None:
    for ev in events:
        if ev.get("kind") == "meta" and ev.get("t0_wall") is not None:
            return float(ev["t0_wall"])
    return None


def merge_fleet_events(
    sinks: dict[str, str], offsets: dict[str, float] | None = None
) -> tuple[list[dict], dict]:
    """Load N per-replica JSONL sinks and re-time every event onto one
    shared timeline.

    ``sinks`` maps a replica label -> its ``serving.trace_log`` path;
    ``offsets`` maps the same labels -> the measured replica-minus-router
    wall-clock offset in seconds (absent labels are taken at 0 — correct
    for the router's own sink, approximate for an unpolled replica).

    Each sink's events are monotonic seconds since *its* recorder epoch;
    the meta line's ``t0_wall`` anchors that epoch to the replica's wall
    clock, and the offset corrects the replica's wall clock to the
    router's. The merged base is the earliest corrected epoch, so the
    merged document starts at ts 0 like a single-sink export.

    Returns ``(events, report)`` where the report carries per-replica
    alignment evidence (t0_wall, applied offset, event count, skipped
    sinks)."""
    offsets = offsets or {}
    loaded: dict[str, tuple[list[dict], float]] = {}
    report: dict = {"replicas": {}, "skipped": {}}
    for label, path in sorted(sinks.items()):
        if not path or not os.path.exists(path):
            report["skipped"][label] = "missing sink"
            continue
        events = read_jsonl(path)
        t0_wall = _sink_t0_wall(events)
        if t0_wall is None:
            report["skipped"][label] = "no meta line (empty sink?)"
            continue
        loaded[label] = (events, t0_wall + float(offsets.get(label) or 0.0))
    if not loaded:
        return [], report
    base = min(t0 for _, t0 in loaded.values())
    merged: list[dict] = [{"kind": "meta", "t0_wall": round(base, 6)}]
    for label, (events, t0_corrected) in sorted(loaded.items()):
        shift = t0_corrected - base
        n = 0
        for ev in events:
            if ev.get("kind") == "meta":
                continue
            ev = dict(ev)
            ev["ts"] = round(float(ev.get("ts", 0.0)) + shift, 6)
            # keep gauge tracks per-replica instead of one shared
            # "gauges" pid — queue depths from two replicas are not one
            # counter
            if ev.get("kind") == "gauge" and "trace" not in ev:
                ev["trace"] = f"{label}:gauges"
            merged.append(ev)
            n += 1
        report["replicas"][label] = {
            "t0_wall": round(t0_corrected, 6),
            "offset_s": round(float(offsets.get(label) or 0.0), 6),
            "shift_s": round(shift, 6),
            "events": n,
        }
    merged[1:] = sorted(merged[1:], key=lambda e: e.get("ts", 0.0))
    return merged, report


def merge_fleet_traces(
    sinks: dict[str, str],
    offsets: dict[str, float] | None = None,
    out_path: str | None = None,
) -> dict:
    """Merge per-replica sinks into one Chrome/Perfetto document (see
    :func:`merge_fleet_events`); the alignment report lands in
    ``otherData.fleet_merge``. With ``out_path`` the document is also
    written to disk."""
    events, report = merge_fleet_events(sinks, offsets)
    doc = to_chrome_trace(events)
    doc.setdefault("otherData", {})["fleet_merge"] = report
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(doc, fh)
    return doc
