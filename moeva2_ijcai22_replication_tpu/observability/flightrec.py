"""Black-box flight recorder: the last N request journeys, dumpable.

The chaos proof (kill-a-replica, ``serving.fleet.sweep``) showed the
fleet *loses nothing it didn't have to* — but everything the dead replica
had in flight vanished with it: which batch was dispatching, which
requests rode it, what the ledger/gap/capacity windows looked like in the
final seconds. This module is the aircraft-style flight recorder that
survives the crash:

- :class:`FlightRecorder` keeps a bounded ring of *completed* request
  entries (id, trace id, status, latency, batch coordinates) — fed from
  the service's done-callback, host-side dict appends only, so the
  capture-on/off contract (zero extra compiles/dispatches, bit-identical
  responses) holds trivially.
- :meth:`FlightRecorder.dump` serializes the ring plus a caller-supplied
  ``extra`` block (the service adds the batcher's in-flight view and
  ledger/gap/capacity/shed snapshots) to
  ``out/flight_<replica>_<reason>.json`` **atomically** (tmp +
  ``os.replace``) — a dump interrupted by the very death it documents
  must never leave a half-written file for the harvester.

The fleet manager triggers a dump over ``POST /debug/flight`` just
before SIGKILL (and the serve.py SIGTERM handler dumps on graceful
drain), then harvests the path — so a chaos ``lost_dead_replica`` row is
attributable to the exact batch it died in, not just to the dead
replica.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

__all__ = ["FlightRecorder", "load_flight_dump"]


class FlightRecorder:
    """Bounded ring of completed-request entries + atomic dump."""

    def __init__(self, capacity: int = 64, clock=time.time):
        self.capacity = int(capacity)
        self.enabled = self.capacity > 0
        self._ring: collections.deque = collections.deque(
            maxlen=max(self.capacity, 1)
        )
        self._lock = threading.Lock()
        self._clock = clock
        self.recorded = 0
        self.dumps = 0

    def note(self, entry: dict) -> None:
        """Append one completed-request entry (host-side, two dict ops
        under a lock — safe on the done-callback path)."""
        if not self.enabled:
            return
        with self._lock:
            self._ring.append(dict(entry, t_wall=round(self._clock(), 6)))
            self.recorded += 1

    def entries(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "recorded": self.recorded,
                "ring_size": len(self._ring),
                "dumps": self.dumps,
            }

    def dump(
        self,
        path: str,
        *,
        reason: str,
        replica_id: str | None = None,
        extra: dict | None = None,
    ) -> dict:
        """Atomically write the flight dump; returns its summary (the
        shape ``POST /debug/flight`` responds with and the fleet manager
        stores as harvest evidence)."""
        doc = {
            "kind": "flight_dump",
            "reason": reason,
            "replica_id": replica_id,
            "t_wall": round(self._clock(), 6),
            "pid": os.getpid(),
            "flight": self.snapshot(),
            "entries": self.entries(),
            "extra": extra or {},
        }
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(doc, fh, default=str)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        with self._lock:
            self.dumps += 1
        return {
            "path": path,
            "reason": reason,
            "replica_id": replica_id,
            "entries": len(doc["entries"]),
            "t_wall": doc["t_wall"],
        }


def load_flight_dump(path: str) -> dict | None:
    """Read a harvested dump; None when missing/unparseable (a dump that
    never completed is itself evidence — the caller reports the absence,
    it must not crash on it)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None
