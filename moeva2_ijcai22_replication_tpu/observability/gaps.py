"""Dispatch-gap ledger: how much device time the host leaves on the table.

The cost ledger (PR 5) says what each executable *costs* and the engines
attribute run seconds at their sync points — but nothing so far says how
much of a run's wall-clock the device spent *idle*, waiting for host-side
serial work between dispatches (ROADMAP item 2's second perf sink, next
to cold start). This module makes that idle time first-class:

- :class:`GapTracker` — a process-wide monotonic dispatch timeline. Each
  engine ``generate`` contributes one :class:`DispatchWindow` at its
  *existing* sync point (MoEvA's ``_attribute_run`` after the final
  fetch, PGD's post-fetch attribution — zero new device syncs): the
  window's wall span, its per-dispatch enqueue timestamps (the
  :class:`~.ledger.LedgeredJit` call instants, host-side ``perf_counter``
  reads the dispatch path already makes), the attributed run seconds per
  dispatch, and the compile seconds. From those the tracker derives the
  window's device-busy intervals (a serial device queue: each dispatch's
  run follows the later of its enqueue and the previous dispatch's
  completion) and therefore its **gaps** — intervals where the device had
  nothing queued. The model is an approximation by construction (run
  seconds are the engines' aggregate attribution, not per-op device
  timestamps) and is documented as such; its error is bounded by the
  attribution error the roofline already carries.

- **Gap attribution** — :func:`join_gaps_to_spans` joins gap intervals
  against the host spans active during them (the ``TraceRecorder`` span
  tree: fetch / decode / parked_merge / gate_fetch / evaluate / write /
  queue_wait / batch_wait…). Each gap instant is attributed to the most
  specific (shortest) covering span; uncovered time lands in the honest
  ``unattributed`` bucket (spans off ⇒ everything unattributed — capture
  degrades, it never lies).

- **Overlap ratio** — device-busy / wall per window, per producer, per
  executable, and per run scope: the single number that says "the device
  worked 62% of this run's wall-clock; the top gap stage was
  parked_merge". ``mark()``/``gaps_block(since=)`` window-scope exports
  exactly like ``CostLedger.mark`` so a record reports *its own* runs.

Capture (config ``system.gap_telemetry``, default on) is pure host-side
bookkeeping on clock reads the dispatch path already makes: on/off adds
zero compiles, zero dispatches, and results stay bit-identical (tier-1
smoke in ``tests/test_gaps.py``).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

#: keys a capture-on ``telemetry.gaps`` block must carry
#: (``records.validate_record`` enforces the block on every
#: bench/grid/serving/runner record, mirroring telemetry.cost/quality).
GAPS_KEYS = ("windows", "busy_s", "overlap_ratio", "attributed")

#: longest gaps listed individually in a gaps block (aggregates cover the
#: rest — the block must not grow with run length).
MAX_GAPS_LISTED = 8

#: longest gaps fed through the span join per block assembly: the join is
#: O(gaps x spans) and a long-lived serving process accumulates both, so
#: a /metrics scrape must not walk every tiny gap of the replica's
#: lifetime. Idle beyond the joined subset stays counted (idle_s is
#: computed independently); only its attribution is foregone.
MAX_GAPS_JOINED = 1024


@dataclass
class DispatchWindow:
    """One engine run on the device timeline: wall span, busy/compile
    seconds, and the derived idle gaps."""

    seq: int
    producer: str
    engine: str | None
    start: float
    end: float
    busy_s: float
    compile_s: float
    dispatches: int
    #: ledger entry key -> attributed busy seconds within this window
    executables: dict = field(default_factory=dict)
    #: (start, dur) idle intervals inside the window, tracker clock base
    gaps: list = field(default_factory=list)

    @property
    def wall_s(self) -> float:
        return max(self.end - self.start, 0.0)

    def overlap_ratio(self) -> float | None:
        """Busy over compile-free wall: compile seconds are the cold
        ledger's business, and folding them into the denominator would
        make every cold window read as a host-stall problem."""
        w = self.wall_s - self.compile_s
        return min(self.busy_s / w, 1.0) if w > 0 else None


def _window_intervals(start, end, dispatches):
    """Derive (busy, compile, gap) intervals of one window from its
    dispatch log ``[(enqueue_ts, run_s, compile_s), ...]``, where
    ``enqueue_ts`` is the POST-compile enqueue instant (the LedgeredJit
    call returns after any compile, so that clock read sits right after
    both) — a compile therefore occupied ``[enqueue_ts - compile_s,
    enqueue_ts]``.

    Serial-device-queue model: a dispatch's device run begins at the
    later of its enqueue and the previous dispatch's completion, so
    back-to-back async dispatches show zero gap even though the host
    enqueued them long before they ran. Everything neither busy nor
    compiling is a gap. All host-side arithmetic on clock reads the
    dispatch path already made."""
    busy, compile_iv, gaps = [], [], []
    cursor = start
    for ts, run_s, compile_s in sorted(dispatches):
        ts = min(max(ts, start), end)
        if compile_s > 0:
            c0 = max(ts - compile_s, start, cursor)
            if ts > c0:
                compile_iv.append((c0, ts - c0))
            if c0 > cursor:
                gaps.append((cursor, c0 - cursor))
            cursor = max(cursor, ts)
        b0 = max(ts, cursor)
        if b0 > cursor:
            gaps.append((cursor, b0 - cursor))
        b1 = min(b0 + max(run_s, 0.0), end)
        if b1 > b0:
            busy.append((b0, b1 - b0))
        cursor = max(cursor, b1)
    if cursor < end:
        gaps.append((cursor, end - cursor))
    return busy, compile_iv, gaps


def join_gaps_to_spans(gaps, spans) -> dict:
    """Attribute idle intervals to the host spans active during them.

    ``gaps`` is ``[(start, dur), ...]``; ``spans`` is ``[{"name",
    "start", "dur"}, ...]`` in the SAME clock base. Each gap instant goes
    to the most specific covering span (shortest duration wins — in a
    span tree the child is always shorter than its parent, so "decode"
    beats the enclosing "dispatch" envelope); uncovered time lands in
    ``unattributed_s``. Returns ``{"attributed": {name: seconds},
    "unattributed_s", "per_gap": [{"start", "dur", "top"}, ...]}``."""
    attributed: dict[str, float] = {}
    per_gap = []
    ordered = sorted(
        (s for s in spans or () if s.get("dur", 0) > 0),
        key=lambda s: s["dur"],
    )
    unattributed = 0.0
    for g0, gdur in gaps:
        g1 = g0 + gdur
        remaining = [(g0, g1)]
        gap_attr: dict[str, float] = {}
        for s in ordered:
            if not remaining:
                break
            s0, s1 = s["start"], s["start"] + s["dur"]
            nxt = []
            for r0, r1 in remaining:
                o0, o1 = max(r0, s0), min(r1, s1)
                if o1 > o0:
                    name = str(s.get("name", "?"))
                    gap_attr[name] = gap_attr.get(name, 0.0) + (o1 - o0)
                    if r0 < o0:
                        nxt.append((r0, o0))
                    if o1 < r1:
                        nxt.append((o1, r1))
                else:
                    nxt.append((r0, r1))
            remaining = nxt
        left = sum(r1 - r0 for r0, r1 in remaining)
        unattributed += left
        for name, sec in gap_attr.items():
            attributed[name] = attributed.get(name, 0.0) + sec
        top = max(gap_attr.items(), key=lambda kv: kv[1])[0] if gap_attr else None
        per_gap.append(
            {
                "start": round(g0, 6),
                "dur": round(gdur, 6),
                "top": top,
            }
        )
    return {
        "attributed": {k: round(v, 6) for k, v in attributed.items()},
        "unattributed_s": round(unattributed, 6),
        "per_gap": per_gap,
    }


#: span names never used as attribution targets: the tracker's own
#: ``device_gap`` slices coincide with the gaps by construction and would
#: otherwise claim 100% of the attribution they exist to visualize.
_SELF_SPANS = ("device_gap",)


def spans_from_trace(trace) -> list[dict]:
    """Span events of a :class:`~.trace.Trace`, converted to the gap
    tracker's clock base (recorder-relative ts + the recorder's
    perf-counter epoch). Empty when the trace is off — gaps then stay
    honestly unattributed."""
    if trace is None or not getattr(trace, "enabled", False):
        return []
    epoch = getattr(trace.recorder, "perf_epoch", 0.0)
    return [
        {
            "name": ev.get("name"),
            "start": float(ev.get("ts", 0.0)) + epoch,
            "dur": float(ev.get("dur", 0.0)),
        }
        for ev in trace.events
        if ev.get("kind") == "span" and ev.get("name") not in _SELF_SPANS
    ]


def spans_from_recorder(recorder) -> list[dict]:
    """Span events currently in a recorder's ring, in the tracker's clock
    base — the serving/grid producers' attribution source (one recorder,
    many traces)."""
    if recorder is None:
        return []
    epoch = getattr(recorder, "perf_epoch", 0.0)
    return [
        {
            "name": ev.get("name"),
            "start": float(ev.get("ts", 0.0)) + epoch,
            "dur": float(ev.get("dur", 0.0)),
        }
        for ev in recorder.events()
        if ev.get("kind") == "span" and ev.get("name") not in _SELF_SPANS
    ]


class GapTracker:
    """Process-wide dispatch timeline + device busy/idle accounting."""

    def __init__(self, enabled: bool = True, capacity: int = 4096, clock=None):
        self._lock = threading.Lock()
        self.enabled = enabled
        self.clock = clock or time.perf_counter
        self._windows: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        # cumulative totals survive ring eviction (serving uptime)
        self._busy_s = 0.0
        self._compile_s = 0.0
        self._wall_s = 0.0
        self._by_producer: dict[str, dict] = {}

    # -- recording -----------------------------------------------------------
    def record_window(
        self,
        *,
        producer: str,
        start: float,
        end: float,
        dispatches,
        engine: str | None = None,
    ) -> DispatchWindow | None:
        """Register one engine run's window at its existing sync point.

        ``dispatches`` is ``[(enqueue_ts, run_s, compile_s, executable_key
        or None), ...]`` — the clock reads the dispatch path already made.
        Returns the window (None when capture is off, or the span is
        degenerate) so the caller can emit its Perfetto events."""
        if not self.enabled or end <= start:
            return None
        disp3 = [(ts, r, c) for ts, r, c, _ in dispatches]
        busy_iv, compile_iv, gap_iv = _window_intervals(start, end, disp3)
        executables: dict[str, float] = {}
        for _, r, _, key in dispatches:
            if key is not None and r > 0:
                executables[key] = executables.get(key, 0.0) + r
        busy = sum(d for _, d in busy_iv)
        compile_s = sum(d for _, d in compile_iv)
        with self._lock:
            self._seq += 1
            w = DispatchWindow(
                seq=self._seq,
                producer=str(producer),
                engine=engine,
                start=start,
                end=end,
                busy_s=busy,
                compile_s=compile_s,
                dispatches=len(dispatches),
                executables=executables,
                gaps=gap_iv,
            )
            self._windows.append(w)
            self._busy_s += busy
            self._compile_s += compile_s
            self._wall_s += w.wall_s
            slot = self._by_producer.setdefault(
                w.producer, {"windows": 0, "busy_s": 0.0, "wall_s": 0.0}
            )
            slot["windows"] += 1
            slot["busy_s"] += busy
            # compile-free wall, matching the overlap-ratio basis
            slot["wall_s"] += max(w.wall_s - compile_s, 0.0)
        return w

    # -- windowing -----------------------------------------------------------
    def mark(self) -> dict:
        """Opaque snapshot for window-scoped gaps blocks
        (``gaps_block(since=mark)``) — the ``CostLedger.mark`` discipline."""
        with self._lock:
            return {
                "seq": self._seq,
                "busy_s": self._busy_s,
                "compile_s": self._compile_s,
                "wall_s": self._wall_s,
            }

    # -- export --------------------------------------------------------------
    def gaps_block(self, since: dict | None = None, spans=None) -> dict:
        """The ``telemetry.gaps`` sub-block every record carries: window
        count, busy/compile/idle seconds, the overlap ratio (device-busy /
        wall), per-producer and per-executable ratios, the longest gaps,
        and the gap↔span attribution (``spans`` in the tracker clock base
        — see :func:`spans_from_trace`). Wall is the contiguous span from
        the first window's start to the last window's end in scope, so
        inter-window idle (grid writer, batch assembly between runs)
        counts as gap time too."""
        if not self.enabled:
            return {"enabled": False}
        min_seq = (since or {}).get("seq", 0)
        with self._lock:
            windows = [w for w in self._windows if w.seq > min_seq]
        if not windows:
            return {
                "enabled": True,
                "windows": 0,
                "wall_s": 0.0,
                "busy_s": 0.0,
                "compile_s": 0.0,
                "idle_s": 0.0,
                "overlap_ratio": None,
                "by_producer": {},
                "by_executable": {},
                "gaps": [],
                "attributed": {},
                "unattributed_s": 0.0,
                "top_gap_stages": [],
            }
        windows.sort(key=lambda w: w.start)
        wall = max(windows[-1].end - windows[0].start, 0.0)
        busy = sum(w.busy_s for w in windows)
        compile_s = sum(w.compile_s for w in windows)
        # intra-window gaps + the idle seams BETWEEN windows (host work
        # separating two runs — the grid writer / batch-assembly stalls)
        gaps = [g for w in windows for g in w.gaps]
        cursor = windows[0].end
        for w in windows[1:]:
            if w.start > cursor:
                gaps.append((cursor, w.start - cursor))
            cursor = max(cursor, w.end)
        gaps.sort()
        idle = sum(d for _, d in gaps)
        # bounded join: the longest gaps carry the attribution story; the
        # un-joined tail stays in idle_s and lands in unattributed below
        join_gaps = gaps
        if len(join_gaps) > MAX_GAPS_JOINED:
            join_gaps = sorted(gaps, key=lambda g: -g[1])[:MAX_GAPS_JOINED]
        scope_spans = [
            s
            for s in spans or ()
            if s["start"] + s["dur"] > windows[0].start
            and s["start"] < windows[-1].end
        ]
        join = join_gaps_to_spans(join_gaps, scope_spans)
        # per-producer / per-executable ratios over the compile-free wall
        # of the windows they appear in (compile is the cold ledger's
        # phase; the overlap ratio isolates host idle)
        by_producer: dict[str, dict] = {}
        by_executable: dict[str, dict] = {}
        for w in windows:
            active = max(w.wall_s - w.compile_s, 0.0)
            p = by_producer.setdefault(
                w.producer, {"windows": 0, "busy_s": 0.0, "wall_s": 0.0}
            )
            p["windows"] += 1
            p["busy_s"] += w.busy_s
            p["wall_s"] += active
            for key, sec in w.executables.items():
                e = by_executable.setdefault(
                    key, {"windows": 0, "busy_s": 0.0, "wall_s": 0.0}
                )
                e["windows"] += 1
                e["busy_s"] += sec
                e["wall_s"] += active
        for slot in list(by_producer.values()) + list(by_executable.values()):
            slot["busy_s"] = round(slot["busy_s"], 6)
            slot["wall_s"] = round(slot["wall_s"], 6)
            slot["overlap_ratio"] = (
                round(min(slot["busy_s"] / slot["wall_s"], 1.0), 4)
                if slot["wall_s"] > 0
                else None
            )
        top_stages = sorted(
            join["attributed"].items(), key=lambda kv: -kv[1]
        )[:3]
        listed = sorted(
            join["per_gap"], key=lambda g: -g["dur"]
        )[:MAX_GAPS_LISTED]
        return {
            "enabled": True,
            "windows": len(windows),
            "wall_s": round(wall, 6),
            "busy_s": round(busy, 6),
            "compile_s": round(compile_s, 6),
            "idle_s": round(idle, 6),
            # busy over compile-free wall: a cold window's compile must
            # not read as host idle (cold has its own ledger and gate)
            "overlap_ratio": (
                round(min(busy / (wall - compile_s), 1.0), 4)
                if wall - compile_s > 0
                else None
            ),
            "by_producer": by_producer,
            "by_executable": by_executable,
            "gaps": listed,
            "attributed": join["attributed"],
            # idle the join did NOT explain — covers both span-free gap
            # time and the un-joined tail beyond MAX_GAPS_JOINED
            "unattributed_s": round(
                max(idle - sum(join["attributed"].values()), 0.0), 6
            ),
            # the exit artifact: which host stage to double-buffer next
            "top_gap_stages": [[k, round(v, 6)] for k, v in top_stages],
        }

    def totals(self) -> dict:
        """Eviction-proof lifetime totals on the per-window wall basis:
        ``wall_s`` sums each window's own span, so idle BETWEEN engine
        runs (a replica waiting for traffic) is not charged as host
        stall — the right basis for process-lifetime scalars, where the
        record-scope block's first-to-last span (which deliberately
        counts inter-run seams of one contiguous run/sweep) is not."""
        with self._lock:
            active = self._wall_s - self._compile_s
            return {
                "windows": self._seq,
                "busy_s": round(self._busy_s, 6),
                "compile_s": round(self._compile_s, 6),
                "wall_s": round(self._wall_s, 6),
                "idle_s": round(max(active - self._busy_s, 0.0), 6),
                "overlap_ratio": (
                    round(min(self._busy_s / active, 1.0), 4)
                    if active > 0
                    else None
                ),
                # lifetime per-producer view (the ring-scoped block's
                # by_producer forgets evicted windows; this never does)
                "by_producer": {
                    p: {
                        "windows": s["windows"],
                        "busy_s": round(s["busy_s"], 6),
                        "wall_s": round(s["wall_s"], 6),
                        "overlap_ratio": (
                            round(min(s["busy_s"] / s["wall_s"], 1.0), 4)
                            if s["wall_s"] > 0
                            else None
                        ),
                    }
                    for p, s in self._by_producer.items()
                },
            }

    def snapshot(self, spans=None) -> dict:
        """Process-lifetime view for /healthz and /metrics. Two clearly
        separated bases: ``totals`` (eviction-proof, per-window wall —
        idle between engine runs is NOT host stall) for the replica-level
        scalars, and ``recent`` (the ring-scoped block, first-to-last
        span basis, with the gap list + span attribution) for detail.
        Nesting them keeps a reader from computing one number across the
        two bases — the one-request-then-idle-an-hour replica must not
        read as a host-stall alarm."""
        return {
            "enabled": self.enabled,
            "totals": self.totals(),
            "recent": self.gaps_block(spans=spans),
        }

    def reset(self) -> None:
        """Drop all state (tests only)."""
        with self._lock:
            self._windows.clear()
            self._seq = 0
            self._busy_s = self._compile_s = self._wall_s = 0.0
            self._by_producer = {}


def validate_gaps(block, kind: str = "record") -> dict:
    """Assert a ``telemetry.gaps`` block is well-formed; returns it. A
    capture-off block (``enabled: False``) passes — the knob may be off,
    dropping the block entirely is not allowed."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's telemetry.gaps block must be a dict, got "
            f"{type(block).__name__}"
        )
    if block.get("enabled") is False:
        return block
    missing = [k for k in GAPS_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's telemetry.gaps block is missing {missing}: "
            "assemble it with observability.records.telemetry_block so "
            "device busy/idle attribution travels with every committed "
            "number"
        )
    return block


def emit_window_trace(trace, window: DispatchWindow | None) -> None:
    """Render one window into a run's trace: a ``device_busy_ratio``
    counter sample (Perfetto 'C' track) plus one named ``device_gap``
    slice per idle interval, positioned at its true timeline instant.
    No-op when the trace is off or the window was not captured."""
    if window is None or trace is None or not getattr(trace, "enabled", False):
        return
    rec = trace.recorder
    epoch = getattr(rec, "perf_epoch", 0.0)
    ratio = window.overlap_ratio()
    if ratio is not None:
        rec.gauge(
            "device_busy_ratio", round(ratio, 4), at=window.end - epoch
        )
    for g0, gdur in window.gaps:
        trace.record_span(
            "device_gap", gdur, at=g0 - epoch, producer=window.producer
        )


#: THE process tracker — engines and record producers share it the way
#: they share ``ledger.LEDGER`` and ``mesh.MESH``.
GAPS = GapTracker()


def get_gap_tracker() -> GapTracker:
    return GAPS


def configure_gap_tracker(config: dict | None) -> GapTracker:
    """Apply config ``system.gap_telemetry`` (default on; the capture is a
    few clock reads and dict writes per engine sync point, never a new
    device sync)."""
    enabled = (config or {}).get("system", {}).get("gap_telemetry", True)
    GAPS.enabled = bool(enabled)
    return GAPS
