"""Incident attribution: SLO-breach detection with frozen evidence.

The serving stack publishes plenty of *symptoms* — p99 histograms, shed
matrices, capacity headroom, balance ratios — but a symptom on /healthz
names no cause: by the time an operator looks, the windowed trackers have
rolled past the interesting seconds. An *incident* is the bridge: a
predicate over the existing windowed trackers trips, and the detector
**freezes the correlated evidence at that instant** (top gap stages,
recompile causes, the shed matrix, the offending traces from the flight
ring — whatever the owner's ``evidence_fn`` gathers) into a record that
outlives the windows. "p99 regressed" becomes "p99 regressed because
bucket-1024 recompiled on replica r02".

Predicates (all host-side comparisons over snapshots the service already
assembles — capture on/off stays zero extra compiles/dispatches):

- ``slo_breach`` — a stage's windowed p99 exceeds ``p99_factor`` × the
  best p99 this detector has seen for that (domain, stage), with at
  least ``min_samples`` observations (the same 3× rule as
  :func:`~.slo.detect_knee`, applied longitudinally instead of across a
  load ladder).
- ``shed_spike`` — the shed total grew by ≥ ``shed_spike_min`` since the
  previous tick (a burst, not a trickle).
- ``capacity_collapse`` — a domain's ``max_sustainable_qps`` fell below
  ``capacity_collapse_ratio`` × its best observed value (a recompile
  storm or a sick device, not load).
- ``balance_drop`` — a balance ratio (mesh per-device, or the fleet's
  routable fraction) fell below ``balance_drop_floor``.
- ``replica_dead`` — opened explicitly by the fleet layer when a kill or
  crashed poll is observed; evidence is the harvested flight dump.

Dedupe/cooldown keep one incident per ongoing condition: re-trips of an
open incident count as ``repeats``; a re-trip within ``cooldown_s`` of a
resolve is suppressed. ``incidents_block`` renders the detector for
/healthz, /metrics and the ``telemetry.incidents`` record block
``records.validate_record`` requires on serving/fleet records.
"""

from __future__ import annotations

import itertools
import json
import threading
import time

__all__ = [
    "INCIDENT_KEYS",
    "INCIDENT_KINDS",
    "IncidentDetector",
    "incidents_block",
    "validate_incidents",
]

#: the keys every ``telemetry.incidents`` block carries
INCIDENT_KEYS = ("enabled", "open", "total", "by_kind", "incidents")

#: the predicate taxonomy (explicit opens may add fleet-side kinds)
INCIDENT_KINDS = (
    "slo_breach",
    "shed_spike",
    "capacity_collapse",
    "balance_drop",
    "replica_dead",
)


def _freeze(evidence) -> tuple[dict, bool]:
    """Deep-copy evidence through JSON so later tracker mutation cannot
    reach into an incident record; returns (evidence, frozen)."""
    if evidence is None:
        return {}, False
    try:
        return json.loads(json.dumps(evidence, default=str)), True
    except (TypeError, ValueError):
        return {"evidence_error": "unserializable"}, False


class IncidentDetector:
    """Predicate evaluation + incident records with frozen evidence."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        clock=time.monotonic,
        cooldown_s: float = 60.0,
        max_history: int = 32,
        p99_factor: float = 3.0,
        min_samples: int = 20,
        shed_spike_min: int = 8,
        capacity_collapse_ratio: float = 0.5,
        balance_drop_floor: float = 0.5,
    ):
        self.enabled = bool(enabled)
        self._clock = clock
        self.cooldown_s = float(cooldown_s)
        self.max_history = int(max_history)
        self.p99_factor = float(p99_factor)
        self.min_samples = int(min_samples)
        self.shed_spike_min = int(shed_spike_min)
        self.capacity_collapse_ratio = float(capacity_collapse_ratio)
        self.balance_drop_floor = float(balance_drop_floor)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self.incidents: list[dict] = []
        self.by_kind: dict[str, int] = {}
        self.total = 0
        self.suppressed = 0
        self._open: dict[str, dict] = {}  # dedupe key -> open incident
        self._last_open_t: dict[str, float] = {}
        # longitudinal predicate baselines
        self._p99_best: dict[tuple, float] = {}
        self._qps_best: dict[str, float] = {}
        self._last_shed_total: int | None = None

    # -- lifecycle -----------------------------------------------------------
    def open(
        self,
        kind: str,
        summary: str,
        *,
        severity: str = "warning",
        evidence: dict | None = None,
        evidence_fn=None,
        dedupe_key: str | None = None,
    ) -> dict | None:
        """Open an incident, freezing its evidence NOW. An already-open
        incident under the same dedupe key absorbs the re-trip as a
        ``repeats`` bump; a re-trip inside the cooldown window after a
        resolve is suppressed (counted, not recorded)."""
        if not self.enabled:
            return None
        key = dedupe_key or kind
        now = self._clock()
        with self._lock:
            existing = self._open.get(key)
            if existing is not None:
                existing["repeats"] += 1
                self.suppressed += 1
                return existing
            last = self._last_open_t.get(key)
            if last is not None and now - last < self.cooldown_s:
                self.suppressed += 1
                return None
        if evidence is None and evidence_fn is not None:
            try:
                evidence = evidence_fn()
            except Exception as e:  # noqa: BLE001 — evidence must not kill
                evidence = {"evidence_error": repr(e)}
        frozen_ev, frozen = _freeze(evidence)
        if "evidence_error" in frozen_ev:
            frozen = False
        inc = {
            "id": next(self._ids),
            "kind": kind,
            "key": key,
            "severity": severity,
            "state": "open",
            "t_open": round(now, 3),
            "summary": summary,
            "frozen": frozen,
            "evidence": frozen_ev,
            "repeats": 0,
        }
        with self._lock:
            self.incidents.append(inc)
            del self.incidents[: -self.max_history]
            self._open[key] = inc
            self._last_open_t[key] = now
            self.total += 1
            self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        return inc

    def resolve(self, dedupe_key: str, note: str | None = None) -> dict | None:
        with self._lock:
            inc = self._open.pop(dedupe_key, None)
        if inc is not None:
            inc["state"] = "resolved"
            inc["t_resolve"] = round(self._clock(), 3)
            if note:
                inc["resolve_note"] = note
        return inc

    def resolve_all(self, note: str | None = None) -> int:
        with self._lock:
            keys = list(self._open)
        return sum(1 for k in keys if self.resolve(k, note) is not None)

    # -- predicate tick ------------------------------------------------------
    def tick(
        self,
        *,
        slo: dict | None = None,
        capacity: dict | None = None,
        balance_ratio: float | None = None,
        balance_label: str = "balance",
        evidence_fn=None,
    ) -> list[dict]:
        """One predicate pass over the snapshots the caller already has
        (nothing here re-reads trackers, so the caller controls the
        window). Returns the incidents opened this tick; resolves open
        incidents whose condition cleared."""
        if not self.enabled:
            return []
        opened: list[dict] = []

        def trip(kind, key, summary, measured):
            ev = None
            if evidence_fn is not None:
                try:
                    ev = evidence_fn()
                except Exception as e:  # noqa: BLE001
                    ev = {"evidence_error": repr(e)}
            ev = dict(ev or {}, trigger=measured)
            inc = self.open(kind, summary, evidence=ev, dedupe_key=key)
            if inc is not None and inc.get("state") == "open" and not inc["repeats"]:
                opened.append(inc)

        # -- slo_breach: windowed p99 vs best-seen, per (domain, stage) ------
        for domain, by_stage in ((slo or {}).get("stages") or {}).items():
            for stage, snap in (by_stage or {}).items():
                p99 = (snap or {}).get("p99")
                n = (snap or {}).get("n") or 0
                if p99 is None or n < self.min_samples:
                    continue
                key = f"slo_breach:{domain}:{stage}"
                best = self._p99_best.get((domain, stage))
                if best is not None and p99 > self.p99_factor * best:
                    trip(
                        "slo_breach",
                        key,
                        f"{domain}/{stage} p99 {p99 * 1e3:.1f}ms > "
                        f"{self.p99_factor:g}x best {best * 1e3:.1f}ms",
                        {
                            "domain": domain,
                            "stage": stage,
                            "p99_s": p99,
                            "baseline_p99_s": best,
                            "n": n,
                        },
                    )
                else:
                    self.resolve(key, "p99 back under factor")
                    self._p99_best[(domain, stage)] = (
                        p99 if best is None else min(best, p99)
                    )
        # -- shed_spike: shed-total delta since the previous tick ------------
        shed_total = ((slo or {}).get("shed") or {}).get("total")
        if isinstance(shed_total, int):
            last = self._last_shed_total
            if last is not None and shed_total - last >= self.shed_spike_min:
                trip(
                    "shed_spike",
                    "shed_spike",
                    f"shed {shed_total - last} requests since last tick "
                    f"(>= {self.shed_spike_min})",
                    {"shed_delta": shed_total - last, "shed_total": shed_total},
                )
            else:
                self.resolve("shed_spike", "shed rate back to normal")
            self._last_shed_total = shed_total
        # -- capacity_collapse: max_sustainable_qps vs best-seen, per domain -
        for domain, d in ((capacity or {}).get("by_domain") or {}).items():
            qps = (d or {}).get("max_sustainable_qps")
            if not qps:
                continue
            key = f"capacity_collapse:{domain}"
            best = self._qps_best.get(domain)
            if best and qps < self.capacity_collapse_ratio * best:
                trip(
                    "capacity_collapse",
                    key,
                    f"{domain} max_sustainable_qps {qps:.1f} < "
                    f"{self.capacity_collapse_ratio:g}x best {best:.1f}",
                    {"domain": domain, "qps": qps, "best_qps": best},
                )
            else:
                self.resolve(key, "capacity recovered")
                self._qps_best[domain] = max(best or 0.0, float(qps))
        # -- balance_drop: caller-supplied ratio under the floor -------------
        if balance_ratio is not None:
            key = f"balance_drop:{balance_label}"
            if balance_ratio < self.balance_drop_floor:
                trip(
                    "balance_drop",
                    key,
                    f"{balance_label} ratio {balance_ratio:.3f} < floor "
                    f"{self.balance_drop_floor:g}",
                    {"label": balance_label, "ratio": balance_ratio},
                )
            else:
                self.resolve(key, "balance recovered")
        return opened

    # -- views ---------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "open": len(self._open),
                "total": self.total,
                "suppressed": self.suppressed,
                "by_kind": dict(self.by_kind),
                "incidents": [dict(i) for i in self.incidents],
            }


def incidents_block(detector: IncidentDetector | None) -> dict:
    """The ``telemetry.incidents`` block: the detector's snapshot, or an
    honest capture-off block when detection is disabled/absent."""
    if detector is None or not detector.enabled:
        return {
            "enabled": False,
            "open": 0,
            "total": 0,
            "by_kind": {},
            "incidents": [],
        }
    return detector.snapshot()


def validate_incidents(block: dict, kind: str = "record") -> dict:
    """Schema check for a ``telemetry.incidents`` block; returns it."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's telemetry.incidents must be a dict "
            "(assemble it with observability.incidents.incidents_block)"
        )
    missing = [k for k in INCIDENT_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's telemetry.incidents block is missing keys "
            f"{missing}: every incidents block carries {list(INCIDENT_KEYS)}"
        )
    for inc in block.get("incidents") or []:
        inc_missing = [
            k
            for k in ("id", "kind", "state", "t_open", "summary", "frozen")
            if k not in inc
        ]
        if inc_missing:
            raise ValueError(
                f"{kind} record has an incident missing {inc_missing} — "
                "incidents must be opened through IncidentDetector.open "
                "so their evidence is frozen at open time"
            )
    return block
