"""Executable cost ledger: what every compiled program *costs*, not just
how long it ran.

PR 4's spans say where wall-clock went; this module records what the
hardware was asked to do. A process-wide :class:`CostLedger` captures, at
the moment each executable is built, its identity (producer, engine cache
key, batch rows, loss strategy, mesh, static knobs), XLA's cost model
(``compiled.cost_analysis()`` FLOPs / bytes accessed), its memory
footprint (``compiled.memory_analysis()`` argument/output/temp/code
bytes), and the compile wall-clock. Joining those static costs with the
measured run seconds (attributed by the engines at their existing sync
points) yields roofline-style attribution: achieved FLOP/s, achieved
bytes/s, and arithmetic intensity per executable.

The capture point is :class:`LedgeredJit`, an AOT compile-and-dispatch
wrapper around a ``jax.jit`` callable: it lowers and compiles explicitly
(``jitted.lower(*args).compile()``) exactly when the implicit jit cache
would have, caches the compiled executable under the argument avals, and
dispatches through it. Same lowering, same executable, one device
execution per call — the ledger only *observes*; ``system.cost_ledger:
false`` turns the bookkeeping off without touching the dispatch path, so
ledger-on and ledger-off runs are bit-identical by construction. (Going
through the jit cache and *separately* AOT-compiling would double every
compile: on jax 0.4.x the AOT and jit executable caches are disjoint.)

Graceful degradation: some jax versions/backends return ``None`` from —
or raise inside — ``cost_analysis()`` / ``memory_analysis()``; the probes
below swallow that and the entry records ``cost_available: false``. If
AOT lowering itself fails, the wrapper falls back to the plain jitted
call and records a degraded (``aot: false``) entry. Observability must
never take an attack down.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from .aotcache import get_aot_cache
from .coldstart import get_coldstart

#: identity attrs pushed by an enclosing dispatch site (e.g. the serving
#: microbatcher's bucket) into every entry compiled under it.
_context: contextvars.ContextVar = contextvars.ContextVar(
    "moeva2_ledger_context", default=None
)

#: transfer-guard mode applied around every AOT executable dispatch
#: (``None`` = off, the production default). ``tools/shard_lint.py`` sets
#: "disallow" so an argument that is not already resident on its devices
#: — an implicit host→device transfer at dispatch — raises instead of
#: silently serialising the hot path through the host.
_dispatch_transfer_guard: str | None = None


def set_dispatch_transfer_guard(mode: str | None) -> str | None:
    """Set the dispatch transfer-guard mode ("disallow"/"log"/None);
    returns the previous mode so lint callers can restore it."""
    global _dispatch_transfer_guard
    prev = _dispatch_transfer_guard
    _dispatch_transfer_guard = mode
    return prev


@contextlib.contextmanager
def ledger_context(**attrs):
    """Merge ``attrs`` into the identity of every executable compiled in
    this dynamic extent (the microbatcher wraps each batch dispatch so the
    bucket size and batch composition land in the ledger)."""
    token = _context.set(dict(_context.get() or {}, **attrs))
    try:
        yield
    finally:
        _context.reset(token)


def current_ledger_context() -> dict:
    """The ambient :func:`ledger_context` attrs of the calling extent —
    how a dispatch closure running under the microbatcher reads the
    batch composition (bucket, batch_requests) the batcher pushed."""
    return dict(_context.get() or {})


# -- cost-model probes --------------------------------------------------------
def probe_cost_analysis(compiled) -> dict | None:
    """Best-effort ``{flops, bytes_accessed, transcendentals}`` from
    ``compiled.cost_analysis()``. None when the backend ships no cost
    model (the call raises, returns None, or returns an empty mapping) —
    jax returns a per-device list on some versions, a bare dict on others,
    and raises ``Unimplemented`` on some backends."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    out = {}
    for src, dst in (
        ("flops", "flops"),
        ("bytes accessed", "bytes_accessed"),
        ("transcendentals", "transcendentals"),
    ):
        v = ca.get(src)
        if v is not None:
            try:
                out[dst] = float(v)
            except (TypeError, ValueError):
                continue
    return out or None


def probe_memory_analysis(compiled) -> dict | None:
    """Best-effort byte footprint from ``compiled.memory_analysis()``:
    argument/output/temp/alias/generated-code sizes. None when the backend
    does not implement it (raises or returns None)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for attr, dst in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "code_bytes"),
    ):
        v = getattr(ma, attr, None)
        if v is not None:
            try:
                out[dst] = int(v)
            except (TypeError, ValueError):
                continue
    return out or None


def nearest_identity_diff(candidates, identity: dict) -> dict | None:
    """Why did a cache miss happen given what's already cached? Diff
    ``identity`` against the nearest of ``candidates`` (an iterable of
    ``(ref, identity_dict)``, nearest = fewest differing fields) and name
    exactly the fields that differed — "rows 64 -> 128" reads a lot
    faster than two opaque keys. None when nothing is comparable (a cold
    miss, not a *re*compile). Shared by the executable ledger and the
    engine cache so the /healthz recompile-cause views stay one schema."""
    best = None
    for ref, ident in candidates:
        fields = sorted(set(identity) | set(ident))
        diffs = [f for f in fields if identity.get(f) != ident.get(f)]
        if best is None or len(diffs) < len(best[2]):
            best = (ref, ident, diffs)
    if best is None:
        return None
    ref, ident, diffs = best
    return {
        "nearest": ref,
        "changed": {
            f: {"from": ident.get(f), "to": identity.get(f)} for f in diffs
        },
    }


# -- entries ------------------------------------------------------------------
@dataclass
class LedgerEntry:
    """One compiled executable: identity + static cost + measured use."""

    key: str  #: stable id: ``{producer}#{seq}``
    producer: str  #: which program family built it (pgd_attack, moeva_segment…)
    identity: dict  #: JSON-ready compile-time identity (cache key, rows, knobs)
    backend: str
    compile_s: float
    cost_available: bool  #: cost OR memory model present (satellite contract)
    flops: float | None = None
    bytes_accessed: float | None = None
    transcendentals: float | None = None
    memory: dict | None = None
    aot: bool = True  #: False = jit fallback (lowering failed); no cost model
    dispatches: int = 0
    run_s: float = 0.0  #: attributed device+fetch seconds (engines' sync points)
    created_wall: float = field(default_factory=time.time)
    #: mesh-scale identity (observability.mesh.probe_compiled): device /
    #: states-partition counts, input/output sharding summary, collective
    #: census — all None/1 for single-device programs or with the mesh
    #: capture off, so single-device records stay byte-stable.
    devices: int = 1
    partitions: int = 1
    sharding: dict | None = None
    collectives: dict | None = None
    #: where the executable came from: "aot" = deserialized from the
    #: persistent AOT cache (compile_s is the load wall-clock, no trace/
    #: lower/compile happened); None = compiled in-process (possibly via
    #: the jax persistent cache — the cold ledger's classification says
    #: which). Only present in entry JSON when set, so default-config
    #: entries stay byte-identical to the pre-AOT schema.
    source: str | None = None

    def per_device(self) -> dict:
        """Whole-program cost split across devices (states-partitioned
        programs split, unpartitioned ones replicate — see
        ``observability.mesh.per_device_cost``)."""
        from .mesh import per_device_cost

        return per_device_cost(
            self.flops, self.bytes_accessed, self.partitions, self.devices
        )

    def roofline(self, dispatches: int | None = None, run_s: float | None = None) -> dict:
        """Achieved rates from the cost model joined with attributed run
        seconds. ``arithmetic_intensity`` is the static model ratio
        (FLOPs per HBM byte — where the program sits on the roofline);
        achieved rates need at least one attributed dispatch. Pass
        ``dispatches``/``run_s`` to compute over a window instead of the
        entry lifetime (the per-record cost blocks)."""
        d = self.dispatches if dispatches is None else dispatches
        r = self.run_s if run_s is None else run_s
        out: dict = {
            "dispatches": d,
            "run_s": round(r, 6),
            "achieved_flops_s": None,
            "achieved_bytes_s": None,
            "arithmetic_intensity": None,
        }
        if self.flops is not None and self.bytes_accessed:
            out["arithmetic_intensity"] = round(
                self.flops / self.bytes_accessed, 4
            )
        if r > 0:
            if self.flops is not None:
                out["achieved_flops_s"] = round(self.flops * d / r, 1)
            if self.bytes_accessed is not None:
                out["achieved_bytes_s"] = round(
                    self.bytes_accessed * d / r, 1
                )
        return out

    def as_dict(
        self,
        compile_s: float | None = None,
        dispatches: int | None = None,
        run_s: float | None = None,
    ) -> dict:
        out = {
            "key": self.key,
            "producer": self.producer,
            "identity": self.identity,
            "backend": self.backend,
            "compile_s": round(
                self.compile_s if compile_s is None else compile_s, 4
            ),
            "cost_available": self.cost_available,
            "aot": self.aot,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "transcendentals": self.transcendentals,
            "memory": self.memory,
            **self.roofline(dispatches, run_s),
        }
        if self.source is not None:
            out["source"] = self.source
        if self.devices > 1:
            # mesh sub-block only on multi-device executables: per-device
            # cost split, sharding summary, collective census — keeping
            # single-device entry JSON byte-identical to the pre-mesh
            # ledger (the committed BENCH series compares against it)
            out["mesh"] = {
                "per_device": self.per_device(),
                "partitions": self.partitions,
                "devices": self.devices,
                "sharding": self.sharding,
                "collectives": self.collectives,
            }
        return out


class CostLedger:
    """Process-wide registry of compiled executables and their costs."""

    #: recompile causes kept (bounded — the ledger must not grow with
    #: serving uptime)
    MAX_CAUSES = 64

    def __init__(self, enabled: bool = True):
        self._lock = threading.Lock()
        self._entries: dict[str, LedgerEntry] = {}
        self._seq = 0
        self.enabled = enabled
        self.hits = 0  #: executable-cache hits (dispatches that reused)
        self.misses = 0  #: compiles (AOT or fallback)
        self.recompile_causes: list[dict] = []

    # -- recording -----------------------------------------------------------
    def record_compile(
        self,
        *,
        producer: str,
        identity: dict,
        backend: str,
        compile_s: float,
        cost: dict | None,
        memory: dict | None,
        aot: bool = True,
        mesh_probe: dict | None = None,
        source: str | None = None,
    ) -> LedgerEntry | None:
        """Register a freshly compiled executable; returns its entry (None
        when the ledger is disabled — the compile itself already happened
        identically either way). ``mesh_probe`` is an
        ``observability.mesh.probe_compiled`` result (sharding summary +
        collective census) for multi-device programs."""
        with self._lock:
            self.misses += 1
            if not self.enabled:
                return None
            self._seq += 1
            key = f"{producer}#{self._seq}"
            cause = self._recompile_cause_locked(producer, identity, key)
            entry = LedgerEntry(
                key=key,
                producer=producer,
                identity=dict(identity),
                backend=backend,
                compile_s=float(compile_s),
                cost_available=bool(cost or memory),
                flops=(cost or {}).get("flops"),
                bytes_accessed=(cost or {}).get("bytes_accessed"),
                transcendentals=(cost or {}).get("transcendentals"),
                memory=memory,
                aot=aot,
                devices=int((mesh_probe or {}).get("devices") or 1),
                partitions=int((mesh_probe or {}).get("partitions") or 1),
                sharding=(mesh_probe or {}).get("sharding"),
                collectives=(mesh_probe or {}).get("collectives"),
                source=source,
            )
            self._entries[key] = entry
            if cause is not None:
                self.recompile_causes.append(cause)
                del self.recompile_causes[: -self.MAX_CAUSES]
            return entry

    def _recompile_cause_locked(
        self, producer: str, identity: dict, key: str
    ) -> dict | None:
        cause = nearest_identity_diff(
            (
                (e.key, e.identity)
                for e in self._entries.values()
                if e.producer == producer
            ),
            identity,
        )
        if cause is None:
            return None
        return {"key": key, "producer": producer, **cause}

    def record_hit(self) -> None:
        with self._lock:
            self.hits += 1

    def record_dispatch(self, key: str) -> None:
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.dispatches += 1

    def add_compile_seconds(self, key: str, seconds: float) -> None:
        """Late compile attribution: the AOT-fallback path pays its real
        trace + XLA compile inside the first jit dispatch, after the entry
        was recorded."""
        if seconds <= 0:
            return
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.compile_s += float(seconds)

    def add_run_seconds(self, key: str, seconds: float) -> None:
        """Attribute measured run wall-clock (dispatch to fetched result,
        compile excluded) to an executable — called by the engines at
        their existing device→host sync points, never by adding one."""
        if seconds <= 0:
            return
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e.run_s += float(seconds)

    # -- introspection -------------------------------------------------------
    def entries(self) -> list[LedgerEntry]:
        with self._lock:
            return list(self._entries.values())

    def summary(self) -> dict:
        """The health-endpoint view: executable count, total compile
        seconds, executable-cache hit ratio."""
        with self._lock:
            entries = list(self._entries.values())
            hits, misses = self.hits, self.misses
        total = hits + misses
        flops = [
            e.flops * e.dispatches
            for e in entries
            if e.flops is not None and e.dispatches
        ]
        return {
            "enabled": self.enabled,
            "executables": len(entries),
            "compile_s_total": round(sum(e.compile_s for e in entries), 3),
            "dispatches": sum(e.dispatches for e in entries),
            # dispatch-weighted model FLOPs — the work normalizer
            # tools/bench_diff.py divides wall-clock by
            "flops_total": sum(flops) if flops else None,
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_ratio": round(hits / total, 4) if total else None,
            "cost_available": any(e.cost_available for e in entries),
        }

    def summary_delta(self, before: dict) -> dict:
        """``summary()`` relative to an earlier snapshot (numeric keys
        subtract; the hit ratio is recomputed over the window) — how a
        grid report scopes the process ledger to one sweep."""
        now = self.summary()
        out = {
            k: now[k] - before.get(k, 0)
            for k in (
                "executables",
                "compile_s_total",
                "dispatches",
                "cache_hits",
                "cache_misses",
            )
        }
        out["compile_s_total"] = round(out["compile_s_total"], 3)
        window = out["cache_hits"] + out["cache_misses"]
        out["cache_hit_ratio"] = (
            round(out["cache_hits"] / window, 4) if window else None
        )
        return out

    def mark(self) -> dict:
        """Opaque snapshot for window-scoped cost blocks
        (``cost_block(since=mark)``): record producers take one at run
        start so ``telemetry.cost`` reports the executables *this run*
        compiled and dispatched — not the process lifetime, which on a
        shared-engine grid would charge every warm point with the first
        point's compile and corrupt bench_diff's work normalizer. Under
        the grid pipeline's host/device overlap a neighbouring point's
        dispatches can bleed into the window; scoping is per-window, not
        per-thread."""
        with self._lock:
            return {
                "entries": {
                    k: (e.dispatches, e.run_s)
                    for k, e in self._entries.items()
                },
                "hits": self.hits,
                "misses": self.misses,
            }

    def cost_block(self, since: dict | None = None) -> dict:
        """The ``telemetry.cost`` sub-block every bench/grid/serving
        record carries: a summary plus per-executable identity, cost, and
        roofline rows (JSON-ready; bounded by the number of compiled
        programs, which the bucket-menu discipline keeps small). With
        ``since`` (a :meth:`mark`), entries and totals are scoped to the
        window: executables compiled in it carry their compile time,
        pre-existing executables appear only if re-dispatched (compile
        charged as 0 — it happened before this run), and dispatch/run
        numbers are deltas."""
        with self._lock:
            entries = list(self._entries.values())
            hits, misses = self.hits, self.misses
        prev = (since or {}).get("entries", {})
        rows: list[tuple[LedgerEntry, float, int, float]] = []
        for e in entries:
            p = prev.get(e.key)
            if p is None:
                rows.append((e, e.compile_s, e.dispatches, e.run_s))
            elif e.dispatches > p[0]:
                rows.append(
                    (e, 0.0, e.dispatches - p[0], max(e.run_s - p[1], 0.0))
                )
        if since is not None:
            hits -= since.get("hits", 0)
            misses -= since.get("misses", 0)
        total = hits + misses
        flops = [
            e.flops * d for (e, _, d, _) in rows
            if e.flops is not None and d
        ]
        return {
            "enabled": self.enabled,
            "executables": len(rows),
            "compile_s_total": round(sum(c for (_, c, _, _) in rows), 3),
            "dispatches": sum(d for (_, _, d, _) in rows),
            "flops_total": sum(flops) if flops else None,
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_ratio": round(hits / total, 4) if total else None,
            "cost_available": any(e.cost_available for (e, _, _, _) in rows),
            "entries": [
                e.as_dict(compile_s=c, dispatches=d, run_s=r)
                for (e, c, d, r) in rows
            ],
        }

    def flops_for(self, executables) -> float | None:
        """Static model FLOPs of a dispatch set (an iterable of entry keys,
        one dispatch each, or a ``{key: dispatch_count}`` mapping) — the
        capacity model's per-batch cost. None when no dispatched
        executable carries a cost model."""
        items = (
            executables.items()
            if isinstance(executables, dict)
            else ((k, 1) for k in executables)
        )
        total = 0.0
        have = False
        with self._lock:
            for k, n in items:
                e = self._entries.get(k)
                if e is not None and e.flops is not None:
                    total += e.flops * n
                    have = True
        return total if have else None

    def roofline_for(self, executables, seconds: float) -> dict | None:
        """Static cost of a dispatch set joined with a caller-measured
        duration (a PR-4 ``device_run`` span): the per-span roofline
        attrs serving attaches to ``meta.trace``. ``executables`` is
        either an iterable of keys (one dispatch each) or a
        ``{key: dispatch_count}`` mapping — a MoEvA span chains the same
        segment executable many times."""
        items = (
            executables.items()
            if isinstance(executables, dict)
            else ((k, 1) for k in executables)
        )
        flops = 0.0
        bytes_ = 0.0
        have = False
        with self._lock:
            for k, n in items:
                e = self._entries.get(k)
                if e is None:
                    continue
                if e.flops is not None:
                    flops += e.flops * n
                    have = True
                if e.bytes_accessed is not None:
                    bytes_ += e.bytes_accessed * n
        if not have or seconds <= 0:
            return None
        return {
            "flops": flops,
            "achieved_flops_s": round(flops / seconds, 1),
            "achieved_bytes_s": round(bytes_ / seconds, 1) if bytes_ else None,
        }

    def reset(self) -> None:
        """Drop all state (tests only — production ledgers live with the
        process)."""
        with self._lock:
            self._entries.clear()
            self._seq = 0
            self.hits = self.misses = 0
            self.recompile_causes = []


#: THE process ledger: every producer records here so one /healthz,
#: /metrics, or telemetry block sees the whole executable population.
LEDGER = CostLedger()


def get_ledger() -> CostLedger:
    return LEDGER


def configure_ledger(config: dict | None) -> CostLedger:
    """Apply config ``system.cost_ledger`` (default on; the capture is a
    few dict writes per *compile*, not per dispatch)."""
    enabled = (config or {}).get("system", {}).get("cost_ledger", True)
    LEDGER.enabled = bool(enabled)
    return LEDGER


# -- the capture point --------------------------------------------------------
class LedgeredJit:
    """AOT compile-and-dispatch wrapper around a ``jax.jit`` callable.

    Caches compiled executables under the dynamic arguments' avals (+
    shardings + static values) — the same partitioning the jit cache
    uses for these call sites — and records each compile into the ledger
    with its identity, cost/memory analysis, and wall-clock. Static
    arguments (``static_argnums`` positions and all kwargs) are passed to
    ``lower()`` and dropped from the compiled call, matching jax AOT
    semantics. ``calls`` counts every dispatch regardless of ledger
    state (the zero-extra-dispatches contract's witness).

    ``identity`` is a dict or zero-arg callable evaluated at compile
    time; ``describe_args`` may add per-shape identity (batch rows, scan
    length) from the actual arguments. ``on_dispatch(entry, compile_s)``
    fires after every call so the owning engine can attribute run time.
    """

    def __init__(
        self,
        jitted,
        *,
        producer: str,
        identity: dict | Callable[[], dict] | None = None,
        describe_args: Callable[..., dict] | None = None,
        static_argnums: tuple = (),
        static_argnames: tuple = (),
        on_dispatch: Callable[[Any, float], None] | None = None,
        ledger: CostLedger | None = None,
    ):
        self._jitted = jitted
        self.producer = producer
        self._identity = identity
        self._describe_args = describe_args
        self._static_argnums = tuple(static_argnums)
        self._static_argnames = tuple(static_argnames)
        self._on_dispatch = on_dispatch
        self._ledger = ledger if ledger is not None else LEDGER
        self._compiled: dict = {}
        self._lock = threading.Lock()
        self.calls = 0  #: total dispatches through this wrapper
        self.last_entry: LedgerEntry | None = None
        #: compile seconds consumed by the most recent call (0.0 on an
        #: executable-cache hit) — callers subtract it from their measured
        #: wall-clock so run attribution never includes compile time
        self.last_call_compile_s = 0.0

    # -- keying --------------------------------------------------------------
    @staticmethod
    def _leaf_sig(leaf) -> tuple:
        import numpy as np

        if isinstance(leaf, (bool, int, float, complex)) and not isinstance(
            leaf, np.generic
        ):
            # python scalars trace as weak types; key them apart from
            # committed arrays of the same dtype
            return ("py", type(leaf).__name__, ())
        sharding = getattr(leaf, "sharding", None)
        return (
            tuple(np.shape(leaf)),
            str(getattr(leaf, "dtype", np.asarray(leaf).dtype)),
            bool(getattr(leaf, "weak_type", False)),
            str(sharding) if sharding is not None else None,
        )

    def _split(self, args):
        dyn, static = [], []
        for i, a in enumerate(args):
            (static if i in self._static_argnums else dyn).append(a)
        return dyn, tuple(static)

    def _key(self, args, kwargs):
        import jax

        dyn, static = self._split(args)
        leaves, treedef = jax.tree_util.tree_flatten(dyn)
        return (
            static,
            tuple(sorted(kwargs.items())),
            treedef,
            tuple(self._leaf_sig(l) for l in leaves),
        )

    # -- compile -------------------------------------------------------------
    @staticmethod
    def _mesh_probe(compiled, lowered) -> dict | None:
        """Best-effort mesh probe of a fresh executable (sharding specs +
        collective census) — compile-time only, skipped entirely when the
        mesh capture is off, and never allowed to fail the compile."""
        try:
            from .mesh import get_mesh_capture, probe_compiled

            if not get_mesh_capture().enabled:
                return None
            probe = probe_compiled(
                compiled, out_info=getattr(lowered, "out_info", None)
            )
            # single-device programs carry no mesh payload (keeps their
            # ledger entries byte-identical to the pre-mesh schema)
            return probe if probe.get("devices", 1) > 1 else None
        except Exception:
            return None

    def _compile(self, args, kwargs, key=None):
        import jax

        coldstart = get_coldstart()
        # serialized-executable tier (observability.aotcache): a hit
        # deserializes the finished binary and skips trace+lower+compile
        # entirely — the fastest possible cold path. Key derivation and
        # loading are best-effort: any failure (unkeyable identity,
        # corrupt/stale/foreign entry — each a counted
        # ``aot_cache_load_failures`` event inside the cache) falls
        # through to the normal compile below, which then refreshes the
        # entry.
        aot_cache = get_aot_cache()
        # ONE pre-compile snapshot serves both the AOT-hit note and the
        # fall-through compile classification: nothing between the AOT
        # load attempt and the compile touches the jax cache, and the
        # probe's directory scan is per-compile I/O worth not doubling
        # on a ~400-executable cold start
        probe = coldstart.compile_probe()
        aot_key = None
        if aot_cache.enabled and key is not None:
            try:
                aot_key = aot_cache.cache_key(
                    self.producer, self._base_identity(args, kwargs), key
                )
            except Exception:
                aot_key = None
        if aot_key is not None:
            t0 = time.perf_counter()
            loaded = aot_cache.load(aot_key)
            if loaded is not None:
                load_s = time.perf_counter() - t0
                entry = self._ledger.record_compile(
                    producer=self.producer,
                    identity=self._full_identity(args, kwargs),
                    backend=jax.default_backend(),
                    compile_s=load_s,
                    cost=probe_cost_analysis(loaded),
                    memory=probe_memory_analysis(loaded),
                    mesh_probe=self._mesh_probe(loaded, None),
                    source="aot",
                )
                coldstart.note_compile(
                    producer=self.producer,
                    key=entry.key if entry is not None else None,
                    lower_s=0.0,
                    compile_s=load_s,
                    probe=probe,
                    aot_cache="hit",
                )
                return (loaded, entry, load_s)
        t0 = time.perf_counter()
        try:
            lowered = self._jitted.lower(*args, **kwargs)
            lower_s = time.perf_counter() - t0
            compiled = lowered.compile()
        except Exception:
            # AOT unavailable for this signature: plain jit dispatch —
            # behavior is preserved, the ledger records the degradation
            compile_s = time.perf_counter() - t0
            entry = self._ledger.record_compile(
                producer=self.producer,
                identity=self._full_identity(args, kwargs),
                backend=jax.default_backend(),
                compile_s=compile_s,
                cost=None,
                memory=None,
                aot=False,
            )
            coldstart.note_compile(
                producer=self.producer,
                key=entry.key if entry is not None else None,
                lower_s=compile_s,
                compile_s=0.0,
                probe=probe,
                aot=False,
            )
            return (None, entry, compile_s)
        compile_s = time.perf_counter() - t0
        # serialize the finished executable for the NEXT process (atomic,
        # best-effort — a failed store degrades to plain compiles); done
        # before the cold-start classification so the entry reads
        # ``aot_stored``. REAL compiles only: an executable that
        # ``lower().compile()`` satisfied from the jax persistent cache
        # serializes into a blob that fails cross-process deserialization
        # ("Symbols not found", observed on CPU PJRT / jax 0.4.37) — and
        # the next process would load it from the jax cache anyway, so
        # skipping loses nothing. Detection is the monitoring-counter
        # delta (best-effort; the load path's counted-failure +
        # self-healing discard backstops an undetected bad store).
        stored = (
            aot_cache.store(aot_key, compiled, producer=self.producer)
            if aot_key is not None
            and not coldstart.saw_cache_hit_since(probe)
            else False
        )
        entry = self._ledger.record_compile(
            producer=self.producer,
            identity=self._full_identity(args, kwargs),
            backend=jax.default_backend(),
            compile_s=compile_s,
            cost=probe_cost_analysis(compiled),
            memory=probe_memory_analysis(compiled),
            mesh_probe=self._mesh_probe(compiled, lowered),
        )
        # cold-start decomposition: the lower-vs-XLA-compile split plus
        # the persistent-cache hit/miss classification (host bookkeeping
        # only — the compile above already happened identically)
        coldstart.note_compile(
            producer=self.producer,
            key=entry.key if entry is not None else None,
            lower_s=lower_s,
            compile_s=max(compile_s - lower_s, 0.0),
            probe=probe,
            aot_cache="stored" if stored else None,
        )
        return (compiled, entry, compile_s)

    def _base_identity(self, args, kwargs) -> dict:
        """Compile-time identity WITHOUT the ambient ledger context —
        the AOT cache keys off this: context attrs (batch composition,
        request ids) vary per dispatch and would fragment a disk key
        that must be stable across processes."""
        ident = self._identity
        out = dict(ident() if callable(ident) else (ident or {}))
        if self._describe_args is not None:
            try:
                out.update(self._describe_args(*args, **kwargs))
            except Exception:
                pass
        return out

    def _full_identity(self, args, kwargs) -> dict:
        out = self._base_identity(args, kwargs)
        ctx = _context.get()
        if ctx:
            out.update(ctx)
        return out

    # -- dispatch ------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        self.calls += 1
        # time-to-first-dispatch for the cold-start decomposition: one
        # None-check per call after the first (never a device sync)
        get_coldstart().note_dispatch()
        try:
            key = self._key(args, kwargs)
        except Exception:
            # unkeyable arguments: stay on the jit path, uninstrumented
            self.last_call_compile_s = 0.0
            return self._jitted(*args, **kwargs)
        rec = self._compiled.get(key)
        if rec is None:
            with self._lock:
                rec = self._compiled.get(key)
                if rec is None:
                    rec = self._compile(args, kwargs, key)
                    self._compiled[key] = rec
                    compiled_now = True
                else:
                    compiled_now = False
        else:
            compiled_now = False
        compiled, entry, compile_s = rec
        if not compiled_now:
            self._ledger.record_hit()
        self.last_call_compile_s = compile_s if compiled_now else 0.0
        self.last_entry = entry
        if compiled is None:
            if compiled_now:
                # fallback path, first call: the REAL trace + XLA compile
                # happens synchronously inside this jit call — book it as
                # compile so the caller's run attribution (elapsed minus
                # last_call_compile_s) keeps compile out of run seconds
                t0 = time.perf_counter()
                out = self._jitted(*args, **kwargs)
                jit_compile_s = time.perf_counter() - t0
                self.last_call_compile_s += jit_compile_s
                if entry is not None:
                    self._ledger.add_compile_seconds(entry.key, jit_compile_s)
            else:
                out = self._jitted(*args, **kwargs)
        else:
            dyn, _ = self._split(args)
            if _dispatch_transfer_guard is not None:
                import jax

                # the lint seam: with the guard armed, any argument not
                # already resident on its devices trips here — the
                # "implicit host↔device transfer in the dispatch path"
                # rule of tools/shard_lint.py
                with jax.transfer_guard(_dispatch_transfer_guard):
                    out = compiled(*dyn)
            else:
                out = compiled(*dyn)
        if entry is not None:
            self._ledger.record_dispatch(entry.key)
        if self._on_dispatch is not None:
            self._on_dispatch(entry, self.last_call_compile_s)
        return out
