"""Mesh-scale observability: per-device cost, collectives, and balance.

Everything the observability stack built through PR 7 reads ONE device:
the HBM watermark probes device 0, the roofline is whole-program, and the
states-sharding contract ("zero collectives in the hot loop",
``attacks/sharding.py``) is asserted in prose only. This module is the
mesh-shaped half:

- **Compile-time probes** — :func:`probe_compiled` inspects a freshly
  compiled executable (the :class:`~.ledger.LedgeredJit` capture point)
  for its input/output sharding specs and, via the partitioned HLO text
  (``compiled.as_text()``), its collective-communication ops
  (all-reduce / all-gather / reduce-scatter / collective-permute /
  all-to-all) with estimated bytes moved and replica-group sizes. Both
  probes follow the cost-model discipline: best-effort, never raising,
  degrading to ``None`` when a backend does not expose them.

- **Per-device cost split** — :func:`per_device_cost` divides a
  whole-program XLA cost model by the states-axis partition count
  (falling back to replicated cost — every device pays the full program
  — when nothing was partitioned), which joined with the balance
  tracker's per-device run seconds yields a per-device roofline.

- **Balance telemetry** — :class:`MeshCapture`, a process-wide
  accumulator the engines feed at their *existing* sync points (never by
  adding one): each recorded window attributes run seconds to devices in
  proportion to their live-row share (SPMD devices run in lockstep, so a
  device whose rows all parked is paying wall-clock for no useful work).
  The balance ratio (mean/max useful seconds, 1.0 = perfectly balanced)
  is gated across the committed bench series by
  ``tools/bench_diff.py --mesh``.

- **Record schema** — :func:`mesh_block` assembles the ``telemetry.mesh``
  sub-block (per-device roofline + HBM, balance, collective
  classification) that :func:`~.records.validate_record` requires on any
  record whose execution mode says it ran on more than one device;
  :func:`mesh_snapshot` is the process-cumulative /healthz · /metrics
  view of the same numbers.

Capture on/off (config ``system.mesh_telemetry``) changes which host-side
bookkeeping runs, never the compiled programs or the dispatch schedule —
the tier-1 smoke in ``tests/test_mesh_observability.py`` pins zero extra
compiles/dispatches and bit-identical results either way.
"""

from __future__ import annotations

import re
import threading

from .trace import all_device_memory_stats

#: HLO collective op mnemonics counted by the communication ledger.
#: Order matters: longest-prefix first so "all-reduce-scatter" style
#: compounds cannot be claimed by a shorter name.
COLLECTIVE_OPS = (
    "reduce-scatter",
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
)

#: producers whose executables ARE the hot loop: a collective here breaks
#: the zero-collective states-sharding contract (init/gate programs run
#: once per segment boundary, not per generation/iteration).
HOT_LOOP_PRODUCERS = ("pgd_attack", "moeva_segment")

#: HLO primitive-type byte widths (tuple/token types carry no payload we
#: can attribute; unknown types fall back to 4 bytes).
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

#: one HLO instruction's result-shape tokens: ``f32[16,4]``; dims may be
#: empty (scalar).
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
#: iota-form replica groups: ``replica_groups=[<n_groups>,<group_size>]<=``
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")
#: list-form replica groups: ``replica_groups={{0,1},{2,3}}``
_LIST_GROUPS_RE = re.compile(r"replica_groups=\{\{([0-9, ]*)\}")


def _shape_bytes(type_text: str) -> tuple[float, float]:
    """``(total_bytes, float_bytes)`` of every ``dtype[dims]`` token in a
    result-type string (handles tuple-shaped async collective results).
    Float bytes are tracked separately: they are candidate/objective DATA
    crossing devices, as opposed to the u32 RNG-key material, pred
    loop-consensus scalars, and s32 index exchanges the SPMD partitioner
    inserts on its own (the lint's hot-loop rule keys off this split)."""
    total = 0.0
    float_total = 0.0
    for dtype, dims in _SHAPE_RE.findall(type_text):
        if dtype == "token":
            continue
        n = 1.0
        if dims:
            for d in dims.split(","):
                n *= float(d)
        b = n * _DTYPE_BYTES.get(dtype, 4)
        total += b
        if dtype.startswith(("f", "bf", "c")):
            float_total += b
    return total, float_total


def parse_collectives(hlo_text: str) -> dict:
    """Count collective ops (and estimate bytes moved) in partitioned HLO.

    One entry per *logical* collective: the async ``-start``/``-done``
    pairs XLA emits count once (at ``-start``). Bytes are the result-shape
    payload — a deliberate, documented lower-bound estimate (a ring
    all-reduce moves ~2(n-1)/n of it per device; what the lint and the
    classification need is "zero vs not zero" and relative magnitude, not
    a NIC-accurate byte count). ``float_count``/``float_bytes`` split out
    collectives moving floating-point payloads: actual candidate /
    objective data, as opposed to the u32 RNG-key derivation, pred
    loop-consensus, and s32 index traffic XLA's SPMD partitioner inserts
    even into embarrassingly parallel programs — the lint tolerates the
    latter (bounded) and fails the former. ``group_sizes`` histograms the
    replica group sizes seen, which :func:`collective_axes` maps back to
    mesh axes."""
    ops: dict[str, dict] = {}
    group_sizes: dict[str, int] = {}
    count = 0
    bytes_total = 0.0
    float_count = 0
    float_bytes = 0.0
    for line in hlo_text.splitlines():
        # find the collective this line dispatches: the call token is
        # " <op>(" or " <op>-start(". Matching the TOKEN (not a prefix of
        # the text before the first "(") is load-bearing twice over: async
        # starts returning TUPLES — "(f32[..], f32[..]) all-gather-start("
        # — put a "(" before the op name, and "-done" completions (already
        # counted at -start) never match because no bare/-start token does.
        op, idx = None, -1
        for cand in COLLECTIVE_OPS:
            for suffix in ("(", "-start("):
                i = line.find(f" {cand}{suffix}")
                if i >= 0:
                    op, idx = cand, i
                    break
            if op is not None:
                break
        if op is None:
            continue
        # result type(s) live between '=' and the op call; a tuple-shaped
        # async result counts every member (operand alias included — the
        # estimate stays order-of-magnitude, which is all the lint needs)
        _, _, result = line[:idx].rpartition("=")
        b, fb = _shape_bytes(result)
        slot = ops.setdefault(
            op, {"count": 0, "bytes": 0.0, "float_count": 0, "float_bytes": 0.0}
        )
        slot["count"] += 1
        slot["bytes"] += b
        count += 1
        bytes_total += b
        if fb > 0:
            slot["float_count"] += 1
            slot["float_bytes"] += fb
            float_count += 1
            float_bytes += fb
        m = _IOTA_GROUPS_RE.search(line)
        if m:
            gs = m.group(2)
        else:
            m = _LIST_GROUPS_RE.search(line)
            gs = str(m.group(1).count(",") + 1) if m and m.group(1).strip() else None
        if gs is not None:
            group_sizes[gs] = group_sizes.get(gs, 0) + 1
    for slot in ops.values():
        slot["bytes"] = float(slot["bytes"])
        slot["float_bytes"] = float(slot["float_bytes"])
    return {
        "count": count,
        "bytes": float(bytes_total),
        "float_count": float_count,
        "float_bytes": float(float_bytes),
        "ops": ops,
        "group_sizes": group_sizes,
    }


def probe_collectives(compiled) -> dict | None:
    """Best-effort collective census of a compiled executable via its
    partitioned HLO text; ``None`` when the backend/runtime exposes no
    ``as_text()`` (same degrade-to-unavailable discipline as the cost
    probes — observability must never take an attack down)."""
    try:
        text = compiled.as_text()
    except Exception:
        return None
    if not isinstance(text, str) or not text:
        return None
    try:
        return parse_collectives(text)
    except Exception:
        return None


def _sharding_partitions(sharding) -> tuple[int, int]:
    """(devices, partitions) of one sharding: how many devices hold the
    array, and into how many distinct shards its data splits (1 = fully
    replicated). Works for NamedSharding (mesh axes named in the spec)
    and degrades to device-set arithmetic otherwise."""
    devices = len(getattr(sharding, "device_set", ()) or ()) or 1
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return devices, 1
    try:
        shape = dict(mesh.shape)
        parts = 1
        for entry in spec:
            axes = entry if isinstance(entry, tuple) else (entry,)
            for ax in axes:
                if ax is not None:
                    parts *= int(shape.get(ax, 1))
        return devices, parts
    except Exception:
        return devices, 1


def _aval_bytes(aval) -> int:
    import numpy as np

    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = getattr(aval, "dtype", None)
    itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
    n = 1
    for d in shape:
        n *= int(d)
    return int(n * itemsize)


def _sharding_rows(shardings, avals) -> list[dict]:
    import jax

    sh_leaves = jax.tree_util.tree_leaves(shardings)
    av_leaves = jax.tree_util.tree_leaves(avals)
    rows = []
    for sh, av in zip(sh_leaves, av_leaves):
        devices, parts = _sharding_partitions(sh)
        spec = getattr(sh, "spec", None)
        rows.append(
            {
                "spec": str(spec) if spec is not None else None,
                "devices": devices,
                "partitions": parts,
                "sharded": parts > 1,
                "bytes": _aval_bytes(av),
            }
        )
    return rows


def _summarize(rows: list[dict]) -> dict:
    largest = max(rows, key=lambda r: r["bytes"], default=None)
    return {
        "arrays": len(rows),
        "sharded": sum(1 for r in rows if r["sharded"]),
        "sharded_bytes": int(sum(r["bytes"] for r in rows if r["sharded"])),
        "replicated_bytes": int(
            sum(r["bytes"] for r in rows if not r["sharded"])
        ),
        "max_replicated_bytes": int(
            max((r["bytes"] for r in rows if not r["sharded"]), default=0)
        ),
        "largest": dict(largest) if largest else None,
    }


def probe_shardings(compiled, out_info=None) -> dict | None:
    """Best-effort input/output sharding summary of a compiled executable:
    per-direction array counts, sharded vs replicated byte totals, and the
    largest array's spec — what the ledger entry records and
    ``tools/shard_lint.py`` lints. ``out_info`` is the lowered stage's
    ``out_info`` (shape/dtype leaves for the outputs, which the Compiled
    object itself does not expose on jax 0.4.x)."""
    try:
        in_sh = compiled.input_shardings
        in_avals = compiled.in_avals
        if isinstance(in_sh, tuple) and len(in_sh) == 2:
            in_sh = in_sh[0]  # (args, kwargs) pair on jax 0.4.x
        if isinstance(in_avals, tuple) and len(in_avals) == 2:
            in_avals = in_avals[0]
        in_rows = _sharding_rows(in_sh, in_avals)
        out_rows = (
            _sharding_rows(compiled.output_shardings, out_info)
            if out_info is not None
            else []
        )
    except Exception:
        return None
    if not in_rows and not out_rows:
        return None
    all_rows = in_rows + out_rows
    return {
        "devices": max((r["devices"] for r in all_rows), default=1),
        "partitions": max((r["partitions"] for r in all_rows), default=1),
        "in": _summarize(in_rows),
        "out": _summarize(out_rows) if out_rows else None,
    }


def probe_compiled(compiled, out_info=None) -> dict:
    """The one mesh probe :class:`~.ledger.LedgeredJit` runs per compile:
    sharding summary + collective census + derived device/partition
    counts. Pure compile-time introspection — dispatch is untouched."""
    sharding = probe_shardings(compiled, out_info=out_info)
    collectives = probe_collectives(compiled)
    return {
        "devices": (sharding or {}).get("devices", 1),
        "partitions": (sharding or {}).get("partitions", 1),
        "sharding": sharding,
        "collectives": collectives,
    }


def per_device_cost(
    flops, bytes_accessed, partitions: int, devices: int
) -> dict:
    """Split a whole-program cost model across devices: a states-partitioned
    program does ``1/partitions`` of the work per device; an unpartitioned
    one is replicated — every device pays the full program (the honest
    fallback the tentpole requires, not a silent ``/devices``)."""
    replicated = partitions <= 1
    div = 1 if replicated else partitions
    return {
        "devices": int(devices),
        "partitions": int(partitions),
        "replicated": replicated,
        "flops": None if flops is None else float(flops) / div,
        "bytes_accessed": (
            None if bytes_accessed is None else float(bytes_accessed) / div
        ),
    }


def collective_axes(group_sizes: dict, mesh_desc: dict | None) -> dict:
    """Map a replica-group-size histogram back onto mesh axes: a group
    size equal to exactly one axis extent attributes to that axis; the
    whole-mesh size attributes to ``"all"``; anything else stays
    ``"group<size>"`` (honest about ambiguity — a 2x4 mesh cannot tell a
    size-8 'all' group from a flattened two-axis group)."""
    out: dict[str, int] = {}
    axes = []
    if mesh_desc:
        axes = list(zip(mesh_desc.get("axes") or [], mesh_desc.get("shape") or []))
    total = (mesh_desc or {}).get("devices")
    for gs_str, n in (group_sizes or {}).items():
        try:
            gs = int(gs_str)
        except (TypeError, ValueError):
            continue
        matches = [name for name, size in axes if int(size) == gs]
        if total is not None and gs == int(total):
            key = "all" if len(axes) != 1 else axes[0][0]
        elif len(matches) == 1:
            key = matches[0]
        else:
            key = f"group{gs}"
        out[key] = out.get(key, 0) + int(n)
    return out


# -- balance telemetry --------------------------------------------------------
class MeshCapture:
    """Process-wide per-device balance accumulator.

    Engines call :meth:`record_balance` at sync points they already have
    (MoEvA's run attribution after the final fetch, PGD's post-fetch run
    attribution) with the live-row count per device for the window and the
    window's attributed run seconds. Useful seconds per device scale with
    its live-row share of the busiest device: SPMD lockstep means every
    device pays the same wall-clock, so a device carrying only parked or
    pad rows accrues wall-clock but no useful seconds — exactly the skew
    the balance ratio surfaces."""

    def __init__(self, enabled: bool = True):
        self._lock = threading.Lock()
        self.enabled = enabled
        self._useful_s: dict[int, float] = {}
        self._sync_points = 0
        self._attributed_s = 0.0
        self._devices = 0

    def record_balance(self, per_device_rows, seconds: float) -> None:
        """Attribute ``seconds`` of run time across devices by live-row
        share. No-op when capture is off, the window is empty, or the
        duration is non-positive — and never raises."""
        if not self.enabled or seconds is None or seconds <= 0:
            return
        try:
            rows = [max(float(r), 0.0) for r in per_device_rows]
        except (TypeError, ValueError):
            return
        if not rows:
            return
        top = max(rows)
        if top <= 0:
            return
        with self._lock:
            self._devices = max(self._devices, len(rows))
            self._sync_points += 1
            self._attributed_s += float(seconds)
            for d, r in enumerate(rows):
                self._useful_s[d] = (
                    self._useful_s.get(d, 0.0) + float(seconds) * r / top
                )

    def mark(self) -> dict:
        """Opaque snapshot for window-scoped balance blocks (the
        ``telemetry.mesh`` discipline mirrors ``CostLedger.mark``)."""
        with self._lock:
            return {
                "useful": dict(self._useful_s),
                "sync_points": self._sync_points,
                "attributed_s": self._attributed_s,
            }

    def balance_block(self, since: dict | None = None) -> dict:
        """JSON-ready balance view, optionally scoped to a window since a
        :meth:`mark`. ``ratio`` = mean/max useful seconds over the window
        (1.0 = perfectly balanced, lower = skewed); ``None`` with no
        attributed windows."""
        with self._lock:
            useful = dict(self._useful_s)
            sync_points = self._sync_points
            attributed = self._attributed_s
            devices = self._devices
        prev = (since or {}).get("useful", {})
        if since is not None:
            useful = {
                d: v - prev.get(d, 0.0)
                for d, v in useful.items()
                if v - prev.get(d, 0.0) > 0 or d in prev
            }
            sync_points -= since.get("sync_points", 0)
            attributed -= since.get("attributed_s", 0.0)
        per_device = [
            round(useful.get(d, 0.0), 6) for d in range(devices)
        ]
        top = max(per_device, default=0.0)
        ratio = (
            round(sum(per_device) / (len(per_device) * top), 4)
            if per_device and top > 0
            else None
        )
        return {
            "devices": devices,
            "per_device_s": per_device,
            "ratio": ratio,
            "sync_points": sync_points,
            "attributed_s": round(max(attributed, 0.0), 6),
        }

    def reset(self) -> None:
        """Drop all state (tests only)."""
        with self._lock:
            self._useful_s.clear()
            self._sync_points = 0
            self._attributed_s = 0.0
            self._devices = 0


#: THE process capture — engines and record producers share it the same
#: way they share ``ledger.LEDGER``.
MESH = MeshCapture()


def get_mesh_capture() -> MeshCapture:
    return MESH


def configure_mesh_capture(config: dict | None) -> MeshCapture:
    """Apply config ``system.mesh_telemetry`` (default on; the capture is
    a compile-time probe plus a few dict writes per engine sync point)."""
    enabled = (config or {}).get("system", {}).get("mesh_telemetry", True)
    MESH.enabled = bool(enabled)
    return MESH


# -- record / endpoint assembly ----------------------------------------------
def _entry_mesh(entry_dict: dict) -> dict | None:
    m = entry_dict.get("mesh")
    return m if isinstance(m, dict) else None


def _aggregate_collectives(entries: list[dict], mesh_desc: dict | None) -> dict:
    """Fold the per-executable collective censuses (scaled by window
    dispatch counts) into one record-level view, split hot-loop vs other
    producers — the compute-vs-comm classification input."""
    total = {"count": 0, "bytes": 0.0, "float_count": 0, "float_bytes": 0.0}
    hot = {"count": 0, "bytes": 0.0, "float_count": 0, "float_bytes": 0.0}
    by_op: dict[str, dict] = {}
    group_sizes: dict[str, int] = {}
    available = False
    for e in entries:
        mesh = _entry_mesh(e)
        col = (mesh or {}).get("collectives")
        if not isinstance(col, dict):
            continue
        available = True
        d = max(int(e.get("dispatches") or 0), 1)
        for agg in (total, hot) if e.get("producer") in HOT_LOOP_PRODUCERS else (total,):
            agg["count"] += col.get("count", 0) * d
            agg["bytes"] += col.get("bytes", 0.0) * d
            agg["float_count"] += col.get("float_count", 0) * d
            agg["float_bytes"] += col.get("float_bytes", 0.0) * d
        for op, slot in (col.get("ops") or {}).items():
            agg = by_op.setdefault(op, {"count": 0, "bytes": 0.0})
            agg["count"] += slot.get("count", 0) * d
            agg["bytes"] += slot.get("bytes", 0.0) * d
        for gs, n in (col.get("group_sizes") or {}).items():
            group_sizes[gs] = group_sizes.get(gs, 0) + int(n) * d
    return {
        "available": available,
        "count": total["count"],
        "bytes": float(total["bytes"]),
        "float_count": total["float_count"],
        "float_bytes": float(total["float_bytes"]),
        "hot_loop": {
            "count": hot["count"],
            "bytes": float(hot["bytes"]),
            "float_count": hot["float_count"],
            "float_bytes": float(hot["float_bytes"]),
        },
        "by_op": by_op,
        "by_axis": collective_axes(group_sizes, mesh_desc),
    }


def mesh_block(
    mesh_desc: dict,
    *,
    ledger=None,
    ledger_since: dict | None = None,
    capture: MeshCapture | None = None,
    capture_since: dict | None = None,
) -> dict:
    """Assemble the ``telemetry.mesh`` sub-block for a record that ran on
    the mesh described by ``mesh_desc`` (an ``attacks.sharding.
    describe_mesh`` dict). Window discipline mirrors ``telemetry.cost``:
    ``ledger_since``/``capture_since`` scope the per-device numbers to
    this run. With capture off the block degrades to
    ``{"enabled": False, ...identity...}`` — still schema-valid, so a
    capture-off multi-device record does not fail validation."""
    capture = capture if capture is not None else MESH
    devices = int(mesh_desc.get("devices") or 1)
    if not capture.enabled:
        return {
            "enabled": False,
            "devices": devices,
            "shape": mesh_desc.get("shape"),
            "axes": mesh_desc.get("axes"),
        }
    from .ledger import get_ledger

    led = ledger if ledger is not None else get_ledger()
    cost = led.cost_block(since=ledger_since)
    entries = cost.get("entries") or []
    balance = capture.balance_block(since=capture_since)
    # per-device model FLOPs over the window, dispatch-weighted from each
    # entry's own mesh.per_device block (the ONE place the split rule
    # lives — per_device_cost: partitioned divides, replicated charges
    # every device the full program). Entries WITHOUT a mesh payload ran
    # on a single device: their cost belongs to that device alone, never
    # to the whole mesh, so they stay out of the per-device numbers.
    flops_per_device = 0.0
    bytes_per_device = 0.0
    cost_available = False
    for e in entries:
        d = int(e.get("dispatches") or 0)
        if not d:
            continue
        pd = (_entry_mesh(e) or {}).get("per_device")
        if not isinstance(pd, dict):
            continue
        if isinstance(pd.get("flops"), (int, float)):
            flops_per_device += pd["flops"] * d
            cost_available = True
        if isinstance(pd.get("bytes_accessed"), (int, float)):
            bytes_per_device += pd["bytes_accessed"] * d
    hbm = all_device_memory_stats()
    # SPMD lockstep: every device pays the same wall-clock (the window's
    # attributed seconds) and executes the same per-shard program, so the
    # per-device achieved rate is uniform; the *useful* seconds from the
    # balance tracker expose the skew as a utilization fraction instead
    # of (misleadingly) inflating an underloaded device's FLOP/s.
    wall_s = balance["attributed_s"]
    per_device = []
    for d in range(devices):
        useful_s = (
            balance["per_device_s"][d]
            if d < len(balance["per_device_s"])
            else 0.0
        )
        per_device.append(
            {
                "device": d,
                "run_s": useful_s,
                "useful_fraction": (
                    round(useful_s / wall_s, 4) if wall_s > 0 else None
                ),
                "flops": flops_per_device if cost_available else None,
                "bytes_accessed": (
                    bytes_per_device if cost_available else None
                ),
                "achieved_flops_s": (
                    round(flops_per_device / wall_s, 1)
                    if cost_available and wall_s > 0
                    else None
                ),
                "hbm": (
                    (hbm or {}).get("per_device", [None] * devices)[d]
                    if hbm and d < len((hbm or {}).get("per_device") or [])
                    else None
                ),
            }
        )
    collectives = _aggregate_collectives(entries, mesh_desc)
    comm_bytes = collectives["bytes"]
    compute_bytes = bytes_per_device * (1 if devices else 0)
    return {
        "enabled": True,
        "devices": devices,
        "shape": mesh_desc.get("shape"),
        "axes": mesh_desc.get("axes"),
        "per_device": per_device,
        "balance": {
            "ratio": balance["ratio"],
            "sync_points": balance["sync_points"],
            "attributed_s": balance["attributed_s"],
        },
        "collectives": collectives,
        # compute-vs-comm classification of the window: collective bytes
        # against per-device HBM traffic — on the contract-clean attack
        # programs comm_fraction must be 0 in the hot loop
        "classification": {
            "comm_bytes": comm_bytes,
            "compute_bytes_per_device": (
                compute_bytes if cost_available else None
            ),
            "comm_fraction": (
                round(comm_bytes / (comm_bytes + compute_bytes), 6)
                if cost_available and (comm_bytes + compute_bytes) > 0
                else None
            ),
        },
    }


#: keys a capture-on ``telemetry.mesh`` block must carry.
MESH_KEYS = ("devices", "per_device", "balance", "collectives")


def validate_mesh(block, kind: str = "record") -> dict:
    """Assert a ``telemetry.mesh`` block is well-formed; returns it.
    A capture-off block (``enabled: False``) passes — the knob is allowed
    to be off, dropping the block entirely is not."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's telemetry.mesh block must be a dict, got "
            f"{type(block).__name__}"
        )
    if block.get("enabled") is False:
        return block
    missing = [k for k in MESH_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's telemetry.mesh block is missing {missing}: "
            "assemble it with observability.mesh.mesh_block so per-device "
            "roofline, balance, and collective attribution travel with "
            "every multi-device record"
        )
    return block


def mesh_snapshot(ledger=None, capture: MeshCapture | None = None) -> dict:
    """Process-cumulative mesh view for /healthz and /metrics: local
    device count, per-device HBM watermarks, balance, and the collective
    census aggregated over every ledgered executable. Device count is
    best-effort (None before JAX initialises)."""
    capture = capture if capture is not None else MESH
    try:
        import jax

        device_count = len(jax.devices())
    except Exception:
        device_count = None
    from .ledger import get_ledger

    led = ledger if ledger is not None else get_ledger()
    entries = [e.as_dict() for e in led.entries()]
    return {
        "enabled": capture.enabled,
        "device_count": device_count,
        "hbm": all_device_memory_stats(),
        "balance": capture.balance_block(),
        "collectives": _aggregate_collectives(entries, None),
    }
