"""Prometheus text exposition (format 0.0.4) for the serving metrics.

Renders a :meth:`~..utils.observability.ServiceMetrics.snapshot` (plus the
cache stats ``AttackService.metrics_snapshot`` appends) to the text format
Prometheus scrapes: counters as ``<prefix>_<name>_total``, gauges as
gauges, bounded sample streams as summaries (windowed p50/p99 quantiles +
full-history ``_count``/``_sum``). ``/metrics?format=prom`` serves this
next to the existing JSON snapshot — same numbers, one recorder, two
wire formats.
"""

from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(prefix: str, name: str, suffix: str = "") -> str:
    return f"{prefix}_{_NAME_RE.sub('_', str(name))}{suffix}"


def _fmt(value) -> str:
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _ledger_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Cost-ledger exposition: summary scalars as gauges plus one labeled
    gauge family per per-executable measure — ``{executable, producer}``
    labels so a dashboard can plot compile time, FLOPs, and achieved
    FLOP/s per compiled program."""
    for key in (
        "executables",
        "compile_s_total",
        "dispatches",
        "cache_hits",
        "cache_misses",
    ):
        v = block.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, f"cost_ledger_{key}")
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_fmt(v)}")
    entries = block.get("entries") or []
    for field in (
        "compile_s",
        "flops",
        "bytes_accessed",
        "dispatches",
        "run_s",
        "achieved_flops_s",
        "achieved_bytes_s",
        "arithmetic_intensity",
    ):
        rows = [
            (e, e.get(field))
            for e in entries
            if isinstance(e.get(field), (int, float))
            and not isinstance(e.get(field), bool)
        ]
        if not rows:
            continue
        n = _name(prefix, f"executable_{field}")
        lines.append(f"# TYPE {n} gauge")
        for e, v in rows:
            labels = (
                f'executable="{_escape_label(e.get("key"))}",'
                f'producer="{_escape_label(e.get("producer"))}"'
            )
            lines.append(f"{n}{{{labels}}} {_fmt(v)}")


def prometheus_text(snapshot: dict, prefix: str = "moeva2") -> str:
    """ServiceMetrics snapshot dict -> Prometheus exposition text."""
    lines: list[str] = []

    ledger_block = snapshot.get("cost_ledger")
    if isinstance(ledger_block, dict):
        _ledger_lines(prefix, ledger_block, lines)

    for name, v in sorted(snapshot.get("counters", {}).items()):
        n = _name(prefix, name, "_total")
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")

    for name, v in sorted(snapshot.get("gauges", {}).items()):
        n = _name(prefix, name)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(v)}")

    for name, s in sorted(snapshot.get("streams", {}).items()):
        n = _name(prefix, name)
        lines.append(f"# TYPE {n} summary")
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            v = s.get(key)
            if v is not None and not (isinstance(v, float) and math.isnan(v)):
                lines.append(f'{n}{{quantile="{q}"}} {_fmt(v)}')
        count = int(s.get("count") or 0)
        mean = s.get("mean")
        lines.append(f"{n}_count {count}")
        lines.append(
            f"{n}_sum {_fmt((mean or 0.0) * count if mean is not None else 0.0)}"
        )

    # flat extras the service appends to its snapshot: scalar numbers become
    # gauges, one-level dicts of numbers (cache stats) become one gauge per
    # sub-key — so engine/artifact cache health is scrapeable too
    for key, v in sorted(snapshot.items()):
        if key in ("counters", "gauges", "streams", "cost_ledger"):
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, key)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_fmt(v)}")
        elif isinstance(v, dict):
            for sub, sv in sorted(v.items()):
                if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                    n = _name(prefix, f"{key}_{sub}")
                    lines.append(f"# TYPE {n} gauge")
                    lines.append(f"{n} {_fmt(sv)}")

    return "\n".join(lines) + "\n"
