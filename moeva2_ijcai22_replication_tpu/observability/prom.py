"""Prometheus text exposition (format 0.0.4) for the serving metrics.

Renders a :meth:`~..utils.observability.ServiceMetrics.snapshot` (plus the
cache stats ``AttackService.metrics_snapshot`` appends) to the text format
Prometheus scrapes: counters as ``<prefix>_<name>_total``, gauges as
gauges, bounded sample streams as summaries (windowed p50/p99 quantiles +
full-history ``_count``/``_sum``). ``/metrics?format=prom`` serves this
next to the existing JSON snapshot — same numbers, one recorder, two
wire formats.
"""

from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _name(prefix: str, name: str, suffix: str = "") -> str:
    return f"{prefix}_{_NAME_RE.sub('_', str(name))}{suffix}"


def _fmt(value) -> str:
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


#: HELP texts for the structured metric families; everything else gets a
#: generated line naming its snapshot section — real scrapers (and the
#: promtool linter) expect every family to carry # HELP and # TYPE.
_HELP = {
    "cost_ledger_executables": "Distinct compiled executables observed by the cost ledger",
    "cost_ledger_compile_s_total": "Total XLA compile wall-clock seconds across all executables",
    "cost_ledger_dispatches": "Total dispatches through ledgered executables",
    "cost_ledger_cache_hits": "AOT executable-cache hits",
    "cost_ledger_cache_misses": "AOT executable-cache misses (compiles)",
    "executable_compile_s": "Per-executable XLA compile wall-clock seconds",
    "executable_flops": "Per-executable model FLOPs per dispatch (XLA cost analysis)",
    "executable_bytes_accessed": "Per-executable bytes accessed per dispatch (XLA cost analysis)",
    "executable_dispatches": "Per-executable dispatch count",
    "executable_run_s": "Per-executable attributed run seconds",
    "executable_achieved_flops_s": "Per-executable achieved FLOP/s (roofline)",
    "executable_bytes_s": "Per-executable achieved bytes/s (roofline)",
    "executable_arithmetic_intensity": "Per-executable arithmetic intensity (FLOPs per byte)",
    "quality_o_rate": "Engine-judged attack success rate per objective column (last MoEvA batch)",
    "quality_best_cv": "Best (minimum) summed constraint violation in the last MoEvA batch",
    "quality_mean_cv": "Mean per-state best constraint violation in the last MoEvA batch",
    "quality_best_dist": "Best engine-objective distance among successful candidates",
    "quality_batches": "MoEvA batches that contributed quality samples",
    "quality_gen": "Generation steps executed by the last sampled MoEvA batch",
    "stage_latency_seconds": "Per-request latency by serving stage, fixed log-spaced buckets. Additive end-to-end decomposition: validate + queue_wait + batch_wait + dispatch; device_run/decode are sub-stages INSIDE dispatch (and dispatch includes compile wall-clock on cold batches, which device_run excludes)",
    "shed_requests": "Requests shed or deadline-overrun, by cause and by the stage that consumed the deadline budget",
    "class_stage_latency_seconds": "Per-request latency by QoS class and serving stage (class-parallel to stage_latency_seconds; present only when serving.qos is on)",
    "class_shed_requests": "Requests shed by QoS class, cause, and the stage that consumed the deadline budget (domain omitted to bound cardinality)",
    "qos_admission_admitted": "Requests admitted by the cost-predictive admission controller",
    "qos_admission_denied": "Requests denied by the cost-predictive admission controller, by QoS class",
    "capacity_qos_requests": "Requests served per domain and QoS class over the capacity window (who the capacity went to)",
    "capacity_max_sustainable_qps": "Ledger-predicted max sustainable requests/s per domain (achieved FLOP/s over predicted FLOPs per request)",
    "capacity_predicted_flops_per_request": "Predicted model FLOPs per request per domain (cost-ledger entries over the capacity window)",
    "capacity_achieved_flops_s": "Achieved FLOP/s per domain over the capacity window (model FLOPs over attributed run seconds)",
    "capacity_utilization": "Attributed device seconds over the capacity window's wall span, per domain",
    "capacity_headroom": "1 - utilization: fraction of the replica's device time still available, per domain",
    "capacity_calibration_error": "Mean |predicted - actual| / actual run seconds per batch: how faithfully FLOPs predict device time",
    "capacity_window_batches": "Batch dispatches currently in the capacity window, per domain",
    "mesh_devices": "Local devices visible to this replica",
    "mesh_balance_ratio": "Per-device balance ratio (mean/max useful run seconds; 1.0 = perfectly balanced)",
    "mesh_balance_sync_points": "Engine sync points that contributed per-device balance windows",
    "mesh_attributed_s": "Run seconds attributed to per-device balance windows",
    "device_run_s": "Useful run seconds attributed per device ordinal (live-row share of SPMD wall-clock)",
    "device_hbm_bytes_in_use": "HBM bytes in use per device ordinal",
    "device_hbm_peak_bytes_in_use": "Peak HBM bytes in use per device ordinal",
    "collective_ops": "Collective-communication ops dispatched, by HLO op (dispatch-weighted census of ledgered executables)",
    "collective_bytes": "Estimated bytes moved by collectives, by HLO op (result-shape lower bound)",
    "collective_hot_loop_ops": "Collective ops inside hot-loop attack executables, incl. tolerated control-plane (u32 RNG/pred consensus) traffic",
    "collective_hot_loop_float_ops": "Collectives moving FLOAT payload inside hot-loop attack executables (states-sharding contract: must be 0)",
    "executable_per_device_flops": "Per-device model FLOPs per dispatch (whole-program cost split by states partitioning; replicated cost when unsharded)",
    "executable_per_device_bytes_accessed": "Per-device bytes accessed per dispatch (whole-program cost split by states partitioning)",
    "overlap_ratio": "Device-busy seconds over wall seconds across recorded dispatch windows (1.0 = device never idle)",
    "device_busy_s": "Device-busy seconds attributed across recorded dispatch windows (engines' sync points)",
    "device_idle_s": "Device-idle gap seconds across recorded dispatch windows (host-side stalls between dispatches)",
    "device_compile_windows_s": "Compile seconds inside recorded dispatch windows (excluded from busy AND idle)",
    "gap_windows": "Engine runs recorded on the dispatch-gap timeline",
    "gap_attributed_s": "Idle gap seconds attributed to a host span/stage active during the gap (recent window ring; pair with gap_unattributed_s, not the lifetime idle gauge)",
    "gap_unattributed_s": "Idle gap seconds no recorded host span covers, over the same recent window ring as gap_attributed_s",
    "producer_overlap_ratio": "Device-busy over compile-free wall per producer (pgd, moeva), lifetime per-window basis",
    "coldstart_phase_s": "Startup-phase seconds by phase (import, artifact_build, trace_lower, xla_compile, device_warmup)",
    "coldstart_persistent_cache_hits": "Persistent-compilation-cache hits observed by jax monitoring in this process",
    "coldstart_persistent_cache_misses": "Persistent-compilation-cache misses observed by jax monitoring in this process",
    "coldstart_cache_entries_added": "Entries this process added to the persistent compilation cache directory",
    "coldstart_time_to_first_dispatch_s": "Seconds from package import to the first compiled-program dispatch",
    "coldstart_executables": "Executables classified by cold source (aot_hit, hit, aot_stored, miss_stored, miss_uncached, fallback, disabled, unknown)",
    "coldstart_aot_load_failures": "Serialized-executable cache entries rejected at load (corrupt, fingerprint-stale, or undeserializable) — each fell back to a fresh compile",
    "incidents_open": "Incidents currently open (SLO-breach/shed-spike/capacity-collapse/balance-drop predicates with frozen evidence)",
    "incidents_total": "Incidents ever opened by the detector, by predicate kind",
    "incidents_suppressed": "Incident re-trips absorbed by dedupe/cooldown (repeats of an open incident or re-trips inside the cooldown window)",
    "flight_ring_entries": "Completed-request entries currently held by the black-box flight recorder ring",
    "flight_dumps": "Flight-recorder dumps written (POST /debug/flight, SIGTERM drain, pre-kill harvest)",
}


def _family(
    lines: list[str], name: str, mtype: str, key: str = "", help_text: str | None = None
):
    """One # HELP + # TYPE header pair per metric family. ``key`` is the
    un-prefixed snapshot name used to look up a curated HELP text; unknown
    families get a generated one — every family MUST carry both lines so
    real scrapers (and promtool) ingest the exposition cleanly."""
    text = help_text or _HELP.get(
        key, f"{key or name} ({mtype} from the moeva2 metrics snapshot)"
    )
    lines.append(f"# HELP {name} {_escape_help(text)}")
    lines.append(f"# TYPE {name} {mtype}")


def _ledger_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Cost-ledger exposition: summary scalars as gauges plus one labeled
    gauge family per per-executable measure — ``{executable, producer}``
    labels so a dashboard can plot compile time, FLOPs, and achieved
    FLOP/s per compiled program."""
    for key in (
        "executables",
        "compile_s_total",
        "dispatches",
        "cache_hits",
        "cache_misses",
    ):
        v = block.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, f"cost_ledger_{key}")
            _family(lines, n, "gauge", f"cost_ledger_{key}")
            lines.append(f"{n} {_fmt(v)}")
    entries = block.get("entries") or []
    for field in (
        "compile_s",
        "flops",
        "bytes_accessed",
        "dispatches",
        "run_s",
        "achieved_flops_s",
        "achieved_bytes_s",
        "arithmetic_intensity",
    ):
        rows = [
            (e, e.get(field))
            for e in entries
            if isinstance(e.get(field), (int, float))
            and not isinstance(e.get(field), bool)
        ]
        if not rows:
            continue
        n = _name(prefix, f"executable_{field}")
        _family(lines, n, "gauge", f"executable_{field}")
        for e, v in rows:
            labels = (
                f'executable="{_escape_label(e.get("key"))}",'
                f'producer="{_escape_label(e.get("producer"))}"'
            )
            lines.append(f"{n}{{{labels}}} {_fmt(v)}")
    # per-device cost split of multi-device executables: the whole-program
    # cost model divided by the states partition count (replicated cost
    # when unsharded) — the per-device roofline's numerator
    for src, key in (
        ("flops", "per_device_flops"),
        ("bytes_accessed", "per_device_bytes_accessed"),
    ):
        rows = [
            (e, ((e.get("mesh") or {}).get("per_device") or {}).get(src))
            for e in entries
            if isinstance(
                ((e.get("mesh") or {}).get("per_device") or {}).get(src),
                (int, float),
            )
        ]
        if not rows:
            continue
        n = _name(prefix, f"executable_{key}")
        _family(lines, n, "gauge", f"executable_{key}")
        for e, v in rows:
            labels = (
                f'executable="{_escape_label(e.get("key"))}",'
                f'producer="{_escape_label(e.get("producer"))}"'
            )
            lines.append(f"{n}{{{labels}}} {_fmt(v)}")


def _quality_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Per-domain attack-quality exposition: one labeled gauge family per
    measure — ``{domain}`` (and ``{domain, objective}`` for the o-rate
    family) labels so a dashboard can plot served success rates per domain
    next to the latency and cost families."""
    by_domain = block.get("by_domain") or {}
    if not by_domain:
        return
    o_rows, scalar_rows = [], {k: [] for k in ("best_cv", "mean_cv", "best_dist", "gen")}
    batch_rows = []
    for domain, q in sorted(by_domain.items()):
        last = q.get("last") or {}
        batch_rows.append((domain, q.get("batches")))
        for i, v in enumerate(last.get("o_rates") or []):
            o_rows.append((domain, f"o{i + 1}", v))
        for k in scalar_rows:
            v = last.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                scalar_rows[k].append((domain, v))
    if o_rows:
        n = _name(prefix, "quality_o_rate")
        _family(lines, n, "gauge", "quality_o_rate")
        for domain, obj, v in o_rows:
            lines.append(
                f'{n}{{domain="{_escape_label(domain)}",'
                f'objective="{obj}"}} {_fmt(v)}'
            )
    for k, rows in scalar_rows.items():
        if not rows:
            continue
        n = _name(prefix, f"quality_{k}")
        _family(lines, n, "gauge", f"quality_{k}")
        for domain, v in rows:
            lines.append(f'{n}{{domain="{_escape_label(domain)}"}} {_fmt(v)}')
    if any(isinstance(v, int) for _, v in batch_rows):
        n = _name(prefix, "quality_batches")
        _family(lines, n, "gauge", "quality_batches")
        for domain, v in batch_rows:
            if isinstance(v, int):
                lines.append(
                    f'{n}{{domain="{_escape_label(domain)}"}} {_fmt(v)}'
                )


def _slo_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """SLO exposition: one NATIVE histogram family for the per-stage
    latency decomposition (``_bucket``/``_sum``/``_count`` with
    ``{domain, stage, le}`` labels — cumulative counts, so scrapes merge
    across replicas) plus a labeled shed counter family
    ``{domain, cause, stage}``."""
    stages = block.get("stages") or {}
    rows = [
        (domain, stage, snap)
        for domain, by_stage in sorted(stages.items())
        for stage, snap in sorted(by_stage.items())
        if isinstance(snap, dict) and snap.get("buckets")
    ]
    if rows:
        n = _name(prefix, "stage_latency_seconds")
        _family(lines, n, "histogram", "stage_latency_seconds")
        for domain, stage, snap in rows:
            labels = (
                f'domain="{_escape_label(domain)}",'
                f'stage="{_escape_label(stage)}"'
            )
            for le, cum in snap["buckets"]:
                le_txt = "+Inf" if le == "+Inf" else _fmt(le)
                lines.append(
                    f'{n}_bucket{{{labels},le="{le_txt}"}} {int(cum)}'
                )
            lines.append(f"{n}_sum{{{labels}}} {_fmt(snap.get('sum', 0.0))}")
            lines.append(f"{n}_count{{{labels}}} {int(snap.get('count', 0))}")
    # class-parallel families (present only when serving.qos is on):
    # the classless families above keep their label sets EXACTLY as
    # before — QoS adds new families, it never relabels existing ones
    classes = block.get("classes") or {}
    class_rows = [
        (klass, domain, stage, snap)
        for klass, by_domain in sorted(classes.items())
        for domain, by_stage in sorted(by_domain.items())
        for stage, snap in sorted(by_stage.items())
        if isinstance(snap, dict) and snap.get("buckets")
    ]
    if class_rows:
        n = _name(prefix, "class_stage_latency_seconds")
        _family(lines, n, "histogram", "class_stage_latency_seconds")
        for klass, domain, stage, snap in class_rows:
            labels = (
                f'class="{_escape_label(klass)}",'
                f'domain="{_escape_label(domain)}",'
                f'stage="{_escape_label(stage)}"'
            )
            for le, cum in snap["buckets"]:
                le_txt = "+Inf" if le == "+Inf" else _fmt(le)
                lines.append(
                    f'{n}_bucket{{{labels},le="{le_txt}"}} {int(cum)}'
                )
            lines.append(f"{n}_sum{{{labels}}} {_fmt(snap.get('sum', 0.0))}")
            lines.append(f"{n}_count{{{labels}}} {int(snap.get('count', 0))}")
    shed = (block.get("shed") or {}).get("by_domain") or {}
    shed_rows = [
        (domain, cause, stage, v)
        for domain, by_cause in sorted(shed.items())
        for cause, by_stage in sorted(by_cause.items())
        for stage, v in sorted(by_stage.items())
        if isinstance(v, int)
    ]
    if shed_rows:
        n = _name(prefix, "shed_requests", "_total")
        _family(lines, n, "counter", "shed_requests")
        for domain, cause, stage, v in shed_rows:
            lines.append(
                f'{n}{{domain="{_escape_label(domain)}",'
                f'cause="{_escape_label(cause)}",'
                f'stage="{_escape_label(stage)}"}} {v}'
            )
    class_shed = (block.get("shed") or {}).get("by_class") or {}
    class_shed_rows = [
        (klass, cause, stage, v)
        for klass, by_cause in sorted(class_shed.items())
        for cause, by_stage in sorted(by_cause.items())
        for stage, v in sorted(by_stage.items())
        if isinstance(v, int)
    ]
    if class_shed_rows:
        n = _name(prefix, "class_shed_requests", "_total")
        _family(lines, n, "counter", "class_shed_requests")
        for klass, cause, stage, v in class_shed_rows:
            lines.append(
                f'{n}{{class="{_escape_label(klass)}",'
                f'cause="{_escape_label(cause)}",'
                f'stage="{_escape_label(stage)}"}} {v}'
            )


def _capacity_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Capacity-model exposition: one ``{domain}``-labeled gauge family
    per published measure, so a load balancer can scrape max sustainable
    QPS and headroom next to the latency histograms."""
    by_domain = block.get("by_domain") or {}
    if not by_domain:
        return
    fields = (
        ("max_sustainable_qps", "max_sustainable_qps"),
        ("predicted_flops_per_request", "predicted_flops_per_request"),
        ("achieved_flops_s", "achieved_flops_s"),
        ("utilization", "utilization"),
        ("headroom", "headroom"),
        ("window_batches", "window_batches"),
    )
    for src, key in fields:
        rows = [
            (domain, d.get(src))
            for domain, d in sorted(by_domain.items())
            if isinstance(d.get(src), (int, float))
            and not isinstance(d.get(src), bool)
        ]
        if not rows:
            continue
        n = _name(prefix, f"capacity_{key}")
        _family(lines, n, "gauge", f"capacity_{key}")
        for domain, v in rows:
            lines.append(f'{n}{{domain="{_escape_label(domain)}"}} {_fmt(v)}')
    cal_rows = [
        (domain, (d.get("calibration") or {}).get("mean_abs_rel_err"))
        for domain, d in sorted(by_domain.items())
        if isinstance(
            (d.get("calibration") or {}).get("mean_abs_rel_err"), (int, float)
        )
    ]
    if cal_rows:
        n = _name(prefix, "capacity_calibration_error")
        _family(lines, n, "gauge", "capacity_calibration_error")
        for domain, v in cal_rows:
            lines.append(f'{n}{{domain="{_escape_label(domain)}"}} {_fmt(v)}')
    qos_rows = [
        (domain, klass, (slot or {}).get("requests"))
        for domain, d in sorted(by_domain.items())
        for klass, slot in sorted((d.get("by_qos_class") or {}).items())
        if isinstance((slot or {}).get("requests"), int)
    ]
    if qos_rows:
        n = _name(prefix, "capacity_qos_requests", "_total")
        _family(lines, n, "counter", "capacity_qos_requests")
        for domain, klass, v in qos_rows:
            lines.append(
                f'{n}{{domain="{_escape_label(domain)}",'
                f'class="{_escape_label(klass)}"}} {_fmt(v)}'
            )


def _mesh_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Mesh exposition (``observability.mesh.mesh_snapshot``): scalar
    balance gauges, one ``{device}``-labeled gauge family per per-device
    measure (label cardinality bounded by the local device count — device
    ordinals, never ids), and a ``{op}``-labeled collective census from
    the fixed HLO op taxonomy."""
    dc = block.get("device_count")
    if isinstance(dc, int):
        n = _name(prefix, "mesh_devices")
        _family(lines, n, "gauge", "mesh_devices")
        lines.append(f"{n} {_fmt(dc)}")
    balance = block.get("balance") or {}
    for src, key in (
        ("ratio", "mesh_balance_ratio"),
        ("sync_points", "mesh_balance_sync_points"),
        ("attributed_s", "mesh_attributed_s"),
    ):
        v = balance.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, key)
            _family(lines, n, "gauge", key)
            lines.append(f"{n} {_fmt(v)}")
    per_dev = balance.get("per_device_s") or []
    if any(isinstance(v, (int, float)) for v in per_dev):
        n = _name(prefix, "device_run_s")
        _family(lines, n, "gauge", "device_run_s")
        for d, v in enumerate(per_dev):
            if isinstance(v, (int, float)):
                lines.append(f'{n}{{device="{d}"}} {_fmt(v)}')
    hbm = block.get("hbm") or {}
    for src, key in (
        ("bytes_in_use", "device_hbm_bytes_in_use"),
        ("peak_bytes_in_use", "device_hbm_peak_bytes_in_use"),
    ):
        rows = [
            (d, (stats or {}).get(src))
            for d, stats in enumerate(hbm.get("per_device") or [])
            if isinstance((stats or {}).get(src), (int, float))
        ]
        if not rows:
            continue
        n = _name(prefix, key)
        _family(lines, n, "gauge", key)
        for d, v in rows:
            lines.append(f'{n}{{device="{d}"}} {_fmt(v)}')
    col = block.get("collectives") or {}
    by_op = col.get("by_op") or {}
    if by_op:
        for src, key in (("count", "collective_ops"), ("bytes", "collective_bytes")):
            n = _name(prefix, key, "_total")
            _family(lines, n, "counter", key)
            for op, slot in sorted(by_op.items()):
                v = (slot or {}).get(src)
                if isinstance(v, (int, float)):
                    lines.append(
                        f'{n}{{op="{_escape_label(op)}"}} {_fmt(v)}'
                    )
    hot = col.get("hot_loop") or {}
    for src, key in (
        # count includes the tolerated control-plane traffic; float_count
        # is the zero-collective contract metric an operator alerts on
        ("count", "collective_hot_loop_ops"),
        ("float_count", "collective_hot_loop_float_ops"),
    ):
        v = hot.get(src)
        if isinstance(v, (int, float)):
            n = _name(prefix, key, "_total")
            _family(lines, n, "counter", key)
            lines.append(f"{n} {_fmt(v)}")


def _gaps_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Dispatch-gap exposition: overlap ratio + busy/idle scalar gauges
    and the ``{producer}``-labeled per-producer family on the LIFETIME
    per-window wall basis (idle between requests is not a host stall),
    plus the ``{stage}``-labeled attributed / unattributed gap-seconds
    pair on the ring-scoped recent basis — the two attribution gauges are
    a self-consistent pair (compare them with each other, not with the
    lifetime idle gauge). Accepts either a ``GapTracker.snapshot()``
    (totals + recent) or a bare ``gaps_block``."""
    if block.get("enabled") is False:
        return
    totals = block.get("totals") if isinstance(block.get("totals"), dict) else {}
    recent = block.get("recent") if isinstance(block.get("recent"), dict) else block
    for src, key in (
        ("overlap_ratio", "overlap_ratio"),
        ("busy_s", "device_busy_s"),
        ("idle_s", "device_idle_s"),
        ("compile_s", "device_compile_windows_s"),
        ("windows", "gap_windows"),
    ):
        v = totals.get(src, recent.get(src))
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, key)
            _family(lines, n, "gauge", key)
            lines.append(f"{n} {_fmt(v)}")
    by_producer = totals.get("by_producer") or recent.get("by_producer") or {}
    rows = [
        (p, d.get("overlap_ratio"))
        for p, d in sorted(by_producer.items())
        if isinstance(d.get("overlap_ratio"), (int, float))
    ]
    if rows:
        n = _name(prefix, "producer_overlap_ratio")
        _family(lines, n, "gauge", "producer_overlap_ratio")
        for p, v in rows:
            lines.append(f'{n}{{producer="{_escape_label(p)}"}} {_fmt(v)}')
    # attribution pair: both gauges read the SAME recent ring scope
    v = recent.get("unattributed_s")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        n = _name(prefix, "gap_unattributed_s")
        _family(lines, n, "gauge", "gap_unattributed_s")
        lines.append(f"{n} {_fmt(v)}")
    attributed = recent.get("attributed") or {}
    rows = [
        (stage, v)
        for stage, v in sorted(attributed.items())
        if isinstance(v, (int, float))
    ]
    if rows:
        n = _name(prefix, "gap_attributed_s")
        _family(lines, n, "gauge", "gap_attributed_s")
        for stage, v in rows:
            lines.append(f'{n}{{stage="{_escape_label(stage)}"}} {_fmt(v)}')


def _coldstart_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Cold-start exposition: per-phase seconds (``{phase}``-labeled),
    persistent-cache hit/miss counters, the entries-added-by-this-process
    gauge (the 'N entries rebuilt' number), and time-to-first-dispatch."""
    if block.get("enabled") is False:
        return
    phases = block.get("phases") or {}
    rows = [
        (p, v) for p, v in sorted(phases.items())
        if isinstance(v, (int, float))
    ]
    if rows:
        n = _name(prefix, "coldstart_phase_s")
        _family(lines, n, "gauge", "coldstart_phase_s")
        for p, v in rows:
            lines.append(f'{n}{{phase="{_escape_label(p)}"}} {_fmt(v)}')
    cache = block.get("persistent_cache") or {}
    for src, key, mtype in (
        ("hits", "coldstart_persistent_cache_hits", "counter"),
        ("misses", "coldstart_persistent_cache_misses", "counter"),
        ("entries_added", "coldstart_cache_entries_added", "gauge"),
    ):
        v = cache.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, key, "_total" if mtype == "counter" else "")
            _family(lines, n, mtype, key)
            lines.append(f"{n} {_fmt(v)}")
    by_outcome = cache.get("by_outcome") or {}
    outcome_rows = [
        (o, v) for o, v in sorted(by_outcome.items())
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    ]
    if outcome_rows:
        n = _name(prefix, "coldstart_executables", "_total")
        _family(lines, n, "counter", "coldstart_executables")
        for o, v in outcome_rows:
            lines.append(f'{n}{{outcome="{_escape_label(o)}"}} {_fmt(v)}')
    aot = cache.get("aot") or {}
    fails = aot.get("load_failures")
    if isinstance(fails, (int, float)) and not isinstance(fails, bool):
        n = _name(prefix, "coldstart_aot_load_failures", "_total")
        _family(lines, n, "counter", "coldstart_aot_load_failures")
        lines.append(f"{n} {_fmt(fails)}")
    ttfd = block.get("time_to_first_dispatch_s")
    if isinstance(ttfd, (int, float)):
        n = _name(prefix, "coldstart_time_to_first_dispatch_s")
        _family(lines, n, "gauge", "coldstart_time_to_first_dispatch_s")
        lines.append(f"{n} {_fmt(ttfd)}")


def _qos_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """QoS exposition: the admission controller's admit/deny counters
    (denials ``{class}``-labeled — the cause x class attribution a
    dashboard alerts on). Class-labeled latency/shed families render from
    the SLO block; capacity's per-class census from the capacity block."""
    admission = block.get("admission") or {}
    v = admission.get("admitted")
    if isinstance(v, int):
        n = _name(prefix, "qos_admission_admitted", "_total")
        _family(lines, n, "counter", "qos_admission_admitted")
        lines.append(f"{n} {_fmt(v)}")
    denied_by_class = admission.get("denied_by_class") or {}
    rows = [
        (klass, v)
        for klass, v in sorted(denied_by_class.items())
        if isinstance(v, int)
    ]
    if rows:
        n = _name(prefix, "qos_admission_denied", "_total")
        _family(lines, n, "counter", "qos_admission_denied")
        for klass, v in rows:
            lines.append(f'{n}{{class="{_escape_label(klass)}"}} {_fmt(v)}')


def _incidents_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Incident exposition: the open-count gauge an operator alerts on,
    the ``{kind}``-labeled lifetime counter, and the dedupe-suppression
    counter (how much noise the cooldown absorbed)."""
    if block.get("enabled") is False:
        return
    v = block.get("open")
    if isinstance(v, int):
        n = _name(prefix, "incidents_open")
        _family(lines, n, "gauge", "incidents_open")
        lines.append(f"{n} {_fmt(v)}")
    by_kind = block.get("by_kind") or {}
    rows = [(k, v) for k, v in sorted(by_kind.items()) if isinstance(v, int)]
    if rows:
        n = _name(prefix, "incidents", "_total")
        _family(lines, n, "counter", "incidents_total")
        for kind, v in rows:
            lines.append(f'{n}{{kind="{_escape_label(kind)}"}} {_fmt(v)}')
    v = block.get("suppressed")
    if isinstance(v, int):
        n = _name(prefix, "incidents_suppressed", "_total")
        _family(lines, n, "counter", "incidents_suppressed")
        lines.append(f"{n} {_fmt(v)}")


def _flight_lines(prefix: str, block: dict, lines: list[str]) -> None:
    """Flight-recorder exposition: ring occupancy + dump counter."""
    if block.get("enabled") is False:
        return
    v = block.get("ring_size")
    if isinstance(v, int):
        n = _name(prefix, "flight_ring_entries")
        _family(lines, n, "gauge", "flight_ring_entries")
        lines.append(f"{n} {_fmt(v)}")
    v = block.get("dumps")
    if isinstance(v, int):
        n = _name(prefix, "flight_dumps", "_total")
        _family(lines, n, "counter", "flight_dumps")
        lines.append(f"{n} {_fmt(v)}")


def prometheus_text(snapshot: dict, prefix: str = "moeva2") -> str:
    """ServiceMetrics snapshot dict -> Prometheus exposition text."""
    lines: list[str] = []

    ledger_block = snapshot.get("cost_ledger")
    if isinstance(ledger_block, dict):
        _ledger_lines(prefix, ledger_block, lines)
    quality_block = snapshot.get("quality")
    if isinstance(quality_block, dict):
        _quality_lines(prefix, quality_block, lines)
    slo = snapshot.get("slo")
    if isinstance(slo, dict):
        _slo_lines(prefix, slo, lines)
    capacity = snapshot.get("capacity")
    if isinstance(capacity, dict):
        _capacity_lines(prefix, capacity, lines)
    mesh = snapshot.get("mesh")
    if isinstance(mesh, dict):
        _mesh_lines(prefix, mesh, lines)
    gaps = snapshot.get("gaps")
    if isinstance(gaps, dict):
        _gaps_lines(prefix, gaps, lines)
    coldstart = snapshot.get("coldstart")
    if isinstance(coldstart, dict):
        _coldstart_lines(prefix, coldstart, lines)
    qos = snapshot.get("qos")
    if isinstance(qos, dict):
        _qos_lines(prefix, qos, lines)
    incidents = snapshot.get("incidents")
    if isinstance(incidents, dict):
        _incidents_lines(prefix, incidents, lines)
    flight = snapshot.get("flight")
    if isinstance(flight, dict):
        _flight_lines(prefix, flight, lines)

    for name, v in sorted(snapshot.get("counters", {}).items()):
        n = _name(prefix, name, "_total")
        _family(lines, n, "counter", name)
        lines.append(f"{n} {_fmt(v)}")

    for name, v in sorted(snapshot.get("gauges", {}).items()):
        n = _name(prefix, name)
        _family(lines, n, "gauge", name)
        lines.append(f"{n} {_fmt(v)}")

    for name, s in sorted(snapshot.get("streams", {}).items()):
        n = _name(prefix, name)
        _family(lines, n, "summary", name)
        for q, key in (("0.5", "p50"), ("0.99", "p99")):
            v = s.get(key)
            if v is not None and not (isinstance(v, float) and math.isnan(v)):
                lines.append(f'{n}{{quantile="{q}"}} {_fmt(v)}')
        count = int(s.get("count") or 0)
        mean = s.get("mean")
        lines.append(f"{n}_count {count}")
        lines.append(
            f"{n}_sum {_fmt((mean or 0.0) * count if mean is not None else 0.0)}"
        )

    # flat extras the service appends to its snapshot: scalar numbers become
    # gauges, one-level dicts of numbers (cache stats) become one gauge per
    # sub-key — so engine/artifact cache health is scrapeable too
    for key, v in sorted(snapshot.items()):
        if key in (
            "counters", "gauges", "streams", "cost_ledger", "quality",
            "slo", "capacity", "mesh", "gaps", "coldstart", "qos",
            "incidents", "flight",
        ):
            continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            n = _name(prefix, key)
            _family(lines, n, "gauge", key)
            lines.append(f"{n} {_fmt(v)}")
        elif isinstance(v, dict):
            for sub, sv in sorted(v.items()):
                if isinstance(sv, (int, float)) and not isinstance(sv, bool):
                    n = _name(prefix, f"{key}_{sub}")
                    _family(lines, n, "gauge", f"{key}_{sub}")
                    lines.append(f"{n} {_fmt(sv)}")

    return "\n".join(lines) + "\n"
