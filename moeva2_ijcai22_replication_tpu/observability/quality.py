"""Attack-quality telemetry: convergence curves and interior-point summaries.

PR 4/5 made the framework observable in *time* and *FLOPs*; this module is
the *quality* axis — the convergence trajectory of an attack as first-class
telemetry. The MoEvA engine (and the PGD restart loop) record per-gate
per-state statistics (``attacks.objective.QUALITY_STAT_COLUMNS``); this
module aggregates them into JSON-ready samples, merges samples across
state chunks, and assembles the ``telemetry.quality`` block every
bench/grid/serving/runner record must carry
(``records.validate_record``). The block's load-bearing part is the
``interior`` summary: success rates pinned at interior budgets
(default {100, 300} generation steps, plus ``full``), exactly where a
survival-semantics regression moves the numbers while a saturated
full-budget record stays all-ones — ``tools/bench_diff.py`` diffs these
across the committed ``BENCH_r*.json`` series and fails tier-1 on drift.

Rounding contract: per-state stats and the per-sample ``success_frac`` /
``o_rates`` in the *recorded history* keep full float precision (drift
thresholds are ~0.1; stacking a 1e-4 rounding per hop is avoidable noise);
rounding to display precision happens only here, at export time, via
``round_digits`` — the same rule the engine's trace events follow
(rounded payloads for humans, full precision in the history).
"""

from __future__ import annotations

import numpy as np

#: interior budgets (generation steps) the exported summary pins by
#: default — the adjudicated botnet trajectory's interior points
#: (0.199/0.080 @100 → 0.959/0.910 @300 → saturated @1000, DESIGN §9).
DEFAULT_INTERIOR_BUDGETS = (100, 300)

#: keys every ``telemetry.quality`` block must carry (validate_record).
QUALITY_KEYS = ("judged", "samples", "curve", "interior")


def sample_from_per_state(gen: int, per_state, **extra) -> dict:
    """One quality sample from a (S, 9) per-state stats array
    (``attacks.objective.QUALITY_STAT_COLUMNS``): o1–o7 rates (fraction of
    states holding ≥1 qualifying candidate), best/mean constraint
    violation, best distance — full precision, with the raw per-state
    array kept under ``per_state`` for chunk merging (stripped at export
    by :func:`quality_block`)."""
    # copy, not view: the engine keeps mutating its ``qual_latest`` buffer
    # after the sample is taken
    ps = np.array(per_state, np.float64)
    # NaN rows = states with no stats yet at this gate (only possible on a
    # checkpoint-resumed compacted run before its first full gate): the
    # aggregates exclude them — NaN would both bias the rates and poison
    # the strict-JSON export
    known = ~np.isnan(ps[:, 0])
    kp = ps[known] if known.any() else np.zeros((0, ps.shape[1]))
    bd = kp[:, 8] if len(kp) else np.zeros(0)
    finite = np.isfinite(bd)

    def _f(v):
        return float(v) if len(kp) else None

    return {
        "gen": int(gen),
        "o_rates": [_f(v) for v in kp[:, :7].mean(axis=0)]
        if len(kp)
        else [None] * 7,
        # success_frac = the o7 rate under the engine criterion; kept as
        # its own key (full precision) because it is the number the gate
        # events round for display
        "success_frac": _f(kp[:, 6].mean()) if len(kp) else None,
        "best_cv": _f(kp[:, 7].min()) if len(kp) else None,
        "mean_cv": _f(kp[:, 7].mean()) if len(kp) else None,
        "best_dist": float(bd[finite].min()) if finite.any() else None,
        "mean_best_dist": float(bd[finite].mean()) if finite.any() else None,
        "states_known": int(known.sum()),
        "per_state": ps,
        **extra,
    }


def merge_chunk_quality(parts: list[dict | None], n_reals: list[int]) -> dict | None:
    """Merge per-chunk engine quality histories (sequential
    ``max_states_per_call`` chunks of one attack) into one history over the
    full states axis: per-state rows are concatenated per gate (chunks
    share the budget and gate cadence) and the aggregates recomputed. A
    chunk that early-exited stops sampling; its last known per-state stats
    carry forward (its states are all solved — that is why it exited)."""
    if not parts or parts[0] is None:
        return None
    # per chunk: gen -> per_state (trimmed to the chunk's real rows)
    per_chunk: list[dict[int, np.ndarray]] = []
    finals: list[np.ndarray] = []
    gens: set[int] = set()
    for part, n_real in zip(parts, n_reals):
        by_gen: dict[int, np.ndarray] = {}
        final = None
        for s in part["samples"]:
            ps = np.asarray(s["per_state"])[:n_real]
            if s.get("final"):
                final = ps
            else:
                by_gen[s["gen"]] = ps
                gens.add(s["gen"])
        per_chunk.append(by_gen)
        finals.append(final)
    samples = []
    last: list[np.ndarray | None] = [None] * len(per_chunk)
    for g in sorted(gens):
        rows = []
        for i, by_gen in enumerate(per_chunk):
            ps = by_gen.get(g)
            if ps is None:  # early-exited chunk: carry its last stats
                ps = last[i] if last[i] is not None else finals[i]
            else:
                last[i] = ps
            if ps is not None:
                rows.append(ps)
        if rows:
            samples.append(sample_from_per_state(g, np.concatenate(rows, axis=0)))
    if all(f is not None for f in finals):
        gen_final = max(p["samples"][-1]["gen"] for p in parts)
        samples.append(
            sample_from_per_state(
                gen_final, np.concatenate(finals, axis=0), final=True
            )
        )
    # header (gate cadence / thresholds / judged) comes from chunk 0 —
    # chunks run one attack's config, so the headers are identical
    return dict(parts[0], samples=samples)


def trim_quality(quality: dict | None, n_real: int) -> dict | None:
    """Drop trailing pad rows from an engine quality history and recompute
    every aggregate. The runners pad the states axis to a mesh multiple
    before ``generate`` (pads duplicate real rows), then trim the attack
    outputs back to ``n_real`` — the recorded rates must be trimmed the
    same way or every mesh run's o-rates count its last state multiple
    times (mesh-dependent drift in exactly the numbers the watchdog gates
    on)."""
    if quality is None:
        return None
    out = dict(quality)
    out["samples"] = [
        sample_from_per_state(
            s["gen"],
            np.asarray(s["per_state"])[:n_real],
            **{k: s[k] for k in ("final",) if k in s},
        )
        for s in quality["samples"]
    ]
    return out


def round_digits(value, digits: int = 4):
    """Display rounding for exported payloads (events, JSON curves)."""
    if isinstance(value, float):
        return round(value, digits)
    if isinstance(value, (list, tuple)):
        return [round_digits(v, digits) for v in value]
    return value


def _export_sample(sample: dict, digits: int | None) -> dict:
    out = {k: v for k, v in sample.items() if k != "per_state"}
    if digits is not None:
        out = {k: round_digits(v, digits) for k, v in out.items()}
    return out


def interior_summary(
    samples: list[dict], budgets=DEFAULT_INTERIOR_BUDGETS, digits: int | None = 6
) -> dict:
    """Pin the curve at interior budgets: for each budget, the latest
    sample at ``gen <= budget`` (exact when the gate cadence divides the
    budget) — but only when the trajectory actually REACHED the budget
    (a 40-generation run has no "@100" point; labeling its final state as
    one would make cross-record diffs compare different budgets); ``full``
    = the last sample. Budgets with no valid sample are omitted rather
    than nulled — their absence in a diff reads as "not comparable",
    never "regressed to nothing"."""
    out: dict = {}
    horizon = max((s["gen"] for s in samples), default=-1)
    for budget in budgets:
        if horizon < budget:
            continue
        eligible = [s for s in samples if s["gen"] <= budget and not s.get("final")]
        if eligible:
            out[str(int(budget))] = _export_sample(eligible[-1], digits)
    if samples:
        out["full"] = _export_sample(samples[-1], digits)
    return out


def quality_block(
    engine_quality: dict | None = None,
    *,
    budgets=DEFAULT_INTERIOR_BUDGETS,
    final: dict | None = None,
    restart_curve=None,
    judged: str | None = None,
    digits: int | None = 6,
) -> dict:
    """Assemble the JSON-ready ``telemetry.quality`` block.

    ``engine_quality`` is a ``MoevaResult.quality`` dict (per-gate samples
    with per-state arrays); ``restart_curve`` a PGD engine's per-restart
    history; ``final`` an externally judged final summary (e.g. the
    runner's post-hoc f64 o-rates) recorded next to — never instead of —
    the engine curve. With no inputs the block is empty but schema-valid
    (``samples: 0``), so every record producer can carry the key
    unconditionally."""
    block: dict = {
        "judged": judged
        or (engine_quality or {}).get("judged")
        or ("engine" if engine_quality else None),
        "samples": 0,
        "curve": [],
        "interior": {},
    }
    if engine_quality:
        samples = engine_quality.get("samples") or []
        block["samples"] = len(samples)
        block["curve"] = [_export_sample(s, digits) for s in samples]
        block["interior"] = interior_summary(samples, budgets, digits)
        for k in ("gate_every", "threshold", "eps", "archive_size"):
            if k in engine_quality:
                v = engine_quality[k]
                # inf thresholds are strict-JSON poison (RFC 8259): null
                block[k] = None if isinstance(v, float) and not np.isfinite(v) else v
    if restart_curve is not None:
        block["restart_curve"] = round_digits(
            [float(v) for v in np.asarray(restart_curve, np.float64)], digits
        )
    if final is not None:
        block["final"] = final
    return block


def validate_quality(block, kind: str = "record") -> dict:
    """Assert ``block`` is a schema-valid quality block; returns it."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's telemetry.quality must be a dict, got "
            f"{type(block).__name__}"
        )
    missing = [k for k in QUALITY_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's telemetry.quality is missing keys {missing}: "
            "assemble it with observability.quality.quality_block so the "
            "convergence curve and interior-point summary travel with "
            "every committed number"
        )
    return block
