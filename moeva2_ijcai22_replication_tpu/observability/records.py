"""Shared record schema: every bench/grid/serving record carries the same
``execution`` + ``telemetry`` keys.

PR 2/3 grew per-record ``execution`` blocks (chunk size, mesh shape,
early-exit mode) so committed numbers stay attributable to their execution
mode; this module adds the matching ``telemetry`` block (span totals, HBM
watermark, events emitted) and a validator the record producers call at
assembly time — so a future refactor cannot silently drop either block
from a record (``tests/test_tracing.py::TestRecordSchema`` additionally
asserts the producers keep calling it).
"""

from __future__ import annotations

from .gaps import (
    get_gap_tracker,
    spans_from_recorder,
    spans_from_trace,
    validate_gaps,
)
from .incidents import incidents_block, validate_incidents
from .ledger import get_ledger
from .mesh import mesh_block, validate_mesh
from .quality import quality_block, validate_quality
from .slo import validate_slo
from .trace import (
    Trace,
    TraceRecorder,
    all_device_memory_stats,
    device_memory_stats,
)

#: the keys every bench / grid-report / serving-sweep record must carry.
REQUIRED_RECORD_KEYS = ("execution", "telemetry")


def telemetry_block(
    *,
    recorder: TraceRecorder | None = None,
    timer=None,
    trace: Trace | None = None,
    device=None,
    ledger=None,
    ledger_since: dict | None = None,
    quality: dict | None = None,
    slo: dict | None = None,
    mesh: dict | None = None,
    mesh_since: dict | None = None,
    gaps_since: dict | None = None,
    incidents: dict | None = None,
) -> dict:
    """JSON-ready telemetry summary for a record: span totals (from a
    PhaseTimer), trace id + event count (from a Trace), recorder counters,
    the device-memory watermark at assembly time, and the executable cost
    ledger (identity + FLOPs/bytes + compile time + roofline per compiled
    program — ``ledger`` defaults to the process ledger). Producers pass
    ``ledger_since`` (a ``CostLedger.mark()`` taken at run start) so the
    record's ``cost`` block covers *this run's* executables, not the
    process lifetime — on a shared-engine grid the difference is every
    warm point otherwise re-reporting the first point's compiles.

    ``quality`` is a pre-assembled ``observability.quality.quality_block``
    (convergence curve + interior-point summary); omitted, an empty but
    schema-valid block is inserted so every producer satisfies the
    ``telemetry.quality`` schema unconditionally.

    ``slo`` is a pre-assembled ``observability.slo.slo_block`` (stage
    latency histograms + shed attribution + saturation knee) — serving
    producers pass it (``validate_record`` enforces it on serving
    records); batch producers have no request path and omit it.

    ``mesh`` is the run's ``attacks.sharding.describe_mesh`` dict (None
    or single-device descs are ignored); when it names more than one
    device the block gains ``telemetry.mesh`` — per-device roofline +
    HBM, balance ratio, collective classification — window-scoped by
    ``mesh_since`` (a ``mesh.MESH.mark()``) the same way ``ledger_since``
    scopes ``telemetry.cost``. ``validate_record`` requires it on any
    record whose execution mode ran multi-device.

    On a multi-device run the HBM watermark reads the RUN'S devices —
    the first ``mesh["devices"]`` local ordinals, matching
    ``experiments.common.build_mesh``'s prefix selection — with ``hbm``
    = the max across them and ``hbm_devices`` the per-ordinal list; a
    watermark over devices the run never touched would absorb foreign
    processes' usage. Single-device records keep the pre-mesh
    ``device_memory_stats(device)`` probe byte-for-byte."""
    n_mesh = int((mesh or {}).get("devices") or 1)
    stats = None
    if n_mesh > 1:
        try:
            import jax

            run_devices = jax.local_devices()[:n_mesh]
        except Exception:
            run_devices = None
        stats = all_device_memory_stats(run_devices or None)
    block: dict = {
        "hbm": stats["max"] if stats else device_memory_stats(device)
    }
    if stats and stats.get("device_count", 0) > 1:
        block["hbm_devices"] = stats["per_device"]
    block["quality"] = validate_quality(
        quality if quality is not None else quality_block()
    )
    if slo is not None:
        block["slo"] = validate_slo(slo)
    # ``incidents`` is a pre-assembled ``incidents.incidents_block``
    # (predicate trips with frozen evidence) — serving/fleet producers
    # pass it (``validate_record`` enforces it on those kinds); batch
    # producers have no detector loop and omit it
    if incidents is not None:
        block["incidents"] = validate_incidents(incidents)
    if timer is not None:
        block["spans_s"] = {k: round(v, 4) for k, v in timer.spans.items()}
        block["span_total_s"] = round(sum(timer.spans.values()), 4)
    if trace is not None:
        block["trace_id"] = trace.id
        block["events"] = len(trace.events)
    if recorder is not None:
        block["events_emitted"] = recorder.events_emitted
        block["counters"] = {k: int(v) for k, v in recorder.counters.items()}
    block["cost"] = (ledger if ledger is not None else get_ledger()).cost_block(
        since=ledger_since
    )
    # dispatch-gap ledger: device busy vs idle over this record's window
    # (``gaps_since`` = a GAPS.mark() taken at run start, mirroring
    # ``ledger_since``), with idle intervals attributed to the host spans
    # the run's trace (or the recorder ring) captured — spans off means
    # honest unattributed idle, never a missing block
    attribution_spans = spans_from_trace(trace) or spans_from_recorder(recorder)
    block["gaps"] = get_gap_tracker().gaps_block(
        since=gaps_since, spans=attribution_spans
    )
    if mesh is not None and int(mesh.get("devices") or 1) > 1:
        block["mesh"] = mesh_block(
            mesh,
            ledger=ledger,
            ledger_since=ledger_since,
            capture_since=mesh_since,
        )
    return block


def validate_record(record: dict, kind: str = "record") -> dict:
    """Assert ``record`` carries the shared schema keys — including the
    ``telemetry.cost`` sub-block (the executable cost ledger); returns it."""
    missing = [k for k in REQUIRED_RECORD_KEYS if k not in record]
    if missing:
        raise ValueError(
            f"{kind} record is missing schema keys {missing}: every "
            f"bench/grid/serving record must carry {list(REQUIRED_RECORD_KEYS)}"
        )
    telemetry = record.get("telemetry")
    if not isinstance(telemetry, dict) or "cost" not in telemetry:
        raise ValueError(
            f"{kind} record's telemetry block is missing the 'cost' "
            "sub-block: assemble it with observability.records."
            "telemetry_block so the executable cost ledger travels with "
            "every committed number"
        )
    if "quality" not in telemetry:
        raise ValueError(
            f"{kind} record's telemetry block is missing the 'quality' "
            "sub-block: assemble it with observability.records."
            "telemetry_block (optionally passing quality_block(...)) so "
            "the convergence curve / interior-point summary travels with "
            "every committed number"
        )
    validate_quality(telemetry["quality"], kind)
    if "gaps" not in telemetry:
        raise ValueError(
            f"{kind} record's telemetry block is missing the 'gaps' "
            "sub-block: assemble it with observability.records."
            "telemetry_block so device busy/idle attribution (the overlap "
            "ratio and its gap stages) travels with every committed number"
        )
    validate_gaps(telemetry["gaps"], kind)
    # multi-device records additionally carry the mesh block (per-device
    # roofline + HBM, balance ratio, collective classification): a record
    # whose own execution mode says it ran on >1 device without one is
    # exactly the "rc=0, tail says ok" blindness this schema exists to
    # close. Device count comes from the record's execution identity —
    # either a full describe_mesh dict or the grid pipeline's plain
    # mesh_devices count.
    execution = record.get("execution")
    devices = 0
    if isinstance(execution, dict):
        mesh_desc = execution.get("mesh")
        if isinstance(mesh_desc, dict):
            devices = int(mesh_desc.get("devices") or 0)
        else:
            devices = int(execution.get("mesh_devices") or 0)
    if devices > 1:
        if "mesh" not in telemetry:
            raise ValueError(
                f"{kind} record ran on {devices} devices but its telemetry "
                "block is missing the 'mesh' sub-block: assemble it with "
                "observability.records.telemetry_block(mesh=...) so "
                "per-device roofline, balance, and collective attribution "
                "travel with every multi-device record"
            )
        validate_mesh(telemetry["mesh"], kind)
    # serving records additionally carry the SLO block (stage histograms,
    # shed attribution, saturation knee) — the request path is the one
    # producer with a latency decomposition to report, and dropping it
    # would disarm the bench_diff --slo gate exactly like losing quality
    # capture would disarm the quality gate
    if kind == "serving":
        if "slo" not in telemetry:
            raise ValueError(
                "serving record's telemetry block is missing the 'slo' "
                "sub-block: assemble it with observability.slo.slo_block "
                "so stage histograms, shed attribution, and the "
                "saturation knee travel with every committed serving "
                "number"
            )
        validate_slo(telemetry["slo"], kind)
    # serving AND fleet records additionally carry the incidents block —
    # the request/fleet paths run the incident detector, and a record
    # without it would let an SLO breach ship unattributed (exactly the
    # blindness the bench_diff --incidents gate exists to close)
    if kind in ("serving", "fleet"):
        if "incidents" not in telemetry:
            raise ValueError(
                f"{kind} record's telemetry block is missing the "
                "'incidents' sub-block: assemble it with "
                "observability.incidents.incidents_block so SLO-breach "
                "attribution (frozen evidence) travels with every "
                "committed serving/fleet number"
            )
        validate_incidents(telemetry["incidents"], kind)
    return record


def git_describe() -> str | None:
    """Best-effort build identity (``git describe``) of this checkout;
    None outside a git work tree or without git on PATH."""
    import os
    import subprocess

    try:
        out = subprocess.run(
            ["git", "describe", "--always", "--dirty", "--tags"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        return out.stdout.strip() or None
    except Exception:
        return None


def build_identity(config: dict) -> dict:
    """Build/config identity for health endpoints: git describe
    (best-effort), the md5 config hash, and the package version — what a
    load balancer needs to detect a mis-deployed or mis-configured
    replica."""
    from .. import __version__
    from ..utils.config import get_dict_hash

    return {
        "git": git_describe(),
        "version": __version__,
        "config_hash": get_dict_hash(config),
    }
