"""Serving SLO observability: latency-decomposition histograms, shed
attribution, and saturation-knee detection.

The serving layer's latency evidence so far is windowed p50/p99 samples
(``ServiceMetrics.observe``) — fine for a dashboard sparkline, useless for
an SLO: quantiles over a sliding window can't be aggregated across
replicas, say nothing about *where* a slow request spent its time, and a
rejected or expired request vanishes from them entirely. This module is
the measurement substrate ROADMAP item 4 (cost-predictive admission
control) builds on:

- :class:`Histogram` / :class:`SloTracker` — fixed-bucket, log-spaced
  latency histograms per ``domain x stage`` over the request lifecycle
  stages the PR-4 trace spans already name (``validate -> queue_wait ->
  batch_wait -> dispatch -> device_run -> decode``). The stages mirror
  the trace TREE, not a flat chain: ``dispatch`` is the batch-closure
  envelope, and ``device_run``/``decode`` are sub-stages *inside* it —
  the additive end-to-end decomposition is validate + queue_wait +
  batch_wait + dispatch; summing all six double-counts device time. On a
  compile-bearing batch ``dispatch`` includes the compile wall-clock that
  ``device_run`` deliberately excludes — a cold class shows up as a
  dispatch-tail outlier while the device_run tail stays honest. Fixed
  buckets make the histograms mergeable across replicas and scrapes
  (Prometheus native ``_bucket``/``_sum``/``_count`` exposition in
  ``observability.prom``), and the per-stage decomposition turns "p99 is
  80ms" into "60ms of it is queue_wait" — the difference between adding
  capacity and tuning ``max_delay_s``. Capture is pure host-side
  arithmetic (a bisect and three adds per observation): SLO capture
  on/off adds zero device dispatches and zero compiles by construction.
- **Shed attribution** — every request the service sheds is counted by
  *cause* (``rejected`` backpressure, ``too_large``, ``invalid``,
  ``expired`` pre-dispatch deadline cancellation, ``overrun`` completed
  past its deadline, ``poisoned`` batch failure) and by the *stage* that
  consumed its deadline budget (queue_wait vs batch_wait vs dispatch vs
  device_run) — so a saturated replica shows `expired@queue_wait` while
  an undersized bucket menu shows `overrun@device_run`, and the fix is
  readable off /metrics.
- :func:`detect_knee` — the saturation knee of an offered-load sweep:
  the highest offered rate the service still serves linearly (throughput
  tracks offered load AND p99 stays within ``p99_factor`` of the
  light-load baseline). The knee is the honest "max sustainable QPS as
  measured" next to the capacity model's predicted one
  (``observability.capacity``), and ``tools/bench_diff.py --slo`` gates
  on its trajectory across the committed BENCH series.

Window scoping follows the cost ledger's precedent: producers take a
:meth:`SloTracker.mark` at run start and export ``snapshot(since=mark)``
so a sweep record reports *its own* traffic, not the warmup's.
"""

from __future__ import annotations

import bisect
import threading

#: log-spaced histogram upper bounds in SECONDS (1-2.5-5 per decade,
#: 100 us .. 60 s) — wide enough for a sub-ms validate and a multi-second
#: cold MoEvA dispatch in one scheme. The implicit +Inf bucket is always
#: appended at export. Override via ``serving.slo_histogram_buckets``.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005,
    0.001, 0.0025, 0.005,
    0.01, 0.025, 0.05,
    0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
    10.0, 30.0, 60.0,
)

#: the request lifecycle stages (the PR-4 span names); every histogram
#: family is keyed (domain, stage). Tree, not chain: device_run/decode
#: are sub-stages of dispatch — validate + queue_wait + batch_wait +
#: dispatch is the additive end-to-end decomposition.
STAGES = (
    "validate",
    "queue_wait",
    "batch_wait",
    "dispatch",
    "device_run",
    "decode",
)

#: shed-cause taxonomy (docs/DESIGN.md § SLO & capacity): why a request's
#: answer never reached (or reached late) its caller.
SHED_CAUSES = (
    "rejected",  # QueueFull backpressure at submit (never queued)
    "too_large",  # exceeds the largest bucket (never queued)
    "invalid",  # failed validation (never queued)
    "expired",  # deadline passed while queued; cancelled pre-dispatch
    "overrun",  # completed, but past its deadline (SLO miss, not an error)
    "poisoned",  # batch execution failed (its own or a batch-mate's rows)
)

#: keys every ``telemetry.slo`` block must carry (validate_record enforces
#: them on serving records, mirroring telemetry.cost / telemetry.quality).
SLO_KEYS = ("stages", "shed", "knee")


class Histogram:
    """Fixed-bucket histogram: counts per upper bound + sum + count.

    Buckets are per-instance-immutable upper bounds (le); observations
    land in the first bucket whose bound >= value, values above the last
    bound in the implicit +Inf overflow. Counts are kept per-bucket
    (non-cumulative) internally and exported cumulative, Prometheus-style,
    so merged/scraped views stay monotone by construction.
    """

    def __init__(self, bounds=DEFAULT_LATENCY_BUCKETS):
        self.bounds = tuple(float(b) for b in bounds)
        if not self.bounds or list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(
                f"histogram bounds must be sorted, unique, non-empty: {bounds}"
            )
        # one extra slot: the +Inf overflow bucket
        self._counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float, count: int = 1) -> None:
        """Fold ``value`` in ``count`` times — how a per-batch duration is
        weighted by the requests that rode the batch, so every stage in
        one histogram family stays request-weighted."""
        v = float(value)
        self._counts[bisect.bisect_left(self.bounds, v)] += count
        self.sum += v * count
        self.count += count

    # -- export --------------------------------------------------------------
    def state(self) -> tuple:
        """Raw counters for mark/delta windowing."""
        return (tuple(self._counts), self.sum, self.count)

    def snapshot(
        self, since: tuple | None = None, state: tuple | None = None
    ) -> dict:
        """JSON-ready cumulative view: ``buckets`` is ``[[le, cumulative]]``
        ending with ``["+Inf", count]``. With ``since`` (an earlier
        :meth:`state`), counters are window deltas. ``state`` lets an
        owner that synchronizes observations itself (SloTracker) pass a
        consistent :meth:`state` taken under its lock — observe()'s three
        counter writes are not atomic, and a snapshot racing one would
        otherwise export a torn view where +Inf != count."""
        counts, total_sum, total_count = (
            state if state is not None else self.state()
        )
        if since is not None:
            prev_counts, prev_sum, prev_count = since
            counts = tuple(c - p for c, p in zip(counts, prev_counts))
            total_sum -= prev_sum
            total_count -= prev_count
        cum, buckets = 0, []
        for le, c in zip(self.bounds + ("+Inf",), counts):
            cum += c
            buckets.append([le, cum])
        return {
            "buckets": buckets,
            "sum": round(total_sum, 6),
            # n rides next to every quantile consumer: a p99 estimated
            # over n < 10 observations is the max, not a tail statistic
            "count": total_count,
            **self._quantiles(counts, total_count),
        }

    def _quantiles(self, counts, total: int) -> dict:
        """Histogram-estimated quantiles (the bucket upper bound containing
        the rank — conservative, never below the true quantile's bucket).
        A rank that falls in the +Inf overflow reports the string
        ``"+Inf"`` (the buckets-key convention): the true quantile is
        beyond the largest bound, and capping it at that bound — what
        promql's histogram_quantile does — would dress an unbounded tail
        as the bucket scheme's max. None when empty; ``n`` always
        reported so consumers can judge confidence (over tiny n the
        estimate degenerates to the max)."""
        out = {"p50": None, "p99": None, "n": total}
        if total <= 0:
            return out
        bounds = self.bounds + (float("inf"),)
        for key, q in (("p50", 0.50), ("p99", 0.99)):
            rank = q * total
            cum = 0
            for le, c in zip(bounds, counts):
                cum += c
                if cum >= rank:
                    out[key] = le if le != float("inf") else "+Inf"
                    break
        return out


class SloTracker:
    """Per-(domain, stage) latency histograms + shed/deadline attribution.

    Thread-safe; ``enabled=False`` turns every method into an immediate
    return (the on/off toggle the overhead smoke pins — though either way
    no device work is ever involved). ``mark()``/``snapshot(since=)``
    scope exports to a window, like ``CostLedger.mark``.
    """

    def __init__(self, bounds=None, enabled: bool = True):
        self.bounds = tuple(
            float(b) for b in (bounds or DEFAULT_LATENCY_BUCKETS)
        )
        # fail at construction, not at the first request: a bad
        # serving.slo_histogram_buckets config must reject the service
        # boot, not 500 every request once traffic arrives
        Histogram(self.bounds)
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._hists: dict[tuple[str, str], Histogram] = {}
        self._shed: dict[tuple[str, str, str], int] = {}
        # QoS-class parallel families (populated only by class-labelled
        # observations — a classless service never allocates here, and
        # every pre-QoS export key above stays byte-identical). Class
        # shed omits the domain dimension to bound label cardinality:
        # cause x stage x class answers "who absorbed the overload",
        # the per-domain split stays on the classless family.
        self._class_hists: dict[tuple[str, str, str], Histogram] = {}
        self._class_shed: dict[tuple[str, str, str], int] = {}

    # -- ingestion -----------------------------------------------------------
    def observe(
        self,
        domain: str,
        stage: str,
        seconds: float,
        count: int = 1,
        qos_class: str | None = None,
    ) -> None:
        """Fold one stage latency in, ``count`` times: per-batch stages
        (device_run, decode) pass the requests that rode the batch so
        every stage in the family is request-weighted — a family mixing
        per-request and per-batch populations would break the per-stage
        decomposition its p99s exist for. ``qos_class`` additionally
        folds the observation into the per-class parallel family (the
        classless family always receives it — class views are a
        refinement, not a partition swap)."""
        if not self.enabled:
            return
        key = (str(domain), str(stage))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(self.bounds)
            h.observe(seconds, count)
            if qos_class is not None:
                ck = (str(qos_class), str(domain), str(stage))
                ch = self._class_hists.get(ck)
                if ch is None:
                    ch = self._class_hists[ck] = Histogram(self.bounds)
                ch.observe(seconds, count)

    def shed(
        self,
        domain: str,
        cause: str,
        stage: str,
        qos_class: str | None = None,
    ) -> None:
        """Count one shed/deadline event: ``cause`` from
        :data:`SHED_CAUSES`, ``stage`` = the stage that consumed the
        request's deadline budget (or where the shed happened).
        ``qos_class`` additionally attributes the event to the per-class
        shed matrix (cause x class is the QoS layer's overload proof)."""
        if not self.enabled:
            return
        key = (str(domain), str(cause), str(stage))
        with self._lock:
            self._shed[key] = self._shed.get(key, 0) + 1
            if qos_class is not None:
                ck = (str(qos_class), str(cause), str(stage))
                self._class_shed[ck] = self._class_shed.get(ck, 0) + 1

    # -- windowing -----------------------------------------------------------
    def mark(self) -> dict:
        """Opaque snapshot for window-scoped exports
        (``snapshot(since=mark)``)."""
        with self._lock:
            return {
                "hists": {k: h.state() for k, h in self._hists.items()},
                "shed": dict(self._shed),
                "class_hists": {
                    k: h.state() for k, h in self._class_hists.items()
                },
                "class_shed": dict(self._class_shed),
            }

    # -- export --------------------------------------------------------------
    def shed_block(self, since: dict | None = None) -> dict:
        prev = (since or {}).get("shed", {})
        prev_class = (since or {}).get("class_shed", {})
        with self._lock:
            items = {
                k: n - prev.get(k, 0)
                for k, n in self._shed.items()
                if n - prev.get(k, 0) > 0
            }
            class_items = {
                k: n - prev_class.get(k, 0)
                for k, n in self._class_shed.items()
                if n - prev_class.get(k, 0) > 0
            }
        by_domain: dict = {}
        for (domain, cause, stage), n in sorted(items.items()):
            by_domain.setdefault(domain, {}).setdefault(cause, {})[stage] = n
        out = {"total": sum(items.values()), "by_domain": by_domain}
        if class_items:
            by_class: dict = {}
            for (klass, cause, stage), n in sorted(class_items.items()):
                by_class.setdefault(klass, {}).setdefault(cause, {})[
                    stage
                ] = n
            out["by_class"] = by_class
        return out

    def snapshot(self, since: dict | None = None) -> dict:
        prev = (since or {}).get("hists", {})
        prev_class = (since or {}).get("class_hists", {})
        # histogram states are read under the SAME lock observe() mutates
        # them under — a scrape racing an observation must never export a
        # torn histogram (+Inf bucket != count breaks the mergeability
        # contract, and a windowed delta could even go negative)
        with self._lock:
            hists = {k: (h, h.state()) for k, h in self._hists.items()}
            class_hists = {
                k: (h, h.state()) for k, h in self._class_hists.items()
            }
        stages: dict = {}
        for (domain, stage), (h, state) in sorted(hists.items()):
            snap = h.snapshot(since=prev.get((domain, stage)), state=state)
            if since is not None and snap["count"] == 0:
                continue  # stage saw no traffic in the window
            stages.setdefault(domain, {})[stage] = snap
        out = {
            "enabled": self.enabled,
            "bucket_bounds": list(self.bounds),
            "stages": stages,
            "shed": self.shed_block(since=since),
        }
        if class_hists:
            classes: dict = {}
            for (klass, domain, stage), (h, state) in sorted(
                class_hists.items()
            ):
                snap = h.snapshot(
                    since=prev_class.get((klass, domain, stage)), state=state
                )
                if since is not None and snap["count"] == 0:
                    continue
                classes.setdefault(klass, {}).setdefault(domain, {})[
                    stage
                ] = snap
            if classes:
                out["classes"] = classes
        return out


def merge_histogram_snapshots(snaps: list[dict]) -> dict | None:
    """Merge :meth:`Histogram.snapshot` views from N replicas into one.

    This is what the fixed-bucket cumulative export exists for: same
    bounds => cumulative counts add pointwise and the merged view is a
    valid histogram snapshot of the union traffic. Quantiles are
    re-estimated from the merged cumulative counts (bucket upper bound
    containing the rank — conservative, like the per-replica export).
    Returns None when the snapshots' bucket bounds disagree: merging
    mismatched schemes would silently misbucket one replica's traffic,
    so the caller skips (and counts) the family instead.
    """
    snaps = [s for s in snaps if isinstance(s, dict) and s.get("buckets")]
    if not snaps:
        return None
    les = [b[0] for b in snaps[0]["buckets"]]
    for s in snaps[1:]:
        if [b[0] for b in s["buckets"]] != les:
            return None
    counts = [
        sum(int(s["buckets"][i][1]) for s in snaps) for i in range(len(les))
    ]
    total = sum(int(s.get("count", 0)) for s in snaps)
    out = {
        "buckets": [[le, c] for le, c in zip(les, counts)],
        "sum": round(sum(float(s.get("sum", 0.0)) for s in snaps), 6),
        "count": total,
        "p50": None,
        "p99": None,
        "n": total,
    }
    if total > 0:
        for key, q in (("p50", 0.50), ("p99", 0.99)):
            rank = q * total
            for le, cum in out["buckets"]:
                if cum >= rank:
                    out[key] = le
                    break
    return out


def merge_slo_snapshots(snaps: list[dict]) -> dict:
    """Merge :meth:`SloTracker.snapshot` views from N replicas into one
    fleet view — the router's /metrics aggregation.

    Stage histograms merge bucket-wise per (domain, stage) via
    :func:`merge_histogram_snapshots`; a family whose replicas disagree
    on bucket bounds is dropped and counted in
    ``skipped_mismatched_bounds`` (fixed shared bounds are the
    mergeability contract — ``serving.slo_histogram_buckets`` must match
    across a pooled fleet, and the build-identity check at adoption
    enforces the config hash that carries it). Shed counters add.

    QoS-class families merge under the same discipline, with one more
    label check: a replica that carries stage traffic but NO class view
    while another replica carries one has a mismatched label set (a
    mixed-version fleet mid-rollout) — its class data can't be invented,
    so it is dropped from the CLASS view only and counted in
    ``skipped_mismatched_labels`` (its classless families still merge).
    """
    snaps = [s for s in snaps if isinstance(s, dict)]
    stages_in: dict[tuple[str, str], list[dict]] = {}
    for s in snaps:
        for domain, by_stage in (s.get("stages") or {}).items():
            for stage, hist in (by_stage or {}).items():
                stages_in.setdefault((domain, stage), []).append(hist)
    stages: dict = {}
    skipped = 0
    for (domain, stage), hists in sorted(stages_in.items()):
        merged = merge_histogram_snapshots(hists)
        if merged is None:
            skipped += 1
            continue
        stages.setdefault(domain, {})[stage] = merged
    # class families: merge only across replicas that export the class
    # label at all; label-set mismatches are counted, never guessed at
    class_carriers = [s for s in snaps if isinstance(s.get("classes"), dict)]
    skipped_labels = 0
    if class_carriers:
        skipped_labels = sum(
            1
            for s in snaps
            if not isinstance(s.get("classes"), dict) and s.get("stages")
        )
    classes_in: dict[tuple[str, str, str], list[dict]] = {}
    for s in class_carriers:
        for klass, by_domain in s["classes"].items():
            for domain, by_stage in (by_domain or {}).items():
                for stage, hist in (by_stage or {}).items():
                    classes_in.setdefault(
                        (klass, domain, stage), []
                    ).append(hist)
    classes: dict = {}
    for (klass, domain, stage), hists in sorted(classes_in.items()):
        merged = merge_histogram_snapshots(hists)
        if merged is None:
            skipped += 1
            continue
        classes.setdefault(klass, {}).setdefault(domain, {})[stage] = merged
    shed_by_domain: dict = {}
    shed_by_class: dict = {}
    shed_total = 0
    for s in snaps:
        shed = s.get("shed") or {}
        shed_total += int(shed.get("total", 0))
        for domain, by_cause in (shed.get("by_domain") or {}).items():
            for cause, by_stage in (by_cause or {}).items():
                for stage, n in (by_stage or {}).items():
                    tgt = shed_by_domain.setdefault(domain, {}).setdefault(
                        cause, {}
                    )
                    tgt[stage] = tgt.get(stage, 0) + int(n)
        for klass, by_cause in (shed.get("by_class") or {}).items():
            for cause, by_stage in (by_cause or {}).items():
                for stage, n in (by_stage or {}).items():
                    tgt = shed_by_class.setdefault(klass, {}).setdefault(
                        cause, {}
                    )
                    tgt[stage] = tgt.get(stage, 0) + int(n)
    bounds = next(
        (list(s["bucket_bounds"]) for s in snaps if s.get("bucket_bounds")),
        [],
    )
    shed_out: dict = {"total": shed_total, "by_domain": shed_by_domain}
    if shed_by_class:
        shed_out["by_class"] = shed_by_class
    out = {
        "enabled": any(s.get("enabled") for s in snaps),
        "bucket_bounds": bounds,
        "stages": stages,
        "shed": shed_out,
        "merged_from": len(snaps),
        "skipped_mismatched_bounds": skipped,
        "skipped_mismatched_labels": skipped_labels,
    }
    if classes:
        out["classes"] = classes
    return out


def detect_knee(
    levels,
    p99_factor: float = 3.0,
    throughput_floor: float = 0.9,
) -> dict:
    """The saturation knee of an offered-load sweep: the highest offered
    rate still served *linearly*, where linear means (a) achieved request
    throughput >= ``throughput_floor`` x offered and (b) p99 <=
    ``p99_factor`` x the lightest level's p99 (the queueing-theory
    departure point: past the knee p99 grows with queue depth, not with
    request cost). A level that completed nothing is saturated by
    definition. ``levels`` are the sweep's per-level dicts
    (``offered_rps`` / ``throughput_rps`` / ``p99_ms``).

    The throughput test prefers a level's ``completion_ratio`` (offered
    requests that completed — drain-proof) over ``throughput_rps /
    offered_rps``: a level's measured duration includes the blocking
    drain of in-flight requests after the last submission, which reads
    as a throughput shortfall at high rates even when the service kept
    pace with every arrival.

    Returns ``{knee_rps, first_saturated_rps, baseline_p99_ms,
    p99_factor, throughput_floor, levels_n}`` with None knee when no
    level was linear (the sweep started past saturation) and None
    first_saturated when every level held (the knee is then a lower
    bound — the sweep never pushed past it).
    """
    usable = sorted(
        (lv for lv in levels if isinstance(lv.get("offered_rps"), (int, float))),
        key=lambda lv: lv["offered_rps"],
    )
    baseline_p99 = next(
        (
            lv["p99_ms"]
            for lv in usable
            if isinstance(lv.get("p99_ms"), (int, float))
        ),
        None,
    )
    knee = None
    first_saturated = None
    for lv in usable:
        p99 = lv.get("p99_ms")
        ratio = lv.get("completion_ratio")
        if not isinstance(ratio, (int, float)):
            thr = lv.get("throughput_rps")
            ratio = (
                thr / lv["offered_rps"]
                if isinstance(thr, (int, float)) and lv["offered_rps"] > 0
                else None
            )
        linear = (
            isinstance(p99, (int, float))
            and isinstance(ratio, (int, float))
            and baseline_p99 is not None
            and p99 <= p99_factor * baseline_p99
            and ratio >= throughput_floor
        )
        if linear and first_saturated is None:
            # the knee never advances past a saturated level: a noisy
            # higher level sneaking back under the bounds must not report
            # "served linearly up to here" above a rate that already
            # failed (and inflate the baseline the --slo gate compares to)
            knee = lv["offered_rps"]
        elif not linear and first_saturated is None:
            first_saturated = lv["offered_rps"]
    return {
        "knee_rps": knee,
        "first_saturated_rps": first_saturated,
        "baseline_p99_ms": baseline_p99,
        "p99_factor": p99_factor,
        "throughput_floor": throughput_floor,
        "levels_n": len(usable),
    }


def slo_block(
    tracker: SloTracker | None = None,
    *,
    since: dict | None = None,
    knee: dict | None = None,
    capacity: dict | None = None,
) -> dict:
    """Assemble the JSON-ready ``telemetry.slo`` block: per-domain stage
    histograms, shed attribution, the detected saturation knee, and
    (optionally) the capacity model's per-domain snapshot. With no
    tracker the block is empty but schema-valid, mirroring
    ``quality_block()``."""
    snap = (
        tracker.snapshot(since=since)
        if tracker is not None
        else {"enabled": False, "bucket_bounds": [], "stages": {},
              "shed": {"total": 0, "by_domain": {}}}
    )
    block = {
        "enabled": snap["enabled"],
        "bucket_bounds": snap["bucket_bounds"],
        "stages": snap["stages"],
        "shed": snap["shed"],
        "knee": knee if knee is not None else {},
    }
    if snap.get("classes"):
        block["classes"] = snap["classes"]
    if capacity is not None:
        block["capacity"] = capacity
    return block


def validate_slo(block, kind: str = "record") -> dict:
    """Assert ``block`` is a schema-valid ``telemetry.slo`` block."""
    if not isinstance(block, dict):
        raise ValueError(
            f"{kind} record's telemetry.slo must be a dict, got "
            f"{type(block).__name__}"
        )
    missing = [k for k in SLO_KEYS if k not in block]
    if missing:
        raise ValueError(
            f"{kind} record's telemetry.slo is missing keys {missing}: "
            "assemble it with observability.slo.slo_block so stage "
            "histograms, shed attribution, and the saturation knee travel "
            "with every committed serving number"
        )
    return block
