"""Structured tracing core: one id-correlated event stream for all paths.

The package grew three disconnected instruments — ``PhaseTimer`` (grid
points), ``ServiceMetrics`` (serving quantiles), and the raw
``jax.profiler`` toggle — with no way to follow one attack request or one
grid point end to end. This module is the shared substrate they all emit
into:

- :class:`TraceRecorder` — a process-scoped event store: a bounded
  in-memory ring (``capacity`` most recent events) plus an optional
  append-only JSONL sink (config ``system.trace_log``). **Cheap counters
  and gauges are always on** (two dict writes under a lock); **span/event
  recording is opt-in** (``spans_enabled``) so the hot paths pay nothing
  when tracing is off — the overhead contract
  ``tests/test_tracing.py::TestTracingOverhead`` pins (zero extra
  dispatches, zero extra compiles on the serving smoke).
- :class:`Trace` — a run/request-scoped context carrying an id, nested
  ``span()``s (parentage tracked per thread; explicit-duration
  ``record_span`` for clocks owned elsewhere, e.g. the microbatcher's
  injectable clock) and point ``event()``s. ``tree()`` renders the nested
  span tree JSON-ready (the ``/attack`` response payload); ``adopt()``
  re-stamps another trace's events under this id (how per-batch device
  spans land in every participating request's trace).

Timestamps: ``ts`` is seconds since the recorder's epoch measured with
``time.perf_counter()`` (monotonic — NTP steps cannot corrupt spans);
``t0_wall`` in the sink's meta line anchors the epoch to wall time.
Exporters: ``observability.export`` renders the JSONL/ring to
Chrome/Perfetto trace-event JSON, ``observability.prom`` to Prometheus
text exposition.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
import uuid

#: process-global span-id source — ids stay unique across traces, so
#: ``Trace.adopt`` can copy events between traces without remapping.
_span_ids = itertools.count(1)

#: ambient trace for code that cannot be handed one explicitly (the
#: service's dispatch closures run under the batcher's per-batch trace).
_current: contextvars.ContextVar = contextvars.ContextVar(
    "moeva2_current_trace", default=None
)


class TraceRecorder:
    """Bounded ring + optional JSONL sink + always-on counters/gauges."""

    def __init__(
        self,
        capacity: int = 4096,
        sink_path: str | None = None,
        spans_enabled: bool | None = None,
        clock=time.perf_counter,
    ):
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.counters: dict[str, int] = {}
        self.gauges: dict[str, float] = {}
        #: total events ever emitted (the ring keeps only the last
        #: ``capacity``; this count never loses history)
        self.events_emitted = 0
        self._clock = clock
        self._t0 = clock()
        self.t0_wall = time.time()
        self.sink_path = sink_path
        self._sink = None
        if sink_path:
            os.makedirs(os.path.dirname(sink_path) or ".", exist_ok=True)
            self._sink = open(sink_path, "a", buffering=1)
        # a sink implies the caller wants spans; counters-only otherwise
        self.spans_enabled = (
            bool(sink_path) if spans_enabled is None else bool(spans_enabled)
        )
        if self._sink is not None:
            # epoch anchor: exporters map monotonic ts back to wall time
            self.emit(
                {
                    "kind": "meta",
                    "t0_wall": round(self.t0_wall, 6),
                    "pid": os.getpid(),
                }
            )

    def now(self) -> float:
        """Seconds since the recorder epoch (monotonic)."""
        return self._clock() - self._t0

    @property
    def perf_epoch(self) -> float:
        """The recorder's clock reading at epoch — what converts its
        relative ``ts`` values to the dispatch-gap tracker's absolute
        clock base (``observability.gaps`` joins gap intervals against
        span events across the two)."""
        return self._t0

    def emit(self, ev: dict) -> None:
        with self._lock:
            self._ring.append(ev)
            self.events_emitted += 1
            if self._sink is not None:
                self._sink.write(json.dumps(ev, default=str) + "\n")

    # -- always-on cheap instruments -----------------------------------------
    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def gauge(self, name: str, value: float, at: float | None = None) -> None:
        """``at`` (recorder-relative seconds) positions the emitted
        counter sample at a specific timeline instant — how the dispatch-
        gap tracker renders a device-busy counter track at the window's
        true position instead of the emission instant."""
        with self._lock:
            self.gauges[name] = float(value)
        if self.spans_enabled:
            self.emit(
                {
                    "kind": "gauge",
                    "name": name,
                    "value": float(value),
                    "ts": round(self.now() if at is None else max(at, 0.0), 6),
                }
            )

    # -- introspection -------------------------------------------------------
    def events(self) -> list[dict]:
        """Snapshot of the ring (most recent ``capacity`` events)."""
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "events_emitted": self.events_emitted,
                "ring_size": len(self._ring),
                "spans_enabled": self.spans_enabled,
                "sink_path": self.sink_path,
            }

    def close(self) -> None:
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None


class Trace:
    """A run/request-scoped id with nested spans and point events.

    ``record=False`` makes a buffer-only trace (events collect in
    ``.events`` without touching the recorder) — the microbatcher's
    per-batch trace, whose events are ``adopt()``-ed into each
    participating request's recording trace afterwards.
    """

    def __init__(
        self,
        recorder: TraceRecorder,
        trace_id: str | None = None,
        name: str = "",
        record: bool = True,
        enabled: bool | None = None,
        root_parent: int | None = None,
    ):
        self.recorder = recorder
        self.id = trace_id or uuid.uuid4().hex[:12]
        self.name = name
        self.events: list[dict] = []
        self.record = record
        self.enabled = recorder.spans_enabled if enabled is None else bool(enabled)
        # cross-process parenting (fleet trace propagation): root spans of
        # this trace parent under a REMOTE span id (the router's attempt
        # span, carried in by the X-Moeva2-Trace header). Local ``tree()``
        # rendering is unaffected — an unknown parent renders as a root —
        # but a merged fleet document nests this trace under its hop.
        self.root_parent = root_parent
        # span parentage is per-thread: a trace may be touched from several
        # threads (submit on a handler thread, dispatch on the flusher) and
        # their span stacks must not interleave
        self._tls = threading.local()

    # -- emission ------------------------------------------------------------
    def _emit(self, ev: dict) -> None:
        ev = {"trace": self.id, **ev}
        self.events.append(ev)
        if self.record:
            self.recorder.emit(ev)

    def _parent(self):
        stack = getattr(self._tls, "stack", ())
        return stack[-1] if stack else self.root_parent

    # -- spans ---------------------------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed nested span; yields the span id (None when disabled).

        The span event is emitted at exit (one event per span, ``ts`` +
        ``dur``), so a crash mid-span loses only the open span — the JSONL
        sink stays parseable line by line.
        """
        if not self.enabled:
            yield None
            return
        sid = next(_span_ids)
        stack = getattr(self._tls, "stack", ())
        parent = stack[-1] if stack else self.root_parent
        self._tls.stack = stack + (sid,)
        t0 = self.recorder.now()
        try:
            yield sid
        finally:
            self._tls.stack = stack
            ev = {
                "kind": "span",
                "name": name,
                "span": sid,
                "parent": parent,
                "ts": round(t0, 6),
                "dur": round(self.recorder.now() - t0, 6),
            }
            if attrs:
                ev["attrs"] = attrs
            self._emit(ev)

    def record_span(
        self, name: str, dur: float, parent=None, at: float | None = None, **attrs
    ) -> int | None:
        """A span whose duration was measured elsewhere (e.g. under the
        batcher's injectable clock): recorded as ending now, ``dur`` seconds
        long — or, with ``at`` (recorder-relative seconds), starting at
        that exact timeline instant (how the dispatch-gap tracker places
        ``device_gap`` slices where the idle actually happened). Parent
        defaults to the calling thread's current span."""
        if not self.enabled:
            return None
        sid = next(_span_ids)
        now = self.recorder.now()
        dur = max(float(dur), 0.0)
        ev = {
            "kind": "span",
            "name": name,
            "span": sid,
            "parent": parent if parent is not None else self._parent(),
            # clamped: a duration measured under a different clock (fake
            # batcher clocks in tests) must not produce a pre-epoch start
            "ts": round(max(now - dur, 0.0) if at is None else max(at, 0.0), 6),
            "dur": round(dur, 6),
        }
        if attrs:
            ev["attrs"] = attrs
        self._emit(ev)
        return sid

    def event(self, name: str, **attrs) -> None:
        """Point event under the calling thread's current span."""
        if not self.enabled:
            return
        ev = {
            "kind": "event",
            "name": name,
            "parent": self._parent(),
            "ts": round(self.recorder.now(), 6),
        }
        if attrs:
            ev["attrs"] = attrs
        self._emit(ev)

    # -- composition ---------------------------------------------------------
    def adopt(self, other: "Trace", parent=None) -> None:
        """Re-stamp ``other``'s events under this trace id (root events get
        ``parent``). Span ids are process-unique, so no remapping needed."""
        if not self.enabled:
            return
        if parent is None:
            parent = self.root_parent
        for ev in other.events:
            ev = dict(ev, trace=self.id)
            if ev.get("parent") is None and parent is not None:
                ev["parent"] = parent
            self._emit(ev)

    def tree(self) -> list[dict]:
        """Nested JSON-ready span/event tree (children sorted by ts) — the
        per-request payload ``/attack`` responses return."""
        nodes: dict[int, dict] = {}
        order: list[tuple[int | None, dict]] = []
        for ev in self.events:
            node = {
                k: ev[k]
                for k in ("kind", "name", "ts", "dur", "value", "attrs")
                if k in ev
            }
            if ev.get("kind") == "span":
                node["children"] = []
                nodes[ev["span"]] = node
            order.append((ev.get("parent"), node))
        roots: list[dict] = []
        for parent, node in order:
            target = nodes.get(parent)
            if target is not None and target is not node:
                target["children"].append(node)
            else:
                roots.append(node)
        for node in nodes.values():
            node["children"].sort(key=lambda n: n.get("ts", 0.0))
        roots.sort(key=lambda n: n.get("ts", 0.0))
        return roots


# -- ambient trace ----------------------------------------------------------
def current_trace() -> Trace | None:
    """The ambient trace installed by :func:`use_trace`, if any."""
    return _current.get()


@contextlib.contextmanager
def use_trace(trace: Trace | None):
    """Install ``trace`` as the ambient trace for the dynamic extent."""
    token = _current.set(trace)
    try:
        yield trace
    finally:
        _current.reset(token)


def maybe_span(trace: Trace | None, name: str, **attrs):
    """``trace.span(...)`` or a no-op context when tracing is off."""
    if trace is not None and trace.enabled:
        return trace.span(name, **attrs)
    return contextlib.nullcontext()


# -- process default + config hook -------------------------------------------
#: the process default: counters/gauges always on, spans off, no sink —
#: what every path uses when no ``system.trace_log`` is configured.
_DEFAULT = TraceRecorder(spans_enabled=False)
_SINKS: dict[str, TraceRecorder] = {}
_SINKS_LOCK = threading.Lock()


def default_recorder() -> TraceRecorder:
    return _DEFAULT


def recorder_for(config: dict | None) -> TraceRecorder:
    """Config ``system.trace_log`` -> a sink-backed recorder (memoized per
    path so every run in a process appends to one stream); absent -> the
    process default (counters on, spans off)."""
    path = (config or {}).get("system", {}).get("trace_log")
    if not path:
        return _DEFAULT
    with _SINKS_LOCK:
        rec = _SINKS.get(path)
        if rec is None:
            rec = _SINKS[path] = TraceRecorder(sink_path=path)
        return rec


# -- device memory watermarks -------------------------------------------------
def all_device_memory_stats(devices=None) -> dict | None:
    """Best-effort HBM watermarks of ALL local devices (default:
    ``jax.local_devices()``): ``{"device_count", "per_device": [stats |
    None per ordinal], "max": stats}`` where ``max`` is the elementwise
    maximum over devices that exposed allocator stats — the
    single-number watermark the pre-mesh surfaces kept reading from
    device 0, now taken over the whole mesh. ``None`` when no device
    exposes stats (CPU) or JAX is not initialised. Never raises."""
    try:
        if devices is None:
            import jax

            devices = jax.local_devices()
        per_device = [device_memory_stats(d) for d in devices]
    except Exception:
        return None
    present = [s for s in per_device if s]
    if not present:
        return None
    max_stats = {
        k: max(s[k] for s in present if k in s)
        for k in {k for s in present for k in s}
    }
    return {
        "device_count": len(per_device),
        "per_device": per_device,
        "max": max_stats,
    }


def device_memory_stats(device=None) -> dict | None:
    """Best-effort HBM watermark of ``device`` (default: the first visible
    device): ``{bytes_in_use, peak_bytes_in_use, ...}`` ints, or None when
    the backend does not expose allocator stats (CPU) or JAX is not
    initialised. Never raises — observability must not take a run down."""
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        stats = device.memory_stats()
        if not stats:
            return None
        out = {
            k: int(stats[k])
            for k in (
                "bytes_in_use",
                "peak_bytes_in_use",
                "bytes_limit",
                "largest_alloc_size",
            )
            if k in stats
        }
        return out or None
    except Exception:
        return None
