"""Attack-as-a-service: a microbatching serving layer over the cached engines.

The batch experiment path (``experiments/``) amortises compiles across grid
points; this package amortises them across *concurrent requests*: an
in-process :class:`AttackService` accepts :class:`AttackRequest` rows,
resolves them to the same process-wide engine/artifact caches the grid
runners use (``experiments.common.ENGINES`` / ``ARTIFACTS``), and executes
them through a shape-bucketed :class:`Microbatcher` — full fixed-shape
device batches from variably-sized requests, one compiled program per
(engine-static-config, bucket-size). ``serving.server`` is the stdlib-only
JSON/HTTP front; ``serving.sweep`` is the offered-load harness behind
``bench.py --serving``; ``serving.fleet`` scales the whole stack out —
N replica processes sharing one AOT/artifact cache directory behind a
capacity-driven router with add/drain lifecycle and a chaos-proof fleet
sweep (``bench.py --fleet``). ``serving.qos`` adds the QoS layer on top:
priority classes with weighted-fair assembly (:class:`QosPolicy`),
cost-predictive admission from the capacity model
(:class:`AdmissionController`), and streaming partial results
(:class:`ResultStream`) fed by the MoEvA early-exit gate.
"""

from .batcher import (
    BatchExecutionError,
    BucketMenu,
    DeadlineExceeded,
    Microbatcher,
    QueueFull,
    RequestTooLarge,
)
from .fleet import (
    BuildMismatch,
    ReplicaHandle,
    ReplicaManager,
    Router,
    serve_router,
)
from .qos import AdmissionController, QosClass, QosPolicy, ResultStream
from .service import AttackRequest, AttackResponse, AttackService, InvalidRequest

__all__ = [
    "AdmissionController",
    "AttackRequest",
    "AttackResponse",
    "AttackService",
    "BatchExecutionError",
    "BucketMenu",
    "BuildMismatch",
    "DeadlineExceeded",
    "InvalidRequest",
    "Microbatcher",
    "QosClass",
    "QosPolicy",
    "QueueFull",
    "ReplicaHandle",
    "ReplicaManager",
    "RequestTooLarge",
    "ResultStream",
    "Router",
    "serve_router",
]
