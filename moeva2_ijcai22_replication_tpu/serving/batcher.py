"""Shape-bucketed microbatcher: concurrent requests -> fixed-shape batches.

The continuous-batching pattern every inference stack relies on, specialised
to attack engines: a jitted program compiles per input *shape*, so serving
arbitrary request sizes naively would compile per request. Instead requests
queue FIFO per batch key (everything that must be identical within one
device dispatch: engine static config + runtime ε/ε-step/budget), a flusher
coalesces each key's queue up to a deadline (``max_delay_s``) or capacity
(a full largest bucket), pads the concatenated states axis to a small fixed
menu of bucket sizes (``experiments.common.BucketMenu`` — power-of-two,
mesh-size multiples, shared with the MoEvA early-exit compaction path — via
``experiments.common.pad_states``), dispatches ONE program per bucket, and
scatters per-request row slices back.

Semantics the service builds on:

- **FIFO fairness within a key**: assembly never reorders or skips past a
  queued request — if the head doesn't fit the remaining capacity, the
  batch closes and the head leads the next one.
- **Backpressure**: total queued rows are bounded; ``submit`` raises
  :class:`QueueFull` (with a retry-after hint) instead of queueing
  unboundedly.
- **Deadlines**: a request whose absolute deadline passed while queued is
  cancelled at assembly time, *before* dispatch, with
  :class:`DeadlineExceeded` — it never consumes device time.
- **Failure isolation**: one poisoned request fails its batch — every
  batch-mate's future gets :class:`BatchExecutionError` naming the cause —
  and the flusher moves on to the next batch; the service never dies with
  a request.

The clock is injectable and ``start=False`` skips the flusher thread so
tests drive :meth:`Microbatcher.flush_due` synchronously under a fake
clock.
"""

from __future__ import annotations

import collections
import threading
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from ..experiments.common import (  # noqa: F401 — BucketMenu/RequestTooLarge
    BucketMenu,  # re-exported: the menu moved to experiments.common so the
    DEFAULT_BUCKET_SIZES,  # batcher, pad_states, and the MoEvA early-exit
    RequestTooLarge,  # compaction path all consume ONE size source of truth
    pad_states,
)
from ..observability import Trace, ledger_context, use_trace


class QueueFull(Exception):
    """Backpressure: the bounded request queue is full; retry later.

    ``retry_after_s`` is the 429 ``Retry-After`` hint. With a live
    capacity window (``Microbatcher(retry_after_fn=...)`` — the service
    wires :meth:`~..observability.capacity.CapacityModel.retry_after_s`)
    it is the predicted drain time of the rows ahead of the caller,
    floored by the flusher's next flush obligation; otherwise the static
    ``max_delay_s`` fallback."""

    def __init__(self, msg: str, retry_after_s: float = 0.05):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class DeadlineExceeded(Exception):
    """The request's deadline passed while it was queued (never dispatched)."""


class BatchExecutionError(Exception):
    """The batch this request was coalesced into failed to execute."""

    def __init__(self, key, cause: BaseException):
        super().__init__(f"batch for key {key!r} failed: {cause!r}")
        self.key = key
        self.cause = cause


@dataclass
class _Pending:
    rows: np.ndarray
    n: int
    future: Future
    enqueued_at: float
    deadline_at: float | None
    meta: dict
    #: the request's observability trace (None when tracing is off — the
    #: batcher then does zero trace work for this request)
    trace: Trace | None = None
    #: QoS class name (None when the batcher runs without a policy)
    qos_class: str | None = None
    #: the instant assembly first REACHED this request but closed the
    #: batch without it (it no longer waits for batch-mates to arrive,
    #: it waits for batch formation) — deadline expiry after this
    #: instant is attributed to batch_wait, not queue_wait
    batched_at: float | None = None
    #: partial-result sink: called with (local_rows, x_rows, gen) as
    #: solved rows belonging to this request surface mid-dispatch
    on_partial: Callable | None = None


@dataclass
class _KeyQueue:
    dispatch: Callable[[np.ndarray], np.ndarray]
    requests: collections.deque = field(default_factory=collections.deque)
    rows_queued: int = 0
    #: QoS mode only: class name -> FIFO deque (``requests`` unused).
    #: None = classless mode, the exact pre-QoS single-deque path.
    by_class: dict[str, collections.deque] | None = None

    def empty(self) -> bool:
        if self.by_class is not None:
            return all(not dq for dq in self.by_class.values())
        return not self.requests

    def heads(self) -> list[_Pending]:
        """The oldest request of each FIFO lane (one lane per class in
        QoS mode, a single lane otherwise)."""
        if self.by_class is not None:
            return [dq[0] for dq in self.by_class.values() if dq]
        return [self.requests[0]] if self.requests else []


class Microbatcher:
    """Per-key FIFO queues + deadline/capacity flusher + bucketed dispatch."""

    def __init__(
        self,
        menu: BucketMenu,
        *,
        max_delay_s: float = 0.010,
        max_queue_rows: int = 4096,
        metrics=None,
        slo=None,
        clock: Callable[[], float] | None = None,
        start: bool = True,
        retry_after_fn: Callable[[int], float | None] | None = None,
        qos=None,
    ):
        import time

        self.menu = menu
        #: QoS policy (``serving.qos.QosPolicy`` or None). None = the
        #: exact pre-QoS path: single FIFO lane per key, no class
        #: bookkeeping anywhere. With a policy, each key grows one FIFO
        #: lane per class, assembly runs weighted-fairness seats then
        #: strict-priority fill, and flush dispatches batches in
        #: priority order (preemption: a flushable high-priority batch
        #: never waits behind a low-priority one).
        self.qos = qos
        self.max_delay_s = float(max_delay_s)
        self.max_queue_rows = int(max_queue_rows)
        self.metrics = metrics
        #: optional honest-backpressure hook: called with the queued row
        #: count on a queue-full rejection, returns the predicted seconds
        #: until that backlog drains (None = no live prediction, fall back
        #: to ``max_delay_s``). The service wires the capacity model's
        #: windowed drain rate here so 429 ``Retry-After`` reflects real
        #: saturation instead of a constant.
        self.retry_after_fn = retry_after_fn
        #: SLO tracker (``observability.slo.SloTracker`` or None): receives
        #: per-request stage latencies (queue_wait/batch_wait/dispatch) and
        #: shed attribution (expired/overrun/poisoned) keyed by the
        #: ``domain`` each request's meta carries — pure host-side counts
        self.slo = slo
        self.clock = clock or time.monotonic
        self._queues: dict[Any, _KeyQueue] = {}
        self._rows_total = 0
        self._batch_seq = 0
        #: the batch currently on the device (None between dispatches) —
        #: the flight recorder's "what died in flight" evidence
        self._inflight: dict | None = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # engines are single-dispatch objects (host-side knobs are mutated
        # per batch); one batch executes at a time even when a drain on the
        # caller thread overlaps the flusher thread
        self._dispatch_lock = threading.Lock()
        self._stop = False
        self._thread: threading.Thread | None = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name="microbatch-flusher", daemon=True
            )
            self._thread.start()

    # -- submission ----------------------------------------------------------
    def submit(
        self,
        key,
        dispatch: Callable[[np.ndarray], np.ndarray],
        rows: np.ndarray,
        *,
        deadline_s: float | None = None,
        meta: dict | None = None,
        trace: Trace | None = None,
        qos_class: str | None = None,
        on_partial: Callable | None = None,
    ) -> Future:
        """Queue ``rows`` under ``key``; resolves to ``(result_rows, meta)``.

        ``dispatch`` is the key's batch function (first submit wins; all
        requests under one key must share it — the service guarantees this
        by deriving the key from everything the closure captures). ``trace``
        (optional) receives the request's queue_wait/batch spans and rides
        back in the result meta as a span tree. ``qos_class`` (QoS mode
        only) picks the request's FIFO lane; ``on_partial`` receives
        ``(local_rows, x_rows, gen)`` as this request's solved rows
        surface mid-dispatch (streaming partial results).
        """
        rows = np.asarray(rows)
        n = rows.shape[0]
        if n < 1:
            raise ValueError("empty request (0 rows)")
        if n > self.menu.max_size:
            raise RequestTooLarge(
                f"{n} rows exceed the largest bucket {self.menu.max_size}; "
                "split the request"
            )
        now = self.clock()
        if self.qos is not None:
            # the service resolves names/tenants; anything unresolved
            # still lands in a valid lane (the policy default)
            if not qos_class or qos_class not in self.qos.classes:
                qos_class = self.qos.default_class
        else:
            qos_class = None
        pending = _Pending(
            rows=rows,
            n=n,
            future=Future(),
            enqueued_at=now,
            deadline_at=None if deadline_s is None else now + float(deadline_s),
            meta=dict(meta or {}),
            trace=trace,
            qos_class=qos_class,
            on_partial=on_partial,
        )
        with self._cond:
            if self._stop:
                raise RuntimeError("microbatcher is stopped")
            if self._rows_total + n > self.max_queue_rows:
                if self.metrics:
                    self.metrics.count("rejected")
                hint = None
                if self.retry_after_fn is not None:
                    try:
                        hint = self.retry_after_fn(self._rows_total)
                    except Exception:  # noqa: BLE001 — a broken hint
                        hint = None  # must not turn a 429 into a 500
                if hint is None:
                    hint = self.max_delay_s
                else:
                    # capacity predicts the DEVICE drain; admission also
                    # waits for the flusher's next flush obligation — the
                    # hint is honest only above both
                    nd = self._next_deadline(now)
                    if nd is not None:
                        hint = max(hint, nd)
                raise QueueFull(
                    f"queue full ({self._rows_total}/{self.max_queue_rows} "
                    f"rows); retry after {hint:.3f}s",
                    retry_after_s=hint,
                )
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _KeyQueue(
                    dispatch=dispatch,
                    by_class={} if self.qos is not None else None,
                )
            if q.by_class is not None:
                q.by_class.setdefault(
                    qos_class, collections.deque()
                ).append(pending)
            else:
                q.requests.append(pending)
            q.rows_queued += n
            self._rows_total += n
            if self.metrics:
                self.metrics.count("requests")
                self.metrics.observe("request_rows", n)
                self.metrics.gauge("queue_depth_rows", self._rows_total)
            # capacity flush: a full largest bucket is waiting — wake now
            self._cond.notify_all()
        return pending.future

    # -- flushing ------------------------------------------------------------
    def _due(self, key: Any, q: _KeyQueue, now: float, force: bool) -> bool:
        heads = q.heads()
        if not heads:
            return False
        if force or q.rows_queued >= self.menu.max_size:
            return True
        for head in heads:
            if now - head.enqueued_at >= self.max_delay_s or (
                head.deadline_at is not None and head.deadline_at <= now
            ):
                return True
        return False

    def _next_deadline(self, now: float) -> float | None:
        """Seconds until the nearest flush obligation, None when idle."""
        nearest = None
        for q in self._queues.values():
            if q.empty():
                continue
            if q.rows_queued >= self.menu.max_size:
                return 0.0
            for head in q.heads():
                t = head.enqueued_at + self.max_delay_s
                if head.deadline_at is not None:
                    t = min(t, head.deadline_at)
                nearest = t if nearest is None else min(nearest, t)
        return None if nearest is None else max(0.0, nearest - now)

    def _cancel_if_expired(self, p: _Pending, now: float) -> bool:
        """Cancel a just-popped request whose deadline already passed."""
        if p.deadline_at is None or p.deadline_at > now:
            return False
        if self.metrics:
            self.metrics.count("timeouts")
        if self.slo is not None:
            # attribute the expiry to the stage that actually consumed
            # the deadline: once assembly reached the request but closed
            # the batch without it (batched_at), its remaining wait is
            # batch formation, not queueing — a deadline instant past
            # that mark sheds as batch_wait
            stage = (
                "batch_wait"
                if p.batched_at is not None and p.deadline_at > p.batched_at
                else "queue_wait"
            )
            self.slo.shed(
                p.meta.get("domain"), "expired", stage, qos_class=p.qos_class
            )
        if p.trace is not None:
            p.trace.event(
                "cancelled",
                reason="deadline",
                queued_s=round(now - p.enqueued_at, 6),
            )
        p.future.set_exception(
            DeadlineExceeded(
                f"deadline passed after {now - p.enqueued_at:.3f}s in "
                "queue; cancelled before dispatch"
            )
        )
        return True

    def _assemble(self, key: Any, q: _KeyQueue, now: float):
        """Pop one FIFO batch for ``key``; cancels expired requests."""
        if q.by_class is not None:
            return self._assemble_qos(key, q, now)
        batch: list[_Pending] = []
        rows_total = 0
        while q.requests and rows_total + q.requests[0].n <= self.menu.max_size:
            p = q.requests.popleft()
            q.rows_queued -= p.n
            self._rows_total -= p.n
            if self._cancel_if_expired(p, now):
                continue
            batch.append(p)
            rows_total += p.n
        if batch and q.requests:
            # the head was reached but the batch closed without it: from
            # here on it waits for batch formation, not batch-mates
            head = q.requests[0]
            if head.batched_at is None:
                head.batched_at = now
        return batch, rows_total

    def _assemble_qos(self, key: Any, q: _KeyQueue, now: float):
        """Class-aware assembly: weighted seats, then strict priority.

        Pass 1 guarantees every class with queued work
        ``floor(capacity * weight / sum(present weights))`` rows, popped
        FIFO, visiting classes in priority order — the starvation bound:
        scavenger work is guaranteed its slice of EVERY batch its key
        flushes, no matter how hot the interactive lane runs. Pass 2
        hands the leftover capacity out in strict priority order.
        """
        cap = self.menu.max_size
        batch: list[_Pending] = []
        rows_total = 0

        def pop_from(dq: collections.deque, limit_rows: int) -> None:
            nonlocal rows_total
            taken = 0
            while (
                dq
                and taken + dq[0].n <= limit_rows
                and rows_total + dq[0].n <= cap
            ):
                p = dq.popleft()
                q.rows_queued -= p.n
                self._rows_total -= p.n
                if self._cancel_if_expired(p, now):
                    continue
                batch.append(p)
                rows_total += p.n
                taken += p.n

        order = [c for c in self.qos.ordered() if q.by_class.get(c.name)]
        w_sum = sum(c.weight for c in order)
        if w_sum > 0:
            for c in order:
                pop_from(
                    q.by_class[c.name], int(cap * c.weight / w_sum)
                )
        for c in order:
            pop_from(q.by_class[c.name], cap)
        if batch:
            for dq in q.by_class.values():
                if dq and dq[0].batched_at is None:
                    dq[0].batched_at = now
        return batch, rows_total

    def flush_due(self, now: float | None = None, force: bool = False) -> int:
        """Assemble and dispatch every due batch; returns batches dispatched.

        The flusher thread's body — also the synchronous entry point for
        fake-clock tests (construct with ``start=False``). ``force`` treats
        every non-empty queue as past its flush delay (the drain path)
        without touching deadline semantics: request deadlines are still
        judged against the real ``now``.
        """
        if now is None:
            now = self.clock()
        todo = []
        with self._lock:
            for key, q in list(self._queues.items()):
                # one batch per due key per pass; a backlog > max bucket
                # stays due and drains on immediate subsequent passes
                if self._due(key, q, now, force):
                    batch, rows_total = self._assemble(key, q, now)
                    if batch:
                        todo.append((key, q.dispatch, batch, rows_total, now))
                # drop drained queues: the key space is client-controlled
                # (ε sweeps), so idle keys must not accumulate flusher work
                if q.empty():
                    del self._queues[key]
            if self.metrics:
                self.metrics.gauge("queue_depth_rows", self._rows_total)
        if self.qos is not None and len(todo) > 1:
            # preemption at flush: a flushable high-priority batch never
            # waits behind a low-priority one from another key (stable
            # sort — equal-priority batches keep assembly order)
            todo.sort(
                key=lambda t: min(
                    self.qos.priority_of(p.qos_class) for p in t[2]
                )
            )
        for key, dispatch, batch, rows_total, t_asm in todo:
            self._dispatch(key, dispatch, batch, rows_total, t_asm)
        return len(todo)

    def _dispatch(
        self, key, dispatch, batch: list[_Pending], rows_total: int, t_asm: float
    ):
        with self._dispatch_lock:
            self._dispatch_one(key, dispatch, batch, rows_total, t_asm)

    def _dispatch_one(
        self, key, dispatch, batch: list[_Pending], rows_total: int, t_asm: float
    ):
        bucket = self.menu.bucket_for(rows_total)
        with self._lock:
            self._batch_seq += 1
            seq = self._batch_seq
        x = (
            batch[0].rows
            if len(batch) == 1 and batch[0].n == rows_total
            else np.concatenate([p.rows for p in batch], axis=0)
        )
        x_pad, _ = pad_states(x, None, bucket=bucket)
        # per-batch trace: only built when at least one batch-mate is traced
        # (tracing off => this whole block is two attribute reads). It is
        # buffer-only (record=False); its spans are adopted into each traced
        # request's own trace after the dispatch, so device work appears in
        # every request's span tree under the request's id.
        bt = None
        for p in batch:
            if p.trace is not None and p.trace.enabled:
                bt = Trace(
                    p.trace.recorder, trace_id=f"batch-{seq}", record=False
                )
                break
        # every executable compiled under this dispatch records the
        # bucket it was built for — the cost ledger's serving identity;
        # batch_rows is the REAL (pre-padding) row count, what the
        # capacity model must count as served (the dispatch closure
        # only ever sees the bucket-padded array)
        ctx: dict[str, Any] = dict(
            bucket=int(bucket),
            batch_requests=len(batch),
            batch_rows=int(rows_total),
        )
        if any(p.qos_class for p in batch):
            census: dict[str, int] = {}
            for p in batch:
                k = p.qos_class or "(none)"
                census[k] = census.get(k, 0) + 1
            ctx["batch_classes"] = census
        router = self._partial_router(batch)
        if router is not None:
            ctx["partial_router"] = router
        # publish the in-flight view BEFORE the dispatch: a flight dump
        # taken while this batch executes (the replica is being killed)
        # names the exact batch and riders that died on the device
        inflight = {
            "batch_seq": seq,
            "bucket": int(bucket),
            "rows": int(rows_total),
            "requests": [
                {
                    "request_id": p.meta.get("request_id"),
                    "domain": p.meta.get("domain"),
                    "rows": p.n,
                    "trace_id": p.trace.id if p.trace is not None else None,
                }
                for p in batch
            ],
            "t_start": round(self.clock(), 6),
        }
        with self._lock:
            self._inflight = inflight
        t0 = self.clock()
        try:
            with ledger_context(**ctx):
                if bt is None:
                    out = np.asarray(dispatch(x_pad))
                else:
                    with use_trace(bt), bt.span(
                        "dispatch",
                        bucket=bucket,
                        rows=rows_total,
                        requests=len(batch),
                    ):
                        out = np.asarray(dispatch(x_pad))
            if out.shape[0] != bucket:
                raise ValueError(
                    f"dispatch returned leading axis {out.shape[0]}, "
                    f"expected bucket size {bucket}"
                )
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            with self._lock:
                if self._inflight is inflight:
                    self._inflight = None
            if self.metrics:
                self.metrics.count("batch_failures")
            err = BatchExecutionError(key, e)
            for p in batch:
                if self.slo is not None:
                    self.slo.shed(
                        p.meta.get("domain"),
                        "poisoned",
                        "dispatch",
                        qos_class=p.qos_class,
                    )
                if p.trace is not None:
                    p.trace.event("batch_failed", batch_seq=seq, error=repr(e))
                p.future.set_exception(err)
            return
        with self._lock:
            if self._inflight is inflight:
                self._inflight = None
        dt = self.clock() - t0
        occupancy = rows_total / bucket
        if self.metrics:
            self.metrics.count("batches")
            self.metrics.count("dispatched_rows", rows_total)
            self.metrics.count("padded_rows", bucket - rows_total)
            self.metrics.observe("batch_occupancy", occupancy)
            self.metrics.observe("dispatch_s", dt)
        t_done = self.clock()
        off = 0
        for p in batch:
            queue_wait = max(t_asm - p.enqueued_at, 0.0)
            batch_wait = max(t0 - t_asm, 0.0)
            meta = dict(
                p.meta,
                bucket_size=bucket,
                batch_rows=rows_total,
                batch_requests=len(batch),
                batch_occupancy=occupancy,
                batch_seq=seq,
                queued_s=round(t0 - p.enqueued_at, 6),
                queue_wait_s=round(queue_wait, 6),
                batch_wait_s=round(batch_wait, 6),
                dispatch_s=round(dt, 6),
            )
            if p.qos_class is not None:
                meta["qos_class"] = p.qos_class
            if self.slo is not None:
                domain = p.meta.get("domain")
                kl = p.qos_class
                self.slo.observe(domain, "queue_wait", queue_wait, qos_class=kl)
                self.slo.observe(domain, "batch_wait", batch_wait, qos_class=kl)
                self.slo.observe(domain, "dispatch", dt, qos_class=kl)
                if p.deadline_at is not None and p.deadline_at <= t_done:
                    # completed, but past its deadline: attribute the
                    # overrun to the stage the deadline instant fell in.
                    # Never queue_wait — _assemble cancels (sheds as
                    # "expired") every request whose deadline passed by
                    # t_asm, so a dispatched request's deadline can only
                    # have fallen in batch_wait or device time.
                    stage = (
                        "batch_wait" if p.deadline_at <= t0 else "device_run"
                    )
                    self.slo.shed(domain, "overrun", stage, qos_class=kl)
            if p.trace is not None and p.trace.enabled:
                # the request's own waits (batcher clock), then the shared
                # batch spans re-stamped under the request's trace id — one
                # correlated tree per request
                p.trace.record_span(
                    "queue_wait", max(t_asm - p.enqueued_at, 0.0)
                )
                p.trace.record_span("batch_wait", max(t0 - t_asm, 0.0))
                if bt is not None:
                    p.trace.adopt(bt)
                meta["trace"] = p.trace.tree()
            p.future.set_result((out[off : off + p.n].copy(), meta))
            off += p.n

    @staticmethod
    def _partial_router(batch: list[_Pending]):
        """Map batch-global solved rows back to each streaming rider.

        Returns a callable ``(rows, x_rows, gen)`` — ``rows`` are row
        indices in the CONCATENATED (pre-padding) batch, ``x_rows`` the
        aligned decoded payloads — or None when no rider streams (the
        common case: the dispatch then carries no partial plumbing at
        all). Padding rows are beyond every rider's slice and never
        route. A broken consumer sink must never poison the batch, so
        sink errors are swallowed.
        """
        sinks = []
        off = 0
        for p in batch:
            if p.on_partial is not None:
                sinks.append((off, off + p.n, p.on_partial))
            off += p.n
        if not sinks:
            return None

        def route(rows, x_rows, gen):
            for lo, hi, sink in sinks:
                local, sel = [], []
                for i, r in enumerate(rows):
                    if lo <= r < hi:
                        local.append(int(r - lo))
                        sel.append(i)
                if local:
                    try:
                        sink(local, x_rows[np.asarray(sel)], int(gen))
                    except Exception:  # noqa: BLE001 — consumer boundary
                        pass

        return route

    # -- lifecycle -----------------------------------------------------------
    def _run(self):
        while True:
            with self._cond:
                if self._stop:
                    return
                wait = self._next_deadline(self.clock())
                if wait is None or wait > 0:
                    self._cond.wait(timeout=wait)
                if self._stop:
                    return
            self.flush_due()

    def queue_depth_rows(self) -> int:
        with self._lock:
            return self._rows_total

    def inflight_view(self) -> dict:
        """What the batcher holds RIGHT NOW: every queued request (id,
        domain, rows, class) plus the batch currently executing on the
        device — the flight dump's in-flight evidence, so a kill mid-
        dispatch stays attributable to the exact batch and riders."""
        with self._lock:
            queued = [
                {
                    "request_id": p.meta.get("request_id"),
                    "domain": p.meta.get("domain"),
                    "rows": p.n,
                    "qos_class": p.qos_class,
                }
                for q in self._queues.values()
                for p in (
                    [p for dq in q.by_class.values() for p in dq]
                    if q.by_class is not None
                    else list(q.requests)
                )
            ]
            return {
                "queued_rows": self._rows_total,
                "queued": queued,
                "dispatching": (
                    dict(self._inflight) if self._inflight else None
                ),
                "batch_seq": self._batch_seq,
            }

    def stop(self, drain: bool = True):
        """Stop the flusher; with ``drain``, flush whatever is queued first
        (flush delays are waived; request deadlines keep real-time
        semantics — a request with time remaining is dispatched, not
        cancelled)."""
        if drain:
            while True:
                with self._lock:
                    pending = self._rows_total
                if pending == 0:
                    break
                if not self.flush_due(force=True):
                    break
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
