"""Fleet serving: N single-process replicas behind a capacity-driven router.

The scale-out assembly of parts that already shipped: the serialized AOT
executable cache gives every spawned replica a warm start from one shared
cache directory, ``serving.prewarm`` boots it ready-to-serve, the capacity
model publishes honest per-domain ``max_sustainable_qps`` + headroom +
freshness on /healthz, and the SLO histograms were designed
mergeable-cumulative — this package wires them into a fleet:

- :class:`ReplicaManager` (``fleet.replica``) — spawn/adopt N
  ``tools/serve.py`` processes over one shared config + cache dir, poll
  their /healthz into a fleet view, refuse mismatched build fingerprints,
  add (admit only after first healthy poll) and drain (stop routing, wait
  for in-flight, terminate), plus autoscaling-shaped policy hooks with
  counted, cause-attributed events.
- :class:`Router` (``fleet.router``) — stdlib HTTP front forwarding
  /attack to the replica with the most predicted headroom (polled
  capacity QPS minus live in-flight), bounded-budget failover on
  rejected/failed forwards, round-robin degradation without capacity, and
  fleet-aggregated /healthz + /metrics with merged SLO histograms.
- :func:`fleet_sweep` (``fleet.sweep``) — the ``bench.py --fleet``
  harness: aggregate knee QPS at 1/2/4 replicas, shared-cache warm-start
  evidence per replica, and the kill-a-replica chaos segment whose shed
  accounting proves only dead-replica in-flight requests are lost.

``tools/fleet.py`` is the operator CLI over the same pieces.
"""

from .replica import BuildMismatch, ReplicaHandle, ReplicaManager
from .router import Router, RouterHTTPServer, serve_router

__all__ = [
    "BuildMismatch",
    "ReplicaHandle",
    "ReplicaManager",
    "Router",
    "RouterHTTPServer",
    "serve_router",
]
