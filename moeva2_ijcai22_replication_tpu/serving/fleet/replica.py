"""Replica lifecycle for the fleet: spawn/adopt, poll, admit, drain, kill.

A fleet replica is one ``tools/serve.py`` process — the whole single-node
serving stack (engine caches, microbatcher, capacity model, SLO tracker)
behind its stdlib HTTP front. The :class:`ReplicaManager` owns N of them:

- **add** spawns a process with ``--port 0 --replica-id <rid>`` pointed at
  one shared config (and thereby one shared AOT + artifact cache directory
  — the PR-10 cross-process executable cache is what makes replica #N boot
  as warm as replica #1), tails its stdout for the ``fleet_ready`` JSON
  line to learn the bound port, then polls /healthz and **admits** the
  replica into the routable set only after the first healthy poll whose
  ``build`` fingerprint matches the fleet's.
- **adopt** pools an already-running replica by URL under the same
  fingerprint discipline — a replica built from a different config hash or
  package version is *refused*, never routed to: capacity numbers and
  bucket menus from mismatched builds are not comparable, and a router
  balancing across them would mix incompatible attack semantics.
- **drain** removes a replica from routing first, then waits for its
  router-observed in-flight count and its own queue depth to reach zero
  before terminating the process — in-flight requests complete, new ones
  never arrive (the state machine DESIGN.md § fleet documents).
- **kill** is SIGKILL with no grace — the chaos path. The manager marks
  the replica dead; everything it had in flight is the router's failover
  problem, and the fleet sweep's shed-accounting proof.

Polling is pull-based (/healthz into a fleet view with per-replica
freshness timestamps) so the router can discount a wedged replica's stale
capacity instead of routing into it. The autoscaling-shaped policy hooks
(:meth:`ReplicaManager.policy_tick`) watch the same view: sustained
headroom exhaustion proposes a spawn, sustained idle proposes a drain,
both surfaced as counted events with cause attribution (``observe`` mode
counts only; ``act`` mode also performs the add/drain).

Everything time- and process-shaped is injectable (``clock``, ``sleep``,
``http_get``, ``spawn_fn``) so the state machine is testable with a fake
clock and scripted health responses — no subprocesses, no sockets.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable

from ...observability.fleetrace import clock_offset

__all__ = [
    "BuildMismatch",
    "ReplicaHandle",
    "ReplicaManager",
    "default_http_get",
    "default_http_post_json",
]

#: replica lifecycle states (the admit/drain state machine)
STATES = (
    "starting",  # spawned, not yet healthy-polled
    "admitted",  # routable: healthy poll + matching build fingerprint
    "draining",  # removed from routing; waiting for in-flight to finish
    "terminated",  # drained and stopped (graceful end state)
    "dead",  # process gone without drain (chaos / crash)
    "refused",  # healthy but mismatched build fingerprint — never routed
)


class BuildMismatch(RuntimeError):
    """A replica's /healthz ``build`` fingerprint does not match the
    fleet's — pooling it would route one logical service across
    incompatible configs/versions."""


def default_http_get(url: str, timeout_s: float = 5.0) -> dict:
    """GET ``url`` and parse the JSON body (the injectable default)."""
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def default_http_post_json(
    url: str, payload: dict, timeout_s: float = 5.0
) -> dict:
    """POST ``payload`` as JSON and parse the JSON reply — the manager's
    control-plane POST (flight-dump harvest), injectable like http_get."""
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class ReplicaHandle:
    """One replica as the manager sees it: process + URL + poll state.

    ``in_flight`` is the *router-observed* concurrent-request count (the
    router increments around each forward via
    :meth:`ReplicaManager.note_inflight`) — the live half of the routing
    signal, next to the capacity model's polled ``max_sustainable_qps``.
    """

    def __init__(self, replica_id: str, *, proc=None, url: str | None = None,
                 log_path: str | None = None, spawned_t: float | None = None,
                 log_start: int = 0):
        self.replica_id = replica_id
        self.proc = proc  #: Popen-like (None for adopted replicas)
        self.url = url
        self.log_path = log_path
        self.log_start = log_start
        self.state = "starting"
        self.in_flight = 0
        self.spawned_t = spawned_t
        self.admitted_t: float | None = None
        self.last_poll_t: float | None = None
        self.last_health: dict | None = None
        self.fingerprint: tuple | None = None
        self.poll_errors = 0
        #: the LAST poll failure, not just its count — the first question
        #: in any incident is "what did the dead replica last say?"
        self.last_poll_error: dict | None = None  # {"error", "t_wall"}
        #: router↔replica clock offset (fleetrace.clock_offset, measured
        #: against healthz ``now_wall`` at poll time) — what the fleet
        #: trace merge uses to align this replica's Perfetto track
        self.clock_offset_s: float | None = None
        self.clock_rtt_s: float | None = None
        #: harvested flight-dump summary ({"path", "entries", ...}) from
        #: the pre-kill POST /debug/flight — the chaos black box
        self.flight_dump: dict | None = None

    # -- derived views -------------------------------------------------------
    def capacity_qps(self) -> float | None:
        """Fleet-summed ``max_sustainable_qps`` from the last healthy poll
        (None when no capacity window is live yet)."""
        health = self.last_health or {}
        by_domain = (health.get("capacity") or {}).get("by_domain") or {}
        vals = [
            b.get("max_sustainable_qps")
            for b in by_domain.values()
            if b and b.get("max_sustainable_qps")
        ]
        return float(sum(vals)) if vals else None

    def capacity_age_s(self) -> float | None:
        """Staleness of the capacity window itself (max ``age_s`` across
        domains) — distinct from poll staleness: a healthy replica serving
        no traffic keeps publishing an aging window."""
        health = self.last_health or {}
        by_domain = (health.get("capacity") or {}).get("by_domain") or {}
        ages = [
            b.get("age_s")
            for b in by_domain.values()
            if b and b.get("age_s") is not None
        ]
        return float(max(ages)) if ages else None

    def headroom(self) -> float | None:
        """Min per-domain capacity headroom from the last poll."""
        health = self.last_health or {}
        by_domain = (health.get("capacity") or {}).get("by_domain") or {}
        vals = [
            b.get("headroom")
            for b in by_domain.values()
            if b and b.get("headroom") is not None
        ]
        return float(min(vals)) if vals else None

    def view(self, now: float | None = None) -> dict:
        """This replica's row in the fleet view."""
        prewarm = (self.last_health or {}).get("prewarm")
        return {
            "replica_id": self.replica_id,
            "state": self.state,
            "url": self.url,
            "pid": getattr(self.proc, "pid", None),
            "in_flight": self.in_flight,
            "poll_age_s": (
                round(now - self.last_poll_t, 3)
                if now is not None and self.last_poll_t is not None
                else None
            ),
            "poll_errors": self.poll_errors,
            "last_poll_error": self.last_poll_error,
            "clock_offset_s": self.clock_offset_s,
            "capacity_qps": self.capacity_qps(),
            "capacity_age_s": self.capacity_age_s(),
            "headroom": self.headroom(),
            "queue_depth_rows": (self.last_health or {}).get(
                "queue_depth_rows"
            ),
            "build": {
                "version": self.fingerprint[0] if self.fingerprint else None,
                "config_hash": self.fingerprint[1] if self.fingerprint else None,
            },
            "prewarm": prewarm,
        }


def _fingerprint(health: dict) -> tuple:
    """The poolability fingerprint from a /healthz payload: package
    version + config hash. Deliberately NOT ``git`` (two processes from
    one checkout share it trivially) and NOT ``replica_id`` (ids differ by
    construction)."""
    build = health.get("build") or {}
    return (build.get("version"), build.get("config_hash"))


class ReplicaManager:
    """Own N serve.py replicas over one shared config + cache directory.

    All process/network/time effects are injectable:

    - ``spawn_fn(replica_id) -> ReplicaHandle`` replaces the subprocess
      spawn (tests return scripted handles);
    - ``http_get(url) -> dict`` replaces urllib (tests script /healthz);
    - ``clock`` / ``sleep`` replace time (fake-clock admit/drain tests).
    """

    def __init__(
        self,
        config_path: str | None = None,
        *,
        spawn_fn: Callable[[str], ReplicaHandle] | None = None,
        http_get: Callable[[str], dict] = default_http_get,
        http_post: Callable[[str, dict], dict] = default_http_post_json,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        wall: Callable[[], float] = time.time,
        log_dir: str | None = None,
        python: str = sys.executable,
        prewarm: bool = True,
        env: dict | None = None,
        boot_timeout_s: float = 600.0,
        poll_timeout_s: float = 60.0,
        expected_build: tuple | None = None,
        autoscale: dict | None = None,
    ):
        self.config_path = config_path
        self.spawn_fn = spawn_fn
        self.http_get = http_get
        self.http_post = http_post
        self.clock = clock
        self.sleep = sleep
        # wall is the SHARED epoch clock for the clock-offset handshake
        # (manager.clock is often a fake monotonic in tests — offsets
        # must not mix the two domains)
        self.wall = wall
        self.log_dir = log_dir
        self.python = python
        self.prewarm = prewarm
        self.env = env
        self.boot_timeout_s = float(boot_timeout_s)
        self.poll_timeout_s = float(poll_timeout_s)
        #: the fleet's build fingerprint: fixed up front, or learned from
        #: the first admitted replica — every later admit must match
        self.expected_build = expected_build
        self._replicas: dict[str, ReplicaHandle] = {}
        self._lock = threading.Lock()
        self._next_id = 0
        # -- autoscaling-shaped policy (observe-only by default) --------------
        pol = dict(autoscale or {})
        self.autoscale = {
            "enabled": bool(pol.get("enabled", False)),
            # observe: count events only; act: also perform the add/drain
            "mode": pol.get("mode", "observe"),
            "headroom_exhausted_below": float(
                pol.get("headroom_exhausted_below", 0.10)
            ),
            "idle_utilization_below": float(
                pol.get("idle_utilization_below", 0.05)
            ),
            "sustain_s": float(pol.get("sustain_s", 10.0)),
            "min_replicas": int(pol.get("min_replicas", 1)),
            "max_replicas": int(pol.get("max_replicas", 8)),
        }
        #: counted policy events with cause attribution
        self.events: list[dict] = []
        self.event_counts: dict[str, int] = {}
        self._exhausted_since: float | None = None
        self._idle_since: float | None = None

    # -- identity ------------------------------------------------------------
    def _new_id(self) -> str:
        with self._lock:
            self._next_id += 1
            return f"r{self._next_id:02d}"

    def replicas(self) -> list[ReplicaHandle]:
        with self._lock:
            return list(self._replicas.values())

    def get(self, replica_id: str) -> ReplicaHandle:
        with self._lock:
            return self._replicas[replica_id]

    def routable(self) -> list[ReplicaHandle]:
        """Replicas the router may forward to — admitted only. Draining,
        dead, refused and still-starting replicas take no new traffic."""
        with self._lock:
            return [
                h for h in self._replicas.values() if h.state == "admitted"
            ]

    # -- spawn ---------------------------------------------------------------
    def _default_spawn(self, replica_id: str) -> ReplicaHandle:
        """Spawn ``tools/serve.py -c <config> --port 0 --replica-id <rid>``
        with stdout tailed to a per-replica log file (the ``fleet_ready``
        line is read back from it)."""
        if not self.config_path:
            raise ValueError("ReplicaManager needs config_path to spawn")
        serve_py = os.path.join(
            os.path.dirname(
                os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
            ),
            "tools",
            "serve.py",
        )
        log_dir = self.log_dir or os.path.join(
            os.path.dirname(os.path.abspath(self.config_path)), "fleet_logs"
        )
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"{replica_id}.log")
        cmd = [
            self.python,
            serve_py,
            "-c",
            self.config_path,
            "--port",
            "0",
            "--replica-id",
            replica_id,
        ]
        if self.prewarm:
            cmd.append("--prewarm")
        # logs append across runs, so remember where THIS process's output
        # starts — a stale fleet_ready line from a previous run must never
        # win the port discovery below
        log_start = os.path.getsize(log_path) if os.path.exists(log_path) else 0
        logf = open(log_path, "ab")  # noqa: SIM115 — lifetime is the proc's
        proc = subprocess.Popen(  # noqa: S603 — our own tools/serve.py
            cmd,
            stdout=logf,
            stderr=subprocess.STDOUT,
            env=self.env,
        )
        return ReplicaHandle(
            replica_id,
            proc=proc,
            log_path=log_path,
            spawned_t=self.clock(),
            log_start=log_start,
        )

    def _wait_ready(self, handle: ReplicaHandle) -> None:
        """Tail the replica's log for its ``fleet_ready`` JSON line (the
        bound URL under ``--port 0``)."""
        if handle.url:
            return
        deadline = self.clock() + self.boot_timeout_s
        while self.clock() < deadline:
            if handle.proc is not None and handle.proc.poll() is not None:
                handle.state = "dead"
                raise RuntimeError(
                    f"replica {handle.replica_id} exited rc="
                    f"{handle.proc.returncode} before ready "
                    f"(log: {handle.log_path})"
                )
            if handle.log_path and os.path.exists(handle.log_path):
                with open(handle.log_path, "rb") as f:
                    f.seek(getattr(handle, "log_start", 0))
                    for raw in f:
                        line = raw.strip()
                        if not line.startswith(b'{"fleet_ready"'):
                            continue
                        try:
                            ready = json.loads(line)["fleet_ready"]
                        except (ValueError, KeyError):
                            continue
                        handle.url = ready["url"]
                        return
            self.sleep(0.2)
        raise TimeoutError(
            f"replica {handle.replica_id} not ready within "
            f"{self.boot_timeout_s}s (log: {handle.log_path})"
        )

    # -- admit / adopt -------------------------------------------------------
    def _admit(self, handle: ReplicaHandle) -> ReplicaHandle:
        """Poll /healthz until the first healthy response; verify the
        build fingerprint; admit or refuse. The routable set only ever
        grows through here."""
        deadline = self.clock() + self.poll_timeout_s
        last_err: Exception | None = None
        while self.clock() < deadline:
            try:
                health = self.http_get(handle.url + "/healthz")
            except Exception as e:  # noqa: BLE001 — booting replica
                last_err = e
                handle.poll_errors += 1
                handle.last_poll_error = {
                    "error": repr(e),
                    "t_wall": round(self.wall(), 3),
                }
                if handle.proc is not None and handle.proc.poll() is not None:
                    handle.state = "dead"
                    raise RuntimeError(
                        f"replica {handle.replica_id} exited rc="
                        f"{handle.proc.returncode} during admission"
                    ) from e
                self.sleep(0.2)
                continue
            if not health.get("ok"):
                self.sleep(0.2)
                continue
            handle.last_poll_t = self.clock()
            handle.last_health = health
            handle.fingerprint = _fingerprint(health)
            if self.expected_build is None:
                # first admitted replica defines the fleet's build
                self.expected_build = handle.fingerprint
            elif handle.fingerprint != tuple(self.expected_build):
                handle.state = "refused"
                self._terminate(handle)
                raise BuildMismatch(
                    f"replica {handle.replica_id} build "
                    f"{handle.fingerprint} != fleet "
                    f"{tuple(self.expected_build)} — refused"
                )
            handle.state = "admitted"
            handle.admitted_t = self.clock()
            return handle
        raise TimeoutError(
            f"replica {handle.replica_id} never became healthy within "
            f"{self.poll_timeout_s}s (last error: {last_err!r})"
        )

    def add(self, replica_id: str | None = None) -> ReplicaHandle:
        """Spawn + wait ready + admit-after-first-healthy-poll."""
        rid = replica_id or self._new_id()
        spawn = self.spawn_fn or self._default_spawn
        handle = spawn(rid)
        with self._lock:
            self._replicas[rid] = handle
        try:
            self._wait_ready(handle)
            self._admit(handle)
        except BuildMismatch:
            raise
        except Exception:
            if handle.state == "starting":
                handle.state = "dead"
            self._terminate(handle)
            raise
        return handle

    def adopt(self, url: str, replica_id: str | None = None) -> ReplicaHandle:
        """Pool an already-running replica by URL (no process handle —
        drain stops routing and waits, but cannot terminate it)."""
        rid = replica_id or self._new_id()
        handle = ReplicaHandle(rid, url=url, spawned_t=self.clock())
        with self._lock:
            self._replicas[rid] = handle
        self._admit(handle)
        return handle

    # -- polling / fleet view ------------------------------------------------
    def poll(self) -> dict:
        """One poll round over every live replica; returns the fleet view."""
        now = self.clock()
        for handle in self.replicas():
            if handle.state not in ("admitted", "draining"):
                continue
            t_send = self.wall()
            try:
                health = self.http_get(handle.url + "/healthz")
            except Exception as e:  # noqa: BLE001 — poll failure is a state
                handle.poll_errors += 1
                handle.last_poll_error = {
                    "error": repr(e),
                    "t_wall": round(self.wall(), 3),
                }
                if handle.proc is not None and handle.proc.poll() is not None:
                    handle.state = "dead"
                continue
            t_recv = self.wall()
            if health.get("ok"):
                handle.last_poll_t = now
                handle.last_health = health
                # clock-offset handshake: the replica stamps its own wall
                # clock (``now_wall``) into every healthz; the NTP midpoint
                # rule against our send/recv wall times gives the offset the
                # fleet trace merge uses to align this replica's track
                remote_wall = health.get("now_wall")
                if remote_wall is not None:
                    off = clock_offset(t_send, t_recv, float(remote_wall))
                    handle.clock_offset_s = off["offset_s"]
                    handle.clock_rtt_s = off["rtt_s"]
        return self.fleet_view()

    def fleet_view(self) -> dict:
        now = self.clock()
        replicas = [h.view(now) for h in self.replicas()]
        by_state: dict[str, int] = {}
        for r in replicas:
            by_state[r["state"]] = by_state.get(r["state"], 0) + 1
        qps = [
            r["capacity_qps"]
            for r in replicas
            if r["state"] == "admitted" and r["capacity_qps"]
        ]
        return {
            "replicas": replicas,
            "by_state": by_state,
            "routable": by_state.get("admitted", 0),
            "fleet_capacity_qps": round(sum(qps), 2) if qps else None,
            "expected_build": (
                list(self.expected_build) if self.expected_build else None
            ),
            "policy": {
                "autoscale": self.autoscale,
                "event_counts": dict(self.event_counts),
                "events": self.events[-16:],
            },
        }

    def note_inflight(self, replica_id: str, delta: int) -> None:
        """Router bookkeeping: +1 before a forward, -1 after it resolves."""
        with self._lock:
            handle = self._replicas.get(replica_id)
            if handle is not None:
                handle.in_flight = max(handle.in_flight + delta, 0)

    # -- drain / kill --------------------------------------------------------
    def _terminate(self, handle: ReplicaHandle) -> None:
        proc = handle.proc
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except Exception:  # noqa: BLE001 — escalate to SIGKILL
            proc.kill()
            proc.wait(timeout=15)

    def drain(self, replica_id: str, timeout_s: float = 60.0) -> dict:
        """Graceful removal: stop routing first (state ``draining``), wait
        for router-observed in-flight AND the replica's own queue depth to
        reach zero, then terminate. Returns a drain report."""
        handle = self.get(replica_id)
        if handle.state not in ("admitted", "draining"):
            raise ValueError(
                f"cannot drain replica {replica_id} in state {handle.state}"
            )
        handle.state = "draining"
        t0 = self.clock()
        deadline = t0 + timeout_s
        drained = False
        while self.clock() < deadline:
            depth = None
            if handle.in_flight == 0:
                try:
                    health = self.http_get(handle.url + "/healthz")
                    depth = health.get("queue_depth_rows")
                except Exception:  # noqa: BLE001 — gone early = drained
                    depth = 0
                if not depth:
                    drained = True
                    break
            self.sleep(0.1)
        self._terminate(handle)
        handle.state = "terminated"
        return {
            "replica_id": replica_id,
            "drained_clean": drained,
            "drain_s": round(self.clock() - t0, 3),
        }

    def kill(self, replica_id: str) -> dict:
        """SIGKILL, no grace — the chaos path. In-flight requests on this
        replica die with it; the fleet sweep's shed accounting proves the
        router loses nothing else.

        Before the signal, the manager harvests the replica's black box
        (best-effort ``POST /debug/flight``): SIGKILL leaves no moment to
        dump, so the flight recorder's last complete journeys + the
        in-flight batch view are captured from outside, one RPC ahead of
        the kill. A replica too wedged to answer yields ``flight: None`` —
        the accounting then says so instead of pretending."""
        handle = self.get(replica_id)
        in_flight = handle.in_flight
        flight = None
        if handle.url:
            try:
                flight = self.http_post(
                    handle.url + "/debug/flight",
                    {"reason": f"chaos_kill_{replica_id}"},
                )
            except Exception:  # noqa: BLE001 — wedged replica: no dump
                flight = None
        handle.flight_dump = flight
        if handle.proc is not None and handle.proc.poll() is None:
            handle.proc.kill()
            handle.proc.wait(timeout=15)
        handle.state = "dead"
        return {
            "replica_id": replica_id,
            "in_flight_at_kill": in_flight,
            "pid": getattr(handle.proc, "pid", None),
            "flight": flight,
        }

    def close(self) -> None:
        for handle in self.replicas():
            if handle.state in ("admitted", "draining", "starting"):
                self._terminate(handle)
                if handle.state != "starting":
                    handle.state = "terminated"

    # -- autoscaling-shaped policy --------------------------------------------
    def _event(self, kind: str, cause: str, now: float, **extra) -> dict:
        ev = {"t": round(now, 3), "kind": kind, "cause": cause, **extra}
        self.events.append(ev)
        key = f"{kind}:{cause}"
        self.event_counts[key] = self.event_counts.get(key, 0) + 1
        return ev

    def policy_tick(self, now: float | None = None) -> list[dict]:
        """One policy evaluation over the current fleet view: sustained
        headroom exhaustion proposes a spawn, sustained idle proposes a
        drain. ``observe`` mode counts the events; ``act`` mode also
        performs them. Returns the events this tick emitted."""
        if not self.autoscale["enabled"]:
            return []
        now = self.clock() if now is None else now
        routable = self.routable()
        emitted: list[dict] = []
        headrooms = [
            h.headroom() for h in routable if h.headroom() is not None
        ]
        # -- scale up: every routable replica's headroom exhausted ----------
        exhausted = bool(headrooms) and all(
            hr < self.autoscale["headroom_exhausted_below"] for hr in headrooms
        )
        if exhausted:
            if self._exhausted_since is None:
                self._exhausted_since = now
            sustained = now - self._exhausted_since
            if (
                sustained >= self.autoscale["sustain_s"]
                and len(routable) < self.autoscale["max_replicas"]
            ):
                ev = self._event(
                    "scale_up",
                    "headroom_exhausted",
                    now,
                    sustained_s=round(sustained, 3),
                    replicas=len(routable),
                    acted=self.autoscale["mode"] == "act",
                )
                emitted.append(ev)
                self._exhausted_since = None  # one event per sustain window
                if self.autoscale["mode"] == "act":
                    self.add()
        else:
            self._exhausted_since = None
        # -- scale down: sustained idle across the fleet --------------------
        utils_ = [
            1.0 - h.headroom()
            for h in routable
            if h.headroom() is not None
        ]
        idle = bool(utils_) and all(
            u < self.autoscale["idle_utilization_below"] for u in utils_
        )
        if idle:
            if self._idle_since is None:
                self._idle_since = now
            sustained = now - self._idle_since
            if (
                sustained >= self.autoscale["sustain_s"]
                and len(routable) > self.autoscale["min_replicas"]
            ):
                victim = min(routable, key=lambda h: h.in_flight)
                ev = self._event(
                    "scale_down",
                    "sustained_idle",
                    now,
                    sustained_s=round(sustained, 3),
                    replicas=len(routable),
                    victim=victim.replica_id,
                    acted=self.autoscale["mode"] == "act",
                )
                emitted.append(ev)
                self._idle_since = None
                if self.autoscale["mode"] == "act":
                    self.drain(victim.replica_id)
        else:
            self._idle_since = None
        return emitted
