"""Capacity-driven HTTP router over a :class:`~.replica.ReplicaManager`.

The routing signal is **predicted headroom**: for each admitted replica,
the capacity model's fleet-summed ``max_sustainable_qps`` from its last
healthy poll, minus the router-observed in-flight request count — the
polled half says what the replica *can* absorb, the live half says what it
is *already* absorbing. The score is a ranking signal, not a unit-honest
rate (QPS minus a count), which is exactly what a router needs: replicas
with equal polled capacity order by live load, replicas with equal load
order by capacity.

Freshness discipline: a replica's capacity block is trusted only when (a)
its last healthy poll is within ``stale_after_s`` AND (b) the block's own
``age_s`` (seconds since the capacity window's last batch — the satellite
field ``observability/capacity.py`` publishes) is within
``capacity_age_max_s``. Stale or absent capacity degrades that replica to
the round-robin tail of the candidate order rather than excluding it —
a fleet that has served no traffic yet (no capacity windows anywhere)
routes pure round-robin.

Failover: rejected (429) and failed (5xx, connection-refused/reset)
forwards retry on the next-best replica with a bounded budget
(``retry_budget`` retries after the first attempt). 400/413 are the
client's problem and 504 means the request's deadline budget is already
spent — none of those retry (a 504 retry would double-spend the deadline
against a second replica). Every failover is a counted event with cause
attribution; exhausting the budget returns the last upstream error (the
final 429's honest ``Retry-After`` flows through) and counts a shed.

The router serves fleet-aggregated ``/healthz`` (fleet view + per-replica
health blocks + router counters) and ``/metrics`` (per-replica metric
snapshots + **merged SLO histograms** via
:func:`~...observability.slo.merge_slo_snapshots` — the fixed cumulative
bucket layout was designed mergeable-cumulative in PR 7 for exactly this
sum). ``/metrics?format=prom`` renders the merged SLO family and router
counters as Prometheus text.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlsplit

from ...observability import Trace, incidents_block, maybe_span
from ...observability.fleetrace import (
    TRACE_HEADER,
    format_trace_context,
    parse_trace_context,
)
from ...observability.prom import _family, _fmt, _name, _slo_lines
from ...observability.slo import merge_slo_snapshots
from .replica import ReplicaHandle, ReplicaManager

__all__ = [
    "Router",
    "RouterHTTPServer",
    "serve_router",
    "default_http_post",
    "default_http_get_raw",
]

#: upstream statuses that are safe + useful to retry on another replica
RETRYABLE_STATUSES = frozenset({429, 500, 502, 503})


def default_http_post(
    url: str,
    body: bytes,
    timeout_s: float = 120.0,
    headers: dict | None = None,
) -> tuple[int, dict, bytes]:
    """POST ``body`` as JSON; returns ``(status, headers, body)`` without
    raising on HTTP error statuses (the router maps them itself).
    Connection-level failures still raise (``URLError``/``OSError``) —
    that distinction is the router's "failed" vs "rejected" cause split.
    ``headers`` adds/overrides request headers (the router forwards
    ``X-Qos-Class`` through it)."""
    req = urllib.request.Request(
        url,
        data=body,
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


def default_http_get_raw(
    url: str, timeout_s: float = 30.0
) -> tuple[int, dict, bytes]:
    """GET returning ``(status, headers, body)`` without raising on HTTP
    error statuses — the stream-poll forwarder needs the raw 404 to probe
    for the replica holding a stream."""
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return resp.status, dict(resp.headers), resp.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers or {}), e.read()


class Router:
    """Forward /attack to the replica with the most predicted headroom."""

    def __init__(
        self,
        manager: ReplicaManager,
        *,
        retry_budget: int = 2,
        stale_after_s: float = 10.0,
        capacity_age_max_s: float = 30.0,
        request_timeout_s: float = 120.0,
        http_post: Callable[..., tuple[int, dict, bytes]] = default_http_post,
        http_get_raw: Callable[..., tuple[int, dict, bytes]] = default_http_get_raw,
        clock: Callable[[], float] = time.monotonic,
        recorder=None,
        incidents=None,
    ):
        self.manager = manager
        self.http_get_raw = http_get_raw
        #: router-side trace recorder (``observability.TraceRecorder`` with
        #: a sink, or None): when its spans are enabled every routed
        #: request gets rank/attempt/failover spans under the SAME trace
        #: id the replica adopts — the router half of the merged fleet
        #: trace. None/off = zero trace work, the capture contract.
        self.recorder = recorder
        #: fleet-level incident detector (``observability.
        #: IncidentDetector`` or None) — surfaced on fleet /healthz so an
        #: operator polling the router sees open incidents with frozen
        #: evidence without walking per-replica endpoints
        self.incidents = incidents
        self.retry_budget = int(retry_budget)
        self.stale_after_s = float(stale_after_s)
        self.capacity_age_max_s = float(capacity_age_max_s)
        self.request_timeout_s = float(request_timeout_s)
        self.http_post = http_post
        self.clock = clock
        self._lock = threading.Lock()
        self._rr = 0  #: round-robin cursor for the capacity-less tail
        self.counters: dict[str, int] = {
            "forwards": 0,
            "retries": 0,
            "shed_no_replica": 0,
            "shed_budget_exhausted": 0,
        }

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self.counters[key] = self.counters.get(key, 0) + n

    # -- candidate ordering ---------------------------------------------------
    def _fresh_capacity(self, handle: ReplicaHandle, now: float) -> float | None:
        """The replica's trusted capacity QPS, or None when the poll or the
        capacity window itself is stale/absent."""
        if (
            handle.last_poll_t is None
            or now - handle.last_poll_t > self.stale_after_s
        ):
            return None
        qps = handle.capacity_qps()
        if qps is None:
            return None
        age = handle.capacity_age_s()
        if age is not None and age > self.capacity_age_max_s:
            return None
        return qps

    def candidates(self, now: float | None = None) -> list[ReplicaHandle]:
        """Routable replicas in forward order: fresh-capacity replicas
        ranked by predicted headroom (capacity QPS − in-flight), then the
        capacity-less remainder in round-robin order."""
        now = self.clock() if now is None else now
        routable = self.manager.routable()
        scored: list[tuple[float, ReplicaHandle]] = []
        tail: list[ReplicaHandle] = []
        for h in routable:
            qps = self._fresh_capacity(h, now)
            if qps is None:
                tail.append(h)
            else:
                scored.append((qps - h.in_flight, h))
        scored.sort(key=lambda sh: sh[0], reverse=True)
        if tail:
            with self._lock:
                self._rr += 1
                rot = self._rr % len(tail)
            tail = tail[rot:] + tail[:rot]
        return [h for _, h in scored] + tail

    # -- forwarding -----------------------------------------------------------
    def route(
        self,
        body: bytes,
        *,
        path: str = "/attack",
        req_headers: dict | None = None,
        trace_context: dict | None = None,
    ) -> tuple[int, dict, bytes]:
        """Forward one /attack body; returns ``(status, headers, body)``.
        Headers include ``X-Served-By`` (the replica that produced the
        returned response) and ``X-Fleet-Attempts``. ``path`` carries the
        client's full path+query (``/attack?stream=poll`` reaches the
        replica intact; a ``stream=1`` chunked reply is buffered by the
        forwarder and delivered whole — poll mode is the streaming path
        that stays incremental through the router). ``req_headers``
        forwards end-to-end request headers — the QoS class rides
        ``X-Qos-Class`` so per-class accounting on the replica matches
        what the client asked the fleet for.

        Distributed tracing: EVERY forwarded attempt (first try and
        failovers alike) is stamped with ``X-Moeva2-Trace`` — one trace
        id per routed request (adopted from ``trace_context`` when an
        upstream hop minted it), the attempt span's id as the remote
        parent, and an incremented hop count — so the replica's
        validate→queue→batch→device tree composes under the router's
        attempt span in a merged fleet document. Successful responses
        additionally gain ``meta.route``: the per-attempt
        ``(replica, status, cause, elapsed_s)`` detail, hop count, and
        trace id."""
        ctx = trace_context or {}
        hop_in = int(ctx.get("hop") or 0)
        trace_id = ctx.get("trace_id") or f"fleet-{uuid.uuid4().hex[:12]}"
        rt = (
            Trace(
                self.recorder,
                trace_id=trace_id,
                name="fleet-route",
                root_parent=ctx.get("parent_span"),
            )
            if self.recorder is not None and self.recorder.spans_enabled
            else None
        )
        order = self.candidates()
        if rt is not None:
            rt.event(
                "rank", candidates=[h.replica_id for h in order[:8]]
            )
        if not order:
            self._count("shed_no_replica")
            return (
                503,
                {"X-Fleet-Attempts": "0"},
                json.dumps({"error": "no routable replica"}).encode(),
            )
        attempts = 0
        detail: list[dict] = []
        last: tuple[int, dict, bytes] | None = None
        last_rid = None
        for handle in order[: self.retry_budget + 1]:
            attempts += 1
            if attempts > 1:
                self._count("retries")
            self.manager.note_inflight(handle.replica_id, +1)
            t_att = self.clock()
            with maybe_span(
                rt, "attempt", replica=handle.replica_id, n=attempts
            ) as sid:
                hdrs = dict(req_headers or {})
                hdrs[TRACE_HEADER] = format_trace_context(
                    trace_id, parent_span=sid, hop=hop_in + 1
                )
                try:
                    status, headers, resp_body = self.http_post(
                        handle.url + path,
                        body,
                        timeout_s=self.request_timeout_s,
                        headers=hdrs,
                    )
                except Exception:  # noqa: BLE001 — connection-level failure
                    # dead/unreachable replica: the chaos path. Count the
                    # cause and try the next-best candidate
                    self._count(f"failover_connection:{handle.replica_id}")
                    self._count("failover_connection_total")
                    detail.append(
                        {
                            "replica": handle.replica_id,
                            "status": None,
                            "cause": "connection",
                            "elapsed_s": round(self.clock() - t_att, 6),
                        }
                    )
                    if rt is not None:
                        rt.event(
                            "failover",
                            cause="connection",
                            replica=handle.replica_id,
                        )
                    last = (
                        502,
                        {},
                        json.dumps(
                            {
                                "error": "replica connection failed",
                                "replica_id": handle.replica_id,
                            }
                        ).encode(),
                    )
                    last_rid = handle.replica_id
                    continue
                finally:
                    self.manager.note_inflight(handle.replica_id, -1)
                last = (status, headers, resp_body)
                last_rid = handle.replica_id
                if status in RETRYABLE_STATUSES:
                    cause = "rejected" if status == 429 else "failed"
                    self._count(f"failover_{cause}:{handle.replica_id}")
                    self._count(f"failover_{cause}_total")
                    detail.append(
                        {
                            "replica": handle.replica_id,
                            "status": int(status),
                            "cause": cause,
                            "elapsed_s": round(self.clock() - t_att, 6),
                        }
                    )
                    if rt is not None:
                        rt.event(
                            "failover",
                            cause=cause,
                            status=int(status),
                            replica=handle.replica_id,
                        )
                    continue
                # success, or a non-retryable client/deadline error: done
                detail.append(
                    {
                        "replica": handle.replica_id,
                        "status": int(status),
                        "cause": "served" if status < 400 else "terminal",
                        "elapsed_s": round(self.clock() - t_att, 6),
                    }
                )
                self._count("forwards")
                if status < 400:
                    # per-replica served counter: the balance-drop
                    # incident predicate's input (a replica that stops
                    # pulling its share shows up here first)
                    self._count(f"served:{handle.replica_id}")
                return self._stamp(
                    self._inject_route_meta(
                        last, detail, trace_id, hop_in + 1
                    ),
                    last_rid,
                    attempts,
                )
        # budget exhausted: surface the last upstream answer honestly (a
        # final 429's Retry-After flows through to the client)
        self._count("shed_budget_exhausted")
        return self._stamp(last, last_rid, attempts)

    @staticmethod
    def _inject_route_meta(
        result: tuple[int, dict, bytes],
        detail: list[dict],
        trace_id: str,
        hops: int,
    ) -> tuple[int, dict, bytes]:
        """Rewrite a successful single-document JSON response so its
        ``meta`` carries the routing story (per-attempt detail, hop
        count, trace id). Buffered ndjson streams, 202 poll tickets, and
        error bodies pass through untouched — only a 200 whose body is a
        dict with a ``meta`` dict is rewritten."""
        status, headers, body = result
        if status != 200:
            return result
        try:
            doc = json.loads(body)
        except ValueError:
            return result
        if not (isinstance(doc, dict) and isinstance(doc.get("meta"), dict)):
            return result
        doc["meta"]["route"] = {
            "attempts": detail,
            "hops": hops,
            "trace_id": trace_id,
        }
        return status, headers, json.dumps(doc).encode()

    @staticmethod
    def _stamp(
        result: tuple[int, dict, bytes], replica_id, attempts: int
    ) -> tuple[int, dict, bytes]:
        status, headers, body = result
        out = {
            k: v
            for k, v in headers.items()
            if k.lower() in ("retry-after", "x-replica-id", "x-qos-class")
        }
        if replica_id:
            out["X-Served-By"] = str(replica_id)
        out["X-Fleet-Attempts"] = str(attempts)
        return status, out, body

    def route_poll(self, path: str) -> tuple[int, dict, bytes]:
        """Forward one ``GET /attack/<id>`` stream poll. The router keeps
        no stream-affinity table (streams live in the memory of the
        replica that ran the request), so it probes candidates in routing
        order and returns the first non-404 answer — a 404 from every
        routable replica means the stream is genuinely unknown or
        evicted."""
        order = self.candidates()
        if not order:
            self._count("shed_no_replica")
            return (
                503,
                {"X-Fleet-Attempts": "0"},
                json.dumps({"error": "no routable replica"}).encode(),
            )
        attempts = 0
        last: tuple[int, dict, bytes] | None = None
        last_rid = None
        for handle in order:
            attempts += 1
            try:
                status, headers, resp_body = self.http_get_raw(
                    handle.url + path, timeout_s=self.request_timeout_s
                )
            except Exception:  # noqa: BLE001 — dead replica: keep probing
                continue
            last = (status, headers, resp_body)
            last_rid = handle.replica_id
            if status != 404:
                return self._stamp(last, last_rid, attempts)
        if last is None:
            return (
                502,
                {"X-Fleet-Attempts": str(attempts)},
                json.dumps({"error": "all replicas unreachable"}).encode(),
            )
        return self._stamp(last, last_rid, attempts)

    # -- aggregated views -----------------------------------------------------
    def served_balance(self) -> dict | None:
        """Mean/max served-request balance across routable replicas
        (1.0 = perfectly balanced). Zero-served routable replicas count —
        a replica that silently stops pulling its share IS the signal.
        None while unprimed (< 2 routable replicas, or no served traffic
        yet) — the predicate arms itself from measurement, the same
        discipline as admission and the bench gates."""
        routable = self.manager.routable()
        if len(routable) < 2:
            return None
        with self._lock:
            served = {
                h.replica_id: int(
                    self.counters.get(f"served:{h.replica_id}", 0)
                )
                for h in routable
            }
        top = max(served.values())
        if top == 0:
            return None
        ratio = (sum(served.values()) / len(served)) / top
        return {"ratio": round(ratio, 4), "served": served}

    def healthz(self) -> dict:
        """Fleet-aggregated health: the manager's fleet view, per-replica
        health blocks (last poll), and router counters. Also the
        balance-drop incident predicate's tick point: /healthz is the
        fleet's heartbeat, so balance is re-measured exactly as often as
        an operator (or the poll loop) looks."""
        view = self.manager.fleet_view()
        balance = self.served_balance()
        if self.incidents is not None and balance is not None:
            self.incidents.tick(
                balance_ratio=balance["ratio"],
                balance_label="fleet_served",
                evidence_fn=lambda: {
                    "served": balance["served"],
                    "fleet": view,
                },
            )
        return {
            "ok": view["routable"] > 0,
            "fleet": view,
            "router": {
                "retry_budget": self.retry_budget,
                "stale_after_s": self.stale_after_s,
                "capacity_age_max_s": self.capacity_age_max_s,
                "counters": self.counters_snapshot(),
                "served_balance": balance,
            },
            # fleet-level incident attribution: open/total incidents with
            # frozen evidence, right where an operator looks first
            "incidents": incidents_block(self.incidents),
            "replicas": {
                h.replica_id: h.last_health
                for h in self.manager.replicas()
                if h.last_health is not None
            },
        }

    def counters_snapshot(self) -> dict:
        with self._lock:
            return dict(self.counters)

    def metrics(self, http_get=None) -> dict:
        """Fleet-aggregated metrics: per-replica /metrics snapshots plus
        the merged SLO histogram view (cumulative buckets sum across
        replicas; quantiles re-derived from the merged distribution)."""
        http_get = http_get or self.manager.http_get
        per_replica: dict[str, dict] = {}
        for h in self.manager.routable():
            try:
                per_replica[h.replica_id] = http_get(h.url + "/metrics")
            except Exception:  # noqa: BLE001 — a scrape miss is a gap,
                per_replica[h.replica_id] = None  # not an outage
        slo_snaps = [
            m.get("slo")
            for m in per_replica.values()
            if isinstance(m, dict) and m.get("slo")
        ]
        return {
            "router": {"counters": self.counters_snapshot()},
            "fleet": self.manager.fleet_view(),
            "slo_merged": merge_slo_snapshots(slo_snaps),
            "per_replica": per_replica,
        }

    def prometheus_text(self, prefix: str = "moeva2_fleet") -> str:
        """Prometheus exposition of the merged fleet view: the merged SLO
        histogram family (same native-histogram layout as a single
        replica's — Prometheus-side aggregation and this router-side merge
        agree by construction) plus router counters and routable gauge."""
        snap = self.metrics()
        lines: list[str] = []
        _family(lines, _name(prefix, "routable_replicas"), "gauge")
        lines.append(
            f"{_name(prefix, 'routable_replicas')} "
            f"{_fmt(snap['fleet']['routable'])}"
        )
        counters = snap["router"]["counters"]
        _family(lines, _name(prefix, "router_events_total"), "counter")
        for key in sorted(counters):
            if ":" in key:  # per-replica attributions stay JSON-side
                continue
            lines.append(
                f"{_name(prefix, 'router_events_total')}"
                f'{{event="{key}"}} {_fmt(counters[key])}'
            )
        merged = snap.get("slo_merged")
        if merged:
            _slo_lines(prefix, merged, lines)
        return "\n".join(lines) + "\n"


class RouterHTTPHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "RouterHTTPServer"

    def _send(self, code: int, body: bytes, headers: dict, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj: dict, headers: dict | None = None):
        self._send(
            code,
            json.dumps(obj).encode(),
            headers or {},
            "application/json",
        )

    def log_message(self, fmt, *args):
        if self.server.verbose:
            super().log_message(fmt, *args)

    def do_GET(self):
        router = self.server.router
        parts = urlsplit(self.path)
        if parts.path == "/healthz":
            self._send_json(200, router.healthz())
        elif parts.path == "/metrics":
            query = parse_qs(parts.query)
            if query.get("format", [""])[0] == "prom":
                self._send(
                    200,
                    router.prometheus_text().encode(),
                    {},
                    "text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._send_json(200, router.metrics())
        elif parts.path.startswith("/attack/"):
            # stream poll: probe replicas for the one holding the stream
            status, headers, resp_body = router.route_poll(self.path)
            self._send(status, resp_body, headers, "application/json")
        else:
            self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            self._send_json(400, {"error": "bad Content-Length header"})
            self.close_connection = True
            return
        body = self.rfile.read(length)
        parts = urlsplit(self.path)
        if parts.path != "/attack":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        # the priority class propagates end-to-end: body-carried classes
        # ride the body untouched; header-carried ones are forwarded
        fwd: dict = {}
        qos_class = self.headers.get("X-Qos-Class")
        if qos_class:
            fwd["X-Qos-Class"] = qos_class
        # an upstream hop (another router, a test harness) may have minted
        # the trace already — adopt it so the id survives the extra hop
        trace_ctx = parse_trace_context(self.headers.get(TRACE_HEADER))
        status, headers, resp_body = self.server.router.route(
            body, path=self.path, req_headers=fwd, trace_context=trace_ctx
        )
        self._send(status, resp_body, headers, "application/json")


class RouterHTTPServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(
        self,
        addr: tuple[str, int],
        router: Router,
        *,
        verbose: bool = False,
    ):
        super().__init__(addr, RouterHTTPHandler)
        self.router = router
        self.verbose = verbose


def serve_router(
    router: Router,
    host: str = "127.0.0.1",
    port: int = 8700,
    **kw,
) -> RouterHTTPServer:
    """Bind and return the router front (caller runs ``serve_forever``;
    port 0 picks an ephemeral port — read ``server.server_address``)."""
    return RouterHTTPServer((host, port), router, **kw)
