"""Fleet sweep: aggregate knee at 1/2/4 replicas + kill-a-replica chaos.

The ``bench.py --fleet`` harness and the FLEET record producer. Unlike
``serving.sweep`` (in-process, no network) this drives *real replica
subprocesses* over HTTP through the fleet :class:`~.router.Router` —
the router's candidate ordering, failover, and shed accounting are the
system under test, so the sweep calls :meth:`Router.route` directly with
the same open-loop pacing discipline as every other level runner
(:func:`arrival_offsets`, latency charged from the *scheduled* arrival —
the coordinated-omission rule).

Four phases, one record:

1. **Warm seed** — a throwaway replica is spawned with ``--prewarm``,
   pays every compile once into the shared AOT cache directory, and is
   drained. Every *measured* replica (including the first) then boots
   from a hot cache — the record's per-replica warm evidence
   (``aot_hits / executables`` from the /healthz prewarm report) is the
   PR-10 cross-process cache made load-bearing, and the acceptance gate
   (≥ 90 % per replica) would catch a cache-layout regression.
2. **Scaling ladder** — for each replica count (1/2/4 by default), the
   offered-rate ladder is the per-replica ladder × N: a fleet that
   scales linearly holds the same *per-replica* rate at every N. The
   per-count knee (:func:`detect_knee`, the PR-7 rule: completion ratio
   ≥ 0.9 at p99 ≤ 3× baseline) yields the scaling ratio
   ``knee(4) / (4 × knee(1))`` the ``bench_diff --fleet`` gate holds
   ≥ 0.8.
3. **Chaos** — the fleet is drained down to two replicas (exercising the
   graceful path), a level is offered at the 2-replica knee, and halfway
   through the submission schedule the busier replica is SIGKILLed with
   its router-observed in-flight count snapshotted at the kill instant.
   Accounting: every request that fails terminally must attribute to the
   dead replica (``lost_unaccounted`` must be 0), losses are bounded by
   the in-flight-at-kill count, and connection failovers ≈ the dead
   replica's interrupted in-flight set — the router lost nothing it
   didn't have to.
4. **Recovery** — the per-replica ladder re-runs on the survivor; the
   post-kill knee must recover to the (N−1)-replica (here 1-replica)
   knee within the gate's floor.

Single-host honesty: on a small shared host the per-replica knee must be
*admission-limited* (queue bound + batching delay), not device-limited —
N replicas then genuinely multiply aggregate admission capacity, which
is the property this sweep proves. ``bench.py --fleet`` configures the
replicas accordingly (see ``run_fleet_bench``).
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Sequence

from ...observability import (
    IncidentDetector,
    detect_knee,
    get_gap_tracker,
    get_ledger,
    incidents_block,
    load_flight_dump,
    telemetry_block,
    validate_record,
)
from ...utils.observability import arrival_offsets, percentile
from .replica import ReplicaManager
from .router import Router


def run_fleet_level(
    router: Router,
    make_body: Callable[[int], bytes],
    offered_rps: float,
    n_requests: int,
    *,
    timeout_s: float = 120.0,
    max_workers: int = 64,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    arrival: str = "poisson",
    seed: int = 42,
    mid_hook: Callable[[int], None] | None = None,
    detail: bool = False,
) -> dict:
    """One offered-load level through the router: submit ``n_requests``
    paced at ``offered_rps``, classify every final status, report the
    level record (same keys as ``serving.sweep.run_level`` so
    :func:`detect_knee` and the gates read both). ``mid_hook`` fires once
    just before the midpoint submission — the chaos segment's kill
    point."""
    offsets = arrival_offsets(arrival, offered_rps, n_requests, seed)
    results: list[dict] = []
    pool = ThreadPoolExecutor(max_workers=max_workers)

    def one(i: int, t_sub: float, body: bytes) -> dict:
        try:
            status, headers, resp = router.route(body)
        except Exception as e:  # noqa: BLE001 — bench counts, not raises
            return {"i": i, "status": -1, "error": repr(e), "t_sub": t_sub}
        out = {
            "i": i,
            "status": status,
            "t_sub": t_sub,
            "t_done": clock(),
            "served_by": headers.get("X-Served-By"),
            "attempts": int(headers.get("X-Fleet-Attempts", 1)),
        }
        if status == 200:
            try:
                meta = json.loads(resp).get("meta") or {}
                out["rows"] = int(meta.get("rows") or 0)
                out["occupancy"] = meta.get("batch_occupancy")
            except ValueError:
                pass
        else:
            try:
                err = json.loads(resp)
                out["error"] = err.get("error")
                out["error_replica"] = err.get("replica_id")
            except ValueError:
                out["error"] = resp[:200].decode("utf-8", "replace")
        return out

    mid = n_requests // 2
    t_start = clock()
    futs = []
    for i in range(n_requests):
        target = t_start + offsets[i]
        delta = target - clock()
        if delta > 0:
            sleep(delta)
        if mid_hook is not None and i == mid:
            mid_hook(i)
        # latency origin is the SCHEDULED arrival (coordinated-omission
        # rule shared with serving.sweep / tools/loadgen.py)
        t_sub = target if offered_rps > 0 else clock()
        futs.append(pool.submit(one, i, t_sub, make_body(i)))
    for fut in futs:
        results.append(fut.result(timeout=timeout_s))
    pool.shutdown(wait=True)
    duration = max(clock() - t_start, 1e-9)

    ok = [r for r in results if r["status"] == 200]
    latencies = sorted(r["t_done"] - r["t_sub"] for r in ok)
    occup = [r["occupancy"] for r in ok if r.get("occupancy") is not None]
    served_by: dict[str, int] = {}
    for r in ok:
        rid = r.get("served_by") or "(unknown)"
        served_by[rid] = served_by.get(rid, 0) + 1
    n_ok = len(ok)
    level = {
        "offered_rps": offered_rps,
        "arrival": arrival,
        "n_requests": n_requests,
        "completed": n_ok,
        "rejected": sum(1 for r in results if r["status"] == 429),
        "deadline_timeouts": sum(1 for r in results if r["status"] == 504),
        "failed": sum(
            1 for r in results if r["status"] not in (200, 429, 504)
        ),
        "retried": sum(1 for r in results if r.get("attempts", 1) > 1),
        "duration_s": round(duration, 3),
        "throughput_rps": round(n_ok / duration, 2),
        "throughput_rows_s": round(
            sum(r.get("rows", 0) for r in ok) / duration, 1
        ),
        "completion_ratio": round(n_ok / n_requests, 4) if n_requests else None,
        "p50_ms": round(percentile(latencies, 0.50) * 1e3, 2) if n_ok else None,
        "p99_ms": round(percentile(latencies, 0.99) * 1e3, 2) if n_ok else None,
        "quantiles_n": n_ok,
        "mean_batch_occupancy": (
            round(sum(occup) / len(occup), 4) if occup else None
        ),
        "served_by": served_by,
    }
    if detail:
        level["requests"] = results
    return level


def _warm_evidence(manager: ReplicaManager, exclude=()) -> dict:
    """Per-replica AOT warm-start evidence from the /healthz prewarm
    reports: how much of each measured replica's boot came out of the
    shared serialized-executable cache."""
    per_replica: dict[str, dict] = {}
    for h in manager.replicas():
        if h.replica_id in exclude or h.last_health is None:
            continue
        pre = h.last_health.get("prewarm") or {}
        executables = int(pre.get("executables") or 0)
        aot_hits = int(pre.get("aot_hits") or 0)
        per_replica[h.replica_id] = {
            "executables": executables,
            "aot_hits": aot_hits,
            "prewarm_s": pre.get("seconds"),
            "warm_fraction": (
                round(aot_hits / executables, 4) if executables else None
            ),
        }
    fracs = [
        v["warm_fraction"]
        for v in per_replica.values()
        if v["warm_fraction"] is not None
    ]
    return {
        "per_replica": per_replica,
        "min_warm_fraction": min(fracs) if fracs else None,
    }


def _attribute_losses(harvest: dict | None, lost_ids: list[str]) -> dict:
    """Join the chaos level's lost request ids against the flight dump
    harvested from the victim just before SIGKILL: every lost row should
    name the exact place it died — riding the batch that was ON the
    device (``dispatching``, with its batch seq), waiting in the queue
    (``queued``), or already completed on the replica with the response
    lost on the wire (``completed``, in the flight ring). Ids the dump
    never saw stay ``untracked`` — the gate's honesty bound."""
    block: dict = {
        "harvested": bool(harvest and harvest.get("path")),
        "dump": harvest,
        "lost_rows": len(lost_ids),
    }
    dump = load_flight_dump(harvest["path"]) if block["harvested"] else None
    if dump is None:
        block["harvested"] = False
        block["attribution"] = None
        return block
    inflight = (dump.get("extra") or {}).get("inflight") or {}
    where: dict[str, dict] = {}
    disp = inflight.get("dispatching")
    if disp:
        for req in disp.get("requests") or []:
            rid = req.get("request_id")
            if rid:
                where[rid] = {
                    "where": "dispatching",
                    "batch_seq": disp.get("batch_seq"),
                    "bucket": disp.get("bucket"),
                }
    for req in inflight.get("queued") or []:
        rid = req.get("request_id")
        if rid:
            where.setdefault(rid, {"where": "queued", "batch_seq": None})
    for entry in dump.get("entries") or []:
        rid = entry.get("request_id")
        if rid:
            where.setdefault(
                rid,
                {
                    "where": "completed",
                    "batch_seq": entry.get("batch_seq"),
                },
            )
    attribution = {rid: where.get(rid) for rid in lost_ids}
    untracked = sorted(r for r, w in attribution.items() if w is None)
    by_where: dict[str, int] = {}
    for w in attribution.values():
        if w is not None:
            by_where[w["where"]] = by_where.get(w["where"], 0) + 1
    block["attribution"] = {
        "by_request": attribution,
        "by_where": by_where,
        "attributed": len(lost_ids) - len(untracked),
        "untracked": untracked,
        "dispatching_batch_seq": disp.get("batch_seq") if disp else None,
    }
    return block


def fleet_sweep(
    config_path: str,
    make_body: Callable[[int], bytes],
    *,
    counts: Sequence[int] = (1, 2, 4),
    per_replica_rates: Sequence[float] = (8.0, 13.0, 18.0, 25.0),
    n_requests: int = 80,
    chaos: bool = True,
    timeout_s: float = 120.0,
    arrival: str = "poisson",
    seed: int = 42,
    manager_kw: dict | None = None,
    router_kw: dict | None = None,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """Run the full fleet proof; returns the validated FLEET record."""
    ledger_mark = get_ledger().mark()
    gaps_mark = get_gap_tracker().mark()
    manager = ReplicaManager(config_path, **(manager_kw or {}))
    # the sweep measures AGGREGATE admission capacity, so its failover
    # budget must be able to reach every replica: capacity scores are
    # frozen between polls, arrivals concentrate on the top-scored
    # replica, and with a smaller budget a rejected request can exhaust
    # its retries while a further-down replica still has queue room —
    # the measured knee would then reflect the budget, not the fleet
    router_kw = dict(router_kw or {})
    router_kw.setdefault("retry_budget", max(int(c) for c in counts) - 1)
    # fleet-level incident detector: the chaos kill opens a replica_dead
    # incident here with the harvested flight dump frozen as evidence,
    # and the router surfaces the same detector on fleet /healthz
    incidents = router_kw.get("incidents") or IncidentDetector(clock=clock)
    router_kw.setdefault("incidents", incidents)
    router = Router(manager, **router_kw)
    level_kw = dict(
        timeout_s=timeout_s,
        arrival=arrival,
        clock=clock,
        sleep=sleep,
    )
    try:
        # -- phase 1: seed the shared AOT cache -------------------------------
        seed_handle = manager.add("warmseed")
        warmseed = {
            "prewarm": (seed_handle.last_health or {}).get("prewarm"),
            "drain": manager.drain("warmseed"),
        }

        # -- phase 2: scaling ladder ------------------------------------------
        stages = []
        knee_by_count: dict[int, float | None] = {}
        for count in counts:
            while len(manager.routable()) < count:
                manager.add()
            manager.poll()
            levels = []
            for li, rate in enumerate(per_replica_rates):
                levels.append(
                    run_fleet_level(
                        router,
                        make_body,
                        float(rate) * count,
                        n_requests * count,
                        seed=seed + li,
                        **level_kw,
                    )
                )
                manager.poll()  # refresh capacity between levels
            knee = detect_knee(levels)
            knee_by_count[count] = knee["knee_rps"]
            stages.append(
                {
                    "replicas": count,
                    "levels": levels,
                    "knee": knee,
                    "fleet": manager.fleet_view(),
                }
            )
        n_lo, n_hi = min(counts), max(counts)
        knee_lo, knee_hi = knee_by_count.get(n_lo), knee_by_count.get(n_hi)
        scaling = {
            "knee_by_replicas": {str(k): v for k, v in knee_by_count.items()},
            # the acceptance ratio: knee(N_hi) over linear extrapolation
            # of knee(N_lo) — 1.0 is perfectly linear scale-out
            "linear_ratio": (
                round(knee_hi / (knee_lo * (n_hi / n_lo)), 4)
                if knee_lo and knee_hi
                else None
            ),
            "from_replicas": n_lo,
            "to_replicas": n_hi,
        }
        warm = _warm_evidence(manager, exclude=("warmseed",))

        # -- phase 3 + 4: chaos + recovery ------------------------------------
        chaos_block = None
        if chaos and len(manager.routable()) >= 2:
            # drain down to two replicas — the graceful path, on record
            drains = []
            victims = sorted(
                manager.routable(), key=lambda h: h.replica_id
            )
            for h in victims[2:]:
                drains.append(manager.drain(h.replica_id))
            manager.poll()
            pair = sorted(manager.routable(), key=lambda h: h.replica_id)
            chaos_rate = knee_by_count.get(2) or (
                2 * float(per_replica_rates[len(per_replica_rates) // 2])
            )
            kill_report: dict = {}

            def mid_hook(_i: int) -> None:
                # kill the busier of the pair at the schedule midpoint,
                # snapshotting its router-observed in-flight count first —
                # the bound every loss must attribute under
                victim = max(pair, key=lambda h: h.in_flight)
                kill_report.update(manager.kill(victim.replica_id))

            counters_before = router.counters_snapshot()

            def chaos_body(i: int) -> bytes:
                # deterministic request ids: the flight dump harvested
                # from the victim names these same ids, so every lost row
                # joins to the dump entry (batch / queue slot) it died in
                doc = json.loads(make_body(i))
                doc["request_id"] = f"chaos-{i:04d}"
                return json.dumps(doc).encode()

            chaos_level = run_fleet_level(
                router,
                chaos_body,
                chaos_rate,
                n_requests * 2,
                seed=seed + 101,
                mid_hook=mid_hook,
                detail=True,
                **level_kw,
            )
            counters_after = router.counters_snapshot()
            victim_id = kill_report.get("replica_id")
            requests = chaos_level.pop("requests")
            lost_dead = lost_unaccounted = 0
            lost_ids: list[str] = []
            for r in requests:
                if r["status"] in (200, 429, 504):
                    continue
                # terminal failure: must attribute to the dead replica
                if r.get("error_replica") == victim_id or (
                    r.get("served_by") == victim_id
                ):
                    lost_dead += 1
                    lost_ids.append(f"chaos-{r['i']:04d}")
                else:
                    lost_unaccounted += 1
            flight_block = _attribute_losses(
                kill_report.get("flight"), lost_ids
            )
            failovers = {
                k: counters_after.get(k, 0) - counters_before.get(k, 0)
                for k in counters_after
                if k.startswith(("failover_", "retries", "shed_"))
                and counters_after.get(k, 0) != counters_before.get(k, 0)
            }
            # the induced kill is an incident on the record: evidence
            # (kill report incl. flight-dump summary, per-batch loss
            # attribution, the failover story) frozen at open time
            incidents.open(
                "replica_dead",
                f"replica {victim_id} SIGKILLed mid-level with "
                f"{kill_report.get('in_flight_at_kill')} in flight",
                severity="critical",
                evidence={
                    "kill": kill_report,
                    "flight": flight_block,
                    "lost_dead_replica": lost_dead,
                    "lost_unaccounted": lost_unaccounted,
                    "router_failover_delta": failovers,
                },
                dedupe_key=f"replica_dead:{victim_id}",
            )
            # recovery: the survivor re-runs the per-replica ladder; its
            # knee must come back to the (N-1)=1-replica level
            manager.poll()
            recovery_levels = [
                run_fleet_level(
                    router,
                    make_body,
                    float(rate),
                    n_requests,
                    seed=seed + 201 + li,
                    **level_kw,
                )
                for li, rate in enumerate(per_replica_rates)
            ]
            recovery_knee = detect_knee(recovery_levels)
            knee_1 = knee_by_count.get(1)
            chaos_block = {
                "offered_rps": chaos_rate,
                "kill": kill_report,
                "drains_before": drains,
                "level": chaos_level,
                "shed_accounting": {
                    "in_flight_at_kill": kill_report.get("in_flight_at_kill"),
                    "lost_dead_replica": lost_dead,
                    "lost_unaccounted": lost_unaccounted,
                    "rejected_backpressure": chaos_level["rejected"],
                    "retried": chaos_level["retried"],
                    "router_failover_delta": failovers,
                    "flight": flight_block,
                },
                "recovery": {
                    "levels": recovery_levels,
                    "knee": recovery_knee,
                    "knee_n_minus_1": knee_1,
                    "recovery_ratio": (
                        round(recovery_knee["knee_rps"] / knee_1, 4)
                        if recovery_knee["knee_rps"] and knee_1
                        else None
                    ),
                },
            }
            # the frozen evidence outlives the resolve — the record keeps
            # the full incident; resolving marks the fleet healthy again
            incidents.resolve(
                f"replica_dead:{victim_id}",
                "survivor recovery ladder complete (recovery_ratio="
                f"{chaos_block['recovery']['recovery_ratio']})",
            )
    finally:
        final_view = manager.fleet_view()
        manager.close()

    record = {
        "counts": list(counts),
        "per_replica_rates": [float(r) for r in per_replica_rates],
        "n_requests_per_replica": n_requests,
        "arrival": arrival,
        "warmseed": warmseed,
        "stages": stages,
        "scaling": scaling,
        "warm": warm,
        "chaos": chaos_block,
        "router": {"counters": router.counters_snapshot()},
        "fleet_final": final_view,
        # the attack work ran in the replica subprocesses; this driver's
        # ledger/gaps windows are honestly near-empty (noted so a reader
        # of telemetry.cost doesn't mistake the router for the fleet)
        "work_in": "replica_subprocesses",
        "execution": {
            "mesh": None,
            "replica_counts": list(counts),
            "router_retry_budget": router.retry_budget,
        },
        "telemetry": telemetry_block(
            ledger_since=ledger_mark,
            gaps_since=gaps_mark,
            incidents=incidents_block(incidents),
        ),
    }
    return validate_record(record, "fleet")
