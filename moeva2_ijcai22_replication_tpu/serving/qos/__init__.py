"""QoS serving layer: priority classes, cost-predictive admission, streams.

Three cooperating pieces (docs/DESIGN.md § QoS):

- :mod:`policy` — the class taxonomy (``interactive``/``batch``/
  ``scavenger`` by default), per-tenant defaults, and the config loader.
- :mod:`admission` — per-(domain, class) token buckets sized from the
  capacity model's ``max_sustainable_qps`` × class rate share; the
  cost-predictive front door that sheds scavenger/batch first under
  overload, with ledger-predicted per-class ``Retry-After``.
- :mod:`stream` — per-request :class:`ResultStream` fed by the MoEvA
  early-exit gate: solved rows surface to the caller as they park,
  before the scan completes.

Everything here is host-side bookkeeping: with no :class:`QosPolicy`
wired into the service the request path is bit-identical and compiles
nothing extra.
"""

from .admission import AdmissionController
from .policy import DEFAULT_CLASSES, QosClass, QosPolicy
from .stream import ResultStream, StreamRegistry

__all__ = [
    "AdmissionController",
    "DEFAULT_CLASSES",
    "QosClass",
    "QosPolicy",
    "ResultStream",
    "StreamRegistry",
]
