"""Cost-predictive admission: per-class token buckets priced by the
capacity model.

The capacity model (observability/capacity.py) already turns the cost
ledger's per-executable FLOP totals into a per-domain
``max_sustainable_qps`` — the rate the device can actually serve at the
currently-observed predicted FLOPs/request. Admission multiplies that by
each class's ``rate_share`` and runs a standard token bucket per
(domain, class): a request costs one token, tokens refill at the class
rate, and the bucket holds ``rate * burst_s`` tokens of burst. The
consequences fall out by construction:

- overload sheds the small-share classes (scavenger, then batch) first,
  because their buckets drain first and refill slowest;
- a queue-full 429's ``Retry-After`` is *predicted* from the class
  refill rate (time until one token exists), not a blind constant;
- while the capacity window is unprimed (no batches observed yet, or
  the model can't price this domain), everything is admitted — the
  bucket arms itself from measurement, mirroring the bench-gate
  "unarmed until first record" discipline.

Pure host-side arithmetic: no compiles, no dispatches, O(1) per request.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable

from .policy import QosPolicy

#: how long one capacity-model read is reused before re-deriving the
#: per-class rates — domain_block() walks the observation window, so
#: pricing every request individually would make admission O(window)
_RATE_CACHE_S = 0.25

#: Retry-After clamp, matching CapacityModel.retry_after_s discipline
_RETRY_FLOOR_S = 0.001
_RETRY_CAP_S = 30.0


class AdmissionDenied(Exception):
    """Raised when a class bucket has no token; carries the predicted wait."""

    def __init__(self, klass: str, retry_after_s: float, rate: float):
        self.klass = klass
        self.retry_after_s = retry_after_s
        self.rate = rate
        super().__init__(
            f"admission: class {klass!r} over its rate "
            f"({rate:.3f} rps); retry in {retry_after_s:.3f}s"
        )


class _Bucket:
    __slots__ = ("tokens", "last_refill", "rate", "burst")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst  # start full: admission never cold-rejects
        self.last_refill = now


class AdmissionController:
    """Per-(domain, class) token buckets sized from the capacity model."""

    def __init__(
        self,
        policy: QosPolicy,
        capacity: Any,
        *,
        clock: Callable[[], float] = time.monotonic,
        burst_s: float | None = None,
    ):
        self.policy = policy
        self.capacity = capacity
        self.clock = clock
        self.burst_s = (
            policy.admission_burst_s if burst_s is None else float(burst_s)
        )
        self._lock = threading.Lock()
        self._buckets: dict[tuple[str, str], _Bucket] = {}
        self._rates: dict[str, tuple[float, float | None]] = {}  # domain -> (t, qps)
        self.admitted = 0
        self.denied = 0
        self.denied_by_class: dict[str, int] = {}

    # -- rates -------------------------------------------------------------

    def _domain_qps(self, domain: str, now: float) -> float | None:
        """Cached ``max_sustainable_qps`` read; None = model unprimed."""
        cached = self._rates.get(domain)
        if cached is not None and now - cached[0] < _RATE_CACHE_S:
            return cached[1]
        qps = None
        if self.capacity is not None:
            try:
                block = self.capacity.domain_block(domain)
                qps = block.get("max_sustainable_qps") if block else None
            except Exception:
                qps = None
        self._rates[domain] = (now, qps)
        return qps

    def class_rate(self, domain: str, klass: str) -> float | None:
        """The refill rate (rps) class ``klass`` currently gets for
        ``domain``; None while the capacity model can't price it."""
        qps = self._domain_qps(domain, self.clock())
        if qps is None or qps <= 0:
            return None
        qc = self.policy.classes.get(klass)
        share = qc.rate_share if qc else 1.0
        return max(qps * share, 0.0)

    # -- admission ---------------------------------------------------------

    def admit(self, domain: str, klass: str) -> None:
        """Take one token or raise :class:`AdmissionDenied`.

        Unpriceable (unprimed-capacity) traffic is always admitted; a
        zero-share class is never admitted once the model is primed.
        """
        now = self.clock()
        with self._lock:
            rate = self._class_rate_locked(domain, klass, now)
            if rate is None:
                self.admitted += 1
                return
            key = (domain, klass)
            burst = max(rate * self.burst_s, 1.0)
            b = self._buckets.get(key)
            if b is None:
                b = _Bucket(rate, burst, now)
                self._buckets[key] = b
            else:
                # re-derive against the live rate: capacity drift resizes
                # the bucket without dropping accumulated tokens past burst
                b.tokens = min(
                    b.tokens + (now - b.last_refill) * b.rate, burst
                )
                b.rate, b.burst, b.last_refill = rate, burst, now
            if b.tokens >= 1.0:
                b.tokens -= 1.0
                self.admitted += 1
                return
            self.denied += 1
            self.denied_by_class[klass] = (
                self.denied_by_class.get(klass, 0) + 1
            )
            if rate > 0:
                wait = (1.0 - b.tokens) / rate
            else:
                wait = _RETRY_CAP_S
            raise AdmissionDenied(
                klass, min(max(wait, _RETRY_FLOOR_S), _RETRY_CAP_S), rate
            )

    def _class_rate_locked(
        self, domain: str, klass: str, now: float
    ) -> float | None:
        qps = self._domain_qps(domain, now)
        if qps is None or qps <= 0:
            return None
        qc = self.policy.classes.get(klass)
        share = qc.rate_share if qc else 1.0
        return max(qps * share, 0.0)

    # -- introspection -----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "enabled": bool(self.policy.admission),
                "burst_s": self.burst_s,
                "admitted": self.admitted,
                "denied": self.denied,
                "denied_by_class": dict(self.denied_by_class),
                "buckets": {
                    f"{d}|{k}": {
                        "rate_rps": round(b.rate, 6),
                        "burst": round(b.burst, 3),
                        "tokens": round(b.tokens, 3),
                    }
                    for (d, k), b in self._buckets.items()
                },
            }
